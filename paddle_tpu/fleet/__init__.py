"""Fleet: unified distributed-training API.

Reference: python/paddle/fluid/incubate/fleet/ — the Fleet facade
(base/fleet_base.py:38), role makers (base/role_maker.py), collective mode
(collective/__init__.py) and parameter-server mode. Usage shape matches the
reference:

    from paddle_tpu.fleet import fleet, DistributedStrategy
    fleet.init(role_maker)
    opt = fleet.distributed_optimizer(fluid.optimizer.Adam(1e-4), strategy)
    opt.minimize(loss)
    exe.run(fleet.main_program, feed=..., fetch_list=...)
"""

from paddle_tpu.fleet.base import DistributedOptimizer, Fleet
from paddle_tpu.fleet.collective import (
    CollectiveOptimizer,
    DistributedStrategy,
    fleet,
)
from paddle_tpu.fleet import role_maker
from paddle_tpu.fleet.role_maker import (
    PaddleCloudRoleMaker,
    Role,
    RoleMakerBase,
    UserDefinedCollectiveRoleMaker,
    UserDefinedRoleMaker,
)

__all__ = [
    "fleet",
    "Fleet",
    "DistributedOptimizer",
    "CollectiveOptimizer",
    "DistributedStrategy",
    "role_maker",
    "Role",
    "RoleMakerBase",
    "PaddleCloudRoleMaker",
    "UserDefinedRoleMaker",
    "UserDefinedCollectiveRoleMaker",
]
