"""Fleet base: the unified distributed-training facade.

Reference: python/paddle/fluid/incubate/fleet/base/fleet_base.py —
`Fleet` :38 (init :184 with a RoleMaker, distributed_optimizer :238) and
`DistributedOptimizer` :256. The TPU build keeps the API shape (user code
stays single-program) but the mechanism is SPMD: every process is one JAX
host in a multi-controller job, and `jax.distributed.initialize` replaces
the NCCL-id RPC bootstrap.
"""

import abc
import os

from paddle_tpu.fleet.role_maker import PaddleCloudRoleMaker, RoleMakerBase

__all__ = ["Fleet", "DistributedOptimizer"]


class Fleet(metaclass=abc.ABCMeta):
    def __init__(self):
        self._role_maker = None
        self._optimizer = None
        self._is_initialized = False
        self._origin_program = None
        self._main_program = None    # post-minimize (compiled) program
        self._startup_program = None

    # ---- role delegation ------------------------------------------------
    def is_first_worker(self):
        return self._role_maker.is_first_worker()

    def worker_index(self):
        return self._role_maker.worker_index()

    def worker_num(self):
        return self._role_maker.worker_num()

    def is_worker(self):
        return self._role_maker.is_worker()

    def server_index(self):
        return self._role_maker.server_index()

    def server_num(self):
        return self._role_maker.server_num()

    def is_server(self):
        return self._role_maker.is_server()

    def worker_endpoints(self, to_string=False):
        eps = self._role_maker.get_trainer_endpoints()
        return ",".join(eps) if to_string else eps

    def server_endpoints(self, to_string=False):
        eps = self._role_maker.get_pserver_endpoints()
        return ",".join(eps) if to_string else eps

    # ---- lifecycle ------------------------------------------------------
    def init(self, role_maker=None):
        """Reference: fleet_base.py:184. Also brings up the JAX distributed
        runtime when the job spans processes (the coordinator plays the role
        of the reference's gen_nccl_id RPC server)."""
        if role_maker is None:
            role_maker = PaddleCloudRoleMaker()
        if not isinstance(role_maker, RoleMakerBase):
            raise TypeError("role_maker must be a RoleMakerBase")
        self._role_maker = role_maker
        role_maker.generate_role()
        self._maybe_init_jax_distributed()
        self._is_initialized = True

    def _maybe_init_jax_distributed(self):
        """Multi-process collective jobs rendezvous through the JAX
        coordinator. Gated on PADDLE_DIST_COORDINATOR so single-process
        tests and PS-mode servers never block on a barrier."""
        coord = os.environ.get("PADDLE_DIST_COORDINATOR", "")
        if not coord or not self._role_maker.is_worker():
            return
        import jax

        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=self._role_maker.worker_num(),
            process_id=self._role_maker.worker_index(),
        )

    @property
    def main_program(self):
        return self._main_program

    @property
    def startup_program(self):
        return self._startup_program

    # ---- to be provided by the mode (collective / parameter server) -----
    @abc.abstractmethod
    def distributed_optimizer(self, optimizer, strategy=None):
        ...

    @abc.abstractmethod
    def init_worker(self):
        ...

    @abc.abstractmethod
    def init_server(self, model_dir=None):
        ...

    @abc.abstractmethod
    def run_server(self):
        ...

    @abc.abstractmethod
    def stop_worker(self):
        ...

    def save_inference_model(
        self,
        executor,
        dirname,
        feeded_var_names,
        target_vars,
        main_program=None,
        export_for_deployment=True,
    ):
        from paddle_tpu import io

        prog = main_program or self._origin_program
        return io.save_inference_model(
            dirname, feeded_var_names, target_vars, executor, main_program=prog
        )

    def save_persistables(self, executor, dirname, main_program=None):
        from paddle_tpu import io

        prog = main_program or self._origin_program
        return io.save_persistables(executor, dirname, main_program=prog)


class DistributedOptimizer(metaclass=abc.ABCMeta):
    """Wraps a regular Optimizer; minimize() additionally rewrites/compiles
    the program for the distributed mode (reference: fleet_base.py:256)."""

    def __init__(self, optimizer, strategy=None):
        self._optimizer = optimizer
        self._strategy = strategy

    @abc.abstractmethod
    def minimize(
        self, loss, startup_program=None, parameter_list=None, no_grad_set=None
    ):
        ...

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        return self._optimizer.backward(
            loss, startup_program, parameter_list, no_grad_set
        )

    def apply_gradients(self, params_grads):
        return self._optimizer.apply_gradients(params_grads)
