"""Role makers: cluster-topology discovery for Fleet.

TPU-native analog of the reference's role makers (reference:
python/paddle/fluid/incubate/fleet/base/role_maker.py — PaddleCloudRoleMaker
:441 reads PADDLE_* env vars, UserDefinedRoleMaker :876). The reference also
ships an MPI role maker (:225); here multi-host rendezvous is owned by
`jax.distributed.initialize` (the analog of the gen_nccl_id RPC bootstrap,
reference: paddle/fluid/operators/collective/c_gen_nccl_id_op.cc), so role
makers only need env/user-supplied topology.
"""

import os

__all__ = [
    "Role",
    "RoleMakerBase",
    "PaddleCloudRoleMaker",
    "UserDefinedRoleMaker",
    "UserDefinedCollectiveRoleMaker",
]


class Role:
    WORKER = 1
    SERVER = 2


class RoleMakerBase:
    def __init__(self):
        self._role = Role.WORKER
        self._current_id = 0
        self._worker_endpoints = []
        self._server_endpoints = []
        self._role_is_generated = False

    def generate_role(self):
        self._role_is_generated = True

    def _ensure_generated(self):
        if not self._role_is_generated:
            self.generate_role()

    def is_worker(self):
        self._ensure_generated()
        return self._role == Role.WORKER

    def is_server(self):
        self._ensure_generated()
        return self._role == Role.SERVER

    def is_first_worker(self):
        return self.is_worker() and self.worker_index() == 0

    def worker_index(self):
        self._ensure_generated()
        return self._current_id if self._role == Role.WORKER else -1

    def server_index(self):
        self._ensure_generated()
        return self._current_id if self._role == Role.SERVER else -1

    def worker_num(self):
        self._ensure_generated()
        return max(len(self._worker_endpoints), 1)

    def server_num(self):
        self._ensure_generated()
        return len(self._server_endpoints)

    def get_trainer_endpoints(self):
        self._ensure_generated()
        return list(self._worker_endpoints)

    def get_pserver_endpoints(self):
        self._ensure_generated()
        return list(self._server_endpoints)


class PaddleCloudRoleMaker(RoleMakerBase):
    """Discover the role from PADDLE_* environment variables (the contract
    set by fleet launch; reference: role_maker.py:441 and launch.py:105-109).

    TRAINING_ROLE=TRAINER|PSERVER selects worker/server; collective jobs
    only set trainer vars.
    """

    def __init__(self, is_collective=True):
        super().__init__()
        self._is_collective = is_collective

    def generate_role(self):
        if self._role_is_generated:
            return
        training_role = os.environ.get("TRAINING_ROLE", "TRAINER")
        self._worker_endpoints = [
            e for e in os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",") if e
        ]
        self._server_endpoints = [
            e
            for e in os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "").split(",")
            if e
        ]
        if training_role == "PSERVER":
            self._role = Role.SERVER
            cur = os.environ.get("PADDLE_CURRENT_ENDPOINT", "")
            self._current_id = (
                self._server_endpoints.index(cur)
                if cur in self._server_endpoints
                else int(os.environ.get("PADDLE_TRAINER_ID", "0"))
            )
        else:
            self._role = Role.WORKER
            self._current_id = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
            if not self._worker_endpoints:
                n = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
                self._worker_endpoints = [""] * n
        self._role_is_generated = True


class UserDefinedRoleMaker(RoleMakerBase):
    """Explicit topology (reference: role_maker.py:876)."""

    def __init__(
        self,
        current_id=0,
        role=Role.WORKER,
        worker_num=1,
        server_endpoints=None,
        worker_endpoints=None,
    ):
        super().__init__()
        self._current_id = current_id
        self._role = role
        self._server_endpoints = list(server_endpoints or [])
        self._worker_endpoints = list(worker_endpoints or [""] * worker_num)

    def generate_role(self):
        self._role_is_generated = True


class UserDefinedCollectiveRoleMaker(RoleMakerBase):
    """Collective-only explicit topology (reference: role_maker.py:952)."""

    def __init__(self, current_id=0, worker_endpoints=None):
        super().__init__()
        self._current_id = current_id
        self._role = Role.WORKER
        self._worker_endpoints = list(worker_endpoints or [""])

    def generate_role(self):
        self._role_is_generated = True
