"""Collective fleet mode: SPMD data/hybrid parallelism over a device mesh.

Reference: python/paddle/fluid/incubate/fleet/collective/__init__.py —
`CollectiveOptimizer` :378 transpiles the program (inserting c_allreduce ops,
python/paddle/fluid/transpiler/collective.py:178) and compiles with
ParallelExecutor (:312-376). Here `minimize` runs the plain optimizer pass,
then hands back a CompiledProgram whose step is pjit-partitioned over a mesh
built from the DistributedStrategy — GSPMD inserts the gradient all-reduces
over ICI/DCN, so there is no transpiler inserting collective ops.
"""

from paddle_tpu.compiler import BuildStrategy, CompiledProgram
from paddle_tpu.core.ir import default_startup_program
from paddle_tpu.fleet.base import DistributedOptimizer, Fleet
from paddle_tpu.parallel.env import make_mesh

__all__ = ["DistributedStrategy", "CollectiveOptimizer", "fleet"]


class DistributedStrategy(BuildStrategy):
    """Extends BuildStrategy the way the reference's collective
    DistributedStrategy does (reference: incubate/fleet/collective/
    __init__.py:134). The meaningful TPU knobs are the mesh factorization and
    feature toggles; NCCL tuning knobs are accepted and ignored (XLA owns
    collective scheduling)."""

    def __init__(self):
        super().__init__()
        # mesh factorization: None → 1-D 'data' mesh over all devices.
        # 2-D (dcn, ici) shapes express hierarchical allreduce
        # (reference: paddle/fluid/framework/parallel_executor.cc:196).
        self.mesh_shape = None
        self.mesh_axis_names = None
        # mesh axis -> 'ici' | 'dcn': feeds the static cost stage's
        # two-level collective model; naming an axis 'dcn' (or tagging it
        # here) makes the hierarchical-allreduce linter a hard error
        self.mesh_axis_tags = None
        self.param_rules = None      # Megatron-style TP rule table
        # pipeline_stack schedule: 'gpipe' | '1f1b' (+ interleave degree);
        # run-time choice, joined into the compile-cache fingerprint
        self.pipeline_schedule = None
        self.pipeline_interleave = None
        self.param_specs = None      # exact name -> PartitionSpec
        self.input_specs = None      # feed name -> PartitionSpec
        # canonical sharding layer (parallel/spec_layout.py): a SpecLayout
        # instance, or True for the default role registry — every param
        # gets a role-derived spec; param_specs stay exact overrides
        self.spec_layout = None
        # feature toggles, applied as program rewrites in minimize()
        self.use_amp = False
        self.amp_lists = None
        self.init_loss_scaling = 2.0 ** 15
        self.use_dynamic_loss_scaling = True
        self.recompute = False
        self.recompute_checkpoints = None
        # accepted-for-parity NCCL knobs (no-ops under XLA)
        self.nccl_comm_num = 1
        self.use_hierarchical_allreduce = False
        self.hierarchical_allreduce_inter_nranks = 1
        self.forward_recompute = False  # alias some configs use

    def build_mesh(self, devices=None):
        return make_mesh(
            shape=self.mesh_shape, axis_names=self.mesh_axis_names, devices=devices
        )


class CollectiveOptimizer(DistributedOptimizer):
    def __init__(self, optimizer, strategy=None):
        super().__init__(optimizer, strategy or DistributedStrategy())

    def minimize(
        self, loss, startup_program=None, parameter_list=None, no_grad_set=None
    ):
        strategy = self._strategy
        opt = self._optimizer
        if strategy.recompute or strategy.forward_recompute:
            from paddle_tpu.optimizer import RecomputeOptimizer

            opt = RecomputeOptimizer(opt)
            if strategy.recompute_checkpoints:
                opt._set_checkpoints(strategy.recompute_checkpoints)
        if strategy.use_amp:
            from paddle_tpu import amp

            opt = amp.decorate(
                opt,
                amp_lists=strategy.amp_lists,
                init_loss_scaling=strategy.init_loss_scaling,
                use_dynamic_loss_scaling=strategy.use_dynamic_loss_scaling,
            )
        optimize_ops, params_grads = opt.minimize(
            loss, startup_program, parameter_list, no_grad_set
        )

        main = loss.block.program
        fleet._origin_program = main
        fleet._startup_program = startup_program or default_startup_program()
        compiled = CompiledProgram(main, build_strategy=strategy).with_parallel(
            mesh=strategy.build_mesh(),
            loss_name=loss.name,
            param_rules=strategy.param_rules,
            param_specs=strategy.param_specs,
            input_specs=strategy.input_specs,
            spec_layout=strategy.spec_layout,
            axis_tags=strategy.mesh_axis_tags,
            pipeline_schedule=strategy.pipeline_schedule,
            pipeline_interleave=strategy.pipeline_interleave,
        )
        fleet._main_program = compiled
        return optimize_ops, params_grads


class _CollectiveFleet(Fleet):
    def distributed_optimizer(self, optimizer, strategy=None):
        self._optimizer = CollectiveOptimizer(optimizer, strategy)
        return self._optimizer

    def init_worker(self):
        pass

    def init_server(self, model_dir=None):
        raise RuntimeError("collective fleet has no servers")

    def run_server(self):
        raise RuntimeError("collective fleet has no servers")

    def stop_worker(self):
        pass


#: module-level singleton, same usage shape as the reference's
#: `from paddle.fluid.incubate.fleet.collective import fleet`
fleet = _CollectiveFleet()
