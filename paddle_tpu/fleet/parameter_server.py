"""Parameter-server fleet mode: billion-feature sparse training.

Reference: python/paddle/fluid/incubate/fleet/parameter_server/ — the pslib
flow (DownpourWorker, device_worker.h:203): per batch, workers pull the
batch's sparse rows from servers, run fwd/bwd locally, and push sparse grads
back; dense parameters stay worker-side. The TPU translation: dense params
live on-device inside the jit step (better than PS round-trips), sparse
tables live on native PS servers (csrc/ps), and the worker's pull -> step ->
push pipeline is host code around the compiled step (PSWorker.run).
sparse_embedding programs need no transpilation (the rows/idx feed
structure is emitted at build time); reference-style
`embedding(is_distributed=True)` programs ARE transpiled by
ParameterServerOptimizer.minimize into in-graph remote lookups — the
DistributeTranspiler rewrite, re-based on host callbacks.

Usage:
    from paddle_tpu.fleet import parameter_server as psfleet
    fleet = psfleet.fleet
    fleet.init(role_maker)
    if fleet.is_server():
        fleet.init_server(); fleet.run_server()
    else:
        opt = fleet.distributed_optimizer(optimizer, strategy)
        opt.minimize(loss)
        fleet.init_worker()
        worker = fleet.worker(exe)
        for batch: worker.run(program, feed, fetch_list)
        fleet.stop_worker()
"""

import os
import time

import numpy as np

from paddle_tpu.core.backward import append_backward
from paddle_tpu.core.ir import default_main_program, default_startup_program
from paddle_tpu.fleet.base import DistributedOptimizer, Fleet
from paddle_tpu.layer_helper import LayerHelper
from paddle_tpu.utils.enforce import enforce

__all__ = ["fleet", "PSDistributedStrategy", "ParameterServerOptimizer", "PSWorker"]

_OPT_CODES = {"sgd": 0, "adagrad": 1}


class PSDistributedStrategy:
    """reference: incubate/fleet/parameter_server/distribute_transpiler/
    distributed_strategy.py (Sync/Async/Geo).

    mode="geo" is GEO-SGD delta-sync (reference: python/paddle/fluid/
    transpiler/geo_sgd_transpiler.py): dense parameters train LOCALLY with
    the full optimizer; every `merge_steps` steps the worker pushes
    (param - param_at_last_sync) / worker_num into the server's global
    copy and pulls the merged result. Sparse tables stay server-side."""

    def __init__(self, mode="sync", sparse_lr=0.1, merge_steps=4):
        enforce(mode in ("sync", "async", "half_async", "geo"), f"bad mode {mode}")
        self.mode = mode
        self.sparse_lr = sparse_lr
        self.merge_steps = merge_steps


class ParameterServerOptimizer(DistributedOptimizer):
    """minimize() = normal dense minimize + grad seeds for every sparse
    table's pulled-rows var (so rows@GRAD exists for the worker to fetch)."""

    def __init__(self, optimizer, strategy=None):
        super().__init__(optimizer, strategy or PSDistributedStrategy())

    def _transpile_distributed_embeddings(self, program, startup_program):
        """The reference's DistributeTranspiler rewrite for
        `embedding(..., is_distributed=True)` (reference: python/paddle/
        fluid/transpiler/distribute_transpiler.py lookup-table handling):
        each lookup over an is_distributed Parameter becomes the remote
        in-graph form — the table never materializes locally. The local
        Parameter and its startup init are removed; the table is created
        server-side at fleet.init_worker."""
        import warnings as _warnings

        block = program.global_block()
        tables = getattr(program, "_remote_tables", None)
        # the rewrite covers the GLOBAL block; an is_distributed lookup
        # buried in a cond/while sub-block must fail loudly, not silently
        # train a worker-local table
        for b in program.blocks[1:]:
            for op in b.ops:
                if op.type not in ("lookup_table", "lookup_table_v2"):
                    continue
                wname = op.inputs.get("W", [None])[0]
                w = block._find_var_recursive(wname) if wname else None
                enforce(
                    w is None or not getattr(w, "is_distributed", False),
                    f"embedding '{wname}': is_distributed=True inside a "
                    "cond/while sub-block cannot transpile to the remote "
                    "path — hoist the lookup out of the control-flow "
                    "region or keep the table local",
                )
        # group by table var first: one W may feed several lookups (shared
        # table across slots) — all of them rewrite against ONE server
        # table, and the var is dropped once
        sites = {}  # wname -> [op index]
        for i, op in enumerate(block.ops):
            if op.type not in ("lookup_table", "lookup_table_v2"):
                continue
            wname = op.inputs.get("W", [None])[0]
            w = block.vars.get(wname)
            if w is None or not getattr(w, "is_distributed", False):
                continue
            # validate BEFORE any mutation: a mid-rewrite failure would
            # leave a half-transpiled program (some lookups remote, the
            # local table still present, no push ops)
            pad = op.attrs.get("padding_idx", -1)
            enforce(
                pad is None or pad < 0,
                f"embedding '{wname}': is_distributed=True with "
                "padding_idx is not supported on the remote path — drop "
                "padding_idx (mask downstream) or keep the table local",
            )
            sites.setdefault(wname, []).append(i)
        # resolve-startup check belongs with the other pre-mutation
        # validations: raising mid-rewrite would leave a half-transpiled
        # program (remote lookups in place, init ops never stripped)
        enforce(
            not sites or startup_program is not None,
            f"embedding(is_distributed=True) tables {sorted(sites)}: "
            "cannot resolve the startup program to strip their init ops — "
            "minimize() ran outside the program's own program_guard and "
            "got no startup_program. Pass minimize(loss, "
            "startup_program=...) (the reference transpiler takes it "
            "explicitly); otherwise running the real startup would still "
            "materialize the full [vocab, dim] local table.",
        )
        rewritten = []
        from paddle_tpu.core.ir import Operator
        from paddle_tpu.layers.nn import _next_table_id

        for wname, idxs in sites.items():
            w = block.vars[wname]
            dim = int(w.shape[-1])
            if tables is None:
                tables = program._remote_tables = {}
            table_id = _next_table_id(program)
            for k, i in enumerate(idxs):
                op = block.ops[i]
                block.ops[i] = Operator(
                    block, "distributed_lookup_table",
                    {"Ids": list(op.inputs["Ids"])},
                    {"Outputs": list(op.outputs["Out"])},
                    {"table_name": wname, "dim": dim},
                )
                out_name = op.outputs["Out"][0]
                ov = block.vars.get(out_name)
                if ov is not None:
                    ov.stop_gradient = False
                entry_key = wname if k == 0 else f"{wname}__use{k}"
                tables[entry_key] = {
                    "table_id": table_id,
                    "table_name": wname,  # the wire/registration name
                    "ids": op.inputs["Ids"][0],
                    "out": out_name,
                    "dim": dim,
                    "init_range": 0.01,
                    "optimizer": "sgd",
                }
            rewritten.append(wname)
            # the table exists only on the servers: drop the local
            # Parameter and its startup initialization
            block.vars.pop(wname, None)
            sblock = startup_program.global_block()
            kept_init = [
                o for o in sblock.ops if wname not in o.output_names()
            ]
            if len(kept_init) == len(sblock.ops):
                _warnings.warn(
                    f"embedding(is_distributed=True) table '{wname}': no "
                    "init ops found in the resolved startup program — if "
                    "another startup program initializes it, the full "
                    "[vocab, dim] local table will still materialize "
                    "there (pass that program via minimize(loss, "
                    "startup_program=...))",
                    stacklevel=4,
                )
            sblock.ops = kept_init
            sblock.vars.pop(wname, None)
        if rewritten:
            program._bump_version()
            _warnings.warn(
                f"embedding(is_distributed=True) tables {rewritten} "
                "transpiled to parameter-server remote lookups (the "
                "reference's distribute_transpiler rewrite); they train "
                "with the server-side optimizer at strategy.sparse_lr",
                stacklevel=3,
            )
        return rewritten

    @staticmethod
    def _resolve_startup(program):
        """The guard-paired startup when it is provably the right one
        (program IS the default main, so the default pair is this
        model's), else None — never a guess."""
        if program is default_main_program():
            return default_startup_program()
        return None

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        program = loss.block.program
        # ADVICE r5 low: default_startup_program() is only the real
        # startup when `program` is itself the default main (i.e. minimize
        # runs inside the user's program_guard, where the guard binds the
        # pair). Outside the guard the default pair belongs to some OTHER
        # model; stripping a table's init ops from it is a no-op on the
        # real startup, which would then still materialize the full
        # [vocab, dim] local table. Resolve honestly: explicit argument >
        # guard-paired default > None (transpile then demands the table's
        # startup explicitly).
        startup_program = startup_program or self._resolve_startup(program)
        self._transpile_distributed_embeddings(program, startup_program)
        tables = getattr(program, "_sparse_tables", {})
        remote = getattr(program, "_remote_tables", {})
        rows_names = [t["rows"] for t in tables.values()]
        # remote in-graph tables: seed their lookup OUTPUT so Out@GRAD gets
        # finalized even though the lookup op has no differentiable inputs
        out_names = [t["out"] for t in remote.values()]
        opt = self._optimizer
        opt.helper = LayerHelper(opt.__class__.__name__)
        opt._create_global_learning_rate()
        params_grads = append_backward(
            loss, parameter_list, no_grad_set,
            extra_seeds=rows_names + out_names,
        )
        block = loss.block
        for tname, t in remote.items():
            # in-step push of the merged row grads (op_role=backward so the
            # microbatched executor runs it per-microbatch with that
            # microbatch's ids)
            block.append_op(
                "distributed_push_sparse",
                {"Ids": [t["ids"]], "Grad": [t["out"] + "@GRAD"]},
                {},
                {"table_name": t.get("table_name", tname), "dim": t["dim"],
                 "op_role": 1},
            )
        optimize_ops = opt.apply_gradients(params_grads)
        # dataset-mode wiring (reference: the transpiler writing opt_info
        # into the program for trainer_factory): train_from_dataset reads
        # this to drive batches through the Downpour device worker
        program._fleet_opt = {
            "trainer": "DistMultiTrainer",
            "device_worker": "DownpourSGD",
        }
        fleet._origin_program = program
        fleet._main_program = program
        fleet._startup_program = startup_program or default_startup_program()
        fleet._strategy = self._strategy
        return optimize_ops, params_grads


class PSWorker:
    """Per-process worker driver: pull -> compiled step -> push.

    The reference runs this loop thread-per-core in C++ DeviceWorkers
    (reference: paddle/fluid/framework/device_worker.h:203 DownpourWorker,
    hogwild_worker.cc:237); here one loop feeds the whole chip because the
    step itself is a single XLA computation — overlap comes from the async
    Communicator and the DataLoader's prefetch thread."""

    GEO_DENSE_TABLE = 1 << 30  # reserved dense table id for geo delta-sync

    def __init__(self, exe, client, tables, strategy, program=None,
                 worker_num=1, is_first_worker=True):
        from paddle_tpu.distributed.ps import Communicator

        self._exe = exe
        self._client = client
        self._tables = tables
        self._strategy = strategy
        mode = "sync" if strategy.mode == "sync" else "async"
        self._comm = Communicator(
            client, mode=mode, merge_steps=strategy.merge_steps
        )
        self._geo = strategy.mode == "geo"
        self._geo_params = []
        self._geo_snapshot = None
        self._geo_step = 0
        self._worker_num = max(int(worker_num), 1)
        if self._geo and program is not None:
            self._geo_params = [p.name for p in program.all_parameters()]
            if self._geo_params:
                total = self._geo_total_size(program)
                if is_first_worker:
                    # create (zero) + seed the global copy with this
                    # worker's init params; creating on every worker would
                    # wipe the seed (create replaces the table)
                    client.create_table(
                        self.GEO_DENSE_TABLE, dense_size=total,
                        is_dense=True, optimizer=0,
                    )
                    vec = self._concat_params()
                    client.push_dense(self.GEO_DENSE_TABLE, -vec, 1.0)
                if self._worker_num > 1:
                    client.barrier(self._worker_num)
                if is_first_worker:
                    self._geo_snapshot = self._concat_params()
                else:
                    # startup broadcast: every worker starts from worker 0's
                    # init (reference: geo_sgd startup param sync)
                    merged = client.pull_dense(self.GEO_DENSE_TABLE)
                    self._scatter_params(merged)
                    self._geo_snapshot = merged

    def _geo_total_size(self, program):
        return sum(
            int(np.prod(p.shape)) for p in program.all_parameters()
        )

    def _concat_params(self, scope=None):
        from paddle_tpu.core.scope import global_scope

        scope = scope or global_scope()
        return np.concatenate([
            np.asarray(scope.find_var(n), dtype=np.float32).reshape(-1)
            for n in self._geo_params
        ])

    def _scatter_params(self, vec, scope=None):
        from paddle_tpu.core.scope import global_scope

        scope = scope or global_scope()
        off = 0
        for n in self._geo_params:
            cur = np.asarray(scope.find_var(n))
            size = cur.size
            scope.set(
                n, vec[off:off + size].reshape(cur.shape).astype(cur.dtype)
            )
            off += size

    def _geo_sync(self, scope=None):
        """Delta push + fresh pull (reference: geo_sgd_transpiler.py — there
        send_vars of deltas to the pserver's sum table)."""
        cur = self._concat_params(scope)
        delta = (cur - self._geo_snapshot) / self._worker_num
        # server runs param -= lr * grad; lr = -1 turns the push into +=
        self._client.push_dense(self.GEO_DENSE_TABLE, delta, -1.0)
        merged = self._client.pull_dense(self.GEO_DENSE_TABLE)
        self._scatter_params(merged, scope)
        self._geo_snapshot = merged

    def prefetch(self, program, next_feed):
        """Announce the NEXT batch's ids so the in-graph remote lookups
        (distributed_embedding) overlap their server pull with the current
        step's compute — the reference's prefetch thread
        (reference: distributed/parameter_prefetch.cc:1)."""
        from paddle_tpu.distributed import lookup as _rl

        _rl.prefetch_for_program(program, next_feed)

    def run(self, program, feed, fetch_list=None, scope=None, infer=False):
        """One batch: pull sparse rows, run the step, push row grads.
        `infer=True` (infer_from_dataset) pulls but neither fetches grads
        nor pushes — evaluation must not move the server tables."""
        fetch_list = list(fetch_list or [])
        feed = dict(feed)
        pulled = {}  # table name -> (uniq_ids,)
        for tname, t in self._tables.items():
            ids = np.asarray(feed[t["ids"]])
            uniq, inv = np.unique(ids.astype(np.uint64), return_inverse=True)
            rows = self._client.pull_sparse(t["table_id"], uniq, t["dim"])
            feed[t["rows"]] = rows
            feed[t["idx"]] = inv.astype(np.int32).reshape(ids.shape)
            pulled[tname] = uniq
        if infer:
            return self._exe.run(
                program, feed=feed, fetch_list=fetch_list, scope=scope
            )
        grad_fetches = [t["rows"] + "@GRAD" for t in self._tables.values()]
        out = self._exe.run(
            program, feed=feed, fetch_list=fetch_list + grad_fetches,
            scope=scope,
        )
        n_user = len(fetch_list)
        for (tname, t), g in zip(self._tables.items(), out[n_user:]):
            self._comm.push_sparse(
                t["table_id"], pulled[tname], np.asarray(g),
                self._strategy.sparse_lr,
            )
        if self._geo and self._geo_params:
            self._geo_step += 1
            if self._geo_step % self._strategy.merge_steps == 0:
                self._geo_sync(scope)
                self._geo_pending = 0
            else:
                self._geo_pending = getattr(self, "_geo_pending", 0) + 1
        return out[:n_user]

    def flush(self):
        self._comm.flush()
        # geo: ship the tail of the last partial merge window — without
        # this, local progress since the last merge_steps boundary never
        # reaches the server's global copy
        if self._geo and getattr(self, "_geo_pending", 0):
            self._geo_sync()
            self._geo_pending = 0

    def stop(self):
        self._comm.stop()


class _PSFleet(Fleet):
    def __init__(self):
        super().__init__()
        self._server = None
        self._client = None
        self._worker_obj = None
        self._strategy = None

    def distributed_optimizer(self, optimizer, strategy=None):
        self._optimizer = ParameterServerOptimizer(optimizer, strategy)
        return self._optimizer

    # -- server side -------------------------------------------------------
    def init_server(self, model_dir=None, port=None):
        from paddle_tpu.distributed.ps import PSServer

        if port is None:
            eps = self.server_endpoints()
            me = self.server_index()
            port = int(eps[me].rsplit(":", 1)[1]) if eps and me >= 0 else 0
        self._server = PSServer(port)
        return self._server

    def run_server(self):
        enforce(self._server is not None, "init_server first")
        while True:
            time.sleep(1)

    # -- worker side -------------------------------------------------------
    def init_worker(self, program=None):
        from paddle_tpu.distributed.ps import PSClient

        program = program or self._origin_program
        eps = self.server_endpoints()
        if not eps and self._server is not None:
            eps = [self._server.endpoint]  # single-process test mode
        enforce(eps, "no server endpoints (set PADDLE_PSERVERS_IP_PORT_LIST)")
        self._client = PSClient(eps)
        tables = getattr(program, "_sparse_tables", {})
        remote = getattr(program, "_remote_tables", {})
        if self.worker_index() <= 0:
            created = set()
            for t in list(tables.values()) + list(remote.values()):
                if t["table_id"] in created:
                    continue  # shared table: several lookups, one table
                created.add(t["table_id"])
                self._client.create_table(
                    t["table_id"],
                    dim=t["dim"],
                    init_range=t["init_range"],
                    optimizer=_OPT_CODES.get(t["optimizer"], 0),
                )
        if remote:
            from paddle_tpu.distributed import lookup as _rl

            strategy = self._strategy or PSDistributedStrategy()
            ctx = _rl.RemoteLookupContext(
                self._client, sparse_lr=strategy.sparse_lr
            )
            for tname, t in remote.items():
                ctx.register(
                    t.get("table_name", tname), t["table_id"], t["dim"]
                )
            _rl.activate(ctx)
        if self.worker_num() > 1:
            self._client.barrier(self.worker_num())

    def worker(self, exe, program=None):
        program = program or self._origin_program
        tables = getattr(program, "_sparse_tables", {})
        self._worker_obj = PSWorker(
            exe, self._client, tables,
            self._strategy or PSDistributedStrategy(),
            program=program,
            worker_num=max(self.worker_num(), 1),
            is_first_worker=self.worker_index() <= 0,
        )
        return self._worker_obj

    def stop_worker(self):
        from paddle_tpu.distributed import lookup as _rl

        if self._worker_obj is not None:
            self._worker_obj.stop()
        _rl.deactivate()
        if self._client is not None:
            self._client.close()
        # clear worker state: a later init_worker/worker cycle (next job or
        # test) must not resurrect this client's tables
        self._worker_obj = None
        self._client = None

    # -- persistence -------------------------------------------------------
    def save_sparse_tables(self, dirname):
        tables = getattr(self._origin_program, "_sparse_tables", {})
        os.makedirs(dirname, exist_ok=True)
        for tname, t in tables.items():
            self._client.save(t["table_id"], os.path.join(dirname, tname + ".tbl"))

    def load_sparse_tables(self, dirname):
        tables = getattr(self._origin_program, "_sparse_tables", {})
        for tname, t in tables.items():
            self._client.load(t["table_id"], os.path.join(dirname, tname + ".tbl"))


fleet = _PSFleet()
