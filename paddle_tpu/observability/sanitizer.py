"""NaN/Inf sanitizer for the interpreted executor path.

The FLAGS_check_nan_inf analog (reference: paddle/fluid/framework/
details/nan_inf_utils_detail.cc, hooked into op dispatch at
operator.cc:1029): in interpreted execution every op output is checked
for non-finite floats; the first violation raises an EnforceError naming
the op type, the offending output variable, basic value statistics, and
the op's recorded *user* Python callstack — the line of model code that
built the bad op, not the executor internals.

The compiled path is one fused XLA computation, so per-op checking only
exists interpreted — the same graph-vs-dygraph trade the reference makes.
Enable via ``FLAGS_check_nan_inf`` / ``fluid.set_flags`` or scoped:

    with observability.sanitize_nan_inf():
        exe.run(main, feed=..., fetch_list=[loss])   # per-op checked
"""

import contextlib

import jax.numpy as jnp

from paddle_tpu.observability import metrics as _metrics
from paddle_tpu.utils.enforce import EnforceError

__all__ = ["check_output", "sanitize_nan_inf", "NanInfError"]


class NanInfError(EnforceError):
    """A sanitized op produced NaN/Inf. ``op_type`` and ``var_name`` are
    machine-readable; the message carries the user callstack."""

    def __init__(self, message, op_type=None, op_callstack=None,
                 var_name=None):
        super().__init__(message, op_type=op_type, op_callstack=op_callstack)
        self.var_name = var_name


def _stats(arr):
    """Small diagnostic summary; concrete arrays only (the interpreted
    path guarantees that)."""
    nan = int(jnp.isnan(arr).sum())
    inf = int(jnp.isinf(arr).sum())
    finite = arr[jnp.isfinite(arr)]
    lo = float(finite.min()) if finite.size else float("nan")
    hi = float(finite.max()) if finite.size else float("nan")
    return nan, inf, lo, hi


def check_output(op, name, val):
    """Check one op output; raises NanInfError on the first non-finite
    float value. Non-float outputs are skipped (ids, masks)."""
    arr = jnp.asarray(val)
    if not jnp.issubdtype(arr.dtype, jnp.floating):
        return
    reg = _metrics.registry()
    reg.counter("sanitizer_checks_total",
                "op outputs checked by the NaN/Inf sanitizer").inc()
    if bool(jnp.all(jnp.isfinite(arr))):
        return
    reg.counter("sanitizer_violations_total",
                "op outputs containing NaN/Inf",
                labels={"op": op.type}).inc()
    nan, inf, lo, hi = _stats(arr)
    finite_part = ("no finite values" if lo != lo
                   else f"finite range [{lo:g}, {hi:g}]")
    raise NanInfError(
        f"NaN/Inf in output {name} of op '{op.type}' "
        f"(shape {tuple(arr.shape)}, dtype {arr.dtype}: "
        f"{nan} NaN, {inf} Inf, {finite_part})",
        op_type=op.type,
        op_callstack=op.attrs.get("op_callstack"),
        var_name=name,
    )


@contextlib.contextmanager
def sanitize_nan_inf():
    """Scoped FLAGS_check_nan_inf: Executor.run inside the block takes the
    interpreted per-op path with every output checked."""
    from paddle_tpu.utils.flags import flags

    old = flags.check_nan_inf
    flags.check_nan_inf = True
    try:
        yield
    finally:
        flags.check_nan_inf = old
