"""Thread-aware span tracer with a Chrome-trace JSON exporter.

Host-side analog of the reference's RecordEvent + DeviceTracer +
tools/timeline.py pipeline (reference: paddle/fluid/platform/profiler.h:199,
device_tracer.h:41, tools/timeline.py): spans are recorded per thread on a
monotonic clock and exported as Chrome trace-event JSON, so any run opens
directly in chrome://tracing or Perfetto. Device-side traces remain
jax.profiler's job (profiler.start_profiler(trace_dir=...)); this tracer
covers the host dispatch path the whole-block XLA design leaves outside
the device timeline.

Zero-overhead-when-disabled contract: ``trace_scope.__enter__`` performs a
single module-global attribute check and returns; no clock is read, no
allocation happens. The hot execute path stays within the <=2% budget
(tools/trace_view.py --smoke measures it).

    with tracing("/tmp/run.trace.json"):
        with trace_scope("step"):
            with trace_scope("fwd"):
                ...

    @trace_scope("load_batch")
    def load_batch(...): ...
"""

import functools
import json
import os
import threading
import time

__all__ = [
    "Tracer",
    "trace_scope",
    "instant",
    "tracing",
    "tracing_enabled",
    "enable_tracing",
    "disable_tracing",
    "export_chrome_trace",
    "get_tracer",
]

# span tuple layout (kept flat — dicts are built once, at export):
# (name, cat, start_ns, dur_ns, tid, thread_name, depth, args)


class Tracer:
    """Span collector. One instance is the process-global default; tests
    may build private ones. ``enabled`` is read unlocked on the hot path
    (a stale read merely drops or keeps one span at the toggle edge)."""

    def __init__(self, max_events=1_000_000):
        self.enabled = False
        self._default_max_events = int(max_events)
        self.max_events = int(max_events)
        self._lock = threading.Lock()
        self._spans = []
        self._instants = []
        self._dropped = 0
        self._epoch_ns = time.perf_counter_ns()
        self._tls = threading.local()

    # -- lifecycle ---------------------------------------------------------
    def start(self, max_events=None):
        with self._lock:
            # a cap set for one capture does not leak into the next
            self.max_events = (int(max_events) if max_events is not None
                               else self._default_max_events)
            self._spans = []
            self._instants = []
            self._dropped = 0
            self._epoch_ns = time.perf_counter_ns()
            self.enabled = True

    def stop(self):
        self.enabled = False

    def clear(self):
        with self._lock:
            self._spans = []
            self._instants = []
            self._dropped = 0

    # -- per-thread nesting ------------------------------------------------
    def _stack(self):
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def current_depth(self):
        return len(self._stack())

    # -- recording ---------------------------------------------------------
    def record_span(self, name, cat, start_ns, end_ns, depth, args=None):
        ev = (
            name, cat, start_ns, end_ns - start_ns,
            threading.get_ident(), threading.current_thread().name,
            depth, args,
        )
        with self._lock:
            if len(self._spans) >= self.max_events:
                self._dropped += 1
                return
            self._spans.append(ev)

    def instant(self, name, cat="event", **args):
        """One-shot structured event (chrome-trace 'i' phase) — the span
        analog of a log line; supervisor restarts, breaker trips, etc."""
        if not self.enabled:
            return
        ev = (
            name, cat, time.perf_counter_ns(), 0,
            threading.get_ident(), threading.current_thread().name,
            len(self._stack()), args or None,
        )
        with self._lock:
            if len(self._instants) >= self.max_events:
                self._dropped += 1
                return
            self._instants.append(ev)

    # -- introspection (tests, summaries) ----------------------------------
    def spans(self):
        """Snapshot of finished spans as dicts (ns-resolution, epoch-
        relative start). For programmatic consumers; the chrome JSON is
        the interchange format."""
        with self._lock:
            spans = list(self._spans)
        return [
            {
                "name": name, "cat": cat,
                "start_ns": start_ns - self._epoch_ns, "dur_ns": dur_ns,
                "tid": tid, "thread": tname, "depth": depth,
                "args": args or {},
            }
            for name, cat, start_ns, dur_ns, tid, tname, depth, args in spans
        ]

    def instants(self):
        with self._lock:
            evs = list(self._instants)
        return [
            {
                "name": name, "cat": cat,
                "ts_ns": ts - self._epoch_ns,
                "tid": tid, "thread": tname, "args": args or {},
            }
            for name, cat, ts, _dur, tid, tname, _d, args in evs
        ]

    @property
    def dropped(self):
        return self._dropped

    # -- export ------------------------------------------------------------
    def chrome_trace(self):
        """The trace as a chrome://tracing-loadable dict: complete ('X')
        events with ts/dur in microseconds, instant ('i') events, and
        process/thread metadata ('M') so tracks carry real names."""
        pid = os.getpid()
        with self._lock:
            spans = list(self._spans)
            instants = list(self._instants)
            epoch = self._epoch_ns
            dropped = self._dropped
        events = [
            {
                "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                "args": {"name": "paddle_tpu"},
            }
        ]
        seen_tids = {}
        for name, cat, start_ns, dur_ns, tid, tname, depth, args in spans:
            seen_tids.setdefault(tid, tname)
            ev = {
                "name": name,
                "cat": cat,
                "ph": "X",
                "ts": (start_ns - epoch) / 1e3,
                "dur": dur_ns / 1e3,
                "pid": pid,
                "tid": tid,
            }
            if args or depth:
                ev["args"] = dict(args or {})
                ev["args"]["depth"] = depth
            events.append(ev)
        for name, cat, ts_ns, _dur, tid, tname, _depth, args in instants:
            seen_tids.setdefault(tid, tname)
            ev = {
                "name": name,
                "cat": cat,
                "ph": "i",
                "s": "t",
                "ts": (ts_ns - epoch) / 1e3,
                "pid": pid,
                "tid": tid,
            }
            if args:
                ev["args"] = dict(args)
            events.append(ev)
        for tid, tname in seen_tids.items():
            events.append({
                "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                "args": {"name": tname},
            })
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {"producer": "paddle_tpu.observability",
                          "dropped_events": dropped},
        }

    def export(self, path):
        """Write the Chrome-trace JSON; returns the number of trace events
        written (metadata included)."""
        doc = self.chrome_trace()
        d = os.path.dirname(os.path.abspath(path))
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(doc, f)
        return len(doc["traceEvents"])


_TRACER = Tracer()


def get_tracer():
    return _TRACER


def tracing_enabled():
    return _TRACER.enabled


def enable_tracing(max_events=None):
    _TRACER.start(max_events=max_events)
    return _TRACER


def disable_tracing():
    _TRACER.stop()
    return _TRACER


def export_chrome_trace(path):
    return _TRACER.export(path)


class tracing:
    """Context manager: enable the default tracer, optionally exporting a
    Chrome-trace JSON on exit.

        with tracing("/tmp/step.trace.json") as tr: ...
    """

    def __init__(self, path=None, max_events=None):
        self.path = path
        self.max_events = max_events

    def __enter__(self):
        return enable_tracing(max_events=self.max_events)

    def __exit__(self, *exc):
        disable_tracing()
        if self.path:
            export_chrome_trace(self.path)
        return False


class trace_scope:
    """RAII span: context manager or decorator; nests freely across
    threads (each thread is its own track). Disabled cost is one global
    attribute check."""

    __slots__ = ("name", "cat", "args", "_t0")

    def __init__(self, name, cat="host", **args):
        self.name = name
        self.cat = cat
        self.args = args or None
        self._t0 = None

    def __enter__(self):
        tr = _TRACER
        if not tr.enabled:
            self._t0 = None
            return self
        tr._stack().append(self.name)
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        if self._t0 is None:
            return False
        t1 = time.perf_counter_ns()
        tr = _TRACER
        stack = tr._stack()
        if stack:
            stack.pop()
        tr.record_span(self.name, self.cat, self._t0, t1, len(stack),
                       self.args)
        self._t0 = None
        return False

    def __call__(self, fn):
        name, cat, args = self.name, self.cat, self.args or {}

        @functools.wraps(fn)
        def wrapped(*a, **kw):
            with trace_scope(name, cat, **args):
                return fn(*a, **kw)

        return wrapped


def instant(name, cat="event", **args):
    """Record an instant event on the default tracer (no-op when
    disabled)."""
    _TRACER.instant(name, cat=cat, **args)
