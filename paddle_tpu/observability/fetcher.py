"""Background periodic fetchers for long-running loops.

``FetchHandlerMonitor`` is the reference's FetchHandlerMonitor analog
(reference: python/paddle/fluid/executor.py:406, trainer_factory.py):
a daemon thread that wakes every ``handler.period_secs`` and delivers the
most recent fetched values to the handler — decoupled from step cadence,
so a slow dataset epoch still reports on schedule. The training loop
publishes values via ``update()``; the monitor never touches the scope
mid-step (the whole-block XLA design has no consistent mid-step scope to
read — published fetches ARE the consistent snapshots).

``PeriodicMetricsDump`` scrapes the metrics registry on a period to a
file or callback — the flat-file analog of a Prometheus pull for rigs
with no scraper.
"""

import threading

from paddle_tpu.observability import metrics as _metrics
from paddle_tpu.observability.lockdep import named_lock

__all__ = ["FetchHandlerMonitor", "PeriodicMetricsDump"]


class _PeriodicThread:
    """Shared machinery: daemon thread firing ``_tick`` every period;
    stop() wakes it immediately and optionally fires once more."""

    def __init__(self, period_secs):
        self.period_secs = float(period_secs)
        self._wake = threading.Event()
        self._stopping = False
        self._thread = None

    def start(self):
        # idempotent while RUNNING, restartable once the thread is dead;
        # a stop() whose join timed out keeps the stuck thread pinned
        # here so start() cannot clear _stopping underneath it (which
        # would revive it NEXT TO a fresh one)
        if self._thread is not None:
            if self._thread.is_alive():
                return self
            self._thread = None
        self._stopping = False
        self._thread = threading.Thread(
            target=self._run, name=type(self).__name__, daemon=True
        )
        self._thread.start()
        return self

    def _run(self):
        while True:
            self._wake.wait(timeout=self.period_secs)
            if self._stopping:
                return
            self._wake.clear()
            self._tick()

    def stop(self, final_tick=True, timeout=5.0):
        if self._thread is None:
            return
        self._stopping = True
        self._wake.set()
        self._thread.join(timeout)
        if self._thread.is_alive():
            # stuck in a user handler: keep it pinned (start() then
            # refuses to revive it) and SKIP the final tick — running it
            # here would make two concurrent _tick writers
            return
        self._thread = None
        if final_tick:
            self._tick()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


class FetchHandlerMonitor(_PeriodicThread):
    """Delivers the latest published fetch dict to ``handler.handler``
    every ``handler.period_secs`` seconds, on a background thread.

        monitor = FetchHandlerMonitor(handler).start()
        for batch in loop:
            out = step(batch)
            monitor.update({"loss": out[0]})
        monitor.stop()          # fires one final delivery
    """

    def __init__(self, handler, period_secs=None):
        super().__init__(period_secs if period_secs is not None
                         else getattr(handler, "period_secs", 60))
        self.handler = handler
        self._lock = named_lock("observability.fetcher")
        self._latest = None
        self.deliveries = 0

    def update(self, fetch_vars):
        """Publish the newest fetched values (called from the training
        loop each step; cheap — one dict swap under a lock)."""
        with self._lock:
            self._latest = dict(fetch_vars)

    def _tick(self):
        with self._lock:
            latest = self._latest
            self._latest = None
        if latest is None:
            return
        try:
            self.handler.handler(latest)
            # lockdep: ok(one writer at a time: the loop thread, or stop()'s final tick strictly AFTER a successful join — stop() skips the final tick when the join times out)
            self.deliveries += 1
        except Exception:
            # a user handler must not kill the monitor (nor the loop)
            from paddle_tpu.observability.logger import get_logger

            get_logger("observability.fetcher").exception(
                "fetch handler raised; continuing"
            )


class PeriodicMetricsDump(_PeriodicThread):
    """Write the registry's Prometheus exposition to ``path`` (or call
    ``fn(text)``) every ``period_secs``. The final scrape fires on
    stop(), so short runs still leave one complete dump behind."""

    def __init__(self, path_or_fn, period_secs=15.0, registry=None):
        super().__init__(period_secs)
        self._target = path_or_fn
        self._registry = registry or _metrics.registry()
        self.dumps = 0

    def _tick(self):
        text = self._registry.to_text()
        if callable(self._target):
            self._target(text)
        else:
            tmp = f"{self._target}.tmp-{threading.get_ident()}"
            with open(tmp, "w") as f:
                f.write(text)
            import os

            os.replace(tmp, self._target)
        # lockdep: ok(one writer at a time: the loop thread, or stop()'s final tick strictly after a successful join)
        self.dumps += 1
