"""Runtime lockdep witness: named locks + a global lock-order graph.

The Linux kernel's lockdep idea, adapted to this runtime: locks belong to
named CLASSES (every ``RequestQueue`` shares the class ``serving.queue``),
and every acquisition taken while other classes are held records a
may-acquire-while-holding edge in one process-global graph. An edge that
closes a cycle — or that contradicts a ``declare_order`` hierarchy — is a
deadlock POTENTIAL and raises ``LockOrderError`` immediately, even though
this particular run did not deadlock. That is the whole value: ONE
single-threaded pass over the test suite proves order-consistency for
every acquisition order it exercised, no thread race required.

Adoption::

    from paddle_tpu.observability import lockdep
    self.lock = lockdep.named_lock("serving.queue", rlock=True)

and at module scope, the INTENDED hierarchy (violations then name the
declared rule, not just the observed inversion)::

    lockdep.declare_order("serving.queue", "decode.tenant")

The witness is env-gated: inert unless ``PADDLE_TPU_LOCKDEP=1`` (or
``enable()`` is called). Disabled cost is one module-flag check per
acquire/release on top of the raw ``threading`` primitive — named locks
stay safe for hot paths. The discovered hierarchy (``snapshot()``) is
committed as CONCURRENCY_EVIDENCE_r11.json by
``tools/stress_concurrency.py --evidence`` and drift-gated by
tests/test_concurrency.py.

Notes on semantics:

* Edges are recorded BEFORE blocking on the raw acquire, so a true ABBA
  under contention raises instead of deadlocking the test run.
* Re-entrant acquisition of the same class (RLock) adds no edges.
* ``threading.Condition(named_lock(...))`` works: the wrapper implements
  the ``_release_save``/``_acquire_restore``/``_is_owned`` protocol, and
  a ``wait()`` fully releases the witness record too.
* The stall hook (``set_stall_hook``) is the stress harness's seam: the
  deterministic-interleaving harness perturbs thread schedules by
  stalling at lock boundaries as a pure function of (lock name,
  per-class acquisition count, seed) — see tools/stress_concurrency.py.
"""

import os
import threading

__all__ = [
    "LockOrderError",
    "named_lock",
    "named_condition",
    "declare_order",
    "declared_orders",
    "enable",
    "enabled",
    "reset",
    "snapshot",
    "violations",
    "set_stall_hook",
    "get_stall_hook",
    "LOCKDEP_ENV",
]

LOCKDEP_ENV = "PADDLE_TPU_LOCKDEP"


class LockOrderError(RuntimeError):
    """A lock acquisition that closes a cycle in the global lock-order
    graph or violates a declared hierarchy (deadlock potential)."""


class _State:
    def __init__(self):
        self.mu = threading.Lock()   # raw on purpose: guards the graph
        self.locks = {}              # name -> {"kind", "file", "line"}
        self.edges = {}              # (a, b) -> first-witness attribution
        self.succ = {}               # a -> set of b with edge (a, b)
        self.declared = {}           # (earlier, later) -> rule string
        self.chains = []             # declared chains, declaration order
        self.violation_log = []      # every raised violation message
        self.counts = {}             # name -> acquisitions (stall-hook key)
        self.tls = threading.local()
        self.enabled = os.environ.get(LOCKDEP_ENV, "") not in ("", "0")
        self.stall_hook = None


_S = _State()


def _stack():
    st = getattr(_S.tls, "stack", None)
    if st is None:
        st = _S.tls.stack = []
    return st


def _caller():
    """file:line of the acquiring frame (first frame outside this module
    and threading.py) — edge attribution for violation messages."""
    import sys

    f = sys._getframe(2)
    here = __file__.rstrip("c")
    while f is not None:
        fn = f.f_code.co_filename
        if fn != here and not fn.endswith("threading.py"):
            return f"{os.path.relpath(fn) if fn.startswith(os.sep) else fn}" \
                   f":{f.f_lineno}"
        f = f.f_back
    return "<unknown>"


def _find_path(src, dst):
    """Edge path src -> ... -> dst in the order graph, or None (DFS)."""
    stack = [(src, (src,))]
    seen = {src}
    while stack:
        node, path = stack.pop()
        for nxt in _S.succ.get(node, ()):
            if nxt == dst:
                return path + (nxt,)
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + (nxt,)))
    return None


def _violate(msg):
    _S.violation_log.append(msg)
    raise LockOrderError(msg)


def _before_acquire(name, owner):
    """Declared-order + cycle check, and edge recording, for acquiring
    `name` (lock instance identity `owner`) with the current thread's
    held stack. Runs BEFORE the raw acquire so a true inversion raises
    rather than deadlocks."""
    st = _stack()
    for ent in st:
        if ent[0] == name:
            if ent[1] == owner:
                return  # re-entrant on the SAME instance: no new info
            # two DIFFERENT instances of one class nested: a same-class
            # ABBA needs no second class (Linux lockdep's "possible
            # recursive locking"); annotate with distinct class names
            # if the nesting is intended
            _violate(
                f"same-class nesting: acquiring a second '{name}' "
                f"instance while one is already held (held chain: "
                f"{' -> '.join(e[0] for e in st)}) at {_caller()} on "
                f"thread {threading.current_thread().name}"
            )
    held = [ent[0] for ent in st]
    hook = _S.stall_hook
    if hook is not None:
        with _S.mu:
            n = _S.counts.get(name, 0) + 1
            _S.counts[name] = n
        hook(name, n)
    if not held:
        return
    where = _caller()
    thread = threading.current_thread().name
    with _S.mu:
        for h in held:
            rule = _S.declared.get((name, h))
            if rule is not None:
                _violate(
                    f"declared lock order '{rule}' violated: acquired "
                    f"'{name}' while holding '{h}' (held chain: "
                    f"{' -> '.join(held)}) at {where} on thread {thread}"
                )
            if (h, name) in _S.edges:
                continue
            path = _find_path(name, h)
            if path is not None:
                prior = []
                for a, b in zip(path, path[1:]):
                    at = _S.edges.get((a, b), {})
                    prior.append(
                        f"{a} -> {b} (first seen at {at.get('at', '?')} "
                        f"on thread {at.get('thread', '?')}, held chain "
                        f"{' -> '.join(at.get('chain', [])) or '-'})"
                    )
                _violate(
                    f"lock-order cycle: acquiring '{name}' while holding "
                    f"'{h}' (held chain: {' -> '.join(held)}) at {where} "
                    f"on thread {thread} inverts the recorded order "
                    + "; ".join(prior)
                )
            _S.edges[(h, name)] = {
                "at": where, "thread": thread, "chain": list(held),
            }
            _S.succ.setdefault(h, set()).add(name)


def _after_acquire(name, owner, count=1):
    st = _stack()
    for ent in st:
        if ent[0] == name and ent[1] == owner:
            ent[2] += count
            return
    st.append([name, owner, count])


def _after_release(name, owner):
    """Runs UNCONDITIONALLY (not gated on the enabled flag): a witness
    toggled off between acquire and release must still pop the record,
    or the stale entry fabricates held-chains when re-armed. Near-free
    when nothing was recorded."""
    st = getattr(_S.tls, "stack", None)
    if not st:
        return
    for i in range(len(st) - 1, -1, -1):
        if st[i][0] == name and st[i][1] == owner:
            st[i][2] -= 1
            if st[i][2] <= 0:
                del st[i]
            return


def _pop_all(name, owner):
    """Remove the record entirely (Condition.wait's full release);
    returns the recursion count so restore can re-push it."""
    st = getattr(_S.tls, "stack", None)
    if not st:
        return 0
    for i in range(len(st) - 1, -1, -1):
        if st[i][0] == name and st[i][1] == owner:
            count = st[i][2]
            del st[i]
            return count
    return 0


class _NamedLock:
    """A lock belonging to a named lockdep class. Instances are cheap;
    the NAME is the node in the order graph (all RequestQueues share
    'serving.queue', exactly like Linux lockdep's lock classes)."""

    __slots__ = ("name", "kind", "_raw")

    def __init__(self, name, raw, kind):
        self.name = name
        self.kind = kind
        self._raw = raw

    # -- core protocol -----------------------------------------------------
    def acquire(self, blocking=True, timeout=-1):
        if _S.enabled:
            _before_acquire(self.name, id(self))
        got = self._raw.acquire(blocking, timeout)
        if got and _S.enabled:
            _after_acquire(self.name, id(self))
        return got

    def release(self):
        self._raw.release()
        _after_release(self.name, id(self))

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        fn = getattr(self._raw, "locked", None)
        if fn is not None:
            return fn()
        if self._raw.acquire(False):
            self._raw.release()
            return False
        return True

    # -- threading.Condition(lock) protocol --------------------------------
    def _is_owned(self):
        fn = getattr(self._raw, "_is_owned", None)
        if fn is not None:
            return fn()
        if self._raw.acquire(False):
            self._raw.release()
            return False
        return True

    def _release_save(self):
        count = _pop_all(self.name, id(self))
        fn = getattr(self._raw, "_release_save", None)
        if fn is not None:
            return (fn(), count)
        self._raw.release()
        return (None, count)

    def _acquire_restore(self, saved):
        # REACQUIRE FIRST, check after: Condition.wait's wake-up must
        # leave the lock held even when the order check raises, or the
        # enclosing `with cond:` __exit__ releases an un-acquired lock
        # and buries the witness's diagnostic under a RuntimeError. The
        # record is pushed in a finally for the same reason — the
        # unwinding release() must find it to pop.
        state, count = saved
        fn = getattr(self._raw, "_acquire_restore", None)
        if fn is not None:
            fn(state)
        else:
            self._raw.acquire()
        if _S.enabled:
            try:
                _before_acquire(self.name, id(self))
            finally:
                _after_acquire(self.name, id(self), max(count, 1))

    def __repr__(self):
        return f"<named_lock {self.name!r} ({self.kind}) {self._raw!r}>"


def named_lock(name, rlock=False):
    """A ``threading.Lock``/``RLock`` registered under lockdep class
    `name`. Every instance created under one name shares that graph
    node; use dotted subsystem names ('embedding.pending')."""
    name = str(name)
    kind = "rlock" if rlock else "lock"
    if name not in _S.locks:
        with _S.mu:
            if name not in _S.locks:
                at = _caller()
                _S.locks[name] = {"kind": kind, "registered_at": at}
    return _NamedLock(name, threading.RLock() if rlock else threading.Lock(),
                      kind)


def named_condition(name, lock=None):
    """A ``threading.Condition`` whose underlying lock is witnessed under
    `name` (or wraps an existing named lock)."""
    return threading.Condition(lock if lock is not None
                               else named_lock(name, rlock=True))


def declare_order(*names):
    """Declare an intended hierarchy: ``declare_order("a", "b", "c")``
    means a is acquired before b before c whenever they nest. Acquiring
    an EARLIER class while holding a LATER one raises immediately (when
    enabled), naming this declared rule — no observed cycle needed.
    Idempotent; call at module import next to the locks it governs."""
    names = [str(n) for n in names]
    if len(names) < 2:
        raise ValueError("declare_order needs at least two lock names")
    with _S.mu:
        if names not in _S.chains:
            _S.chains.append(names)
        rule = " -> ".join(names)
        for i in range(len(names)):
            for j in range(i + 1, len(names)):
                _S.declared.setdefault((names[i], names[j]), rule)
    return tuple(names)


def declared_orders():
    with _S.mu:
        return [list(c) for c in _S.chains]


def enable(on=True):
    """Flip the witness at runtime (tests / the stress harness). Call
    ``reset()`` too when starting a fresh evidence pass."""
    _S.enabled = bool(on)
    return _S.enabled


def enabled():
    return _S.enabled


def set_stall_hook(hook):
    """Install `hook(name, nth_acquisition)` called before every
    enabled acquire — the deterministic stall seam. None removes it."""
    _S.stall_hook = hook


def get_stall_hook():
    return _S.stall_hook


def reset():
    """Clear the observed graph, violation log, stall counters, and the
    CALLING thread's held stack. Declared hierarchies and the lock-name
    registry survive (they are import-time structure, not observations)."""
    with _S.mu:
        _S.edges.clear()
        _S.succ.clear()
        _S.violation_log.clear()
        _S.counts.clear()
    _S.tls.stack = []


def violations():
    with _S.mu:
        return list(_S.violation_log)


def snapshot():
    """The witnessed state: registered lock classes, the observed
    may-acquire-while-holding edges (with first-witness attribution),
    declared hierarchies, and any cycles still present in the graph
    (always [] unless violations were swallowed by the caller) — the
    CONCURRENCY_EVIDENCE payload."""
    with _S.mu:
        edges = sorted((a, b) for (a, b) in _S.edges)
        attributed = [
            [a, b, dict(_S.edges[(a, b)])] for a, b in edges
        ]
        locks = {n: dict(v) for n, v in _S.locks.items()}
        chains = [list(c) for c in _S.chains]
        # cycle scan over the committed graph (defensive: _before_acquire
        # refuses cycle-closing edges, so this should stay empty)
        cycles = []
        for a, b in edges:
            path = _find_path(b, a)
            if path is not None:
                cyc = list(path) + [b] if path[-1] != b else list(path)
                lo = cyc.index(min(cyc))
                cycles.append(cyc[lo:] + cyc[:lo])
        seen, uniq = set(), []
        for c in cycles:
            key = tuple(c)
            if key not in seen:
                seen.add(key)
                uniq.append(c)
    return {
        "enabled": _S.enabled,
        "locks": locks,
        "edges": [[a, b] for a, b in edges],
        "edge_witness": attributed,
        "declared": chains,
        "cycles": uniq,
        "violations": list(_S.violation_log),
    }
