"""Structured + rate-limited logging glued to the tracer and registry.

``log_event`` is the one-call structured event: a stdlib log record, an
instant trace event (visible in the chrome timeline next to the spans it
explains), and a counter in the metrics registry — so a gang restart or a
skipped record is simultaneously grep-able, plottable, and scrape-able.

``RateLimitedLogger`` caps repetitive per-record messages (reader skips,
retry storms) at N pass-throughs, then stays silent until ``summarize()``
emits one aggregate line — bounded log volume with zero information loss
about the count.
"""

import logging
import threading

from paddle_tpu.observability import metrics as _metrics
from paddle_tpu.observability import tracer as _tracer

__all__ = ["get_logger", "log_event", "RateLimitedLogger"]

_ROOT = "paddle_tpu"


def get_logger(name=None):
    """Namespaced stdlib logger (``paddle_tpu.<name>``)."""
    if name is None:
        return logging.getLogger(_ROOT)
    if name.startswith(_ROOT):
        return logging.getLogger(name)
    return logging.getLogger(f"{_ROOT}.{name}")


def log_event(kind, _level=logging.INFO, _logger=None, **fields):
    """Record one structured event everywhere at once: instant trace
    event, ``events_total{kind=...}`` counter, and (optionally) a log
    line. Returns the event dict."""
    _tracer.instant(kind, cat="event", **fields)
    _metrics.registry().counter(
        "events_total", "structured events by kind",
        labels={"kind": kind},
    ).inc()
    if _logger is not None:
        _logger.log(_level, "%s %s", kind, fields)
    return dict(kind=kind, **fields)


class RateLimitedLogger:
    """Pass through the first ``max_records`` messages, count the rest;
    ``summarize()`` reports totals. Each skipped-through or suppressed
    message also bumps a registry counter keyed by the logger name, so
    the rate of the underlying condition stays visible after the log
    goes quiet."""

    def __init__(self, name_or_logger, max_records=8, counter=None):
        self._log = (name_or_logger if isinstance(name_or_logger,
                                                  logging.Logger)
                     else get_logger(name_or_logger))
        self.max_records = int(max_records)
        self._lock = threading.Lock()
        self.emitted = 0
        self.suppressed = 0
        self._counter = counter or _metrics.registry().counter(
            "ratelimited_log_messages_total",
            "messages offered to a rate-limited logger",
            labels={"logger": self._log.name},
        )

    def _offer(self, level, msg, *args):
        self._counter.inc()
        with self._lock:
            if self.emitted < self.max_records:
                self.emitted += 1
                fire = True
                last = self.emitted == self.max_records
            else:
                self.suppressed += 1
                fire = last = False
        if fire:
            self._log.log(level, msg, *args)
            if last:
                self._log.log(
                    level,
                    "(rate limit reached after %d messages; further "
                    "occurrences will be counted and summarized)",
                    self.max_records,
                )

    def debug(self, msg, *args):
        self._offer(logging.DEBUG, msg, *args)

    def info(self, msg, *args):
        self._offer(logging.INFO, msg, *args)

    def warning(self, msg, *args):
        self._offer(logging.WARNING, msg, *args)

    def error(self, msg, *args):
        self._offer(logging.ERROR, msg, *args)

    @property
    def total(self):
        with self._lock:
            return self.emitted + self.suppressed

    def summarize(self, level=logging.WARNING, what="messages"):
        """Emit the aggregate line (only if anything was suppressed);
        resets nothing — callers may keep offering."""
        with self._lock:
            emitted, suppressed = self.emitted, self.suppressed
        if suppressed:
            self._log.log(
                level,
                "%d %s total (%d logged, %d suppressed by rate limit)",
                emitted + suppressed, what, emitted, suppressed,
            )
        return emitted + suppressed
