"""Metrics registry: typed counters, gauges, and bucketed histograms.

One always-on registry for the whole process (a serving replica must
answer a scrape whether or not anyone is profiling). Histograms are
bucketed — p50/p95/p99 come from bucket counts by linear interpolation,
never from stored sample lists, so memory is O(buckets) regardless of
traffic. ``scrape_text()`` emits Prometheus text exposition format.

Series are keyed (family name, labels): two ServingEngines in one process
are two label sets of the same family, so per-engine snapshots stay exact
while the scrape shows the fleet.
"""

import bisect
import re
import threading

from paddle_tpu.observability.lockdep import named_lock

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "registry",
    "scrape_text",
    "DEFAULT_BUCKETS",
]

# latency ladder: 1-2.5-5 per decade from 10us to 50s — wide enough for a
# feed-dict hot path and a cold XLA compile in the same histogram
DEFAULT_BUCKETS = tuple(
    b * (10.0 ** e) for e in range(-5, 2) for b in (1.0, 2.5, 5.0)
)

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def sanitize_name(name):
    """Prometheus-legal metric name from a dotted/arbitrary one."""
    name = _NAME_RE.sub("_", str(name))
    if name and name[0].isdigit():
        name = "_" + name
    return name


def _label_key(labels):
    return tuple(sorted((str(k), str(v)) for k, v in (labels or {}).items()))


def _label_str(labels):
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in labels)
    return "{" + inner + "}"


class _Metric:
    __slots__ = ("name", "help", "labels", "_lock")

    def __init__(self, name, help="", labels=()):
        self.name = name
        self.help = help
        self.labels = labels  # sorted (k, v) tuple
        # deliberately a RAW lock, not a lockdep named one: series locks
        # sit on per-op hot paths (every counter inc), and they are a
        # statically-proven LEAF — no acquisition ever nests inside one
        # (tools/lint_concurrency.py would report an edge if that
        # changed), so they cannot participate in a cycle
        self._lock = threading.Lock()


class Counter(_Metric):
    """Monotonic counter (float-valued: occupancy sums etc. count too)."""

    __slots__ = ("_value",)
    kind = "counter"

    def __init__(self, name, help="", labels=()):
        super().__init__(name, help, labels)
        self._value = 0

    def inc(self, n=1):
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease (n={n})")
        with self._lock:
            self._value += n

    @property
    def value(self):
        with self._lock:
            return self._value

    def reset(self):
        with self._lock:
            self._value = 0

    def _expose(self):
        return [(self.name, self.labels, self.value)]


class Gauge(_Metric):
    """Set/inc/dec instantaneous value (queue depth, open breakers)."""

    __slots__ = ("_value",)
    kind = "gauge"

    def __init__(self, name, help="", labels=()):
        super().__init__(name, help, labels)
        self._value = 0

    def set(self, v):
        with self._lock:
            self._value = v

    def inc(self, n=1):
        with self._lock:
            self._value += n

    def dec(self, n=1):
        with self._lock:
            self._value -= n

    @property
    def value(self):
        with self._lock:
            return self._value

    def reset(self):
        with self._lock:
            self._value = 0

    def _expose(self):
        return [(self.name, self.labels, self.value)]


class Histogram(_Metric):
    """Bucketed distribution. ``bounds`` are inclusive upper bounds of the
    finite buckets; one implicit +Inf bucket catches the tail. Quantiles
    interpolate linearly inside the bucket holding the target rank (the
    Prometheus histogram_quantile rule), so their error is bounded by the
    bucket width — the price of O(buckets) memory."""

    __slots__ = ("bounds", "_counts", "_sum", "_count")
    kind = "histogram"

    def __init__(self, name, help="", labels=(), buckets=None):
        super().__init__(name, help, labels)
        bounds = sorted(float(b) for b in (buckets or DEFAULT_BUCKETS))
        if not bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self.bounds = tuple(bounds)
        self._counts = [0] * (len(bounds) + 1)
        self._sum = 0.0
        self._count = 0

    def observe(self, v):
        v = float(v)
        i = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    @property
    def count(self):
        with self._lock:
            return self._count

    @property
    def sum(self):
        with self._lock:
            return self._sum

    @property
    def avg(self):
        with self._lock:
            return self._sum / self._count if self._count else 0.0

    def bucket_counts(self):
        """Per-bucket (non-cumulative) counts, +Inf bucket last."""
        with self._lock:
            return list(self._counts)

    def quantile(self, q):
        """q in [0, 1]. Linear interpolation inside the target bucket;
        the +Inf bucket reports the largest finite bound (no upper edge
        to interpolate toward)."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile {q} outside [0, 1]")
        with self._lock:
            total = self._count
            counts = list(self._counts)
        if total == 0:
            return 0.0
        rank = q * total
        cum = 0
        for i, c in enumerate(counts):
            if cum + c >= rank or i == len(counts) - 1:
                if i >= len(self.bounds):  # +Inf bucket
                    return self.bounds[-1]
                lo = self.bounds[i - 1] if i > 0 else 0.0
                hi = self.bounds[i]
                if c == 0:
                    return hi
                frac = (rank - cum) / c
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
            cum += c
        return self.bounds[-1]

    def percentile(self, p):
        return self.quantile(p / 100.0)

    def reset(self):
        with self._lock:
            self._counts = [0] * (len(self.bounds) + 1)
            self._sum = 0.0
            self._count = 0

    def snapshot(self, prefix):
        """Legacy-shaped latency summary (serving.stats() keys)."""
        with self._lock:
            count, total = self._count, self._sum
        return {
            f"{prefix}_count": count,
            f"{prefix}_avg_s": total / count if count else 0.0,
            f"{prefix}_p50_s": self.quantile(0.50),
            f"{prefix}_p95_s": self.quantile(0.95),
            f"{prefix}_p99_s": self.quantile(0.99),
        }

    def _expose(self):
        with self._lock:
            counts = list(self._counts)
            total, s = self._count, self._sum
        rows = []
        cum = 0
        for bound, c in zip(self.bounds, counts):
            cum += c
            le = (("le", repr(bound) if bound != int(bound)
                   else str(bound)),)
            rows.append((self.name + "_bucket", self.labels + le, cum))
        rows.append(
            (self.name + "_bucket", self.labels + (("le", "+Inf"),), total)
        )
        rows.append((self.name + "_sum", self.labels, s))
        rows.append((self.name + "_count", self.labels, total))
        return rows


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class MetricsRegistry:
    """Get-or-create store of metric series keyed (family, labels).
    Re-requesting an existing series returns it; requesting an existing
    family with a different type raises (one family, one type — the
    Prometheus exposition invariant)."""

    def __init__(self):
        self._lock = named_lock("metrics.registry")
        self._series = {}   # (name, label_key) -> metric
        self._families = {}  # name -> (kind, help)

    def _get_or_create(self, kind, name, help, labels, **kw):
        name = sanitize_name(name)
        lk = _label_key(labels)
        with self._lock:
            fam = self._families.get(name)
            if fam is not None and fam[0] != kind:
                raise ValueError(
                    f"metric family '{name}' already registered as "
                    f"{fam[0]}, requested {kind}"
                )
            m = self._series.get((name, lk))
            if m is None:
                m = _KINDS[kind](name, help or (fam[1] if fam else ""),
                                 labels=lk, **kw)
                self._series[(name, lk)] = m
                if fam is None:
                    self._families[name] = (kind, help)
            return m

    def counter(self, name, help="", labels=None):
        return self._get_or_create("counter", name, help, labels)

    def gauge(self, name, help="", labels=None):
        return self._get_or_create("gauge", name, help, labels)

    def histogram(self, name, help="", labels=None, buckets=None):
        return self._get_or_create("histogram", name, help, labels,
                                   buckets=buckets)

    # -- read side ---------------------------------------------------------
    def collect(self):
        with self._lock:
            return list(self._series.values())

    def get(self, name, labels=None):
        with self._lock:
            return self._series.get((sanitize_name(name),
                                     _label_key(labels)))

    def snapshot(self):
        """{family: {label_str: value-or-histogram-summary}} — the
        one-registry view the acceptance smoke reads."""
        out = {}
        for m in self.collect():
            fam = out.setdefault(m.name, {})
            key = _label_str(m.labels) or ""
            if m.kind == "histogram":
                fam[key] = {
                    "count": m.count, "sum": m.sum,
                    "p50": m.quantile(0.5), "p95": m.quantile(0.95),
                    "p99": m.quantile(0.99),
                }
            else:
                fam[key] = m.value
        return out

    def to_text(self):
        """Prometheus text exposition (version 0.0.4)."""
        by_family = {}
        for m in self.collect():
            by_family.setdefault(m.name, []).append(m)
        lines = []
        for name in sorted(by_family):
            series = by_family[name]
            kind, help = self._families.get(name, (series[0].kind, ""))
            if help:
                lines.append(f"# HELP {name} {help}")
            lines.append(f"# TYPE {name} {kind}")
            for m in series:
                for row_name, labels, value in m._expose():
                    lines.append(f"{row_name}{_label_str(labels)} {value}")
        return "\n".join(lines) + "\n"

    # -- maintenance (tests, engine teardown) ------------------------------
    def reset(self):
        for m in self.collect():
            m.reset()

    def remove(self, name, labels=None):
        with self._lock:
            m = self._series.pop((sanitize_name(name), _label_key(labels)),
                                 None)
            if not any(k[0] == sanitize_name(name) for k in self._series):
                self._families.pop(sanitize_name(name), None)
            return m

    def clear(self):
        with self._lock:
            self._series.clear()
            self._families.clear()


_REGISTRY = MetricsRegistry()


def registry():
    """The process-global registry — the single scrape."""
    return _REGISTRY


def scrape_text():
    return _REGISTRY.to_text()
