"""Unified observability substrate: spans, metrics, sanitizer, logging.

One telemetry layer for the whole framework (SURVEY §5.1, §5.2, §5.5 —
the reference's RecordEvent/DeviceTracer/timeline.py/FLAGS_check_nan_inf/
FetchHandler stack, rebuilt TPU-native):

* ``tracer``    — thread-aware span tracer (``trace_scope``) with a
  Chrome-trace JSON exporter; open any run in chrome://tracing/Perfetto.
* ``metrics``   — typed counters/gauges/bucketed histograms in one
  registry with Prometheus-style text exposition (``scrape_text``).
* ``sanitizer`` — the FLAGS_check_nan_inf interpreter mode: every op
  output checked, violations named with the op and its user callstack.
* ``logger``    — rate-limited structured logging + ``log_event`` (one
  call fans out to the log, an instant trace event, and a counter).
* ``fetcher``   — background periodic fetchers for long training loops
  (FetchHandlerMonitor) and registry scrapes (PeriodicMetricsDump).
* ``lockdep``   — runtime lock-order witness: named lock classes, one
  global may-acquire-while-holding graph, cycle + declared-hierarchy
  violations raised at acquire time (env-gated, PADDLE_TPU_LOCKDEP=1).

The legacy surfaces (``paddle_tpu.profiler``, ``serving.metrics``,
``resilience.supervisor`` events) are thin shims over this layer, so
serving stats, gang-restart events, and compile-cache hit rates all land
in ONE timeline and ONE scrape.
"""

from paddle_tpu.observability.tracer import (
    Tracer,
    disable_tracing,
    enable_tracing,
    export_chrome_trace,
    get_tracer,
    instant,
    trace_scope,
    tracing,
    tracing_enabled,
)
from paddle_tpu.observability.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    registry,
    scrape_text,
)
from paddle_tpu.observability.logger import (
    RateLimitedLogger,
    get_logger,
    log_event,
)
from paddle_tpu.observability.sanitizer import (
    NanInfError,
    check_output,
    sanitize_nan_inf,
)
from paddle_tpu.observability.fetcher import (
    FetchHandlerMonitor,
    PeriodicMetricsDump,
)
from paddle_tpu.observability import lockdep
from paddle_tpu.observability.lockdep import (
    LockOrderError,
    declare_order,
    named_condition,
    named_lock,
)

__all__ = [
    "Tracer",
    "trace_scope",
    "instant",
    "tracing",
    "tracing_enabled",
    "enable_tracing",
    "disable_tracing",
    "export_chrome_trace",
    "get_tracer",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "registry",
    "scrape_text",
    "RateLimitedLogger",
    "get_logger",
    "log_event",
    "NanInfError",
    "check_output",
    "sanitize_nan_inf",
    "FetchHandlerMonitor",
    "PeriodicMetricsDump",
    "lockdep",
    "LockOrderError",
    "declare_order",
    "named_condition",
    "named_lock",
]
