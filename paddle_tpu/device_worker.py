"""DeviceWorker: the per-batch execution strategy for dataset-mode training.

reference: python/paddle/fluid/device_worker.py:95 (DownpourSGD emitting a
protobuf for the C++ DownpourWorker, framework/device_worker.h:203) — the
worker pulls the batch's sparse rows, runs fwd/bwd, pushes sparse/dense
grads. TPU-native: the step is one XLA computation, so a "device worker"
is the host-side driver around it:

* Hogwild       — plain compiled step (dense training).
* DownpourSGD   — the PS loop: host-pull tables (layers.sparse_embedding)
                  route through the fleet PSWorker's pull -> step -> push;
                  in-graph remote tables (layers.distributed_embedding)
                  pull/push inside the step via io_callbacks and prefetch
                  one batch ahead.
* Section       — microbatched pipeline step (PipelineOptimizer programs).

TrainerFactory mirrors the reference's trainer_factory.py: it reads
`program._fleet_opt` (set by the distributed optimizer) and assembles the
TrainerDesc + DeviceWorker that Executor.train_from_dataset consumes.
"""

from paddle_tpu.trainer_desc import DistMultiTrainer, MultiTrainer
from paddle_tpu.utils.enforce import enforce

__all__ = [
    "DeviceWorker",
    "Hogwild",
    "DownpourSGD",
    "Section",
    "DeviceWorkerFactory",
    "TrainerFactory",
]


class DeviceWorker:
    def __init__(self):
        self._infer = False
        self._program = None

    def _set_infer(self, infer):
        self._infer = infer

    def _set_program(self, program):
        self._program = program

    def prepare(self, exe, program, scope):
        """Called once before the batch loop."""

    def run_batch(self, exe, program, feed, fetch_list, scope):
        raise NotImplementedError

    def finish(self):
        """Called once after the batch loop (flush pending pushes)."""


class Hogwild(DeviceWorker):
    """reference: device_worker.py:72 — plain per-batch step."""

    def run_batch(self, exe, program, feed, fetch_list, scope):
        return exe.run(program, feed=feed, fetch_list=fetch_list, scope=scope)


class DownpourSGD(DeviceWorker):
    """reference: device_worker.py:95. Host-pull sparse tables go through
    the fleet's PSWorker; in-graph remote tables ride the step's own
    io_callbacks (ops/misc_extra.py distributed_lookup_table)."""

    def __init__(self):
        super().__init__()
        self._ps_worker = None

    def prepare(self, exe, program, scope):
        tables = getattr(program, "_sparse_tables", None)
        if not tables:
            return  # remote-only (or dense) program: the step is self-contained
        from paddle_tpu.fleet import parameter_server as psfleet

        worker = psfleet.fleet._worker_obj
        if worker is None and psfleet.fleet._client is not None:
            worker = psfleet.fleet.worker(exe, program)
        enforce(
            worker is not None,
            "DownpourSGD needs an initialized PS worker for host-pull "
            "sparse tables: call fleet.init_worker() (and optionally "
            "fleet.worker(exe)) before train_from_dataset",
        )
        self._ps_worker = worker

    def run_batch(self, exe, program, feed, fetch_list, scope):
        if self._ps_worker is not None:
            return self._ps_worker.run(
                program, feed, fetch_list=fetch_list, scope=scope,
                infer=self._infer,
            )
        return exe.run(program, feed=feed, fetch_list=fetch_list, scope=scope)

    def finish(self):
        if self._ps_worker is not None and not self._infer:
            self._ps_worker.flush()


class Section(DeviceWorker):
    """reference: device_worker.py:301 (pipeline section worker). The
    microbatch schedule lives in the compiled step (core/executor.py
    _make_microbatched_step); per-batch driving is the plain step."""

    def run_batch(self, exe, program, feed, fetch_list, scope):
        return exe.run(program, feed=feed, fetch_list=fetch_list, scope=scope)


class DeviceWorkerFactory:
    def _create_device_worker(self, worker_type):
        classes = {c.__name__: c for c in (Hogwild, DownpourSGD, Section)}
        enforce(
            worker_type in classes,
            f"unknown device worker {worker_type!r} "
            f"(have {sorted(classes)})",
        )
        return classes[worker_type]()


class TrainerFactory:
    """reference: python/paddle/fluid/trainer_factory.py — assemble the
    trainer desc from the program's fleet opt info."""

    def _create_trainer(self, opt_info=None):
        opt_info = opt_info or {}
        trainer_name = opt_info.get("trainer", "MultiTrainer")
        worker_name = opt_info.get("device_worker", "Hogwild")
        trainers = {
            "MultiTrainer": MultiTrainer,
            "DistMultiTrainer": DistMultiTrainer,
        }
        enforce(
            trainer_name in trainers,
            f"unknown trainer {trainer_name!r} (have {sorted(trainers)})",
        )
        trainer = trainers[trainer_name]()
        trainer._set_device_worker(
            DeviceWorkerFactory()._create_device_worker(worker_name)
        )
        if "fleet_desc" in opt_info:
            trainer._set_fleet_desc(opt_info["fleet_desc"])
        return trainer
