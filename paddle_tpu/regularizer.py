"""Weight-decay regularizers appended as grad-side ops
(reference: python/paddle/fluid/regularizer.py)."""

from paddle_tpu.layer_helper import LayerHelper


class WeightDecayRegularizer:
    def _append_regularization_op(self, param, grad):
        raise NotImplementedError


class L2DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = regularization_coeff

    def _append_regularization_op(self, param, grad):
        helper = LayerHelper("l2_decay")
        decay = helper.create_variable_for_type_inference(grad.dtype)
        helper.append_op(
            "scale",
            {"X": [param.name]},
            {"Out": [decay.name]},
            {"scale": self._coeff, "op_role": 1},
        )
        out = helper.create_variable_for_type_inference(grad.dtype)
        helper.append_op(
            "sum",
            {"X": [grad.name, decay.name]},
            {"Out": [out.name]},
            {"op_role": 1},
        )
        return out


class L1DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = regularization_coeff

    def _append_regularization_op(self, param, grad):
        helper = LayerHelper("l1_decay")
        sign = helper.create_variable_for_type_inference(grad.dtype)
        helper.append_op("sign", {"X": [param.name]}, {"Out": [sign.name]}, {"op_role": 1})
        decay = helper.create_variable_for_type_inference(grad.dtype)
        helper.append_op(
            "scale",
            {"X": [sign.name]},
            {"Out": [decay.name]},
            {"scale": self._coeff, "op_role": 1},
        )
        out = helper.create_variable_for_type_inference(grad.dtype)
        helper.append_op(
            "sum", {"X": [grad.name, decay.name]}, {"Out": [out.name]}, {"op_role": 1}
        )
        return out


L1Decay = L1DecayRegularizer
L2Decay = L2DecayRegularizer
