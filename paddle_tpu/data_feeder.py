"""DataFeeder: convert user samples into feed dicts of batched numpy arrays.

Reference: python/paddle/fluid/data_feeder.py — DataFeeder.feed converts a
list of samples (one tuple per sample, one entry per feed var) into
LoDTensors. Here the target is dense numpy arrays (the executor device-puts
them); ragged sequence data should be pre-padded or fed with segment ids
(SURVEY §5.7: LoD is subsumed by padding + segment-ids on TPU).

Mismatched feeds fail HERE, by name: a batch whose dtype cannot be
safely cast to the feed var's, or whose per-sample shape disagrees with
the declaration, raises a ValueError naming the variable and the
expected vs actual dtype/shape — instead of surfacing as an opaque XLA
signature error three layers down (the reference's check_feed_shape_type
plays the same role, data_feeder.py:109).
"""

import numpy as np

from paddle_tpu.core.dtypes import to_numpy_dtype
from paddle_tpu.utils.enforce import enforce

__all__ = ["DataFeeder", "check_feed_array"]


def _shape_str(shape):
    return "[" + ", ".join(str(d) for d in shape) + "]"


def check_feed_array(name, value, dtype, shape):
    """Validate one BATCHED array against its feed var declaration.

    Returns the (possibly cast/reshaped) array. Within-kind casts
    (float64 -> float32) and value-preserving promotions (int32 ->
    int64, int32 -> float64) happen silently; anything cross-kind lossy
    (int64 -> float32, float -> int, object/str -> number) raises naming
    the variable. (The per-sample DataFeeder.feed path is additionally
    lenient on int -> float of any width — python scalars and lists
    carry incidental int64.) Declared trailing dims that are fully known
    must match by element count — compatible flat feeds are reshaped,
    true mismatches raise."""
    want = np.dtype(to_numpy_dtype(dtype)) if dtype is not None else None
    arr = np.asarray(value)
    if want is not None and arr.dtype != want:
        # within-kind casts (float64->float32) and value-preserving
        # promotions (int32->int64, int32->float64) stay silent; a
        # cross-kind lossy cast (int64->float32, float->int, str->any)
        # is a feed bug and fails by name
        castable = arr.dtype.kind not in "OUS" and (
            (arr.dtype.kind == want.kind
             and np.can_cast(arr.dtype, want, casting="same_kind"))
            or np.can_cast(arr.dtype, want, casting="safe")
        )
        if not castable:
            raise ValueError(
                f"feed variable '{name}': dtype mismatch — expected "
                f"{want.name}, got {arr.dtype.name} "
                f"(batch shape {tuple(arr.shape)})"
            )
        arr = arr.astype(want)
    trailing = list(shape[1:]) if shape else []
    if trailing and all(isinstance(d, int) and d > 0 for d in trailing):
        declared_n = int(np.prod(trailing))
        got = list(arr.shape[1:])
        got_n = int(np.prod(got)) if got else 1
        if got_n != declared_n:
            raise ValueError(
                f"feed variable '{name}': shape mismatch — expected "
                f"{_shape_str(['batch'] + trailing)} "
                f"({declared_n} elements per sample), got "
                f"{_shape_str(list(arr.shape))} ({got_n} elements per "
                "sample)"
            )
        if got != trailing:
            arr = arr.reshape([arr.shape[0]] + trailing)
    return arr


class DataFeeder:
    def __init__(self, feed_list, place=None, program=None):
        from paddle_tpu.core.ir import Variable, default_main_program

        program = program or default_main_program()
        self.feed_names = []
        self.feed_dtypes = []
        self.feed_shapes = []
        for v in feed_list:
            if isinstance(v, str):
                v = program.global_block().var(v)
            enforce(isinstance(v, Variable), f"feed_list entry {v!r} invalid")
            self.feed_names.append(v.name)
            self.feed_dtypes.append(v.dtype)
            self.feed_shapes.append(v.shape)
        self.place = place

    def feed(self, iterable):
        """iterable: list of samples; each sample is a tuple/list with one
        entry per feed var. Returns {name: batched ndarray}."""
        columns = [[] for _ in self.feed_names]
        for sample in iterable:
            enforce(
                len(sample) == len(self.feed_names),
                f"sample has {len(sample)} fields, expected "
                f"{len(self.feed_names)} ({self.feed_names})",
            )
            for c, v in zip(columns, sample):
                c.append(v)
        out = {}
        for name, dtype, shape, col in zip(
            self.feed_names, self.feed_dtypes, self.feed_shapes, columns
        ):
            want = np.dtype(to_numpy_dtype(dtype))
            converted = []
            for i, v in enumerate(col):
                try:
                    actual = np.asarray(v)
                except (ValueError, TypeError) as e:  # ragged nested list
                    raise ValueError(
                        f"feed variable '{name}': sample {i} is not a "
                        f"rectangular array ({e})"
                    ) from e
                # the per-sample path stays lenient on int->float (python
                # scalars/lists carry incidental int64), but float->int
                # TRUNCATES values — that is a feed bug, not a cast
                if actual.dtype.kind in "fc" and want.kind in "iub":
                    raise ValueError(
                        f"feed variable '{name}': dtype mismatch — "
                        f"expected {want.name}, sample {i} is "
                        f"{actual.dtype.name} (float->int feeds truncate; "
                        "cast explicitly if intended)"
                    )
                try:
                    converted.append(actual.astype(want, copy=False))
                except (ValueError, TypeError) as e:
                    raise ValueError(
                        f"feed variable '{name}': sample {i} cannot be "
                        f"converted to {want.name} (got dtype "
                        f"{actual.dtype.name}, shape "
                        f"{tuple(actual.shape)}): {e}"
                    ) from e
            try:
                arr = np.stack(converted)
            except ValueError as e:
                shapes = sorted({tuple(a.shape) for a in converted})
                raise ValueError(
                    f"feed variable '{name}': samples have inconsistent "
                    f"shapes {shapes[:4]} — pad ragged sequences before "
                    f"feeding ({e})"
                ) from e
            # validate + reshape flat samples to the declared trailing
            # shape; a true element-count mismatch raises by name
            out[name] = check_feed_array(name, arr, dtype, shape)
        return out
