"""DataFeeder: convert user samples into feed dicts of batched numpy arrays.

Reference: python/paddle/fluid/data_feeder.py — DataFeeder.feed converts a
list of samples (one tuple per sample, one entry per feed var) into
LoDTensors. Here the target is dense numpy arrays (the executor device-puts
them); ragged sequence data should be pre-padded or fed with segment ids
(SURVEY §5.7: LoD is subsumed by padding + segment-ids on TPU).
"""

import numpy as np

from paddle_tpu.core.dtypes import to_numpy_dtype
from paddle_tpu.utils.enforce import enforce

__all__ = ["DataFeeder", "convert_sample"]


def convert_sample(value, dtype):
    arr = np.asarray(value, dtype=to_numpy_dtype(dtype))
    return arr


class DataFeeder:
    def __init__(self, feed_list, place=None, program=None):
        from paddle_tpu.core.ir import Variable, default_main_program

        program = program or default_main_program()
        self.feed_names = []
        self.feed_dtypes = []
        self.feed_shapes = []
        for v in feed_list:
            if isinstance(v, str):
                v = program.global_block().var(v)
            enforce(isinstance(v, Variable), f"feed_list entry {v!r} invalid")
            self.feed_names.append(v.name)
            self.feed_dtypes.append(v.dtype)
            self.feed_shapes.append(v.shape)
        self.place = place

    def feed(self, iterable):
        """iterable: list of samples; each sample is a tuple/list with one
        entry per feed var. Returns {name: batched ndarray}."""
        columns = [[] for _ in self.feed_names]
        for sample in iterable:
            enforce(
                len(sample) == len(self.feed_names),
                f"sample has {len(sample)} fields, expected "
                f"{len(self.feed_names)} ({self.feed_names})",
            )
            for c, v in zip(columns, sample):
                c.append(v)
        out = {}
        for name, dtype, shape, col in zip(
            self.feed_names, self.feed_dtypes, self.feed_shapes, columns
        ):
            arr = np.stack([convert_sample(v, dtype) for v in col])
            # reshape flat samples to the declared trailing shape if needed
            if shape is not None:
                trailing = [d for d in shape[1:]]
                if all(isinstance(d, int) and d > 0 for d in trailing):
                    want = int(np.prod(trailing)) if trailing else 1
                    got = int(np.prod(arr.shape[1:])) if arr.ndim > 1 else 1
                    if got == want and list(arr.shape[1:]) != trailing:
                        arr = arr.reshape([arr.shape[0]] + trailing)
            out[name] = arr
        return out
