"""Optimizers: append_backward + update ops, as program transforms.

Same architecture as the reference (reference: python/paddle/fluid/
optimizer.py:54 Optimizer — backward :608, apply_gradients :672, minimize
:780): minimize() rewrites the program with grad ops then appends one update
op per parameter, with accumulators as persistable vars initialized in the
startup program. The update ops lower to fused fp32-master-arithmetic jnp
rules (ops/optimizers.py) and compile into the same XLA step as the model.
"""

from paddle_tpu.core.backward import append_backward
from paddle_tpu.core.ir import default_main_program, default_startup_program, Parameter
from paddle_tpu.layer_helper import LayerHelper
from paddle_tpu.layers import tensor as tensor_layers
from paddle_tpu.utils import unique_name
from paddle_tpu.utils.enforce import enforce

#: every accumulator slot name the optimizers use (accumulator vars are
#: named f"{param}_{slot}_{idx}", see _add_accumulator). Seeded with the
#: built-in optimizers' slots and grown at _add_accumulator time, so a new
#: optimizer's slots join automatically once it runs. parallel/sharding.py
#: restricts optimizer-slot partition-spec inheritance to THESE suffixes —
#: an unrelated user var that merely prefix-extends a param name must not
#: silently inherit its sharding.
ACCUMULATOR_SLOT_NAMES = {
    "velocity", "moment", "moment1", "moment2",
    "beta1_pow_acc", "beta2_pow_acc", "inf_norm",
    "_avg_squared_grad", "_avg_squared_update",
    "momentum", "mean_square", "mean_grad",
    "squared", "linear", "dgc_u", "dgc_v",
}

_OP_ROLE_OPTIMIZE = 2


class Optimizer:
    def __init__(self, learning_rate, regularization=None, grad_clip=None, name=None):
        self._learning_rate = learning_rate
        self.regularization = regularization
        self._grad_clip = grad_clip
        self._name = name
        self._accumulators = {}
        self._lr_var = None
        self.helper = None

    # -- learning rate ------------------------------------------------
    def _create_global_learning_rate(self):
        if self._lr_var is not None:
            return
        from paddle_tpu.core.ir import Variable

        if isinstance(self._learning_rate, Variable):
            self._lr_var = self._learning_rate
        else:
            self._lr_var = tensor_layers.create_global_var(
                shape=[1],
                value=float(self._learning_rate),
                dtype="float32",
                persistable=True,
                name=unique_name.generate("learning_rate"),
            )

    def _global_learning_rate(self):
        return self._lr_var

    @property
    def learning_rate_var(self):
        return self._lr_var

    def current_step_lr(self, scope=None):
        import numpy as np

        from paddle_tpu.core.scope import global_scope

        scope = scope or global_scope()
        v = scope.find_var(self._lr_var.name)
        return None if v is None else float(np.asarray(v).reshape(-1)[0])

    def _param_lr(self, param):
        plr = param.optimize_attr.get("learning_rate", 1.0)
        if plr == 1.0:
            return self._lr_var
        from paddle_tpu import layers

        return layers.scale(self._lr_var, scale=float(plr))

    # -- accumulators -------------------------------------------------
    def _add_accumulator(self, name, param, fill_value=0.0, dtype="float32", shape=None):
        ACCUMULATOR_SLOT_NAMES.add(name)
        acc = self._accumulators.setdefault(name, {})
        if param.name in acc:
            return acc[param.name]
        var_name = unique_name.generate(f"{param.name}_{name}")
        shape = shape if shape is not None else list(param.shape)
        main_block = default_main_program().global_block()
        var = main_block.create_var(
            name=var_name, shape=shape, dtype=dtype, persistable=True
        )
        var.stop_gradient = True
        sblock = default_startup_program().global_block()
        sblock.create_var(name=var_name, shape=shape, dtype=dtype, persistable=True)
        sblock.append_op(
            "fill_constant",
            {},
            {"Out": [var_name]},
            {"shape": shape, "dtype": dtype, "value": fill_value},
        )
        acc[param.name] = var
        return var

    def _get_accumulator(self, name, param):
        return self._accumulators[name][param.name]

    def _create_accumulators(self, block, parameters):
        pass

    # -- pipeline -----------------------------------------------------
    def backward(self, loss, startup_program=None, parameter_list=None, no_grad_set=None):
        return append_backward(loss, parameter_list, no_grad_set)

    def _append_regularization(self, params_grads):
        from paddle_tpu import layers

        out = []
        for p, g in params_grads:
            reg = p.regularizer or self.regularization
            if reg is None or g is None:
                out.append((p, g))
                continue
            out.append((p, reg._append_regularization_op(p, g)))
        return out

    def apply_gradients(self, params_grads):
        block = default_main_program().global_block()
        start = len(block.ops)
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        params_grads = self._append_regularization(params_grads)
        self._create_accumulators(block, [p for p, _ in params_grads])
        ops = []
        for p, g in params_grads:
            if g is None:
                continue
            ops.append(self._append_optimize_op(block, (p, g)))
        self._finish_update(block, params_grads)
        # everything appended here — clip, regularization, lr scaling, the
        # update ops — is the optimize region (the reference stamps it via
        # an op-role guard around apply_gradients). Microbatched execution
        # relies on this: raw @GRADs are accumulated across microbatches and
        # the whole optimize region (incl. clipping) then runs ONCE.
        for op in block.ops[start:]:
            op.attrs["op_role"] = _OP_ROLE_OPTIMIZE
        return ops

    def _finish_update(self, block, params_grads):
        pass

    def apply_optimize(self, loss, startup_program, params_grads):
        return self.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None, no_grad_set=None):
        from paddle_tpu.dygraph.base import in_dygraph_mode

        if in_dygraph_mode():
            return self._dygraph_minimize(loss, parameter_list)
        self.helper = LayerHelper(self.__class__.__name__)
        self._create_global_learning_rate()
        params_grads = self.backward(
            loss, startup_program, parameter_list, no_grad_set
        )
        optimize_ops = self.apply_gradients(params_grads)
        return optimize_ops, params_grads

    # -- dygraph path --------------------------------------------------
    # The reference's dygraph optimizers run one eager update kernel per
    # parameter (python/paddle/fluid/optimizer.py minimize under
    # in_dygraph_mode). TPU-native: the SAME _append_optimize_op machinery
    # builds a static "apply program" once (all updates + lr + clip +
    # regularization), which compiles to ONE XLA computation; accumulators
    # live in a private Scope. Eager per-param dispatch would bottleneck on
    # host launches.
    def _dygraph_minimize(self, loss, parameter_list):
        import jax.numpy as jnp

        from paddle_tpu.core.executor import Executor
        from paddle_tpu.core.ir import Program, program_guard
        from paddle_tpu.core.places import TPUPlace
        from paddle_tpu.core.scope import Scope, scope_guard

        enforce(
            parameter_list is not None,
            "parameter_list is required for minimize() in dygraph mode "
            "(pass layer.parameters())",
        )
        params = [
            p
            for p in parameter_list
            if getattr(p, "trainable", True) and p.grad_value is not None
        ]
        if not params:
            return [], []
        key = tuple((p.name, tuple(p.shape), str(p.dtype)) for p in params)
        if getattr(self, "_dy_key", None) != key:
            self._dy_scope = Scope()
            self._dy_exe = Executor(TPUPlace(0))
            main, startup = Program(), Program()
            self._lr_var = None
            self._accumulators = {}
            self.helper = LayerHelper(self.__class__.__name__)
            with program_guard(main, startup):
                self._create_global_learning_rate()
                block = main.global_block()
                params_grads = []
                for p in params:
                    sp = block.create_parameter(
                        shape=list(p.shape), dtype=p.dtype, name=p.name
                    )
                    sp.optimize_attr = dict(p.optimize_attr)
                    sp.regularizer = p.regularizer
                    g = block.create_var(
                        name=p.name + "@GRAD", shape=list(p.shape), dtype=p.dtype
                    )
                    params_grads.append((sp, g))
                self.apply_gradients(params_grads)
            with scope_guard(self._dy_scope):
                self._dy_exe.run(startup)
            self._dy_prog = main
            self._dy_key = key
        feed = {p.name: p.value for p in params}
        for p in params:
            feed[p.name + "@GRAD"] = jnp.asarray(p.grad_value)
        with scope_guard(self._dy_scope):
            outs = self._dy_exe.run(
                self._dy_prog,
                feed=feed,
                fetch_list=[p.name for p in params],
                return_numpy=False,
            )
        for p, v in zip(params, outs):
            p.value = v
        return [], [(p, p.grad_value) for p in params]

    def state_dict(self):
        """Dygraph accumulator state (reference: dygraph optimizer
        state_dict)."""
        import numpy as np

        out = {}
        scope = getattr(self, "_dy_scope", None)
        if scope is None:
            return out
        for name, per_param in self._accumulators.items():
            for pname, var in per_param.items():
                val = scope.find_var(var.name)
                if val is not None:
                    out[var.name] = np.asarray(val)
        if self._lr_var is not None:
            val = scope.find_var(self._lr_var.name)
            if val is not None:
                out[self._lr_var.name] = np.asarray(val)
        return out

    def set_state_dict(self, state_dict):
        scope = getattr(self, "_dy_scope", None)
        enforce(scope is not None, "optimizer has no state yet (run a step first)")
        for name, val in state_dict.items():
            scope.set(name, __import__("jax").numpy.asarray(val))

    set_dict = set_state_dict

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError


class SGDOptimizer(Optimizer):
    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        return block.append_op(
            "sgd",
            {
                "Param": [p.name],
                "Grad": [g.name],
                "LearningRate": [self._param_lr(p).name],
            },
            {"ParamOut": [p.name]},
            {"op_role": _OP_ROLE_OPTIMIZE},
        )

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        result = super().minimize(
            loss, startup_program, parameter_list, no_grad_set
        )
        from paddle_tpu.dygraph.base import in_dygraph_mode
        from paddle_tpu.utils.flags import flags as _flags

        if not in_dygraph_mode() and _flags.sparse_embedding_update:
            # SelectedRows analog (reference: operators/optimizers/sgd_op.h
            # sparse branch): single-use embedding grads become row-sparse
            # scatter updates instead of [V, D] dense tensors. The rewrite
            # is DEFERRED to first execution (executor applies it) because
            # a PipelineOptimizer wrapping this one sets _num_microbatches
            # only after we return — and the fused form cannot microbatch.
            loss.block.program._wants_sparse_embedding = True
        return result


class MomentumOptimizer(Optimizer):
    def __init__(self, learning_rate, momentum, use_nesterov=False, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        velocity = self._get_accumulator("velocity", p)
        return block.append_op(
            "momentum",
            {
                "Param": [p.name],
                "Grad": [g.name],
                "Velocity": [velocity.name],
                "LearningRate": [self._param_lr(p).name],
            },
            {"ParamOut": [p.name], "VelocityOut": [velocity.name]},
            {
                "mu": self._momentum,
                "use_nesterov": self._use_nesterov,
                "op_role": _OP_ROLE_OPTIMIZE,
            },
        )


class LarsMomentumOptimizer(Optimizer):
    def __init__(
        self,
        learning_rate,
        momentum=0.9,
        lars_coeff=0.001,
        lars_weight_decay=0.0005,
        epsilon=0.0,
        **kwargs,
    ):
        super().__init__(learning_rate, **kwargs)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_weight_decay = lars_weight_decay
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        velocity = self._get_accumulator("velocity", p)
        return block.append_op(
            "lars_momentum",
            {
                "Param": [p.name],
                "Grad": [g.name],
                "Velocity": [velocity.name],
                "LearningRate": [self._param_lr(p).name],
            },
            {"ParamOut": [p.name], "VelocityOut": [velocity.name]},
            {
                "mu": self._momentum,
                "lars_coeff": self._lars_coeff,
                "lars_weight_decay": self._lars_weight_decay,
                "epsilon": self._epsilon,
                "op_role": _OP_ROLE_OPTIMIZE,
            },
        )


class AdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, initial_accumulator_value=0.0, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._epsilon = epsilon
        self._initial = initial_accumulator_value

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p, fill_value=self._initial)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        moment = self._get_accumulator("moment", p)
        return block.append_op(
            "adagrad",
            {
                "Param": [p.name],
                "Grad": [g.name],
                "Moment": [moment.name],
                "LearningRate": [self._param_lr(p).name],
            },
            {"ParamOut": [p.name], "MomentOut": [moment.name]},
            {"epsilon": self._epsilon, "op_role": _OP_ROLE_OPTIMIZE},
        )


class AdamOptimizer(Optimizer):
    _op_type = "adam"

    def __init__(
        self,
        learning_rate=0.001,
        beta1=0.9,
        beta2=0.999,
        epsilon=1e-8,
        lazy_mode=False,
        **kwargs,
    ):
        super().__init__(learning_rate, **kwargs)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment1", p)
            self._add_accumulator("moment2", p)
            self._add_accumulator("beta1_pow_acc", p, fill_value=self._beta1, shape=[1])
            self._add_accumulator("beta2_pow_acc", p, fill_value=self._beta2, shape=[1])

    def _extra_attrs(self):
        return {}

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m1 = self._get_accumulator("moment1", p)
        m2 = self._get_accumulator("moment2", p)
        b1p = self._get_accumulator("beta1_pow_acc", p)
        b2p = self._get_accumulator("beta2_pow_acc", p)
        attrs = {
            "beta1": self._beta1,
            "beta2": self._beta2,
            "epsilon": self._epsilon,
            "op_role": _OP_ROLE_OPTIMIZE,
        }
        attrs.update(self._extra_attrs())
        return block.append_op(
            self._op_type,
            {
                "Param": [p.name],
                "Grad": [g.name],
                "Moment1": [m1.name],
                "Moment2": [m2.name],
                "Beta1Pow": [b1p.name],
                "Beta2Pow": [b2p.name],
                "LearningRate": [self._param_lr(p).name],
            },
            {
                "ParamOut": [p.name],
                "Moment1Out": [m1.name],
                "Moment2Out": [m2.name],
                "Beta1PowOut": [b1p.name],
                "Beta2PowOut": [b2p.name],
            },
            attrs,
        )


class AdamWOptimizer(AdamOptimizer):
    _op_type = "adamw"

    def __init__(self, learning_rate=0.001, weight_decay=0.01, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._coeff = weight_decay

    def _extra_attrs(self):
        return {"coeff": self._coeff}


class LambOptimizer(AdamOptimizer):
    _op_type = "lamb"

    def __init__(
        self,
        learning_rate=0.001,
        lamb_weight_decay=0.01,
        beta1=0.9,
        beta2=0.999,
        epsilon=1e-6,
        exclude_from_weight_decay_fn=None,
        **kwargs,
    ):
        super().__init__(learning_rate, beta1=beta1, beta2=beta2, epsilon=epsilon, **kwargs)
        self._weight_decay = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        wd = self._weight_decay
        if self._exclude_fn is not None and self._exclude_fn(p):
            wd = 0.0
        m1 = self._get_accumulator("moment1", p)
        m2 = self._get_accumulator("moment2", p)
        b1p = self._get_accumulator("beta1_pow_acc", p)
        b2p = self._get_accumulator("beta2_pow_acc", p)
        return block.append_op(
            "lamb",
            {
                "Param": [p.name],
                "Grad": [g.name],
                "Moment1": [m1.name],
                "Moment2": [m2.name],
                "Beta1Pow": [b1p.name],
                "Beta2Pow": [b2p.name],
                "LearningRate": [self._param_lr(p).name],
            },
            {
                "ParamOut": [p.name],
                "Moment1Out": [m1.name],
                "Moment2Out": [m2.name],
                "Beta1PowOut": [b1p.name],
                "Beta2PowOut": [b2p.name],
            },
            {
                "beta1": self._beta1,
                "beta2": self._beta2,
                "epsilon": self._epsilon,
                "weight_decay": wd,
                "op_role": _OP_ROLE_OPTIMIZE,
            },
        )


class AdamaxOptimizer(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)
            self._add_accumulator("inf_norm", p)
            self._add_accumulator("beta1_pow_acc", p, fill_value=self._beta1, shape=[1])

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        return block.append_op(
            "adamax",
            {
                "Param": [p.name],
                "Grad": [g.name],
                "Moment": [self._get_accumulator("moment", p).name],
                "InfNorm": [self._get_accumulator("inf_norm", p).name],
                "Beta1Pow": [self._get_accumulator("beta1_pow_acc", p).name],
                "LearningRate": [self._param_lr(p).name],
            },
            {
                "ParamOut": [p.name],
                "MomentOut": [self._get_accumulator("moment", p).name],
                "InfNormOut": [self._get_accumulator("inf_norm", p).name],
            },
            {
                "beta1": self._beta1,
                "beta2": self._beta2,
                "epsilon": self._epsilon,
                "op_role": _OP_ROLE_OPTIMIZE,
            },
        )

    def _finish_update(self, block, params_grads):
        """beta1_pow *= beta1 after all updates
        (reference: python/paddle/fluid/optimizer.py Adamax._finish_update)."""
        from paddle_tpu import layers

        for p, g in params_grads:
            if g is None:
                continue
            b1p = self._get_accumulator("beta1_pow_acc", p)
            block.append_op(
                "scale",
                {"X": [b1p.name]},
                {"Out": [b1p.name]},
                {"scale": self._beta1, "op_role": _OP_ROLE_OPTIMIZE},
            )


class AdadeltaOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, rho=0.95, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._epsilon, self._rho = epsilon, rho

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("_avg_squared_grad", p)
            self._add_accumulator("_avg_squared_update", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        asg = self._get_accumulator("_avg_squared_grad", p)
        asu = self._get_accumulator("_avg_squared_update", p)
        return block.append_op(
            "adadelta",
            {
                "Param": [p.name],
                "Grad": [g.name],
                "AvgSquaredGrad": [asg.name],
                "AvgSquaredUpdate": [asu.name],
            },
            {
                "ParamOut": [p.name],
                "AvgSquaredGradOut": [asg.name],
                "AvgSquaredUpdateOut": [asu.name],
            },
            {"epsilon": self._epsilon, "rho": self._rho, "op_role": _OP_ROLE_OPTIMIZE},
        )


class DecayedAdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._decay, self._epsilon = decay, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        moment = self._get_accumulator("moment", p)
        return block.append_op(
            "decayed_adagrad",
            {
                "Param": [p.name],
                "Grad": [g.name],
                "Moment": [moment.name],
                "LearningRate": [self._param_lr(p).name],
            },
            {"ParamOut": [p.name], "MomentOut": [moment.name]},
            {"decay": self._decay, "epsilon": self._epsilon, "op_role": _OP_ROLE_OPTIMIZE},
        )


class RMSPropOptimizer(Optimizer):
    def __init__(
        self,
        learning_rate,
        rho=0.95,
        epsilon=1e-6,
        momentum=0.0,
        centered=False,
        **kwargs,
    ):
        super().__init__(learning_rate, **kwargs)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("momentum", p)
            self._add_accumulator("mean_square", p)
            self._add_accumulator("mean_grad", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        mom = self._get_accumulator("momentum", p)
        ms = self._get_accumulator("mean_square", p)
        mg = self._get_accumulator("mean_grad", p)
        return block.append_op(
            "rmsprop",
            {
                "Param": [p.name],
                "Grad": [g.name],
                "Moment": [mom.name],
                "MeanSquare": [ms.name],
                "MeanGrad": [mg.name],
                "LearningRate": [self._param_lr(p).name],
            },
            {
                "ParamOut": [p.name],
                "MomentOut": [mom.name],
                "MeanSquareOut": [ms.name],
                "MeanGradOut": [mg.name],
            },
            {
                "decay": self._rho,
                "epsilon": self._epsilon,
                "momentum": self._momentum,
                "centered": self._centered,
                "op_role": _OP_ROLE_OPTIMIZE,
            },
        )


class FtrlOptimizer(Optimizer):
    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("squared", p)
            self._add_accumulator("linear", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        sq = self._get_accumulator("squared", p)
        lin = self._get_accumulator("linear", p)
        return block.append_op(
            "ftrl",
            {
                "Param": [p.name],
                "Grad": [g.name],
                "SquaredAccumulator": [sq.name],
                "LinearAccumulator": [lin.name],
                "LearningRate": [self._param_lr(p).name],
            },
            {
                "ParamOut": [p.name],
                "SquaredAccumOut": [sq.name],
                "LinearAccumOut": [lin.name],
            },
            {
                "l1": self._l1,
                "l2": self._l2,
                "lr_power": self._lr_power,
                "op_role": _OP_ROLE_OPTIMIZE,
            },
        )


class DpsgdOptimizer(Optimizer):
    def __init__(self, learning_rate, clip=10.0, batch_size=16.0, sigma=1.0, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self._clip, self._batch_size, self._sigma = clip, batch_size, sigma

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        return block.append_op(
            "dpsgd",
            {
                "Param": [p.name],
                "Grad": [g.name],
                "LearningRate": [self._param_lr(p).name],
            },
            {"ParamOut": [p.name]},
            {
                "clip": self._clip,
                "batch_size": self._batch_size,
                "sigma": self._sigma,
                "op_role": _OP_ROLE_OPTIMIZE,
            },
        )


# public aliases matching the reference API surface
SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adagrad = AdagradOptimizer
Adam = AdamOptimizer
AdamW = AdamWOptimizer
Adamax = AdamaxOptimizer
Adadelta = AdadeltaOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
class RecomputeOptimizer(Optimizer):
    """Activation recomputation (reference: python/paddle/fluid/
    optimizer.py:3714 RecomputeOptimizer + backward.py:618
    _append_backward_ops_with_checkpoints_).

    The reference re-emits forward ops between user checkpoints inside the
    backward section so activations need not be stored. Here checkpoints are
    recorded on the program and append_backward collapses each
    inter-checkpoint forward segment into one recompute_segment_grad op that
    replays the segment under jax.vjp(jax.checkpoint(...)) at backward time
    (core/backward.py _collapse_segments, ops/recompute.py) — only segment
    boundaries stay live across fwd->bwd. Gradients are mathematically
    identical with or without recompute.

    ``policy`` keys the jax.checkpoint remat policy THROUGH THE IR
    (paddle_tpu/kernels/remat.py): "full" (default, save nothing),
    "dots" / "dots_no_batch" (keep matmul outputs, replay only
    elementwise work), "save_all" (no-remat control). The choice is
    stamped as ``__remat_policy__`` on every collapsed segment op —
    ``analysis/memory.py`` predicts the peak-HBM delta of a policy
    change before any compile, and a flip retraces via the
    content-addressed cache because the attr is program content.
    """

    def __init__(self, optimizer, policy=None):
        from paddle_tpu.kernels import remat as _remat

        self._inner = optimizer
        self._checkpoints = None
        self._policy = _remat.validate_policy(
            policy or _remat.DEFAULT_POLICY)

    def _set_checkpoints(self, checkpoints, policy=None):
        from paddle_tpu.kernels import remat as _remat

        self._checkpoints = [
            c if isinstance(c, str) else c.name for c in checkpoints
        ]
        if policy is not None:
            self._policy = _remat.validate_policy(policy)

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def _arm(self, program):
        program._recompute_checkpoints = list(self._checkpoints)
        program._recompute_policy = self._policy

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        if self._checkpoints:
            self._arm(loss.block.program)
        return self._inner.backward(
            loss, startup_program, parameter_list, no_grad_set
        )

    def apply_gradients(self, params_grads):
        return self._inner.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        if self._checkpoints:
            self._arm(loss.block.program)
        return self._inner.minimize(
            loss, startup_program, parameter_list, no_grad_set
        )


class PipelineOptimizer:
    """Microbatch-pipelined training (reference: python/paddle/fluid/
    optimizer.py:3414 — cuts the program into sections run by SectionWorker
    threads passing scopes through queues, trainer.h:118).

    TPU-native translation: the whole fwd/bwd region is replayed per
    microbatch inside ONE compiled step with gradients averaged before a
    single optimizer region (executor _make_microbatched_step). Combined
    with CompiledProgram.with_parallel and stage-sharded parameters (the
    'stage' mesh axis, parallel/pipeline.py), XLA overlaps the per-stage
    work — scope queues and section threads have no TPU analog because the
    schedule lives inside the compiler. cut_list/place_list/concurrency are
    accepted for API parity and ignored."""

    def __init__(self, optimizer, num_microbatches=1, cut_list=None,
                 place_list=None, concurrency_list=None, queue_size=30,
                 start_cpu_core_id=0):
        self._inner = optimizer
        self._num_microbatches = max(int(num_microbatches), 1)

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        result = self._inner.minimize(
            loss, startup_program, parameter_list, no_grad_set
        )
        loss.block.program._num_microbatches = self._num_microbatches
        return result


class DGCMomentumOptimizer(MomentumOptimizer):
    """Momentum with Deep Gradient Compression (reference: python/paddle/
    fluid/optimizer.py:1042 DGCMomentumOptimizer; paddle/fluid/operators/
    dgc_op.cc; details/sparse_all_reduce_op_handle.h).

    The reference sparsifies gradients to top-k before NCCL allreduce to cut
    communication. TPU forms here:

    * THIS optimizer + CompiledProgram data parallelism (pure-DP mesh):
      the compiler runs the block per-shard under shard_map, U/V become
      per-shard error-feedback state (leading shard axis in the scope),
      and the exchange is a top-k (index, value) all_gather over the data
      axis — 2*k*n floats on the wire instead of the dense gradient
      (compiler.py dgc_sparse mode; ops/optimizers.py sparse branch).
    * THIS optimizer uncompiled / on a hybrid mesh: the fused dense form —
      DGC update semantics (momentum correction + error feedback +
      magnitude selection with warmup ramp) but compiler-inserted dense
      traffic; the compiler warns when it falls back.
    * parallel/dgc.py `dgc_allreduce`: the same honest exchange for
      functional shard_map training loops.
    """

    def __init__(self, learning_rate, momentum, rampup_begin_step=0,
                 rampup_step=1, sparsity=(0.999,), use_nesterov=False,
                 regularization=None, grad_clip=None, name=None):
        super().__init__(learning_rate, momentum,
                         use_nesterov=use_nesterov,
                         regularization=regularization,
                         grad_clip=grad_clip, name=name)
        self._rampup_begin_step = rampup_begin_step
        self._rampup_step = rampup_step
        self._sparsity = [float(s) for s in sparsity]
        self._step_var = None

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("dgc_u", p)
            self._add_accumulator("dgc_v", p)
        if self._step_var is None:
            self._step_var = tensor_layers.create_global_var(
                shape=[1], value=0.0, dtype="float32", persistable=True,
                name=unique_name.generate("dgc_step"),
            )

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        return block.append_op(
            "dgc_momentum",
            {
                "Param": [p.name],
                "Grad": [g.name],
                "U": [self._get_accumulator("dgc_u", p).name],
                "V": [self._get_accumulator("dgc_v", p).name],
                "LearningRate": [self._param_lr(p).name],
                "CurrentStep": [self._step_var.name],
            },
            {
                "ParamOut": [p.name],
                "UOut": [self._get_accumulator("dgc_u", p).name],
                "VOut": [self._get_accumulator("dgc_v", p).name],
            },
            {
                "mu": self._momentum,
                "use_nesterov": self._use_nesterov,
                "rampup_begin_step": float(self._rampup_begin_step),
                "rampup_step": float(self._rampup_step),
                "sparsity": self._sparsity,
                "op_role": _OP_ROLE_OPTIMIZE,
            },
        )

    def _finish_update(self, block, params_grads):
        block.append_op(
            "increment",
            {"X": [self._step_var.name]},
            {"Out": [self._step_var.name]},
            {"step": 1.0, "op_role": _OP_ROLE_OPTIMIZE},
        )


class ExponentialMovingAverage:
    """EMA of trainable parameters (reference: python/paddle/fluid/
    optimizer.py:3166). update() appends in-graph shadow updates to the main
    program (run them every step, after the optimizer ops); apply() is a
    context manager that swaps EMA values into the scope for evaluation and
    restores on exit. `thres_steps` (the reference's dynamic-decay ramp) is
    accepted for API parity but not applied — decay is constant."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._name = name or "ema"
        self._shadows = {}  # param name -> shadow var name

    def update(self):
        from paddle_tpu.core.ir import default_main_program, default_startup_program

        block = default_main_program().global_block()
        sblock = default_startup_program().global_block()
        for p in block.all_parameters():
            if not p.trainable:
                continue
            sname = unique_name.generate(f"{self._name}_{p.name}")
            self._shadows[p.name] = sname
            shape = list(p.shape)
            block.create_var(name=sname, shape=shape, dtype=p.dtype,
                            persistable=True).stop_gradient = True
            sblock.create_var(name=sname, shape=shape, dtype=p.dtype,
                              persistable=True)
            # shadow starts at the initial param value
            sblock.append_op("assign", {"X": [p.name]}, {"Out": [sname]}, {})
            block.append_op(
                "ema_update",
                {"Param": [p.name], "Shadow": [sname]},
                {"ShadowOut": [sname]},
                {"decay": self._decay, "op_role": _OP_ROLE_OPTIMIZE},
            )

    def apply(self, executor=None, need_restore=True):
        import contextlib

        import numpy as np

        from paddle_tpu.core.scope import global_scope

        @contextlib.contextmanager
        def _ctx():
            scope = global_scope()
            saved = {}
            for pname, sname in self._shadows.items():
                shadow = scope.find_var(sname)
                if shadow is None:
                    continue
                saved[pname] = scope.find_var(pname)
                scope.set(pname, shadow)
            try:
                yield
            finally:
                if need_restore:
                    for pname, val in saved.items():
                        scope.set(pname, val)

        return _ctx()

    def restore(self, executor=None):
        pass  # restoration is handled by the apply() context exit


class ModelAverage:
    """Sliding-window parameter averaging (reference: python/paddle/fluid/
    optimizer.py:2862). Accumulates running sums in-graph; apply() swaps the
    averaged values in for evaluation. The effective window follows the
    reference: clamp(average_window_rate * num_updates, min_average_window,
    max_average_window) — once the count reaches the window, old snapshots
    age out geometrically."""

    def __init__(self, average_window_rate=0.15, min_average_window=10000,
                 max_average_window=10000, name=None):
        self._name = name or "model_avg"
        self._rate = float(average_window_rate)
        self._min_window = float(min_average_window)
        self._max_window = float(max_average_window)
        self._sums = {}  # param -> (sum var, count var)

    def _build(self):
        from paddle_tpu.core.ir import default_main_program, default_startup_program

        block = default_main_program().global_block()
        sblock = default_startup_program().global_block()
        for p in block.all_parameters():
            if not p.trainable:
                continue
            ssum = unique_name.generate(f"{self._name}_sum_{p.name}")
            scnt = unique_name.generate(f"{self._name}_cnt_{p.name}")
            # count var holds (window_count, total_updates)
            for name, shape in ((ssum, list(p.shape)), (scnt, [2])):
                block.create_var(name=name, shape=shape, dtype="float32",
                                 persistable=True).stop_gradient = True
                sblock.create_var(name=name, shape=shape, dtype="float32",
                                  persistable=True)
                sblock.append_op(
                    "fill_constant", {}, {"Out": [name]},
                    {"shape": shape, "dtype": "float32", "value": 0.0},
                )
            self._sums[p.name] = (ssum, scnt)
            block.append_op(
                "model_average_update",
                {"Param": [p.name], "Sum": [ssum], "Count": [scnt]},
                {"SumOut": [ssum], "CountOut": [scnt]},
                {"rate": self._rate,
                 "min_window": self._min_window,
                 "max_window": self._max_window,
                 "op_role": _OP_ROLE_OPTIMIZE},
            )

    def minimize_after(self, optimizer_result=None):
        """Call once after optimizer.minimize() to append averaging ops."""
        self._build()

    def apply(self, executor=None, need_restore=True):
        import contextlib

        import numpy as np

        from paddle_tpu.core.scope import global_scope

        @contextlib.contextmanager
        def _ctx():
            scope = global_scope()
            saved = {}
            for pname, (ssum, scnt) in self._sums.items():
                s = scope.find_var(ssum)
                c = scope.find_var(scnt)
                if s is None or c is None:
                    continue
                cnt = float(np.asarray(c).reshape(-1)[0])  # window_count
                if cnt <= 0:
                    continue
                saved[pname] = scope.find_var(pname)
                scope.set(pname, np.asarray(s) / cnt)
            try:
                yield
            finally:
                if need_restore:
                    for pname, val in saved.items():
                        scope.set(pname, val)

        return _ctx()

    def restore(self, executor=None):
        pass


RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer
Lamb = LambOptimizer
LarsMomentum = LarsMomentumOptimizer
Dpsgd = DpsgdOptimizer
