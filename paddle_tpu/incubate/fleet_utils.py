"""FleetUtil: production train/infer helpers.

reference: python/paddle/fluid/incubate/fleet/utils/fleet_util.py:40 —
rank-0 logging, global AUC from the distributed metric states, program
introspection, model save/compare helpers. TPU translation: metric states
are in-scope arrays (metrics.py auc op accumulators); cross-worker
reduction goes through the PS barrier/dense tables or is single-host.
"""

import os

import numpy as np

from paddle_tpu.core.scope import global_scope

__all__ = ["FleetUtil"]


class FleetUtil:
    def __init__(self, fleet=None):
        self._fleet = fleet

    # -- logging --------------------------------------------------------
    def rank0_print(self, *args, **kwargs):
        """reference: fleet_util.py rank0_print."""
        if self._rank() == 0:
            print(*args, **kwargs, flush=True)

    def rank0_error(self, *args):
        if self._rank() == 0:
            import logging

            logging.getLogger("paddle_tpu.fleet").error(" ".join(map(str, args)))

    def _rank(self):
        if self._fleet is not None:
            try:
                return self._fleet.worker_index()
            except Exception:
                pass
        return int(os.environ.get("PADDLE_TRAINER_ID", "0"))

    # -- metrics --------------------------------------------------------
    def get_global_auc(self, stat_pos, stat_neg, scope=None):
        """AUC from the in-graph auc op's positive/negative histogram
        accumulators (reference: fleet_util.py get_global_auc — there the
        stats all-reduce over workers first; here the single-host form, the
        multi-worker sum arriving via the PS dense table when used in a
        fleet)."""
        scope = scope or global_scope()
        stat_pos = stat_pos if isinstance(stat_pos, str) else stat_pos.name
        stat_neg = stat_neg if isinstance(stat_neg, str) else stat_neg.name
        pos = scope.find_var(stat_pos)
        neg = scope.find_var(stat_neg)
        if pos is None or neg is None:
            return None
        pos = np.asarray(pos, dtype=np.float64).reshape(-1)
        neg = np.asarray(neg, dtype=np.float64).reshape(-1)
        # histogram walk, high threshold -> low
        tp = fp = 0.0
        area = 0.0
        for i in range(len(pos) - 1, -1, -1):
            new_tp = tp + pos[i]
            new_fp = fp + neg[i]
            area += (new_fp - fp) * (tp + new_tp) / 2.0
            tp, fp = new_tp, new_fp
        if tp == 0 or fp == 0:
            return 0.5
        return float(area / (tp * fp))

    # -- program introspection -------------------------------------------
    def program_summary(self, program):
        """Op/param census (reference: fleet_util.py's program_type_trans +
        print helpers, condensed)."""
        block = program.global_block()
        op_counts = {}
        for op in block.ops:
            op_counts[op.type] = op_counts.get(op.type, 0) + 1
        params = program.all_parameters()
        n_elems = int(sum(int(np.prod(p.shape or [0])) for p in params))
        return {
            "num_ops": len(block.ops),
            "op_counts": dict(sorted(op_counts.items())),
            "num_params": len(params),
            "param_elements": n_elems,
        }

    def print_program_summary(self, program):
        s = self.program_summary(program)
        self.rank0_print(
            f"program: {s['num_ops']} ops, {s['num_params']} params "
            f"({s['param_elements']:,} elements)"
        )
        return s

    # -- model compare ----------------------------------------------------
    def params_allclose(self, program, dirname, rtol=1e-5, atol=1e-8,
                        scope=None):
        """Compare in-scope params with a save_persistables directory
        (reference: fleet_util.py check_two_programs-style model compare).
        Returns {param: max_abs_diff} for mismatches (empty = equal)."""
        scope = scope or global_scope()
        state = {}
        for fn in os.listdir(dirname):
            if fn.endswith(".npy"):
                state[fn[:-4]] = np.load(os.path.join(dirname, fn))
        bad = {}
        for p in program.all_parameters():
            cur = np.asarray(scope.find_var(p.name))
            ref = state.get(p.name.replace("/", "_"))
            if ref is None:
                bad[p.name] = float("inf")
            elif not np.allclose(cur, ref, rtol=rtol, atol=atol):
                bad[p.name] = float(np.abs(cur - ref).max())
        return bad

    # -- persistence glue -------------------------------------------------
    def save_program(self, program, dirname, executor=None, scope=None):
        from paddle_tpu import io as pio

        pio.save_persistables(executor, dirname, main_program=program)

    def load_program(self, program, dirname, executor=None):
        from paddle_tpu import io as pio

        pio.load_persistables(executor, dirname, main_program=program)
