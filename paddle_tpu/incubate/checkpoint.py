"""Automatic checkpoint + resume: the recovery story (SURVEY §5.3).

The reference's recovery is checkpoint-based, not elastic: pservers
snapshot on checkpoint_notify (reference: paddle/fluid/operators/
distributed_ops/checkpoint_notify_op.cc) and jobs restart from the last
save (reference: python/paddle/fluid/io.py:405 _save_distributed
_persistables). TPU-native version: JAX multi-host failure = job restart,
so the unit of recovery is (persistable state + step counter) written
ASYNCHRONOUSLY (device->host snapshot on the training thread, file IO on a
background thread — the chip never waits for the disk) with an atomic
`latest` pointer, plus `resume()` on restart.
"""

import json
import os
import shutil
import threading
import time

import numpy as np

from paddle_tpu import io as pio
from paddle_tpu.core.scope import global_scope
from paddle_tpu.utils.enforce import enforce

__all__ = ["AutoCheckpoint", "HeartBeatMonitor"]


class AutoCheckpoint:
    """Periodic async checkpoints with auto-resume.

        ckpt = AutoCheckpoint(exe, program, dirname, save_interval_steps=100)
        start_step = ckpt.resume()          # 0 on a fresh run
        for step in range(start_step, n):
            exe.run(...)
            ckpt.maybe_save(step)
        ckpt.close()
    """

    def __init__(self, exe, program, dirname, save_interval_steps=100,
                 max_to_keep=3, scope=None):
        self._exe = exe
        self._program = program
        self._dir = dirname
        self._interval = int(save_interval_steps)
        self._keep = int(max_to_keep)
        self._scope = scope
        self._thread = None
        self._lock = threading.Lock()
        self._last_error = None
        os.makedirs(dirname, exist_ok=True)

    # -- save ----------------------------------------------------------
    def _persistable_names(self):
        return [
            v.name
            for v in self._program.global_block().vars.values()
            if v.persistable
        ]

    def maybe_save(self, step, blocking=False):
        if (step + 1) % self._interval:
            return False
        self.save(step, blocking=blocking)
        return True

    def save(self, step, blocking=False):
        """Snapshot device state NOW (cheap: device->host copies), write
        files on a background thread (the reference's checkpoint_notify is
        likewise fire-and-forget from the trainer's view)."""
        scope = self._scope or global_scope()
        snap = {}
        for n in self._persistable_names():
            v = scope.find_var(n)
            if v is not None:
                snap[n] = np.asarray(v)
        # one async writer at a time; a newer save supersedes a pending one
        self._join()
        if self._last_error is not None:
            err, self._last_error = self._last_error, None
            raise RuntimeError(
                f"previous async checkpoint write failed: {err}"
            )

        def write():
            d = os.path.join(self._dir, f"ckpt_{step}")
            tmp = d + ".tmp"
            shutil.rmtree(tmp, ignore_errors=True)
            os.makedirs(tmp, exist_ok=True)
            np.savez(os.path.join(tmp, "state.npz"),
                     **{k: v for k, v in snap.items()})
            with open(os.path.join(tmp, "meta.json"), "w") as f:
                json.dump({"step": step, "time": time.time()}, f)
            shutil.rmtree(d, ignore_errors=True)
            os.replace(tmp, d)
            # atomic latest pointer
            ptr = os.path.join(self._dir, "latest.tmp")
            with open(ptr, "w") as f:
                f.write(f"ckpt_{step}")
            os.replace(ptr, os.path.join(self._dir, "latest"))
            self._gc()

        def guarded():
            try:
                write()
            except Exception as e:  # surfaced on the NEXT save/close
                import logging

                logging.getLogger("paddle_tpu.checkpoint").error(
                    "async checkpoint write failed: %s", e
                )
                self._last_error = e

        if blocking:
            write()
        else:
            self._thread = threading.Thread(target=guarded, daemon=True)
            self._thread.start()

    def _gc(self):
        entries = os.listdir(self._dir)
        # clear debris from a save killed mid-write
        for d in entries:
            if d.endswith(".tmp"):
                shutil.rmtree(os.path.join(self._dir, d), ignore_errors=True)
        kept = sorted(
            (d for d in entries
             if d.startswith("ckpt_") and d.split("_", 1)[1].isdigit()),
            key=lambda d: int(d.split("_", 1)[1]),
        )
        for d in kept[: -self._keep]:
            shutil.rmtree(os.path.join(self._dir, d), ignore_errors=True)

    def _join(self):
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    # -- resume ----------------------------------------------------------
    def resume(self):
        """Restore the newest complete checkpoint into the scope; returns
        the step AFTER the checkpointed one (0 on a fresh start)."""
        ptr = os.path.join(self._dir, "latest")
        if not os.path.exists(ptr):
            return 0
        with open(ptr) as f:
            name = f.read().strip()
        d = os.path.join(self._dir, name)
        state_p = os.path.join(d, "state.npz")
        meta_p = os.path.join(d, "meta.json")
        if not (os.path.exists(state_p) and os.path.exists(meta_p)):
            return 0
        with open(meta_p) as f:
            meta = json.load(f)
        scope = self._scope or global_scope()
        with np.load(state_p) as z:
            for n in z.files:
                scope.set(n, z[n])
        return int(meta["step"]) + 1

    def close(self):
        self._join()
        if self._last_error is not None:
            err, self._last_error = self._last_error, None
            raise RuntimeError(f"async checkpoint write failed: {err}")


class HeartBeatMonitor:
    """Chief-side worker-lost detection over the PS heartbeat table
    (reference: paddle/fluid/operators/distributed/heart_beat_monitor.h:54 —
    UNINITED/RUNNING/COMPLETED per worker, lost workers logged).

        mon = HeartBeatMonitor(client, worker_id=0, worker_num=2,
                               timeout=5.0, on_lost=callback)
        mon.start();  ...  mon.stop()
    """

    def __init__(self, client, worker_id, worker_num, timeout=30.0,
                 period=1.0, on_lost=None):
        self._client = client
        self._id = int(worker_id)
        self._n = int(worker_num)
        self._timeout = float(timeout)
        self._period = float(period)
        self._on_lost = on_lost
        self._stop = threading.Event()
        self._thread = None
        self._seen = set()
        self.lost = set()

    def _loop(self):
        import logging

        log = logging.getLogger("paddle_tpu.heartbeat")
        start = time.monotonic()
        while not self._stop.is_set():
            try:
                ages = self._client.heartbeat(self._id)
            except Exception as e:  # server gone: report and stop
                log.warning("heartbeat RPC failed: %s", e)
                break
            self._seen.update(ages)
            # a worker that NEVER heartbeats (died during startup) has no
            # server entry — treat absence past the grace window as lost
            # (the reference's UNINITED state, heart_beat_monitor.h:38)
            elapsed = time.monotonic() - start
            for wid in range(self._n):
                if wid == self._id or wid in ages or wid in self._seen:
                    continue
                if elapsed > self._timeout:
                    ages = dict(ages)
                    ages[wid] = elapsed
            for wid, age in ages.items():
                if age > self._timeout and wid not in self.lost:
                    self.lost.add(wid)
                    log.warning(
                        "worker %d LOST: no heartbeat for %.1fs "
                        "(timeout %.1fs)", wid, age, self._timeout,
                    )
                    if self._on_lost is not None:
                        self._on_lost(wid, age)
            self._stop.wait(self._period)

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
