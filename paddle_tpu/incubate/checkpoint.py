"""Automatic checkpoint + resume: the recovery story (SURVEY §5.3).

The reference's recovery is checkpoint-based, not elastic: pservers
snapshot on checkpoint_notify (reference: paddle/fluid/operators/
distributed_ops/checkpoint_notify_op.cc) and jobs restart from the last
save (reference: python/paddle/fluid/io.py:405 _save_distributed
_persistables). TPU-native version: JAX multi-host failure = job restart,
so the unit of recovery is (persistable state + step counter) written
ASYNCHRONOUSLY (device->host snapshot on the training thread, file IO on a
background thread — the chip never waits for the disk) with an atomic
`latest` pointer, plus `resume()` on restart.

Crash consistency (the CheckFreq/Check-N-Run recipe): every checkpoint
directory carries a manifest with a CRC32 per array AND of the state
file itself, written BEFORE the atomic rename — so a torn write, a bad
disk, or a fault-injected corruption is DETECTED at resume time instead
of silently loading garbage. `resume()` verifies the `latest` target and
walks back the checkpoint chain past corrupt/torn entries, quarantining
them as `<name>.corrupt` for forensics. File IO runs under the shared
retry policy (resilience/retry.py), and the write path is fault-
injection instrumented (sites `checkpoint.io`,
`checkpoint.before_rename`, `checkpoint.before_latest`) so tests and
tools/chaos_train.py can rehearse every failure point deterministically.
"""

import io as _io
import json
import logging
import os
import shutil
import threading
import time
import zlib

import numpy as np

from paddle_tpu.core.scope import global_scope
from paddle_tpu.dataio.state import STATE_KEY, decode_state, encode_state
from paddle_tpu.io import array_crc32
from paddle_tpu.resilience import faults
from paddle_tpu.resilience.retry import RetryPolicy

__all__ = [
    "AutoCheckpoint",
    "HeartBeatMonitor",
    "CheckpointCorruptError",
    "verify_checkpoint",
    "newest_valid_checkpoint",
    "load_checkpoint",
]

log = logging.getLogger("paddle_tpu.checkpoint")

MANIFEST_NAME = "manifest.json"
_DEFAULT_IO_RETRY = RetryPolicy(max_attempts=3, base_delay_s=0.02,
                                max_delay_s=0.5)


class CheckpointCorruptError(RuntimeError):
    """A checkpoint directory failed integrity verification."""


def _ckpt_step(name):
    tail = name.split("_", 1)[1] if "_" in name else ""
    return int(tail) if tail.isdigit() else None


def verify_checkpoint(dirname, level="full"):
    """Integrity-check one checkpoint directory; returns (step, arrays)
    — arrays is None at level="file" — or raises CheckpointCorruptError
    naming exactly what is wrong.

    Checks, outside-in: meta/state files present -> state.npz whole-file
    CRC + size against the manifest -> (level="full" only) npz readable
    -> per-array CRC32. The state file is read ONCE; the arrays are
    parsed from the same bytes the CRC covered. level="file" stops after
    the whole-file checks — the cheap pre-relaunch screen the supervisor
    uses, while the relaunched worker's resume() re-verifies fully.
    Pre-manifest (legacy) checkpoints pass on readability alone."""
    state_p = os.path.join(dirname, "state.npz")
    meta_p = os.path.join(dirname, "meta.json")
    man_p = os.path.join(dirname, MANIFEST_NAME)
    for p in (state_p, meta_p):
        if not os.path.exists(p):
            raise CheckpointCorruptError(f"{dirname}: missing {os.path.basename(p)}")
    try:
        with open(meta_p) as f:
            meta = json.load(f)
        step = int(meta["step"])
    except (ValueError, TypeError, KeyError, json.JSONDecodeError) as e:
        raise CheckpointCorruptError(f"{dirname}: bad meta.json ({e})")
    manifest = None
    raw = None
    if os.path.exists(man_p):
        try:
            with open(man_p) as f:
                manifest = json.load(f)
        except (ValueError, json.JSONDecodeError) as e:
            raise CheckpointCorruptError(f"{dirname}: bad manifest ({e})")
        finfo = manifest.get("files", {}).get("state.npz", {})
        size = os.path.getsize(state_p)
        if "size" in finfo and size != finfo["size"]:
            raise CheckpointCorruptError(
                f"{dirname}: state.npz is {size} bytes, manifest says "
                f"{finfo['size']} (torn write)"
            )
        if "crc32" in finfo:
            with open(state_p, "rb") as f:
                raw = f.read()
            crc = zlib.crc32(raw) & 0xFFFFFFFF
            if crc != finfo["crc32"]:
                raise CheckpointCorruptError(
                    f"{dirname}: state.npz CRC {crc:#x} != manifest "
                    f"{finfo['crc32']:#x}"
                )
    if level == "file":
        return step, None
    arrays = {}
    try:
        with np.load(_io.BytesIO(raw) if raw is not None else state_p) as z:
            for n in z.files:
                arrays[n] = z[n]
    except Exception as e:
        raise CheckpointCorruptError(f"{dirname}: unreadable state.npz ({e})")
    if manifest is not None:
        want = manifest.get("arrays", {})
        missing = sorted(set(want) - set(arrays))
        if missing:
            raise CheckpointCorruptError(
                f"{dirname}: arrays missing from state.npz: {missing[:5]}"
            )
        for n, info in want.items():
            crc = array_crc32(arrays[n])
            if crc != info["crc32"]:
                raise CheckpointCorruptError(
                    f"{dirname}: array '{n}' CRC {crc:#x} != manifest "
                    f"{info['crc32']:#x}"
                )
    return step, arrays


def _quarantine(dirname, reason):
    """Rename a corrupt checkpoint out of the chain (never delete — a
    human may want the bytes). Idempotent against name collisions."""
    target = dirname + ".corrupt"
    n = 0
    while os.path.exists(target):
        n += 1
        target = f"{dirname}.corrupt{n}"
    try:
        os.replace(dirname, target)
        log.error("quarantined corrupt checkpoint %s -> %s (%s)",
                  dirname, target, reason)
    except OSError as e:
        log.error("could not quarantine %s: %s", dirname, e)
    return target


def _candidates(dirname):
    """Checkpoint names to try, best first: the `latest` pointer target,
    then every other ckpt_<step> newest-first (the fallback chain)."""
    try:
        entries = os.listdir(dirname)
    except OSError:
        return []
    chain = sorted(
        (d for d in entries
         if d.startswith("ckpt_") and _ckpt_step(d) is not None),
        key=_ckpt_step, reverse=True,
    )
    ptr = os.path.join(dirname, "latest")
    if os.path.exists(ptr):
        try:
            with open(ptr) as f:
                name = f.read().strip()
        except OSError:
            name = ""
        if name in chain:
            chain.remove(name)
            chain.insert(0, name)
    return chain


def newest_valid_checkpoint(dirname, quarantine=True, level="file"):
    """Walk the chain (pointer target first, then newest-first) and
    return the first checkpoint name that verifies; corrupt entries are
    quarantined as `*.corrupt` along the way (quarantine=False only
    inspects). Returns None when nothing valid remains. Defaults to the
    cheap file-level screen (size + whole-file CRC) — callers that will
    LOAD the result (resume()) re-verify fully anyway."""
    for name in _candidates(dirname):
        d = os.path.join(dirname, name)
        try:
            verify_checkpoint(d, level=level)
            return name
        except CheckpointCorruptError as e:
            if quarantine:
                _quarantine(d, str(e))
    return None


def load_checkpoint(dirname, scope=None, data_state=None):
    """Restore the newest VALID checkpoint into the scope, walking back
    past corrupt/torn entries (quarantining them); returns the step
    AFTER the checkpointed one (0 when nothing valid exists).

    `data_state` (anything with load_state_dict(), e.g. a
    dataio.DataEngine) additionally restores the input-iterator position
    the checkpoint recorded under the ``__dataio_state__`` array — the
    parameter half and the data half of training state come back from
    the SAME verified manifest, so a resumed run neither replays nor
    skips samples. Checkpoints written without data state leave the
    iterator untouched (legacy behavior)."""
    scope = scope or global_scope()
    for name in _candidates(dirname):
        d = os.path.join(dirname, name)
        try:
            step, arrays = verify_checkpoint(d)
        except CheckpointCorruptError as e:
            _quarantine(d, str(e))
            continue
        blob = arrays.pop(STATE_KEY, None)
        for n, a in arrays.items():
            scope.set(n, a)
        if data_state is not None and blob is not None:
            data_state.load_state_dict(decode_state(blob))
        return step + 1
    return 0


class AutoCheckpoint:
    """Periodic async checkpoints with auto-resume.

        ckpt = AutoCheckpoint(exe, program, dirname, save_interval_steps=100)
        start_step = ckpt.resume()          # 0 on a fresh run
        for step in range(start_step, n):
            exe.run(...)
            ckpt.maybe_save(step)
        ckpt.close()
    """

    def __init__(self, exe, program, dirname, save_interval_steps=100,
                 max_to_keep=3, scope=None, retry=None, data_state=None):
        self._exe = exe
        self._program = program
        self._dir = dirname
        self._interval = int(save_interval_steps)
        self._keep = int(max_to_keep)
        self._scope = scope
        self._data_state = data_state
        self._thread = None
        self._lock = threading.Lock()
        self._last_error = None
        self._pending = None  # (step, snap) of an in-flight/failed write
        self._retry = retry if retry is not None else _DEFAULT_IO_RETRY
        os.makedirs(dirname, exist_ok=True)

    # -- save ----------------------------------------------------------
    def _persistable_names(self):
        return [
            v.name
            for v in self._program.global_block().vars.values()
            if v.persistable
        ]

    def maybe_save(self, step, blocking=False):
        if (step + 1) % self._interval:
            return False
        self.save(step, blocking=blocking)
        return True

    def _write(self, step, snap):
        """The full crash-consistent write protocol: serialize + manifest
        into a tmp dir, atomic-rename the dir, then atomically swing the
        `latest` pointer. A crash at ANY point leaves either the old
        chain intact or a complete new entry the pointer doesn't name
        yet — both of which resume() handles."""
        d = os.path.join(self._dir, f"ckpt_{step}")
        tmp = d + ".tmp"

        def write_files():
            shutil.rmtree(tmp, ignore_errors=True)
            os.makedirs(tmp, exist_ok=True)
            # serialize in memory first so the whole-file CRC in the
            # manifest is computed from the exact bytes that hit disk
            buf = _io.BytesIO()
            np.savez(buf, **{k: v for k, v in snap.items()})
            raw = buf.getvalue()
            with open(os.path.join(tmp, "state.npz"), "wb") as f:
                f.write(raw)
                f.flush()
                os.fsync(f.fileno())
            # injected IO failure lands mid-protocol: state written, no
            # manifest yet — a retry restarts write_files from scratch,
            # a kill leaves classic torn-write debris in the .tmp dir
            faults.fire("checkpoint.io", step=step,
                        path=os.path.join(tmp, "state.npz"))
            manifest = {
                "format": 1,
                "step": step,
                "arrays": {
                    n: {
                        "crc32": array_crc32(a),
                        "dtype": str(np.asarray(a).dtype),
                        "shape": list(np.shape(a)),
                    }
                    for n, a in snap.items()
                },
                "files": {
                    "state.npz": {
                        "size": len(raw),
                        "crc32": zlib.crc32(raw) & 0xFFFFFFFF,
                    }
                },
            }
            with open(os.path.join(tmp, MANIFEST_NAME), "w") as f:
                json.dump(manifest, f)
            with open(os.path.join(tmp, "meta.json"), "w") as f:
                json.dump({"step": step, "time": time.time()}, f)

        self._retry.call(write_files)
        faults.fire("checkpoint.before_rename", step=step, path=tmp)
        shutil.rmtree(d, ignore_errors=True)
        os.replace(tmp, d)
        # the pointer update is the COMMIT point: resume() prefers the
        # pointer target, so a crash here simply leaves the previous
        # checkpoint committed; the complete new dir only gets used if
        # the pointer target itself is later lost or corrupt
        faults.fire("checkpoint.before_latest", step=step, path=d)
        ptr = os.path.join(self._dir, "latest.tmp")
        with open(ptr, "w") as f:
            f.write(f"ckpt_{step}")
        os.replace(ptr, os.path.join(self._dir, "latest"))
        self._gc()

    def save(self, step, blocking=False):
        """Snapshot device state NOW (cheap: device->host copies), write
        files on a background thread (the reference's checkpoint_notify is
        likewise fire-and-forget from the trainer's view)."""
        scope = self._scope or global_scope()
        snap = {}
        for n in self._persistable_names():
            v = scope.find_var(n)
            if v is not None:
                snap[n] = np.asarray(v)
        if self._data_state is not None:
            # the iterator position is snapshotted at the SAME instant as
            # the parameters, and rides the manifest (per-array CRC,
            # atomic rename) like any other array
            st = self._data_state.state_dict()
            if st is not None:  # e.g. a prefetcher over a stateless source
                snap[STATE_KEY] = encode_state(st)
        # one async writer at a time; a newer save supersedes a pending one
        self._join()
        if self._last_error is not None:
            err, self._last_error = self._last_error, None
            self._pending = None
            raise RuntimeError(
                f"previous async checkpoint write failed: {err}"
            )

        def guarded():
            try:
                self._write(step, snap)
                self._pending = None
            except Exception as e:  # surfaced on the NEXT save, or close()
                log.error("async checkpoint write failed: %s", e)
                self._last_error = e

        if blocking:
            self._pending = (step, snap)
            self._write(step, snap)
            self._pending = None
        else:
            self._pending = (step, snap)
            self._thread = threading.Thread(target=guarded, daemon=True)
            self._thread.start()

    def _gc(self):
        entries = os.listdir(self._dir)
        # clear debris from a save killed mid-write (quarantined
        # *.corrupt entries are kept — they are evidence, not debris)
        for d in entries:
            if d.endswith(".tmp"):
                shutil.rmtree(os.path.join(self._dir, d), ignore_errors=True)
        kept = sorted(
            (d for d in entries
             if d.startswith("ckpt_") and d.split("_", 1)[1].isdigit()),
            key=lambda d: int(d.split("_", 1)[1]),
        )
        for d in kept[: -self._keep]:
            shutil.rmtree(os.path.join(self._dir, d), ignore_errors=True)

    def _join(self):
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    def attach_data_state(self, provider):
        """Register a checkpointable iterator (state_dict/load_state_dict,
        e.g. dataio.DataEngine): subsequent saves snapshot its position
        and resume() restores it."""
        self._data_state = provider
        return self

    # -- resume ----------------------------------------------------------
    def resume(self):
        """Restore the newest VALID checkpoint into the scope (verifying
        CRCs, walking back past corrupt/torn entries and quarantining
        them as *.corrupt); returns the step AFTER the checkpointed one
        (0 on a fresh start). An attached data_state gets its iterator
        position restored from the same checkpoint."""
        return load_checkpoint(self._dir, scope=self._scope or global_scope(),
                               data_state=self._data_state)

    def close(self):
        """Join the async writer and SURFACE its failure (a failed last
        write used to be silently dropped here). When the failed
        snapshot is still pending, retry it as a final blocking save
        first — only raise when the state truly could not be persisted."""
        self._join()
        if self._last_error is not None:
            err, self._last_error = self._last_error, None
            if self._pending is not None:
                step, snap = self._pending
                try:
                    self._write(step, snap)
                    self._pending = None
                    log.warning(
                        "final blocking save of step %d recovered the "
                        "failed async write (%s)", step, err,
                    )
                    return
                except Exception as e2:
                    raise RuntimeError(
                        f"async checkpoint write failed: {err}; final "
                        f"blocking save also failed: {e2}"
                    )
            raise RuntimeError(f"async checkpoint write failed: {err}")


class HeartBeatMonitor:
    """Chief-side worker-lost detection over the PS heartbeat table
    (reference: paddle/fluid/operators/distributed/heart_beat_monitor.h:54 —
    UNINITED/RUNNING/COMPLETED per worker, lost workers logged).

        mon = HeartBeatMonitor(client, worker_id=0, worker_num=2,
                               timeout=5.0, on_lost=callback)
        mon.start();  ...  mon.stop()
    """

    def __init__(self, client, worker_id, worker_num, timeout=30.0,
                 period=1.0, on_lost=None):
        self._client = client
        self._id = int(worker_id)
        self._n = int(worker_num)
        self._timeout = float(timeout)
        self._period = float(period)
        self._on_lost = on_lost
        self._stop = threading.Event()
        self._thread = None
        self._seen = set()
        self.lost = set()

    def _loop(self):
        hb_log = logging.getLogger("paddle_tpu.heartbeat")
        start = time.monotonic()
        while not self._stop.is_set():
            try:
                ages = self._client.heartbeat(self._id)
            except Exception as e:  # server gone: report and stop
                hb_log.warning("heartbeat RPC failed: %s", e)
                break
            self._seen.update(ages)
            # a worker that NEVER heartbeats (died during startup) has no
            # server entry — treat absence past the grace window as lost
            # (the reference's UNINITED state, heart_beat_monitor.h:38)
            elapsed = time.monotonic() - start
            for wid in range(self._n):
                if wid == self._id or wid in ages or wid in self._seen:
                    continue
                if elapsed > self._timeout:
                    ages = dict(ages)
                    ages[wid] = elapsed
            for wid, age in ages.items():
                if age > self._timeout and wid not in self.lost:
                    self.lost.add(wid)
                    hb_log.warning(
                        "worker %d LOST: no heartbeat for %.1fs "
                        "(timeout %.1fs)", wid, age, self._timeout,
                    )
                    if self._on_lost is not None:
                        self._on_lost(wid, age)
            self._stop.wait(self._period)

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
