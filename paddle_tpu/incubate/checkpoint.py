"""Automatic checkpoint + resume: the recovery story (SURVEY §5.3).

The reference's recovery is checkpoint-based, not elastic: pservers
snapshot on checkpoint_notify (reference: paddle/fluid/operators/
distributed_ops/checkpoint_notify_op.cc) and jobs restart from the last
save (reference: python/paddle/fluid/io.py:405 _save_distributed
_persistables). TPU-native version: JAX multi-host failure = job restart,
so the unit of recovery is (persistable state + step counter) written
ASYNCHRONOUSLY (device->host snapshot on the training thread, file IO on a
background thread — the chip never waits for the disk) with an atomic
`latest` pointer, plus `resume()` on restart.

Crash consistency (the CheckFreq/Check-N-Run recipe): every checkpoint
directory carries a manifest with a CRC32 per array AND of the state
file itself, written BEFORE the atomic rename — so a torn write, a bad
disk, or a fault-injected corruption is DETECTED at resume time instead
of silently loading garbage. `resume()` verifies the `latest` target and
walks back the checkpoint chain past corrupt/torn entries, quarantining
them as `<name>.corrupt` for forensics. File IO runs under the shared
retry policy (resilience/retry.py), and the write path is fault-
injection instrumented (sites `checkpoint.io`,
`checkpoint.before_rename`, `checkpoint.before_latest`) so tests and
tools/chaos_train.py can rehearse every failure point deterministically.

Sharded checkpoints (manifest format 2, PR 7): a scope value that is a
mesh-sharded jax.Array is snapshotted PER SHARD — each unique device
shard is copied device->host individually and written to the host's
``shards_p<process>.npz``, so saving never gathers a full weight onto
one host (the gather was the restart-at-scale bottleneck ROADMAP item 1
names: O(model) host RAM + a cross-host collective per array). The
manifest records every shard's slice bounds and CRC32 under the same
scheme as whole arrays; a corrupt SHARD therefore walks the chain back
exactly like a corrupt array. On load, `load_checkpoint(shardings=...)`
rebuilds each array shard-wise with `jax.make_array_from_callback`
against the TARGET sharding: restoring onto a different mesh
factorization (N -> M shards) stitches the requested slices from the
stored blocks — still no full-array host materialization for arrays the
target keeps sharded, and bit-identical values either way (shards are
exact slices). Replicated/single-device values keep the format-1 path
byte-for-byte.
"""

import io as _io
import json
import logging
import os
import shutil
import threading
import time
import zlib

import numpy as np

from paddle_tpu.core.scope import global_scope
from paddle_tpu.dataio.state import STATE_KEY, decode_state, encode_state
from paddle_tpu.io import array_crc32
from paddle_tpu.observability import lockdep
from paddle_tpu.resilience import faults
from paddle_tpu.resilience.retry import RetryPolicy

# The save path's declared hierarchy: the checkpoint writer-state lock
# ("manifest") is ABOVE the sharded stores it snapshots through
# extra_state.checkpoint_arrays() (the embedding host tier + its
# pending-marker map) — never take manifest state while holding a shard
# store, and never hold "checkpoint.manifest" across the (blocking)
# flush itself.
lockdep.declare_order("checkpoint.manifest", "embedding.table",
                      "embedding.pending")

__all__ = [
    "AutoCheckpoint",
    "HeartBeatMonitor",
    "CheckpointCorruptError",
    "ShardedArray",
    "snapshot_value",
    "verify_checkpoint",
    "newest_valid_checkpoint",
    "load_checkpoint",
    "load_data_state",
    "gang_generations",
    "GANG_GENERATION_ENV",
]

log = logging.getLogger("paddle_tpu.checkpoint")

MANIFEST_NAME = "manifest.json"
# Injected by resilience/elastic.py's ElasticGangSupervisor: a
# monotonically increasing gang-generation counter, stamped into every
# manifest + meta.json this process writes so the checkpoint chain
# records WHICH gang incarnation (and therefore which world size /
# shard geometry) produced each entry.
GANG_GENERATION_ENV = "PADDLE_ELASTIC_GANG_GENERATION"
_DEFAULT_IO_RETRY = RetryPolicy(max_attempts=3, base_delay_s=0.02,
                                max_delay_s=0.5)


class CheckpointCorruptError(RuntimeError):
    """A checkpoint directory failed integrity verification."""


# ---------------------------------------------------------------------------
# sharded values (manifest format 2)
# ---------------------------------------------------------------------------


def _spec_str(sharding):
    try:
        return str(getattr(sharding, "spec", sharding))
    except Exception:
        return ""


class _ShardSnap:
    """Save-side snapshot of a mesh-sharded array: one host block per
    UNIQUE shard index (replicas dedupe), each copied device->host
    individually — the whole array never materializes on one host."""

    __slots__ = ("shape", "dtype", "spec", "blocks")

    def __init__(self, shape, dtype, spec, blocks):
        self.shape = tuple(int(d) for d in shape)
        self.dtype = str(dtype)
        self.spec = spec
        self.blocks = blocks  # [(start tuple, stop tuple, np.ndarray)]


def _normalize_index(index, shape):
    """jax shard index (tuple of slices) -> (start, stop) int tuples."""
    start, stop = [], []
    for sl, dim in zip(index, shape):
        s = 0 if sl.start is None else int(sl.start)
        e = int(dim) if sl.stop is None else int(sl.stop)
        start.append(s)
        stop.append(e)
    return tuple(start), tuple(stop)


def snapshot_value(value):
    """np.ndarray for host/replicated/single-device values (the format-1
    path, byte-identical), _ShardSnap for genuinely sharded jax.Arrays —
    per-shard device->host copies, no gather."""
    try:
        import jax
    except ImportError:
        return np.asarray(value)
    if not isinstance(value, jax.Array):
        return np.asarray(value)
    try:
        shards = value.addressable_shards
    except Exception:
        return np.asarray(value)
    shape = tuple(value.shape)
    seen = {}
    for sh in shards:
        key = _normalize_index(sh.index, shape)
        if key not in seen:
            seen[key] = sh
    if len(seen) <= 1:
        # replicated or single-device: one block IS the array
        return np.asarray(value)
    blocks = [
        (start, stop, np.asarray(sh.data))
        for (start, stop), sh in sorted(seen.items())
    ]
    return _ShardSnap(shape, value.dtype, _spec_str(value.sharding), blocks)


class ShardedArray:
    """Load-side view over verified shard blocks: assembles the full
    array on demand, or rebuilds a jax.Array shard-wise against a TARGET
    sharding (``to_jax``) — each requested device shard is stitched from
    the overlapping stored blocks, so an N-shard save restores onto an
    M-shard mesh without a full host materialization."""

    __slots__ = ("name", "shape", "dtype", "spec", "blocks")

    def __init__(self, name, shape, dtype, spec, blocks):
        self.name = name
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)
        self.spec = spec
        self.blocks = blocks

    def read_slice(self, start, stop):
        """Stitch an arbitrary [start, stop) box from the stored blocks;
        incomplete coverage is corruption (a missing shard)."""
        out_shape = tuple(e - s for s, e in zip(start, stop))
        out = np.empty(out_shape, self.dtype)
        covered = 0
        for bstart, bstop, data in self.blocks:
            lo = tuple(max(s, bs) for s, bs in zip(start, bstart))
            hi = tuple(min(e, be) for e, be in zip(stop, bstop))
            if any(l >= h for l, h in zip(lo, hi)):
                continue
            dst = tuple(
                slice(l - s, h - s) for l, h, s in zip(lo, hi, start)
            )
            src = tuple(
                slice(l - bs, h - bs) for l, h, bs in zip(lo, hi, bstart)
            )
            out[dst] = data[src]
            n = 1
            for l, h in zip(lo, hi):
                n *= h - l
            covered += n
        want = 1
        for d in out_shape:
            want *= d
        if covered < want:
            raise CheckpointCorruptError(
                f"sharded array '{self.name}': slice {start}..{stop} only "
                f"{covered}/{want} elements covered by stored shards"
            )
        return out

    def assemble(self):
        return self.read_slice((0,) * len(self.shape), self.shape)

    def to_jax(self, sharding):
        """Rebuild on device against ``sharding`` shard-wise — only this
        host's addressable target shards are materialized."""
        import jax

        return jax.make_array_from_callback(
            self.shape, sharding,
            lambda idx: self.read_slice(
                *_normalize_index(idx, self.shape)
            ),
        )


def _shard_key(name, i):
    return f"{name}::{i}"


def _process_index():
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0


def _process_count():
    try:
        import jax

        return jax.process_count()
    except Exception:
        return 1


def _write_shard_file(tmp, sharded, pid, write_index):
    """Write one host's ``shards_p<pid>.npz`` (+ fsync) and, for
    non-chief hosts, the ``shards_p<pid>.index.json`` sidecar the chief
    merges into the manifest: {file: {size, crc32}, sharded: {name:
    {dtype, shape, spec, shards: [...]}}}. Returns (files_entry,
    sharded_entries) for the caller's own bookkeeping."""
    shard_file = f"shards_p{pid}.npz"
    entries = {}
    sharded_entries = {}
    for n, s in sharded.items():
        shard_list = []
        for i, (start, stop, data) in enumerate(s.blocks):
            key = _shard_key(n, i) + f"@p{pid}"
            entries[key] = data
            shard_list.append({
                "file": shard_file,
                "key": key,
                "start": list(start),
                "stop": list(stop),
                "crc32": array_crc32(data),
                "nbytes": int(data.nbytes),
            })
        sharded_entries[n] = {
            "dtype": s.dtype,
            "shape": list(s.shape),
            "spec": s.spec,
            "shards": shard_list,
        }
    files_entry = {}
    if entries:
        sbuf = _io.BytesIO()
        np.savez(sbuf, **entries)
        sraw = sbuf.getvalue()
        with open(os.path.join(tmp, shard_file), "wb") as f:
            f.write(sraw)
            f.flush()
            os.fsync(f.fileno())
        files_entry[shard_file] = {
            "size": len(sraw),
            "crc32": zlib.crc32(sraw) & 0xFFFFFFFF,
        }
    if write_index:
        # the index commits via atomic rename: the chief's merge poll
        # must never read a half-written json
        idx = {"files": files_entry, "sharded": sharded_entries}
        ipath = os.path.join(tmp, f"shards_p{pid}.index.json")
        with open(ipath + ".tmp", "w") as f:
            json.dump(idx, f)
        os.replace(ipath + ".tmp", ipath)
    return files_entry, sharded_entries


def _merge_host_indices(tmp, world, files, sharded_manifest,
                        timeout=None):
    """Chief-side merge (PR 7's remaining note): fold every non-chief
    host's shard index into the manifest, so the manifest names EVERY
    host's shard file and blocks. A host whose index never appears
    raises — the save fails loudly instead of committing a manifest
    that silently thins coverage; a host's shard file that later goes
    missing fails verify_checkpoint the same way (the manifest lists
    it)."""
    if timeout is None:
        timeout = float(os.environ.get("PADDLE_TPU_CKPT_MERGE_TIMEOUT",
                                       "120"))
    deadline = time.monotonic() + timeout
    for k in range(1, world):
        ipath = os.path.join(tmp, f"shards_p{k}.index.json")
        idx = None
        while idx is None:
            if os.path.exists(ipath):
                with open(ipath) as f:
                    candidate = json.load(f)
                # the sidecar must describe the npz bytes ON DISK — a
                # stale index from a crashed earlier attempt at this
                # step (or a mid-rewrite window) mismatches and keeps
                # polling until the host republishes (index is renamed
                # into place AFTER the npz, so a matching pair is a
                # complete publication)
                ok = True
                for fname, finfo in candidate.get("files", {}).items():
                    fpath = os.path.join(tmp, fname)
                    if (not os.path.exists(fpath)
                            or os.path.getsize(fpath) != finfo["size"]):
                        ok = False
                        break
                    with open(fpath, "rb") as f:
                        crc = zlib.crc32(f.read()) & 0xFFFFFFFF
                    if crc != finfo["crc32"]:
                        ok = False
                        break
                if ok:
                    idx = candidate
                    break
            if time.monotonic() > deadline:
                raise CheckpointCorruptError(
                    f"multi-host checkpoint: host {k}/{world} never "
                    f"published a consistent "
                    f"{os.path.basename(ipath)} within {timeout:.0f}s "
                    "— refusing to commit a manifest with thinned "
                    "shard coverage"
                )
            time.sleep(0.05)
        files.update(idx.get("files", {}))
        for name, info in idx.get("sharded", {}).items():
            cur = sharded_manifest.get(name)
            if cur is None:
                sharded_manifest[name] = {
                    "dtype": info["dtype"],
                    "shape": list(info["shape"]),
                    "spec": info.get("spec"),
                    "shards": list(info["shards"]),
                }
                continue
            if (cur["dtype"] != info["dtype"]
                    or list(cur["shape"]) != list(info["shape"])):
                raise CheckpointCorruptError(
                    f"multi-host checkpoint: host {k} disagrees on "
                    f"'{name}' ({info['dtype']}{info['shape']} vs "
                    f"{cur['dtype']}{cur['shape']})"
                )
            cur["shards"].extend(info["shards"])
        # NOTE: the sidecar stays on disk here — write_files runs under
        # the retry policy, and a retry must be able to re-read it; the
        # caller removes sidecars after the whole protocol succeeds


def _ckpt_step(name):
    tail = name.split("_", 1)[1] if "_" in name else ""
    return int(tail) if tail.isdigit() else None


def verify_checkpoint(dirname, level="full", assemble=True):
    """Integrity-check one checkpoint directory; returns (step, arrays)
    — arrays is None at level="file" — or raises CheckpointCorruptError
    naming exactly what is wrong.

    Checks, outside-in: meta/state files present -> whole-file CRC +
    size for EVERY manifest-listed file (state.npz and any
    shards_p*.npz) -> (level="full" only) npz readable -> per-array and
    per-shard CRC32. Each file is read ONCE; arrays are parsed from the
    same bytes the CRC covered. level="file" stops after the whole-file
    checks — the cheap pre-relaunch screen the supervisor uses, while
    the relaunched worker's resume() re-verifies fully. Pre-manifest
    (legacy) checkpoints pass on readability alone.

    ``assemble=False`` returns format-2 sharded entries as
    ``ShardedArray`` views (shard blocks CRC-verified, full array NOT
    materialized) — the no-gather path load_checkpoint uses; the default
    assembles everything to numpy for plain callers."""
    state_p = os.path.join(dirname, "state.npz")
    meta_p = os.path.join(dirname, "meta.json")
    man_p = os.path.join(dirname, MANIFEST_NAME)
    for p in (state_p, meta_p):
        if not os.path.exists(p):
            raise CheckpointCorruptError(f"{dirname}: missing {os.path.basename(p)}")
    try:
        with open(meta_p) as f:
            meta = json.load(f)
        step = int(meta["step"])
    except (ValueError, TypeError, KeyError, json.JSONDecodeError) as e:
        raise CheckpointCorruptError(f"{dirname}: bad meta.json ({e})")
    manifest = None
    file_bytes = {}  # fname -> raw bytes (only files the manifest CRCs)
    if os.path.exists(man_p):
        try:
            with open(man_p) as f:
                manifest = json.load(f)
        except (ValueError, json.JSONDecodeError) as e:
            raise CheckpointCorruptError(f"{dirname}: bad manifest ({e})")
        for fname, finfo in manifest.get("files", {}).items():
            fpath = os.path.join(dirname, fname)
            if not os.path.exists(fpath):
                raise CheckpointCorruptError(
                    f"{dirname}: missing {fname} (manifest lists it)"
                )
            size = os.path.getsize(fpath)
            if "size" in finfo and size != finfo["size"]:
                raise CheckpointCorruptError(
                    f"{dirname}: {fname} is {size} bytes, manifest says "
                    f"{finfo['size']} (torn write)"
                )
            if "crc32" in finfo:
                with open(fpath, "rb") as f:
                    raw = f.read()
                crc = zlib.crc32(raw) & 0xFFFFFFFF
                if crc != finfo["crc32"]:
                    raise CheckpointCorruptError(
                        f"{dirname}: {fname} CRC {crc:#x} != manifest "
                        f"{finfo['crc32']:#x}"
                    )
                file_bytes[fname] = raw
    if level == "file":
        return step, None
    arrays = {}
    raw = file_bytes.get("state.npz")
    try:
        with np.load(_io.BytesIO(raw) if raw is not None else state_p) as z:
            for n in z.files:
                arrays[n] = z[n]
    except Exception as e:
        raise CheckpointCorruptError(f"{dirname}: unreadable state.npz ({e})")
    if manifest is not None:
        want = manifest.get("arrays", {})
        missing = sorted(set(want) - set(arrays))
        if missing:
            raise CheckpointCorruptError(
                f"{dirname}: arrays missing from state.npz: {missing[:5]}"
            )
        for n, info in want.items():
            crc = array_crc32(arrays[n])
            if crc != info["crc32"]:
                raise CheckpointCorruptError(
                    f"{dirname}: array '{n}' CRC {crc:#x} != manifest "
                    f"{info['crc32']:#x}"
                )
        # format-2 sharded entries: load each shard file once, CRC every
        # block, and hand back ShardedArray views (or assembled numpy).
        # finally-close so a CRC/coverage failure mid-walk-back does not
        # leak open npz handles
        shard_zips = {}
        try:
            for name, info in manifest.get("sharded", {}).items():
                blocks = []
                for i, sh in enumerate(info.get("shards", [])):
                    fname = sh["file"]
                    z = shard_zips.get(fname)
                    if z is None:
                        braw = file_bytes.get(fname)
                        fpath = os.path.join(dirname, fname)
                        try:
                            z = np.load(
                                _io.BytesIO(braw) if braw is not None
                                else fpath
                            )
                        except Exception as e:
                            raise CheckpointCorruptError(
                                f"{dirname}: unreadable {fname} ({e})"
                            )
                        shard_zips[fname] = z
                    key = sh.get("key", _shard_key(name, i))
                    if key not in z.files:
                        raise CheckpointCorruptError(
                            f"{dirname}: shard '{key}' missing from {fname}"
                        )
                    data = z[key]
                    crc = array_crc32(data)
                    if crc != sh["crc32"]:
                        raise CheckpointCorruptError(
                            f"{dirname}: shard '{key}' CRC {crc:#x} != "
                            f"manifest {sh['crc32']:#x}"
                        )
                    blocks.append(
                        (tuple(sh["start"]), tuple(sh["stop"]), data)
                    )
                total = sum(int(np.prod(b[2].shape)) for b in blocks)
                want = int(np.prod(info["shape"])) if info["shape"] else 1
                if total != want:
                    raise CheckpointCorruptError(
                        f"{dirname}: sharded array '{name}' blocks cover "
                        f"{total}/{want} elements"
                    )
                view = ShardedArray(
                    name, info["shape"], info["dtype"], info.get("spec"),
                    blocks,
                )
                if assemble:
                    arrays[name] = view.assemble()
                else:
                    arrays[name] = view
        finally:
            for z in shard_zips.values():
                z.close()
    return step, arrays


def _quarantine(dirname, reason):
    """Rename a corrupt checkpoint out of the chain (never delete — a
    human may want the bytes). Idempotent against name collisions."""
    target = dirname + ".corrupt"
    n = 0
    while os.path.exists(target):
        n += 1
        target = f"{dirname}.corrupt{n}"
    try:
        os.replace(dirname, target)
        log.error("quarantined corrupt checkpoint %s -> %s (%s)",
                  dirname, target, reason)
    except OSError as e:
        log.error("could not quarantine %s: %s", dirname, e)
    return target


def _candidates(dirname):
    """Checkpoint names to try, best first: the `latest` pointer target,
    then every other ckpt_<step> newest-first (the fallback chain)."""
    try:
        entries = os.listdir(dirname)
    except OSError:
        return []
    chain = sorted(
        (d for d in entries
         if d.startswith("ckpt_") and _ckpt_step(d) is not None),
        key=_ckpt_step, reverse=True,
    )
    ptr = os.path.join(dirname, "latest")
    if os.path.exists(ptr):
        try:
            with open(ptr) as f:
                name = f.read().strip()
        except OSError:
            name = ""
        if name in chain:
            chain.remove(name)
            chain.insert(0, name)
    return chain


def newest_valid_checkpoint(dirname, quarantine=True, level="file"):
    """Walk the chain (pointer target first, then newest-first) and
    return the first checkpoint name that verifies; corrupt entries are
    quarantined as `*.corrupt` along the way (quarantine=False only
    inspects). Returns None when nothing valid remains. Defaults to the
    cheap file-level screen (size + whole-file CRC) — callers that will
    LOAD the result (resume()) re-verify fully anyway."""
    for name in _candidates(dirname):
        d = os.path.join(dirname, name)
        try:
            verify_checkpoint(d, level=level)
            return name
        except CheckpointCorruptError as e:
            if quarantine:
                _quarantine(d, str(e))
    return None


def load_checkpoint(dirname, scope=None, data_state=None, shardings=None,
                    extra_state=None, step=None):
    """Restore the newest VALID checkpoint into the scope, walking back
    past corrupt/torn entries (quarantining them); returns the step
    AFTER the checkpointed one (0 when nothing valid exists).

    ``step`` pins the restore to exactly ``ckpt_<step>`` — the elastic
    resume contract: a resized gang must come back from the SYNC
    checkpoint its supervisor validated, identically on every rank, so
    a rank that silently walked back to a different entry would desync
    the gang's data stream. A pinned entry that is missing or fails
    verification is quarantined and raises ``CheckpointCorruptError``
    (the worker exits nonzero; the supervisor re-validates and picks a
    new sync step) instead of falling back.

    `data_state` (anything with load_state_dict(), e.g. a
    dataio.DataEngine) additionally restores the input-iterator position
    the checkpoint recorded under the ``__dataio_state__`` array — the
    parameter half and the data half of training state come back from
    the SAME verified manifest, so a resumed run neither replays nor
    skips samples. Checkpoints written without data state leave the
    iterator untouched (legacy behavior).

    `shardings` maps var name -> jax sharding (e.g. a SpecLayout's
    derive_shardings result): format-2 sharded entries restore
    SHARD-WISE onto the target sharding via device_put-per-shard
    (jax.make_array_from_callback) — no full host materialization, and
    the target mesh may factor differently than the saving one (N -> M
    resharding stitches slices from the stored blocks, bit-exactly).
    Sharded entries without a target sharding assemble to numpy.

    `extra_state` (anything with owns(name)/restore_arrays(dict) plus
    checkpoint_arrays() on the save side, e.g. an
    embedding.EmbeddingEngine) claims its namespaced arrays — names
    carrying a "::" marker are provider state, never scope variables —
    and restores from them after the scope is populated. With no
    provider attached, provider arrays are skipped, not leaked into the
    scope."""
    scope = scope or global_scope()
    shardings = shardings or {}
    if step is not None:
        name = f"ckpt_{int(step)}"
        d = os.path.join(dirname, name)
        if not os.path.isdir(d):
            raise CheckpointCorruptError(
                f"{dirname}: pinned checkpoint {name} does not exist"
            )
        candidates = [name]
    else:
        candidates = _candidates(dirname)
    for name in candidates:
        d = os.path.join(dirname, name)
        try:
            got_step, arrays = verify_checkpoint(d, assemble=False)
            blob = arrays.pop(STATE_KEY, None)
            restored, extra = {}, {}
            for n, a in arrays.items():
                if extra_state is not None and extra_state.owns(n):
                    extra[n] = a.assemble() if isinstance(a, ShardedArray) \
                        else a
                    continue
                if "::" in n:
                    continue  # provider namespace, no provider attached
                if isinstance(a, ShardedArray):
                    sh = shardings.get(n)
                    restored[n] = a.to_jax(sh) if sh is not None \
                        else a.assemble()
                else:
                    restored[n] = a
        except CheckpointCorruptError as e:
            _quarantine(d, str(e))
            if step is not None:
                raise CheckpointCorruptError(
                    f"{dirname}: pinned checkpoint {name} failed "
                    f"verification ({e}); quarantined — refusing to "
                    "fall back past an elastic sync point"
                )
            continue
        for n, a in restored.items():
            scope.set(n, a)
        if data_state is not None and blob is not None:
            data_state.load_state_dict(decode_state(blob))
        if extra_state is not None:
            extra_state.restore_arrays(extra)
        return got_step + 1
    return 0


def load_data_state(dirname, step=None):
    """Read ONLY the data-position blob (``__dataio_state__``) from a
    checkpoint, without touching any scope: the decoded state dict, or
    None when the checkpoint carries no data state. ``step`` pins the
    entry exactly like ``load_checkpoint``; without it the newest VALID
    entry is consulted (corrupt entries are quarantined on the walk).

    This is the grown-rank half of an elastic resume: a rank joining a
    gang mid-job has no checkpoint of its own at the sync step, so it
    pulls the CHIEF's data blob, and ``DataEngine(elastic=True)``
    translates the recorded geometry onto its new (world, rank).
    Verification runs with ``assemble=False``: the blob is a small
    plain array, so a multi-GB sharded model is never materialized on
    the joining host just to read a cursor."""
    if step is not None:
        name = f"ckpt_{int(step)}"
        d = os.path.join(dirname, name)
        if not os.path.isdir(d):
            raise CheckpointCorruptError(
                f"{dirname}: pinned checkpoint {name} does not exist"
            )
        try:
            _, arrays = verify_checkpoint(d, assemble=False)
        except CheckpointCorruptError as e:
            # same contract as load_checkpoint's pinned branch: the bad
            # entry leaves the chain so the supervisor's next sync walk
            # stops seeing it, and the failure stays loud
            _quarantine(d, str(e))
            raise CheckpointCorruptError(
                f"{dirname}: pinned checkpoint {name} failed "
                f"verification ({e}); quarantined"
            )
        blob = arrays.get(STATE_KEY)
        return decode_state(blob) if blob is not None else None
    for name in _candidates(dirname):
        d = os.path.join(dirname, name)
        try:
            _, arrays = verify_checkpoint(d, assemble=False)
        except CheckpointCorruptError as e:
            _quarantine(d, str(e))
            continue
        blob = arrays.get(STATE_KEY)
        return decode_state(blob) if blob is not None else None
    return None


def gang_generations(dirname):
    """[(step, gang_generation)] for every committed ``ckpt_<step>`` in
    the directory, sorted by step; generation is None for entries
    written outside an elastic supervisor. The elastic property gate
    asserts this sequence is monotonically non-decreasing — a
    generation that moved BACKWARDS would mean a stale gang incarnation
    wrote over a newer one's chain."""
    out = []
    try:
        entries = os.listdir(dirname)
    except OSError:
        return out
    for name in entries:
        if not (name.startswith("ckpt_") and _ckpt_step(name) is not None):
            continue
        man_p = os.path.join(dirname, name, MANIFEST_NAME)
        gen = None
        try:
            with open(man_p) as f:
                gen = json.load(f).get("gang_generation")
        except (OSError, ValueError, json.JSONDecodeError):
            pass
        out.append((_ckpt_step(name), gen))
    return sorted(out)


class AutoCheckpoint:
    """Periodic async checkpoints with auto-resume.

        ckpt = AutoCheckpoint(exe, program, dirname, save_interval_steps=100)
        start_step = ckpt.resume()          # 0 on a fresh run
        for step in range(start_step, n):
            exe.run(...)
            ckpt.maybe_save(step)
        ckpt.close()
    """

    def __init__(self, exe, program, dirname, save_interval_steps=100,
                 max_to_keep=3, scope=None, retry=None, data_state=None,
                 extra_state=None, gang_generation=None):
        self._exe = exe
        self._program = program
        self._dir = dirname
        self._interval = int(save_interval_steps)
        self._keep = int(max_to_keep)
        self._scope = scope
        self._data_state = data_state
        self._extra_state = extra_state
        # explicit value wins; else the elastic supervisor's env
        # injection (GANG_GENERATION_ENV); else unstamped (byte-compat)
        self._gang_generation = gang_generation
        self._thread = None
        # guards _last_error/_pending: the async writer thread sets them
        # while save()/close() on the training thread read-and-clear
        # (found by the r11 concurrency audit — the lock existed but
        # nothing acquired it)
        self._lock = lockdep.named_lock("checkpoint.manifest")
        self._last_error = None
        self._pending = None  # (step, snap) of an in-flight/failed write
        self._retry = retry if retry is not None else _DEFAULT_IO_RETRY
        os.makedirs(dirname, exist_ok=True)

    # -- save ----------------------------------------------------------
    def _persistable_names(self):
        return [
            v.name
            for v in self._program.global_block().vars.values()
            if v.persistable
        ]

    def maybe_save(self, step, blocking=False):
        if (step + 1) % self._interval:
            return False
        self.save(step, blocking=blocking)
        return True

    def _generation(self):
        """gang-generation to stamp, or None: ctor value, else the
        elastic supervisor's env injection (read at write time so a
        long-lived process restamped by a resize picks it up)."""
        if self._gang_generation is not None:
            return int(self._gang_generation)
        env = os.environ.get(GANG_GENERATION_ENV)
        try:
            return int(env) if env is not None else None
        except ValueError:
            log.warning("ignoring non-integer %s=%r",
                        GANG_GENERATION_ENV, env)
            return None

    def _write(self, step, snap):
        """The full crash-consistent write protocol: serialize + manifest
        into a tmp dir, atomic-rename the dir, then atomically swing the
        `latest` pointer. A crash at ANY point leaves either the old
        chain intact or a complete new entry the pointer doesn't name
        yet — both of which resume() handles."""
        d = os.path.join(self._dir, f"ckpt_{step}")
        tmp = d + ".tmp"

        plain = {n: v for n, v in snap.items()
                 if not isinstance(v, _ShardSnap)}
        sharded = {n: v for n, v in snap.items()
                   if isinstance(v, _ShardSnap)}

        pid, world = _process_index(), _process_count()
        if pid != 0:
            # non-chief host: contribute this host's shard file + index
            # sidecar into the shared tmp dir and stop — the chief owns
            # state.npz, the (merged) manifest, meta, and the commit
            def write_host_shards():
                os.makedirs(tmp, exist_ok=True)
                _write_shard_file(tmp, sharded, pid, write_index=True)

            self._retry.call(write_host_shards)
            return

        def write_files():
            if world == 1:
                shutil.rmtree(tmp, ignore_errors=True)
            os.makedirs(tmp, exist_ok=True)
            # serialize in memory first so the whole-file CRC in the
            # manifest is computed from the exact bytes that hit disk
            buf = _io.BytesIO()
            np.savez(buf, **{k: v for k, v in plain.items()})
            raw = buf.getvalue()
            with open(os.path.join(tmp, "state.npz"), "wb") as f:
                f.write(raw)
                f.flush()
                os.fsync(f.fileno())
            files = {
                "state.npz": {
                    "size": len(raw),
                    "crc32": zlib.crc32(raw) & 0xFFFFFFFF,
                }
            }
            sharded_manifest = {}
            if sharded:
                # this host's shards, one npz per host (multi-controller
                # jobs write disjoint files; single-host writes all)
                files_entry, sharded_manifest = _write_shard_file(
                    tmp, sharded, 0, write_index=False
                )
                files.update(files_entry)
            if world > 1:
                # fold every other host's shard index into THIS manifest
                # (each host wrote its own shards_p<k>.npz above)
                _merge_host_indices(tmp, world, files, sharded_manifest)
            # injected IO failure lands mid-protocol: state written, no
            # manifest yet — a retry restarts write_files from scratch,
            # a kill leaves classic torn-write debris in the .tmp dir
            faults.fire("checkpoint.io", step=step,
                        path=os.path.join(tmp, "state.npz"))
            gen = self._generation()
            manifest = {
                "format": 2 if sharded_manifest else 1,
                "step": step,
                "arrays": {
                    n: {
                        "crc32": array_crc32(a),
                        "dtype": str(np.asarray(a).dtype),
                        "shape": list(np.shape(a)),
                    }
                    for n, a in plain.items()
                },
                "sharded": sharded_manifest,
                "files": files,
            }
            if not sharded_manifest:
                manifest.pop("sharded")
            if gen is not None:
                manifest["gang_generation"] = gen
            with open(os.path.join(tmp, MANIFEST_NAME), "w") as f:
                json.dump(manifest, f)
            meta = {"step": step, "time": time.time()}
            if gen is not None:
                meta["gang_generation"] = gen
            with open(os.path.join(tmp, "meta.json"), "w") as f:
                json.dump(meta, f)

        self._retry.call(write_files)
        # merged sidecars leave the tree only once every (possibly
        # retried) write_files pass is done — they are not part of the
        # committed checkpoint
        for k in range(1, world):
            try:
                os.remove(os.path.join(tmp, f"shards_p{k}.index.json"))
            except OSError:
                pass
        faults.fire("checkpoint.before_rename", step=step, path=tmp)
        shutil.rmtree(d, ignore_errors=True)
        os.replace(tmp, d)
        # the pointer update is the COMMIT point: resume() prefers the
        # pointer target, so a crash here simply leaves the previous
        # checkpoint committed; the complete new dir only gets used if
        # the pointer target itself is later lost or corrupt
        faults.fire("checkpoint.before_latest", step=step, path=d)
        ptr = os.path.join(self._dir, "latest.tmp")
        with open(ptr, "w") as f:
            f.write(f"ckpt_{step}")
        os.replace(ptr, os.path.join(self._dir, "latest"))
        self._gc()

    def save(self, step, blocking=False):
        """Snapshot device state NOW (cheap: device->host copies — one
        PER SHARD for mesh-sharded values, never a gather), write files
        on a background thread (the reference's checkpoint_notify is
        likewise fire-and-forget from the trainer's view)."""
        scope = self._scope or global_scope()
        snap = {}
        for n in self._persistable_names():
            v = scope.find_var(n)
            if v is not None:
                snap[n] = snapshot_value(v)
        if self._extra_state is not None:
            # e.g. an EmbeddingEngine: flushes its device hot cache to
            # the authoritative host tier, then hands back the tier as
            # per-shard _ShardSnap entries (the format-2 manifest path)
            snap.update(self._extra_state.checkpoint_arrays())
        if self._data_state is not None:
            # the iterator position is snapshotted at the SAME instant as
            # the parameters, and rides the manifest (per-array CRC,
            # atomic rename) like any other array
            st = self._data_state.state_dict()
            if st is not None:  # e.g. a prefetcher over a stateless source
                snap[STATE_KEY] = encode_state(st)
        # one async writer at a time; a newer save supersedes a pending one
        self._join()
        with self._lock:
            err, self._last_error = self._last_error, None
            if err is not None:
                self._pending = None
        if err is not None:
            raise RuntimeError(
                f"previous async checkpoint write failed: {err}"
            )

        def guarded():
            try:
                self._write(step, snap)
                with self._lock:
                    self._pending = None
            except Exception as e:  # surfaced on the NEXT save, or close()
                log.error("async checkpoint write failed: %s", e)
                with self._lock:
                    self._last_error = e

        if blocking:
            with self._lock:
                self._pending = (step, snap)
            self._write(step, snap)
            with self._lock:
                self._pending = None
        else:
            with self._lock:
                self._pending = (step, snap)
            self._thread = threading.Thread(target=guarded, daemon=True)
            self._thread.start()

    def _gc(self):
        entries = os.listdir(self._dir)
        # clear debris from a save killed mid-write (quarantined
        # *.corrupt entries are kept — they are evidence, not debris)
        for d in entries:
            if d.endswith(".tmp"):
                shutil.rmtree(os.path.join(self._dir, d), ignore_errors=True)
        kept = sorted(
            (d for d in entries
             if d.startswith("ckpt_") and d.split("_", 1)[1].isdigit()),
            key=lambda d: int(d.split("_", 1)[1]),
        )
        for d in kept[: -self._keep]:
            shutil.rmtree(os.path.join(self._dir, d), ignore_errors=True)

    def _join(self):
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    def attach_data_state(self, provider):
        """Register a checkpointable iterator (state_dict/load_state_dict,
        e.g. dataio.DataEngine): subsequent saves snapshot its position
        and resume() restores it."""
        self._data_state = provider
        return self

    def attach_extra_state(self, provider):
        """Register a namespaced state provider (checkpoint_arrays /
        owns / restore_arrays — e.g. embedding.EmbeddingEngine): saves
        snapshot its arrays alongside the scope's, resume() hands them
        back."""
        self._extra_state = provider
        return self

    # -- resume ----------------------------------------------------------
    def resume(self, shardings=None, step=None):
        """Restore the newest VALID checkpoint into the scope (verifying
        CRCs, walking back past corrupt/torn entries and quarantining
        them as *.corrupt); returns the step AFTER the checkpointed one
        (0 on a fresh start). An attached data_state gets its iterator
        position restored from the same checkpoint. ``shardings`` (name
        -> target sharding) restores format-2 sharded entries shard-wise
        with no full-array host materialization (see load_checkpoint).
        ``step`` pins the restore to exactly ``ckpt_<step>`` (the
        elastic sync contract — no silent walk-back; a bad pinned entry
        raises CheckpointCorruptError instead)."""
        return load_checkpoint(self._dir, scope=self._scope or global_scope(),
                               data_state=self._data_state,
                               shardings=shardings,
                               extra_state=self._extra_state, step=step)

    def close(self):
        """Join the async writer and SURFACE its failure (a failed last
        write used to be silently dropped here). When the failed
        snapshot is still pending, retry it as a final blocking save
        first — only raise when the state truly could not be persisted."""
        self._join()
        with self._lock:
            err, self._last_error = self._last_error, None
            pending = self._pending
        if err is not None:
            if pending is not None:
                step, snap = pending
                try:
                    self._write(step, snap)
                    with self._lock:
                        self._pending = None
                    log.warning(
                        "final blocking save of step %d recovered the "
                        "failed async write (%s)", step, err,
                    )
                    return
                except Exception as e2:
                    raise RuntimeError(
                        f"async checkpoint write failed: {err}; final "
                        f"blocking save also failed: {e2}"
                    )
            raise RuntimeError(f"async checkpoint write failed: {err}")


class HeartBeatMonitor:
    """Chief-side worker-lost detection over the PS heartbeat table
    (reference: paddle/fluid/operators/distributed/heart_beat_monitor.h:54 —
    UNINITED/RUNNING/COMPLETED per worker, lost workers logged).

        mon = HeartBeatMonitor(client, worker_id=0, worker_num=2,
                               timeout=5.0, on_lost=callback)
        mon.start();  ...  mon.stop()
    """

    def __init__(self, client, worker_id, worker_num, timeout=30.0,
                 period=1.0, on_lost=None):
        self._client = client
        self._id = int(worker_id)
        self._n = int(worker_num)
        self._timeout = float(timeout)
        self._period = float(period)
        self._on_lost = on_lost
        self._stop = threading.Event()
        self._thread = None
        self._seen = set()
        # guards `lost`: the monitor thread adds while callers read
        self._mu = lockdep.named_lock("resilience.heartbeat")
        self.lost = set()

    def _loop(self):
        hb_log = logging.getLogger("paddle_tpu.heartbeat")
        start = time.monotonic()
        while not self._stop.is_set():
            try:
                ages = self._client.heartbeat(self._id)
            except Exception as e:  # server gone: report and stop
                hb_log.warning("heartbeat RPC failed: %s", e)
                break
            self._seen.update(ages)
            # a worker that NEVER heartbeats (died during startup) has no
            # server entry — treat absence past the grace window as lost
            # (the reference's UNINITED state, heart_beat_monitor.h:38)
            elapsed = time.monotonic() - start
            for wid in range(self._n):
                if wid == self._id or wid in ages or wid in self._seen:
                    continue
                if elapsed > self._timeout:
                    ages = dict(ages)
                    ages[wid] = elapsed
            for wid, age in ages.items():
                with self._mu:
                    newly = age > self._timeout and wid not in self.lost
                    if newly:
                        self.lost.add(wid)
                if newly:
                    hb_log.warning(
                        "worker %d LOST: no heartbeat for %.1fs "
                        "(timeout %.1fs)", wid, age, self._timeout,
                    )
                    if self._on_lost is not None:
                        self._on_lost(wid, age)
            self._stop.wait(self._period)

    def start(self):
        # idempotent while the monitor is RUNNING, restartable once it
        # is not: a loop that self-terminated (heartbeat RPC failure)
        # leaves a dead _thread behind, and a stop() whose join timed
        # out keeps the stuck thread pinned here so start() cannot
        # clear _stop underneath it (which would revive it NEXT TO a
        # fresh one)
        if self._thread is not None:
            if self._thread.is_alive():
                return self
            self._thread = None
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            if not self._thread.is_alive():
                self._thread = None
