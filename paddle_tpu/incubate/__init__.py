from paddle_tpu.incubate import checkpoint  # noqa: F401
from paddle_tpu.incubate import data_generator  # noqa: F401
from paddle_tpu.incubate import fleet_utils  # noqa: F401
from paddle_tpu.incubate.fleet_utils import FleetUtil  # noqa: F401
