from paddle_tpu.incubate import checkpoint  # noqa: F401
