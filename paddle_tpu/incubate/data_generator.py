"""User-side dataset line parsers emitting the MultiSlot text format.

reference: python/paddle/fluid/incubate/data_generator/__init__.py:21
(DataGenerator base — users subclass, implement generate_sample(line)
returning an iterator of (slot_name, values) pairs; run_from_stdin pipes
raw lines in, MultiSlot text out). The output format is exactly what the
native datafeed parses (csrc/datafeed/datafeed.cc parse_line:
"per slot: <count> v0 v1 ..."), so generated files plug straight into
InMemoryDataset/QueueDataset.
"""

import sys

from paddle_tpu.utils.enforce import enforce

__all__ = ["DataGenerator", "MultiSlotDataGenerator"]


class DataGenerator:
    def __init__(self):
        self.batch_size_ = 32
        self._line_limit = None

    def set_batch(self, batch_size):
        self.batch_size_ = batch_size

    # -- to be provided by the subclass --------------------------------
    def generate_sample(self, line):
        """Return a callable yielding (slot_name, list-of-values) pairs for
        one raw input line (or None to drop the line)."""
        raise NotImplementedError(
            "implement generate_sample(line) in your DataGenerator subclass"
        )

    def generate_batch(self, samples):
        """Optional batch-level hook: receives the list of samples of one
        batch; yields processed samples. Default passthrough."""

        def local_iter():
            for s in samples:
                yield s

        return local_iter

    # -- drivers --------------------------------------------------------
    def _format(self, sample):
        """[(name, values), ...] -> MultiSlot text line."""
        parts = []
        for _name, values in sample:
            enforce(
                isinstance(values, (list, tuple)) and len(values) > 0,
                f"slot '{_name}' must carry a non-empty list of values",
            )
            parts.append(str(len(values)))
            parts.extend(str(v) for v in values)
        return " ".join(parts)

    def run_from_stdin(self, out=None):
        self._run(sys.stdin, out or sys.stdout)

    def run_from_file(self, path, out_path):
        with open(path) as fin, open(out_path, "w") as fout:
            self._run(fin, fout)

    def run_from_memory(self, lines, out=None):
        """Process an iterable of raw lines; returns the output lines when
        `out` is None."""
        collected = []

        class _Sink:
            def write(self, s):
                collected.append(s)

        self._run(iter(lines), out or _Sink())
        if out is None:
            return [l for l in "".join(collected).splitlines() if l]

    def _run(self, lines_in, out):
        batch = []
        n = 0
        for line in lines_in:
            it = self.generate_sample(line)
            if it is None:
                continue
            for sample in it():
                if sample is None:
                    continue
                batch.append(sample)
                if len(batch) >= self.batch_size_:
                    self._flush(batch, out)
                    batch = []
            n += 1
            if self._line_limit and n >= self._line_limit:
                break
        if batch:
            self._flush(batch, out)

    def _flush(self, batch, out):
        for sample in self.generate_batch(batch)():
            out.write(self._format(sample) + "\n")


class MultiSlotDataGenerator(DataGenerator):
    """Name kept for reference parity (reference: data_generator/
    __init__.py:282 MultiSlotDataGenerator — the MultiSlot text formatter
    is already the base behavior here)."""
