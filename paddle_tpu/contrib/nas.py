"""Neural architecture search: token search spaces + simulated-annealing
controller + a light-NAS driver.

reference: python/paddle/fluid/contrib/slim/nas/{search_space.py,
light_nas_strategy.py, search_agent.py} and contrib/slim/searcher/
controller.py SAController. The reference splits the loop across a
controller SERVER and socket search agents (multi-machine trials); here
trials run in-process on the Executor — each candidate is one jit-compiled
train/eval program, so a trial is one XLA compile + a short train, and the
annealing loop is plain Python around it. FLOPs constraints take the place
of the reference's latency lookup tables.
"""

import math

import numpy as np

__all__ = ["SearchSpace", "SAController", "light_nas_search"]


class SearchSpace:
    """Architecture search space contract (reference: nas/search_space.py).

    Subclasses define:
      init_tokens()  -> list[int]         starting architecture
      range_table()  -> list[int]         tokens[i] ranges over [0, table[i])
      create_net(tokens) -> (startup_program, train_program, eval_program,
                             train_fetch, eval_fetch)  — eval_fetch's first
                             element is the reward metric (higher = better)
    """

    def init_tokens(self):
        raise NotImplementedError("Abstract method.")

    def range_table(self):
        raise NotImplementedError("Abstract method.")

    def create_net(self, tokens):
        raise NotImplementedError("Abstract method.")


class SAController:
    """Simulated-annealing token controller (reference:
    slim/searcher/controller.py:59 SAController — same accept rule:
    accept if reward improves, else with prob exp(dr/T), T decaying by
    reduce_rate per iteration)."""

    def __init__(self, range_table=None, reduce_rate=0.85,
                 init_temperature=1024, max_iter_number=300, seed=0):
        self._range_table = range_table
        self._reduce_rate = reduce_rate
        self._init_temperature = init_temperature
        self._max_iter_number = max_iter_number
        self._rng = np.random.RandomState(seed)
        # -inf, not the reference's -1: rewards like -loss are routinely
        # below -1, and a -1 floor would leave best_tokens None forever
        self._reward = -np.inf
        self._tokens = None
        self._max_reward = -np.inf
        self._best_tokens = None
        self._constrain_func = None
        self._iter = 0

    def reset(self, range_table, init_tokens, constrain_func=None):
        self._range_table = list(range_table)
        self._tokens = list(init_tokens)
        self._constrain_func = constrain_func
        self._iter = 0

    def update(self, tokens, reward):
        self._iter += 1
        temperature = self._init_temperature * (
            self._reduce_rate ** self._iter
        )
        if reward > self._reward or self._rng.random_sample() <= math.exp(
            min((reward - self._reward) / max(temperature, 1e-9), 0.0)
        ):
            self._reward = reward
            self._tokens = list(tokens)
        if reward > self._max_reward:
            self._max_reward = reward
            self._best_tokens = list(tokens)

    def next_tokens(self, control_token=None):
        tokens = list(control_token or self._tokens)
        for _ in range(100):
            new_tokens = list(tokens)
            index = int(len(self._range_table) * self._rng.random_sample())
            r = self._range_table[index]
            if r > 1:
                new_tokens[index] = (
                    new_tokens[index] + self._rng.randint(r - 1) + 1
                ) % r
            if self._constrain_func is None or self._constrain_func(
                new_tokens
            ):
                return new_tokens
        return tokens  # constraint too tight: stay put

    @property
    def best_tokens(self):
        return self._best_tokens

    @property
    def max_reward(self):
        return self._max_reward


def light_nas_search(space, exe, train_feeds, eval_feeds, steps_per_trial=20,
                     search_steps=10, controller=None, constrain_func=None,
                     scope_factory=None):
    """Run the light-NAS loop (reference: nas/light_nas_strategy.py
    on_compression_begin): for `search_steps` rounds, materialize the
    candidate network, train it `steps_per_trial` steps, read the reward
    from the FIRST eval fetch, and anneal.

    train_feeds/eval_feeds: iterables of feed dicts (cycled).
    Returns (best_tokens, max_reward, history)."""
    from paddle_tpu.core.scope import Scope, scope_guard

    train_feeds = list(train_feeds)  # cycled + re-read every trial
    eval_feeds = list(eval_feeds)
    controller = controller or SAController()
    controller.reset(space.range_table(), space.init_tokens(),
                     constrain_func)
    history = []
    tokens = list(space.init_tokens())
    for step in range(search_steps):
        startup, train_prog, eval_prog, train_fetch, eval_fetch = \
            space.create_net(tokens)
        sc = scope_factory() if scope_factory else Scope()
        with scope_guard(sc):
            exe.run(startup)
            ti = 0
            for _ in range(steps_per_trial):
                feed = train_feeds[ti % len(train_feeds)]
                ti += 1
                exe.run(train_prog, feed=feed, fetch_list=list(train_fetch))
            rewards = []
            for feed in eval_feeds:
                out = exe.run(eval_prog, feed=feed,
                              fetch_list=[eval_fetch[0]])
                rewards.append(float(np.asarray(out[0]).reshape(-1)[0]))
        reward = float(np.mean(rewards))
        controller.update(tokens, reward)
        history.append((list(tokens), reward))
        tokens = controller.next_tokens()
    return controller.best_tokens, controller.max_reward, history
