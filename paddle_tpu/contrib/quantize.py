"""Quantization-aware training as a program-rewriting pass.

Reference: python/paddle/fluid/contrib/slim/quantization/
quantization_pass.py — QuantizationTransformPass inserts fake_quantize /
dequantize op pairs on the inputs of quantizable ops (mul/conv2d/matmul),
with abs-max scales for weights and moving-average abs-max state for
activations; QuantizationFreezePass converts for inference.

TPU translation: the fake-quant ops lower to round/clip jnp with a
straight-through-estimator grad (registered `*_grad` lowerings), so QAT
trains inside the same whole-block XLA computation. int8 *execution* is not
a TPU win (MXU is bf16/int8-via-XLA), so "freeze" keeps the simulated-quant
graph with frozen scales rather than emitting int8 kernels — the numerics
users deploy against match training exactly.
"""

import numpy as np

import jax.numpy as jnp

from paddle_tpu.core.ir import Parameter
from paddle_tpu.core.registry import register_op
from paddle_tpu.ops.common import first
from paddle_tpu.utils import unique_name

__all__ = ["QuantizationTransformPass", "quantize_program"]


# ---------------------------------------------------------------------------
# fake-quant ops (with straight-through-estimator grads)
# ---------------------------------------------------------------------------


def _fake_quant(x, scale, bits):
    qmax = float(2 ** (bits - 1) - 1)
    scale = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(x / scale * qmax), -qmax, qmax)
    return q * scale / qmax


@register_op("fake_quantize_dequantize_abs_max", nondiff_inputs=())
def _fq_abs_max(ins, attrs):
    """Per-tensor abs-max weight quant (reference: paddle/fluid/operators/
    fake_quantize_op.cc FakeQuantizeDequantizeAbsMax)."""
    x = first(ins, "X")
    bits = attrs.get("bit_length", 8)
    scale = jnp.max(jnp.abs(x))
    return {"Out": [_fake_quant(x, scale, bits)], "OutScale": [scale.reshape(1)]}


@register_op("fake_quantize_dequantize_abs_max_grad")
def _fq_abs_max_grad(ins, attrs):
    # straight-through estimator: d out / d x = 1 inside the clip range
    return {"X@GRAD": [first(ins, "Out@GRAD")]}


@register_op("fake_quantize_dequantize_moving_average_abs_max")
def _fq_moving(ins, attrs):
    """Activation quant with moving-average abs-max state (reference:
    fake_quantize_op.cc FakeQuantizeMovingAverageAbsMax)."""
    x = first(ins, "X")
    in_scale = first(ins, "InScale").reshape(())
    bits = attrs.get("bit_length", 8)
    rate = attrs.get("moving_rate", 0.9)
    if attrs.get("is_test", False):
        scale = in_scale
    else:
        cur = jnp.max(jnp.abs(x)).astype(in_scale.dtype)
        # first batch (scale==0) adopts the current abs-max outright
        scale = jnp.where(in_scale <= 0, cur, rate * in_scale + (1 - rate) * cur)
    return {
        "Out": [_fake_quant(x, scale, bits)],
        "OutScale": [scale.reshape(1)],
    }


@register_op("fake_quantize_dequantize_moving_average_abs_max_grad")
def _fq_moving_grad(ins, attrs):
    return {"X@GRAD": [first(ins, "Out@GRAD")]}


# ---------------------------------------------------------------------------
# transform pass
# ---------------------------------------------------------------------------

_DEFAULT_QUANTIZABLE = ("mul", "matmul", "conv2d")


class QuantizationTransformPass:
    """reference: slim/quantization/quantization_pass.py
    QuantizationTransformPass."""

    def __init__(self, weight_bits=8, activation_bits=8, moving_rate=0.9,
                 quantizable_op_type=_DEFAULT_QUANTIZABLE, skip_pattern=None):
        self._wbits = weight_bits
        self._abits = activation_bits
        self._rate = moving_rate
        self._ops = set(quantizable_op_type)
        self._skip = skip_pattern

    def apply(self, program, startup_program):
        block = program.global_block()
        sblock = startup_program.global_block()
        quantized = {}  # src var name -> quantized var name (reuse per var)

        i = 0
        while i < len(block.ops):
            op = block.ops[i]
            if op.type not in self._ops or op.attrs.get("__quant_skip__"):
                i += 1
                continue
            if self._skip and self._skip in op.attrs.get("op_namescope", ""):
                i += 1
                continue
            inserted = 0
            for slot, names in op.inputs.items():
                new_names = []
                for name in names:
                    v = block._find_var_recursive(name)
                    if v is None or "int" in str(v.dtype):
                        new_names.append(name)
                        continue
                    if name in quantized:
                        new_names.append(quantized[name])
                        continue
                    qname = unique_name.generate(name + ".quantized")
                    block.create_var(
                        name=qname, shape=v.shape, dtype=v.dtype,
                        persistable=False,
                    ).stop_gradient = v.stop_gradient
                    if isinstance(v, Parameter):
                        qop, qins, qattrs = (
                            "fake_quantize_dequantize_abs_max",
                            {"X": [name]},
                            {"bit_length": self._wbits},
                        )
                        scale_name = unique_name.generate(name + ".wscale")
                        block.create_var(
                            name=scale_name, shape=[1], dtype="float32",
                        ).stop_gradient = True
                        qouts = {"Out": [qname], "OutScale": [scale_name]}
                    else:
                        scale_name = unique_name.generate(name + ".scale")
                        block.create_var(
                            name=scale_name, shape=[1], dtype="float32",
                            persistable=True,
                        ).stop_gradient = True
                        sblock.create_var(
                            name=scale_name, shape=[1], dtype="float32",
                            persistable=True,
                        )
                        sblock.append_op(
                            "fill_constant", {}, {"Out": [scale_name]},
                            {"shape": [1], "dtype": "float32", "value": 0.0},
                        )
                        qop = "fake_quantize_dequantize_moving_average_abs_max"
                        qins = {"X": [name], "InScale": [scale_name]}
                        qattrs = {
                            "bit_length": self._abits,
                            "moving_rate": self._rate,
                            "is_test": False,
                        }
                        qouts = {"Out": [qname], "OutScale": [scale_name]}
                    block._insert_op(i + inserted, qop, qins, qouts, qattrs)
                    inserted += 1
                    quantized[name] = qname
                    new_names.append(qname)
                op.inputs[slot] = new_names
            i += inserted + 1
        program._bump_version()
        return program


def quantize_program(program, startup_program, weight_bits=8,
                     activation_bits=8, **kwargs):
    """Convenience wrapper: apply QAT rewriting in place before minimize()
    ... actually BEFORE building the optimizer: quantize, then call
    optimizer.minimize(loss) so grads flow through the STE fake-quant ops."""
    return QuantizationTransformPass(
        weight_bits, activation_bits, **kwargs
    ).apply(program, startup_program)


def convert_to_test(program):
    """Freeze for inference: moving-average scales stop updating (reference:
    QuantizationFreezePass — scales become constants)."""
    test = program.clone(for_test=True)
    for b in test.blocks:
        for op in b.ops:
            if op.type == "fake_quantize_dequantize_moving_average_abs_max":
                op.attrs["is_test"] = True
    return test
