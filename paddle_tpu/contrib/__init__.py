"""contrib: mixed-precision lives in paddle_tpu.amp; quantization here.

Reference: python/paddle/fluid/contrib/ (slim/quantization, mixed_precision).
"""

from paddle_tpu.contrib import quantize  # noqa: F401
from paddle_tpu.contrib import slim  # noqa: F401
from paddle_tpu.contrib import nas  # noqa: F401
