"""Model-slimming toolkit: pruning + distillation (+ QAT via .quantize).

reference: python/paddle/fluid/contrib/slim/ — prune/pruner.py
(Pruner/StructurePruner with ratio/magnitude criteria), distillation/
distiller.py (L2Distiller, SoftLabelDistiller, FSPDistiller building a
merged teacher+student graph). TPU-native redesign: pruning is expressed as
masked parameters (a persistable 0/1 mask multiplied into the weight inside
the compiled step — sparsity XLA can fold), not host-side tensor surgery;
distillation merges the teacher program into the student's with frozen
teacher vars and emits the combined loss in ONE compiled step.
"""

import re

import numpy as np

from paddle_tpu.core.scope import global_scope
from paddle_tpu.layer_helper import LayerHelper
from paddle_tpu.utils.enforce import enforce

__all__ = [
    "MagnitudePruner",
    "StructuredPruner",
    "sensitivity",
    "merge_teacher_program",
    "l2_distill_loss",
    "soft_label_distill_loss",
]


# ---------------------------------------------------------------------------
# pruning
# ---------------------------------------------------------------------------


class MagnitudePruner:
    """Unstructured magnitude pruning via weight masks
    (reference: slim/prune/pruner.py Pruner.prune — ratio criterion).

    apply() rewrites the program so every matched parameter W is replaced
    by W * W@MASK at use sites (mask persistable, 0/1); update_masks()
    recomputes masks from current magnitudes at the requested sparsity.
    Masked weights keep training (the optimizer sees the dense gradient),
    so iterative magnitude pruning schedules work.
    """

    def __init__(self, params=None, pattern=".*\\.w.*|.*w_.*"):
        self._explicit = list(params) if params else None
        self._pattern = re.compile(pattern)
        self._masked = []  # (param name, mask name)

    def _match(self, program):
        if self._explicit is not None:
            return [
                p for p in program.all_parameters()
                if p.name in self._explicit
            ]
        return [
            p for p in program.all_parameters()
            if self._pattern.fullmatch(p.name) and len(p.shape or []) >= 2
        ]

    def apply(self, program, startup_program):
        """Insert `masked = W * mask` ops ahead of every consumer of W."""
        block = program.global_block()
        sblock = startup_program.global_block()
        for p in self._match(program):
            mask_name = p.name + "@MASK"
            if any(m == mask_name for _, m in self._masked):
                continue
            block.create_var(
                name=mask_name, shape=list(p.shape), dtype=p.dtype,
                persistable=True, stop_gradient=True,
            )
            sv = sblock.create_var(
                name=mask_name, shape=list(p.shape), dtype=p.dtype,
                persistable=True,
            )
            sblock.append_op(
                "fill_constant",
                {},
                {"Out": [mask_name]},
                {"shape": list(p.shape), "dtype": p.dtype, "value": 1.0},
            )
            masked_name = p.name + "@PRUNED"
            block.create_var(
                name=masked_name, shape=list(p.shape), dtype=p.dtype,
            )
            # insert the mask-multiply right before the first consumer
            first_use = None
            for i, op in enumerate(block.ops):
                if p.name in op.input_names():
                    first_use = i
                    break
            insert_at = first_use if first_use is not None else len(block.ops)
            block._insert_op(
                insert_at,
                "elementwise_mul",
                {"X": [p.name], "Y": [mask_name]},
                {"Out": [masked_name]},
                {"axis": -1},
            )
            for op in block.ops:
                if op.type == "elementwise_mul" and mask_name in op.input_names():
                    continue
                # never rewrite the optimizer region: its Param slot must
                # read/write the RAW weight (W := W - lr*g), or pruned
                # entries get re-zeroed every step and can never regrow
                if op.attrs.get("op_role", 0) == 2:
                    continue
                for slot, names in op.inputs.items():
                    op.inputs[slot] = [
                        masked_name if n == p.name else n for n in names
                    ]
            self._masked.append((p.name, mask_name))
        program._bump_version()
        return self

    def update_masks(self, ratio, scope=None):
        """Recompute every mask to zero the smallest-|w| `ratio` fraction."""
        scope = scope or global_scope()
        for pname, mname in self._masked:
            w = np.asarray(scope.find_var(pname))
            k = int(round(w.size * ratio))
            mask = np.ones(w.size, dtype=w.dtype)
            if k > 0:
                # argsort (not a threshold compare): ties at the cut
                # magnitude must not prune MORE than k entries
                idx = np.argsort(np.abs(w).reshape(-1), kind="stable")[:k]
                mask[idx] = 0
            scope.set(mname, mask.reshape(w.shape))
        return self

    def sparsity(self, scope=None):
        scope = scope or global_scope()
        zeros = total = 0
        for _, mname in self._masked:
            m = np.asarray(scope.find_var(mname))
            zeros += int((m == 0).sum())
            total += m.size
        return zeros / max(total, 1)


class StructuredPruner(MagnitudePruner):
    """Whole-row/column pruning by L1 norm along `axis`
    (reference: slim/prune/pruner.py StructurePruner l1_norm criterion,
    pruning_axis). Masks entire output channels so the zeroed structure is
    removable at export time."""

    def __init__(self, params=None, pattern=".*\\.w.*|.*w_.*", axis=1):
        super().__init__(params, pattern)
        self._axis = axis

    def update_masks(self, ratio, scope=None):
        scope = scope or global_scope()
        for pname, mname in self._masked:
            w = np.asarray(scope.find_var(pname))
            ax = self._axis % w.ndim
            reduce_axes = tuple(i for i in range(w.ndim) if i != ax)
            norms = np.abs(w).sum(axis=reduce_axes)
            k = int(round(norms.size * ratio))
            mask = np.ones_like(w)
            if k > 0:
                idx = np.argsort(norms)[:k]
                sl = [slice(None)] * w.ndim
                sl[ax] = idx
                mask[tuple(sl)] = 0
            scope.set(mname, mask.astype(w.dtype))
        return self


def sensitivity(program, exe, feed, fetch_loss, pruner, ratios, scope=None):
    """Per-ratio loss degradation map (reference: slim/prune/
    auto_prune_strategy.py's sensitivity analysis, simplified): returns
    {ratio: loss} with masks restored afterwards."""
    scope = scope or global_scope()
    saved = {
        m: np.asarray(scope.find_var(m)) for _, m in pruner._masked
    }
    out = {}
    for r in ratios:
        pruner.update_masks(r, scope)
        loss = exe.run(program, feed=feed, fetch_list=[fetch_loss])[0]
        out[r] = float(np.asarray(loss).reshape(-1)[0])
    for m, v in saved.items():
        scope.set(m, v)
    return out


# ---------------------------------------------------------------------------
# distillation
# ---------------------------------------------------------------------------


def merge_teacher_program(student_program, teacher_program, prefix="teacher/"):
    """Copy the teacher's global block into the student program with all
    vars renamed `prefix+name` and marked stop_gradient (frozen teacher —
    reference: slim/distillation/distillation_strategy.py
    _create_distillation_graph merges teacher into the student graph).
    Teacher FEED vars keep the student's name when shapes match, so one
    feed drives both nets. Returns {teacher var name -> merged name}."""
    sblock = student_program.global_block()
    tblock = teacher_program.global_block()
    mapping = {}
    student_feeds = {
        v.name: v for v in sblock.vars.values() if getattr(v, "is_data", False)
    }
    for name, v in tblock.vars.items():
        if getattr(v, "is_data", False) and name in student_feeds:
            mapping[name] = name  # shared feed
            continue
        new = prefix + name
        mapping[name] = new
        if new not in sblock.vars:
            nv = sblock.create_var(
                name=new, shape=v.shape, dtype=v.dtype,
                persistable=v.persistable, stop_gradient=True,
            )
    for op in tblock.ops:
        sblock.append_op(
            op.type,
            {s: [mapping[n] for n in ns] for s, ns in op.inputs.items()},
            {s: [mapping[n] for n in ns] for s, ns in op.outputs.items()},
            dict(op.attrs),
        )
    student_program._bump_version()
    return mapping


def load_teacher_vars(exe, dirname, teacher_program, mapping, scope=None,
                      prefix="teacher/"):
    """Load saved teacher persistables into their prefixed names."""
    from paddle_tpu import io as pio

    state = pio.load_program_state(dirname)
    scope = scope or global_scope()
    for name, arr in state.items():
        scope.set(mapping.get(name, prefix + name), arr)


def l2_distill_loss(student_var, teacher_var, weight=1.0, name=None):
    """reference: slim/distillation/distiller.py L2Distiller."""
    import paddle_tpu as fluid

    diff = fluid.layers.elementwise_sub(student_var, teacher_var)
    return fluid.layers.scale(
        fluid.layers.mean(fluid.layers.square(diff)), scale=float(weight)
    )


def soft_label_distill_loss(student_logits, teacher_logits,
                            student_temperature=1.0,
                            teacher_temperature=1.0, weight=1.0):
    """reference: slim/distillation/distiller.py SoftLabelDistiller —
    cross entropy of softened teacher probabilities against softened
    student log-probs."""
    import paddle_tpu as fluid

    s = fluid.layers.softmax(
        fluid.layers.scale(student_logits, scale=1.0 / student_temperature)
    )
    t = fluid.layers.softmax(
        fluid.layers.scale(teacher_logits, scale=1.0 / teacher_temperature)
    )
    t.stop_gradient = True
    ce = fluid.layers.reduce_sum(
        fluid.layers.elementwise_mul(
            t, fluid.layers.scale(fluid.layers.log(s), scale=-1.0)
        ),
        dim=[-1],
    )
    return fluid.layers.scale(fluid.layers.mean(ce), scale=float(weight))
