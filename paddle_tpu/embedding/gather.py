"""Per-step deduplicated gather: unique ids + inverse index, bucketed.

A CTR batch repeats feature ids heavily (the head of the zipfian slot
distribution appears in most samples). The reference deduplicates inside
its RPC path (parameter_prefetch.cc merges ids before pulling); here the
dedup happens ONCE per batch on the host — ``np.unique`` gives the
sorted unique ids and the inverse index — and the compiled step gathers
the slab exactly once at the unique slots:

    rows = table[slots]          # [U_pad, D]  — the ONLY table-wide gather
    out  = rows[inv]             # [B, S, D]   — local fan-out, cache-sized

so each distinct feature id crosses the interconnect once per step, and
the backward's segment-sum over ``inv`` merges duplicate-id gradients
before the row scatter (the SelectedRows aggregation, selected_rows.h).

Unique counts vary per batch; ``next_bucket`` pads the slot vector to a
power-of-two bucket (padding repeats slot[0]: its forward rows are never
indexed by ``inv`` and its backward segments are zero, so padding is
bit-invisible). Each bucket is one compile-cache entry — the bounded
retrace set, exactly the serving batcher's shape discipline.

``stablehlo_table_gathers`` is the evidence scan (test_hlo.py style): it
parses the lowered step's gather ops and reports, per table-shaped
operand, how many gathers touch it and how many rows each moves — the
dedup claim is asserted from the emitted HLO, not trusted.
"""

import re

import numpy as np

__all__ = ["dedup_ids", "next_bucket", "stablehlo_table_gathers",
           "dedup_evidence"]


def next_bucket(n, min_bucket=8):
    """Smallest power-of-two >= max(n, min_bucket)."""
    b = max(int(min_bucket), 1)
    n = max(int(n), 1)
    while b < n:
        b *= 2
    return b


def dedup_ids(ids, min_bucket=8, dedup=True):
    """(uniq u64 [U], slots_pad_len U_pad, inv int32 ids.shape).

    Returns the batch's unique ids (sorted — np.unique order, so the
    slot vector is deterministic for a given id set), the padded bucket
    length, and the inverse index mapping every occurrence back to its
    unique row. ``dedup=False`` is the bench control: every occurrence
    becomes its own "unique" entry (inv = arange), so the step gathers
    len(ids) rows — what the dedup saves is measured against this.
    """
    arr = np.asarray(ids)
    flat = arr.reshape(-1).astype(np.uint64)
    if dedup:
        uniq, inv = np.unique(flat, return_inverse=True)
    else:
        uniq, inv = flat, np.arange(flat.size)
    u_pad = next_bucket(len(uniq), min_bucket)
    return uniq, u_pad, inv.reshape(arr.shape).astype(np.int32)


# ---------------------------------------------------------------------------
# HLO evidence (test_hlo.py discipline: properties are read off the
# emitted computation, never assumed)
# ---------------------------------------------------------------------------

# StableHLO gather in MLIR generic or pretty form:
#   %5 = "stablehlo.gather"(%2, %4) <{...}> : (tensor<64x8xf32>, ...) -> tensor<16x8xf32>
#   %5 = stablehlo.gather %2, %4 ... : (tensor<64x8xf32>, ...) -> tensor<16x8xf32>
_GATHER_RE = re.compile(
    r"stablehlo\.(?:gather|dynamic_gather)[^\n]*?:\s*"
    r"\(tensor<([0-9x]+)x[a-z0-9]+>.*?->\s*tensor<([0-9x]+)x[a-z0-9]+>"
)


def _dims(s):
    return tuple(int(d) for d in s.split("x") if d)


def stablehlo_table_gathers(text, table_shape):
    """Gathers whose OPERAND is exactly ``table_shape``: list of result
    shapes (one entry per gather op touching the table)."""
    want = tuple(int(d) for d in table_shape)
    out = []
    for m in _GATHER_RE.finditer(text):
        if _dims(m.group(1)) == want:
            out.append(_dims(m.group(2)))
    return out


def dedup_evidence(text, table_shape, n_ids):
    """{gathers, rows_moved, n_ids, dedup_saves}: the per-table dedup
    claim from lowered StableHLO — exactly ONE gather reads the table
    and it moves U_pad < n_ids rows (callers assert both)."""
    hits = stablehlo_table_gathers(text, table_shape)
    rows = [s[0] for s in hits if s]
    return {
        "gathers": len(hits),
        "rows_moved": max(rows) if rows else 0,
        "n_ids": int(n_ids),
        "dedup_saves": bool(rows) and max(rows) < int(n_ids),
    }
