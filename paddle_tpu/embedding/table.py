"""Table config, feature-hash partition, and deterministic row init.

The reference shards a distributed lookup table by ``id % n_pservers``
(reference: python/paddle/fluid/transpiler/distribute_transpiler.py
slice_variable round-robin); raw CTR ids are hash-clustered (consecutive
ids from one slot), so the TPU engine partitions by a mixed hash instead:
``shard(id) = splitmix64(id ^ seed) % ep`` spreads any id distribution
evenly over the ``ep`` mesh axis, the way DLRM/Monolith hash tables do.

Row initialization is a pure function of (table seed, id): the initial
row is derived per (id, lane) from the same splitmix64 stream. A row can
therefore materialize lazily in EITHER tier — first touch on the host
store, first admission to the device cache, or after an N->M checkpoint
restore that re-partitions every id — and the bytes are identical every
time. That purity is what makes the two-tier engine's bit-exactness
guarantees (store.py) possible at all.
"""

import numpy as np

__all__ = ["TableConfig", "hash_shard", "init_rows", "splitmix64"]

_GOLDEN = np.uint64(0x9E3779B97F4A7C15)
_MIX1 = np.uint64(0xBF58476D1CE4E5B9)
_MIX2 = np.uint64(0x94D049BB133111EB)
_U64 = np.uint64(0xFFFFFFFFFFFFFFFF)


def splitmix64(x):
    """Vectorized splitmix64 finalizer over uint64 ndarrays (wrapping
    arithmetic; numpy uint64 ops wrap mod 2^64 natively)."""
    x = np.asarray(x, dtype=np.uint64)
    with np.errstate(over="ignore"):
        x = (x + _GOLDEN) & _U64
        x = ((x ^ (x >> np.uint64(30))) * _MIX1) & _U64
        x = ((x ^ (x >> np.uint64(27))) * _MIX2) & _U64
        return x ^ (x >> np.uint64(31))


def hash_shard(ids, n_shards, seed=0):
    """Owner shard on the ep axis for each id: splitmix64(id ^ seed) mod
    n_shards — NOT ``id % n`` (CTR ids arrive hash-clustered per slot;
    the mix keeps shard load even for any id distribution)."""
    ids = np.asarray(ids, dtype=np.uint64)
    if n_shards <= 1:
        return np.zeros(ids.shape, dtype=np.int64)
    h = splitmix64(ids ^ np.uint64(seed))
    return (h % np.uint64(n_shards)).astype(np.int64)


def init_rows(ids, dim, init_range, seed=0):
    """[len(ids), dim] float32 initial rows, a pure function of
    (seed, id, lane): uniform in [-init_range, init_range). init_range=0
    gives zero rows (the wide/linear-term convention in models/ctr.py)."""
    ids = np.asarray(ids, dtype=np.uint64).reshape(-1)
    if init_range == 0.0 or dim == 0:
        return np.zeros((len(ids), dim), dtype=np.float32)
    with np.errstate(over="ignore"):
        base = splitmix64(ids ^ np.uint64(seed))[:, None]
        lanes = (np.arange(dim, dtype=np.uint64) * _GOLDEN)[None, :]
        bits = splitmix64((base + lanes) & _U64)
    unit = (bits >> np.uint64(11)).astype(np.float64) / float(1 << 53)
    return ((unit * 2.0 - 1.0) * float(init_range)).astype(np.float32)


class TableConfig:
    """One sharded table's static configuration.

    capacity      total device hot-cache rows, split evenly over the ep
                  shards (must divide); the slab var is [capacity, dim].
    ep            hash-partition count == the ep mesh axis size the slab
                  is row-sharded over (1 = single-shard, still cached).
    vocab_size    advisory only (ids span the full u64 space; the host
                  store grows on demand like the reference's pservers).
    init_range    uniform init half-width; 0 = zero-init (wide tables).
    lr            the table's own SGD rate — embedding tables train with
                  their own sparse rule, never the dense optimizer (an
                  Adam step on an un-touched cached row would drift it,
                  breaking cache-size invariance).
    min_bucket    smallest padded unique-id bucket (gather.py).
    """

    __slots__ = ("name", "dim", "capacity", "ep", "vocab_size",
                 "init_range", "lr", "seed", "min_bucket")

    def __init__(self, name, dim, capacity, ep=1, vocab_size=None,
                 init_range=0.01, lr=0.1, seed=0, min_bucket=8):
        from paddle_tpu.utils.enforce import enforce

        self.name = str(name)
        self.dim = int(dim)
        self.capacity = int(capacity)
        self.ep = int(ep)
        self.vocab_size = vocab_size
        self.init_range = float(init_range)
        self.lr = float(lr)
        self.seed = int(seed)
        self.min_bucket = int(min_bucket)
        enforce(self.dim > 0, f"table {name}: dim must be > 0")
        enforce(self.ep >= 1, f"table {name}: ep must be >= 1")
        enforce(
            self.capacity >= self.ep and self.capacity % self.ep == 0,
            f"table {name}: capacity {self.capacity} must be a positive "
            f"multiple of ep={self.ep} (the slab row-shards evenly over "
            "the ep axis)",
        )

    @property
    def cap_per_shard(self):
        return self.capacity // self.ep

    @property
    def slab_name(self):
        return f"{self.name}__slab"

    def shard_of(self, ids):
        return hash_shard(ids, self.ep, self.seed)

    def init_for(self, ids):
        return init_rows(ids, self.dim, self.init_range, self.seed)

    def digest(self):
        """Content digest folded into the lookup op's attrs — engine
        config that changes lookup semantics joins the compile-cache
        program fingerprint through the serialized block desc."""
        return (
            f"v1:dim={self.dim}:cap={self.capacity}:ep={self.ep}"
            f":init={self.init_range!r}:lr={self.lr!r}:seed={self.seed}"
            f":minb={self.min_bucket}"
        )

    def to_attrs(self):
        return {
            "table_name": self.name,
            "dim": self.dim,
            "capacity": self.capacity,
            "ep": self.ep,
            "lr": self.lr,
            "engine_digest": self.digest(),
        }

    @classmethod
    def from_entry(cls, entry):
        """Rebuild from a program's ``_sharded_tables`` registry entry."""
        return cls(
            entry["table_name"], entry["dim"], entry["capacity"],
            ep=entry.get("ep", 1), vocab_size=entry.get("vocab_size"),
            init_range=entry.get("init_range", 0.01),
            lr=entry.get("lr", 0.1), seed=entry.get("seed", 0),
            min_bucket=entry.get("min_bucket", 8),
        )
