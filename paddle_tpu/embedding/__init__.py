"""Sharded embedding engine: hash-partitioned tables over the ep mesh axis.

The reference answers billion-feature sparse models with a parameter-server
fleet (distributed lookup_table ops + pserver processes; reference:
paddle/fluid/operators/distributed/parameter_prefetch.cc); this package is
the TPU-native translation, following the hierarchical-memory embedding
designs of DLRM (Naumov et al., 2019) and Monolith (Liu et al., 2022):

* ``table.py``  — per-table config, the feature-hash partition over the
  ``ep`` mesh axis, and the deterministic per-id row initializer (a row's
  initial value is a pure function of (table seed, id), so a row can
  materialize lazily at ANY tier, at ANY time, bit-identically).
* ``gather.py`` — per-step deduplicated gather: unique ids + inverse index
  computed once per batch, bucketed so each distinct feature id crosses
  the interconnect once (HLO-evidence helpers included).
* ``store.py``  — the two-tier store: a host-RAM overflow tier for the
  cold tail and a device-resident hot-ID cache with LRU admission and
  write-back eviction, async pull/push riding distributed/lookup.py's
  retry policy and fault sites.

``layers.sharded_embedding`` is the graph entry point; ``EmbeddingEngine``
is the host-side driver (``prepare_feed`` per step, ``flush`` before
reads, checkpoint via ``AutoCheckpoint(extra_state=engine)``).
"""

from paddle_tpu.embedding.table import TableConfig, hash_shard, init_rows
from paddle_tpu.embedding.gather import dedup_ids, next_bucket
from paddle_tpu.embedding.store import EmbeddingEngine, HostStore, STORE_PREFIX

__all__ = [
    "TableConfig",
    "hash_shard",
    "init_rows",
    "dedup_ids",
    "next_bucket",
    "EmbeddingEngine",
    "HostStore",
    "STORE_PREFIX",
]
