"""Two-tier embedding store: device hot-ID cache over a host-RAM tier.

The DLRM/Monolith memory hierarchy, mapped onto this framework: the full
table lives in host RAM as hash-sharded (id -> row) maps (the overflow
tier — rows materialize lazily from the deterministic initializer, so
the u64 id space costs nothing until touched), while the rows the
traffic actually hits live in a device-resident slab (`<table>__slab`,
[capacity, dim], row-sharded over the ep mesh axis) managed by per-shard
LRU admission. The compiled step reads and UPDATES only the slab
(ops/sharded_embedding.py); the host tier is reconciled by write-back:

  * admission — a missed id is pulled from the host tier and scattered
    into its hash-owner shard's slot range of the slab;
  * eviction  — the per-shard LRU victim's CURRENT device row is read
    back and pushed to the host tier before its slot is reused;
  * flush     — every dirty (device-updated, not yet pushed) row is
    pushed; checkpoints call this first so the host tier is
    authoritative (incubate/checkpoint.py saves it format-2 per-shard).

That write-back discipline is the bit-exactness contract: a row's value
is ALWAYS its last trained value, whether it sat on device the whole run
or bounced through the host tier a thousand times — so lookup results
(and whole training runs) are bit-identical across cache capacities,
which tools/bench_embedding.py --smoke asserts.

Pull/push ride distributed/lookup.py's shared retry policy and fire its
``lookup.pull`` / ``lookup.push`` fault sites, so resilience/faults.py
schedules written for the PS path exercise this engine unchanged.
Pushes run on a small pool (async write-back; ``flush`` is the
barrier); a pull of an id with an in-flight push waits for that push
first — the ordering that keeps the tiers coherent.
"""

import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from paddle_tpu.embedding.gather import dedup_ids, next_bucket
from paddle_tpu.embedding.table import TableConfig
from paddle_tpu.observability import lockdep
from paddle_tpu.observability import metrics as obs_metrics
from paddle_tpu.resilience import faults
from paddle_tpu.utils.enforce import EnforceError, enforce

__all__ = ["HostStore", "EmbeddingEngine", "STORE_PREFIX"]

# The write-back discipline (PR 8 prose, now declared): the host-tier
# TABLE lock comes before the PENDING-marker lock — a push worker
# finishes store.push() before touching markers, and nothing may pull
# from the table while holding the marker map (the stale-read guard
# waits on futures OUTSIDE the lock instead).
lockdep.declare_order("embedding.table", "embedding.pending")

#: checkpoint array-name prefix — names carrying it are engine state, not
#: scope variables (incubate/checkpoint.py routes them to the engine)
STORE_PREFIX = "__embedding_store__::"


def _with_retry(fn):
    """Pull/push failure semantics are the PS lookup path's: the shared
    (swappable) retry policy in distributed/lookup.py."""
    from paddle_tpu.distributed import lookup as _lookup

    return _lookup._with_retry(fn)


def _kernels_emb():
    from paddle_tpu.kernels import embedding as kemb

    return kemb


class HostStore:
    """Host-RAM overflow tier: per-ep-shard (id -> float32 row) maps.

    Authoritative for every row NOT currently dirty on device. Absent
    rows materialize from the deterministic initializer at pull time —
    the same bytes no matter which tier or process materializes them."""

    def __init__(self, cfg):
        self.cfg = cfg
        self._shards = [dict() for _ in range(cfg.ep)]
        self._lock = lockdep.named_lock("embedding.table")

    def __len__(self):
        with self._lock:
            return sum(len(s) for s in self._shards)

    def pull(self, ids):
        """[len(ids), dim] rows; fires the ``lookup.pull`` fault site and
        retries under the shared policy. Returns (rows, n_materialized)."""
        ids = np.asarray(ids, dtype=np.uint64).reshape(-1)
        owners = self.cfg.shard_of(ids)

        def do_pull():
            faults.fire("lookup.pull")
            rows = np.empty((len(ids), self.cfg.dim), dtype=np.float32)
            with self._lock:
                absent = [
                    i for i, (idv, k) in enumerate(zip(ids.tolist(),
                                                       owners.tolist()))
                    if idv not in self._shards[k]
                ]
                if absent:
                    # one vectorized init for every absent id (per-id
                    # init is a pure function, so batching is
                    # byte-identical to one-at-a-time materialization)
                    init = self.cfg.init_for(ids[absent])
                    for j, i in enumerate(absent):
                        self._shards[owners[i]][int(ids[i])] = init[j]
                for i, (idv, k) in enumerate(zip(ids.tolist(),
                                                 owners.tolist())):
                    rows[i] = self._shards[k][idv]
            return rows, len(absent)

        return _with_retry(do_pull)

    def push(self, ids, rows):
        """Overwrite rows (write-back from the device tier); fires the
        ``lookup.push`` fault site under the shared retry policy."""
        ids = np.asarray(ids, dtype=np.uint64).reshape(-1)
        rows = np.asarray(rows, dtype=np.float32).reshape(len(ids), -1)
        owners = self.cfg.shard_of(ids)

        def do_push():
            faults.fire("lookup.push")
            with self._lock:
                for idv, k, row in zip(ids.tolist(), owners.tolist(), rows):
                    self._shards[k][idv] = row.copy()

        _with_retry(do_push)

    def snapshot_blocks(self):
        """Per-shard (ids u64 [n_k], rows f32 [n_k, dim]) with ids sorted
        inside each shard — the deterministic block layout the format-2
        checkpoint path records."""
        with self._lock:
            blocks = []
            for shard in self._shards:
                ids = np.fromiter(shard.keys(), dtype=np.uint64,
                                  count=len(shard))
                order = np.argsort(ids, kind="stable")
                ids = ids[order]
                rows = (
                    np.stack([shard[i] for i in ids.tolist()])
                    if len(ids) else
                    np.zeros((0, self.cfg.dim), dtype=np.float32)
                )
                blocks.append((ids, rows))
            return blocks

    def restore(self, ids, rows):
        """Rebuild from flat (ids, rows) — re-partitioned by the CURRENT
        hash config, so an N-shard save restores onto M shards with
        bit-identical row values."""
        ids = np.asarray(ids, dtype=np.uint64).reshape(-1)
        rows = np.asarray(rows, dtype=np.float32).reshape(len(ids), -1)
        owners = self.cfg.shard_of(ids)
        with self._lock:
            self._shards = [dict() for _ in range(self.cfg.ep)]
            for idv, k, row in zip(ids.tolist(), owners.tolist(), rows):
                self._shards[k][idv] = row.copy()


class _TableRuntime:
    """One table's host-side state: slot map, per-shard LRU, dirty set."""

    def __init__(self, cfg, scope, engine):
        self.cfg = cfg
        self.scope = scope
        self.engine = engine
        self.store = HostStore(cfg)
        self._slot = {}                      # id -> slab row index
        self._lru = [dict() for _ in range(cfg.ep)]   # id -> slot, insert-ordered
        self._free = [
            list(range((k + 1) * cfg.cap_per_shard - 1,
                       k * cfg.cap_per_shard - 1, -1))
            for k in range(cfg.ep)
        ]
        self._dirty = set()
        self._oldest_dirty = None            # monotonic ts of oldest dirty row
        self._pending_push = {}              # id -> Future (in-flight write-back)
        reg = obs_metrics.registry()
        labels = {"table": cfg.name}
        self.m_hits = reg.counter(
            "embedding_cache_hits_total",
            "batch unique ids found in the device hot cache", labels)
        self.m_misses = reg.counter(
            "embedding_cache_misses_total",
            "batch unique ids pulled from the host tier", labels)
        self.m_evictions = reg.counter(
            "embedding_cache_evictions_total",
            "LRU evictions from the device hot cache", labels)
        self.m_writebacks = reg.counter(
            "embedding_writebacks_total",
            "dirty rows pushed back to the host tier", labels)
        self.m_prefetch = reg.counter(
            "embedding_prefetch_materialized_total",
            "host-tier rows materialized ahead of the batch", labels)
        self.g_occupancy = reg.gauge(
            "embedding_cache_occupancy",
            "rows resident in the device hot cache", labels)
        self.g_store_rows = reg.gauge(
            "embedding_store_rows",
            "rows materialized in the host tier", labels)
        self.g_staleness = reg.gauge(
            "embedding_staleness_seconds",
            "age of the oldest device row not yet written back", labels)

    # -- slab access -------------------------------------------------------
    def slab_host(self):
        v = self.scope.find_var(self.cfg.slab_name)
        enforce(
            v is not None,
            f"table {self.cfg.name}: slab var {self.cfg.slab_name!r} not in "
            "scope (run the startup program before preparing feeds)",
        )
        return np.asarray(v)

    def reset_slab(self):
        self.scope.set(
            self.cfg.slab_name,
            np.zeros((self.cfg.capacity, self.cfg.dim), dtype=np.float32),
        )
        self._slot.clear()
        self._dirty.clear()
        self._oldest_dirty = None
        self._lru = [dict() for _ in range(self.cfg.ep)]
        self._free = [
            list(range((k + 1) * self.cfg.cap_per_shard - 1,
                       k * self.cfg.cap_per_shard - 1, -1))
            for k in range(self.cfg.ep)
        ]
        self.g_occupancy.set(0)
        self.g_staleness.set(0)

    def _device_admission(self):
        """On-device miss admission applies unless the operator opted
        out (PADDLE_TPU_KERNELS=off restores the legacy host path
        byte-for-byte) or the slab is mesh-sharded (the multichip arm
        keeps its P('ep') placement; a host-driven per-shard scatter
        would need resharding machinery this path does not carry)."""
        from paddle_tpu.kernels import registry as kreg

        if kreg.mode() == "off":
            return False
        v = self.scope.find_var(self.cfg.slab_name)
        sharding = getattr(v, "sharding", None)
        if sharding is not None and len(
                getattr(sharding, "device_set", ())) > 1:
            return False
        return True

    # -- the per-step path -------------------------------------------------
    def lookup(self, ids, dedup=True, train=True):
        """Resolve a batch: admit misses, evict victims (write-back),
        return (slots int32 [U_pad], inv int32 ids.shape) feeds."""
        uniq, u_pad, inv = dedup_ids(ids, self.cfg.min_bucket, dedup)
        uu = uniq if dedup else np.unique(uniq)
        curr = set(uu.tolist())
        owner = dict(zip(uu.tolist(), self.cfg.shard_of(uu).tolist()))
        miss = [i for i in uu.tolist() if i not in self._slot]
        miss_set = set(miss)
        self.m_hits.inc(len(uu) - len(miss))
        self.m_misses.inc(len(miss))

        if miss:
            self._wait_pushes(miss)
            rows, fresh = self.store.pull(miss)
            # allocate a slot in each id's hash-owner shard, collecting
            # LRU victims (never a member of the current batch)
            evicted, evicted_slots = [], []
            new_slots = []
            for idv in miss:
                k = owner[idv]
                if self._free[k]:
                    s = self._free[k].pop()
                else:
                    victim = next(
                        (c for c in self._lru[k] if c not in curr), None
                    )
                    if victim is None:
                        raise EnforceError(
                            f"table {self.cfg.name}: shard {k} needs more "
                            f"than its {self.cfg.cap_per_shard} cache slots "
                            "for ONE batch's unique ids — raise capacity "
                            "or shrink the batch"
                        )
                    s = self._lru[k].pop(victim)
                    del self._slot[victim]
                    evicted.append(victim)
                    evicted_slots.append(s)
                self._slot[idv] = s
                self._lru[k][idv] = s
                new_slots.append(s)
            self.m_evictions.inc(len(evicted))

            dirty_ev = [i for i in evicted if i in self._dirty]
            ev_slots = [s for i, s in zip(evicted, evicted_slots)
                        if i in self._dirty]
            if self._device_admission():
                # on-device admission (kernels/embedding.py): gather ONLY
                # the victims' rows for write-back, scatter the pulled
                # miss rows in place (donated) — the [capacity, dim] slab
                # never round-trips through host numpy
                slab_dev = self.scope.find_var(self.cfg.slab_name)
                if dirty_ev:
                    # read-back BEFORE the scatter reuses the slots: the
                    # victims' device values are the authoritative ones
                    self._async_push(
                        dirty_ev, _kernels_emb().read_rows(
                            slab_dev, ev_slots))
                    self._dirty.difference_update(dirty_ev)
                self.scope.set(
                    self.cfg.slab_name,
                    _kernels_emb().admit_rows(slab_dev, new_slots, rows),
                )
            else:
                # legacy host path (PADDLE_TPU_KERNELS=off, or a
                # mesh-sharded slab): full capacity-slab round-trip,
                # counted so the kernel evidence can assert ZERO
                _kernels_emb().admission_roundtrip_counter().inc()
                slab = np.array(self.slab_host())  # host copy
                if dirty_ev:
                    # write-back BEFORE the slots are reused
                    self._async_push(dirty_ev, slab[ev_slots].copy())
                    self._dirty.difference_update(dirty_ev)
                slab[new_slots] = rows
                self.scope.set(self.cfg.slab_name, slab)

        # LRU touch for hits (misses were appended above)
        for idv in uu.tolist():
            if idv not in miss_set:
                lru = self._lru[owner[idv]]
                s = lru.pop(idv)
                lru[idv] = s

        if train:
            self._dirty.update(curr)
            if self._oldest_dirty is None:
                self._oldest_dirty = time.monotonic()
        self._refresh_gauges()

        slots = np.fromiter(
            (self._slot[i] for i in uniq.tolist()), dtype=np.int32,
            count=len(uniq),
        )
        if len(slots) < u_pad:
            pad = slots[0] if len(slots) else np.int32(0)
            slots = np.concatenate(
                [slots, np.full(u_pad - len(slots), pad, dtype=np.int32)]
            )
        return slots, inv

    def prefetch(self, ids):
        """Materialize the next batch's missing host-tier rows on the
        push pool (the async pull): by the time lookup() runs, its
        store.pull finds them resident. Fires lookup.pull like any pull."""
        uniq, _u, _inv = dedup_ids(ids, self.cfg.min_bucket, True)
        miss = [i for i in uniq.tolist() if i not in self._slot]
        if not miss:
            return None

        def warm():
            _rows, fresh = self.store.pull(miss)
            if fresh:
                self.m_prefetch.inc(fresh)

        return self.engine._pool.submit(warm)

    # -- write-back --------------------------------------------------------
    def _async_push(self, ids, rows):
        self.m_writebacks.inc(len(ids))
        done = threading.Event()

        def push():
            done.wait()  # marker registration precedes the write
            self.store.push(ids, rows)
            with self.engine._push_lock:
                for i in ids:
                    # pop ONLY our own marker: a newer in-flight push for
                    # the same id must keep its marker or a later pull
                    # skips its wait and reads a stale row
                    if self._pending_push.get(i) is fut:
                        del self._pending_push[i]

        fut = self.engine._pool.submit(push)
        with self.engine._push_lock:
            for i in ids:
                self._pending_push[i] = fut
        done.set()
        return fut

    def _wait_pushes(self, ids):
        """A pull of an id with an in-flight write-back must observe the
        pushed value — wait for exactly those pushes."""
        with self.engine._push_lock:
            futs = {self._pending_push[i] for i in ids
                    if i in self._pending_push}
        for f in futs:
            f.result()

    def flush(self):
        """Push every dirty device row to the host tier (the barrier the
        checkpoint save and any external read runs behind). Drains ALL
        in-flight write-backs first so a snapshot taken after flush()
        sees every eviction push, not just flush's own."""
        with self.engine._push_lock:
            pending = set(self._pending_push.values())
        for f in pending:
            f.result()
        dirty = sorted(self._dirty)
        if dirty:
            slab = self.slab_host()
            slots = [self._slot[i] for i in dirty]
            fut = self._async_push(dirty, np.array(slab[slots]))
            fut.result()
            self._dirty.clear()
        self._oldest_dirty = None
        self._refresh_gauges()

    def _refresh_gauges(self):
        self.g_occupancy.set(len(self._slot))
        self.g_store_rows.set(len(self.store))
        if not self._dirty:
            # eviction write-backs can empty the dirty set without a
            # flush — an empty set means zero un-written-back rows, so
            # the staleness clock must not keep running
            self._oldest_dirty = None
        self.g_staleness.set(
            0.0 if self._oldest_dirty is None
            else time.monotonic() - self._oldest_dirty
        )

    def stats(self):
        return {
            "hits": self.m_hits.value,
            "misses": self.m_misses.value,
            "evictions": self.m_evictions.value,
            "writebacks": self.m_writebacks.value,
            "occupancy": len(self._slot),
            "store_rows": len(self.store),
            "hit_rate": (
                self.m_hits.value /
                max(1, self.m_hits.value + self.m_misses.value)
            ),
        }


class EmbeddingEngine:
    """Host-side driver for every sharded table of a program.

        engine = EmbeddingEngine(scope=scope)
        for batch, nxt in pairwise(batches):
            feed = engine.prepare_feed(main, dict(batch))
            engine.prefetch(main, nxt)            # optional async pull
            exe.run(main, feed=feed, ...)
        engine.flush()                            # before external reads

    Checkpointing: ``AutoCheckpoint(..., extra_state=engine)`` flushes
    the hot cache and saves the host tier through the format-2 per-shard
    manifest path; resume restores it bit-identically (N -> M re-hash
    included) and cold-resets the device cache.
    """

    def __init__(self, scope=None, push_workers=2):
        from paddle_tpu.core.scope import global_scope

        self._scope = scope if scope is not None else global_scope()
        self._tables = {}
        self._pending_restore = {}   # checkpoint arrays for tables not
        #                              registered yet (resume() often runs
        #                              before the first prepare_feed)
        self._pool = ThreadPoolExecutor(
            max_workers=push_workers,
            thread_name_prefix="embedding-push",
        )
        self._push_lock = lockdep.named_lock("embedding.pending")

    @property
    def tables(self):
        return dict(self._tables)

    def register(self, cfg):
        enforce(
            cfg.name not in self._tables,
            f"table {cfg.name!r} already registered",
        )
        rt = _TableRuntime(cfg, self._scope, self)
        self._tables[cfg.name] = rt
        rt.reset_slab()
        if self._pending_restore:
            self._apply_restore(cfg.name, rt)
        return rt

    def _runtime_for(self, entry):
        rt = self._tables.get(entry["table_name"])
        if rt is None:
            rt = self.register(TableConfig.from_entry(entry))
        return rt

    # -- the step API ------------------------------------------------------
    def prepare_feed(self, program, feed, train=True, dedup=True):
        """Translate each registered table's raw id feed into the
        (slots, inv) feeds the compiled step consumes. Mutates and
        returns ``feed``. Must run on the training thread, in step
        order — cache state advances with the stream."""
        prog = getattr(program, "program", program)  # unwrap CompiledProgram
        tables = getattr(prog, "_sharded_tables", None) or {}
        for tname, entry in tables.items():
            ids = feed.get(entry["ids"])
            if ids is None:
                continue
            rt = self._runtime_for(entry)
            slots, inv = rt.lookup(ids, dedup=dedup, train=train)
            feed[entry["slots"]] = slots
            feed[entry["inv"]] = inv
        return feed

    def prefetch(self, program, next_feed):
        """Announce the NEXT batch's ids: missing host-tier rows
        materialize on the background pool (the async pull half; pushes
        are async write-backs)."""
        prog = getattr(program, "program", program)
        tables = getattr(prog, "_sharded_tables", None) or {}
        futs = []
        for entry in tables.values():
            ids = next_feed.get(entry["ids"])
            if ids is None:
                continue
            f = self._runtime_for(entry).prefetch(ids)
            if f is not None:
                futs.append(f)
        return futs

    def flush(self):
        for rt in self._tables.values():
            rt.flush()

    def stats(self):
        return {name: rt.stats() for name, rt in self._tables.items()}

    # -- checkpoint protocol (incubate/checkpoint.py extra_state) ----------
    def owns(self, name):
        return name.startswith(STORE_PREFIX)

    def checkpoint_arrays(self):
        """Hot cache flushed first, then the host tier per table as TWO
        logical arrays (ids u64, rows f32) blocked per ep shard — the
        format-2 per-shard manifest entries (_ShardSnap), so each shard
        carries its own CRC and bounds and N -> M restores stitch."""
        from paddle_tpu.incubate.checkpoint import _ShardSnap

        self.flush()
        out = {}
        for name, rt in self._tables.items():
            blocks = rt.store.snapshot_blocks()
            sizes = [len(ids) for ids, _rows in blocks]
            total = sum(sizes)
            dim = rt.cfg.dim
            if total == 0:
                out[STORE_PREFIX + name + "::ids"] = np.zeros(
                    (0,), dtype=np.uint64)
                out[STORE_PREFIX + name + "::rows"] = np.zeros(
                    (0, dim), dtype=np.float32)
                continue
            id_blocks, row_blocks, off = [], [], 0
            for ids, rows in blocks:
                if not len(ids):
                    continue
                id_blocks.append(((off,), (off + len(ids),), ids))
                row_blocks.append(
                    ((off, 0), (off + len(ids), dim), rows)
                )
                off += len(ids)
            out[STORE_PREFIX + name + "::ids"] = _ShardSnap(
                (total,), "uint64", f"ep({rt.cfg.ep})", id_blocks)
            out[STORE_PREFIX + name + "::rows"] = _ShardSnap(
                (total, dim), "float32", f"ep({rt.cfg.ep})", row_blocks)
        return out

    def restore_arrays(self, arrays):
        """Rebuild each table's host tier from checkpoint arrays (ids
        re-hashed under the CURRENT ep config — N -> M restores are
        bit-identical in VALUE space) and cold-reset the device cache:
        the first batch re-admits its working set from the restored
        tier, so lookups resume bit-identically. Arrays for tables not
        registered yet (resume() usually precedes the first
        prepare_feed) are stashed and applied at registration."""
        self._pending_restore = dict(arrays)
        for name, rt in self._tables.items():
            self._apply_restore(name, rt)

    def _apply_restore(self, name, rt):
        ids = self._pending_restore.pop(
            STORE_PREFIX + name + "::ids", None)
        rows = self._pending_restore.pop(
            STORE_PREFIX + name + "::rows", None)
        rt.reset_slab()
        if ids is None or rows is None:
            rt.store.restore(
                np.zeros((0,), np.uint64),
                np.zeros((0, rt.cfg.dim), np.float32),
            )
        else:
            rt.store.restore(ids, rows)
        rt._refresh_gauges()

    def close(self):
        self._pool.shutdown(wait=True)
