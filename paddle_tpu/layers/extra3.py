"""Layer builders for the third/fourth op tranches — the fluid.layers.*
user surface over ops/misc_extra.py and ops/vision_extra.py.

reference: python/paddle/fluid/layers/{nn.py, loss.py, detection.py} —
edit_distance, sampled_softmax_with_cross_entropy, teacher_student_
sigmoid_loss, crop, hash, psroi_pool, prroi_pool, deformable_conv,
deformable_roi_pooling, fsp (slim distillation uses the op directly),
sampling_id, gaussian_random_batch_size_like, random_crop,
similarity_focus, generate_proposals, distribute_fpn_proposals,
collect_fpn_proposals, retinanet_detection_output, locality_aware_nms.
"""

import numpy as np

from paddle_tpu.layer_helper import LayerHelper
from paddle_tpu.param_attr import ParamAttr

__all__ = [
    "edit_distance",
    "sampled_softmax_with_cross_entropy",
    "teacher_student_sigmoid_loss",
    "fsp_matrix",
    "crop",
    "hash",
    "sampling_id",
    "gaussian_random_batch_size_like",
    "random_crop",
    "similarity_focus",
    "psroi_pool",
    "prroi_pool",
    "deformable_conv",
    "deformable_roi_pooling",
    "generate_proposals",
    "distribute_fpn_proposals",
    "collect_fpn_proposals",
    "retinanet_detection_output",
    "locality_aware_nms",
    "proximal_gd",  # exposed for parity; normally reached via optimizers
    "unique",
    "unique_with_counts",
]


def _out(helper, dtype, stop_gradient=False):
    v = helper.create_variable_for_type_inference(dtype)
    v.stop_gradient = stop_gradient
    return v


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None):
    """reference: python/paddle/fluid/layers/loss.py:352 — padded+lengths
    form only (LoD-free); ignored_tokens is unsupported here (filter ids
    upstream)."""
    helper = LayerHelper("edit_distance")
    out = _out(helper, "float32", stop_gradient=True)
    seq_num = _out(helper, "int64", stop_gradient=True)
    ins = {"Hyps": [input.name], "Refs": [label.name]}
    if (input_length is None) != (label_length is None):
        from paddle_tpu.utils.enforce import EnforceError

        raise EnforceError(
            "edit_distance: provide BOTH input_length and label_length "
            "(padded form), or neither (full-width rows)"
        )
    if input_length is not None:
        ins["HypsLength"] = [input_length.name]
        ins["RefsLength"] = [label_length.name]
    helper.append_op(
        "edit_distance", ins,
        {"Out": [out.name], "SequenceNum": [seq_num.name]},
        {"normalized": normalized},
    )
    return out, seq_num


def sampled_softmax_with_cross_entropy(logits, label, num_samples,
                                       num_true=1,
                                       remove_accidental_hits=True,
                                       use_customized_samples=False,
                                       customized_samples=None,
                                       customized_probabilities=None,
                                       seed=0):
    """reference: python/paddle/fluid/layers/loss.py:1007 — sample_logits op
    followed by softmax_with_cross_entropy on the sampled slate (true
    labels re-indexed to positions [0, num_true))."""
    from paddle_tpu.layers import nn as nn_layers

    helper = LayerHelper("sample_logits")
    samples = _out(helper, "int64", stop_gradient=True)
    probabilities = _out(helper, "float32", stop_gradient=True)
    sampled_logits = _out(helper, logits.dtype)
    sampled_label = _out(helper, "int64", stop_gradient=True)
    ins = {"Logits": [logits.name], "Labels": [label.name]}
    if use_customized_samples:
        ins["CustomizedSamples"] = [customized_samples.name]
        ins["CustomizedProbabilities"] = [customized_probabilities.name]
    helper.append_op(
        "sample_logits", ins,
        {"Samples": [samples.name], "Probabilities": [probabilities.name],
         "SampledLogits": [sampled_logits.name],
         "SampledLabels": [sampled_label.name]},
        {"num_samples": num_samples,
         "use_customized_samples": use_customized_samples,
         "remove_accidental_hits": remove_accidental_hits, "seed": seed},
    )
    loss = nn_layers.softmax_with_cross_entropy(
        sampled_logits, sampled_label
    )
    return loss


def teacher_student_sigmoid_loss(input, label, soft_max_up_bound=15.0,
                                 soft_max_lower_bound=-15.0):
    """reference: python/paddle/fluid/layers/loss.py teacher_student_
    sigmoid_loss."""
    helper = LayerHelper("teacher_student_sigmoid_loss")
    out = _out(helper, input.dtype)
    helper.append_op(
        "teacher_student_sigmoid_loss",
        {"X": [input.name], "Label": [label.name]},
        {"Y": [out.name]},
        {"soft_max_up_bound": soft_max_up_bound,
         "soft_max_lower_bound": soft_max_lower_bound},
    )
    return out


def fsp_matrix(x, y):
    """reference: python/paddle/fluid/contrib/slim uses the fsp op for
    distillation; exposed as a layer for direct use."""
    helper = LayerHelper("fsp")
    out = _out(helper, x.dtype)
    helper.append_op(
        "fsp", {"X": [x.name], "Y": [y.name]}, {"Out": [out.name]}, {}
    )
    return out


def crop(x, shape=None, offsets=None, name=None):
    """reference: python/paddle/fluid/layers/nn.py:8024 (static form)."""
    helper = LayerHelper("crop", name=name)
    out = _out(helper, x.dtype)
    attrs = {}
    ins = {"X": [x.name]}
    if hasattr(shape, "name"):
        ins["Y"] = [shape.name]
    else:
        attrs["shape"] = list(shape)
    if offsets is not None:
        attrs["offsets"] = list(offsets)
    helper.append_op("crop", ins, {"Out": [out.name]}, attrs)
    return out


def hash(input, hash_size, num_hash=1, name=None):
    """reference: python/paddle/fluid/layers/nn.py hash."""
    helper = LayerHelper("hash", name=name)
    out = _out(helper, "int64", stop_gradient=True)
    helper.append_op(
        "hash", {"X": [input.name]}, {"Out": [out.name]},
        {"mod_by": hash_size, "num_hash": num_hash},
    )
    return out


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="int64"):
    """reference: python/paddle/fluid/layers/nn.py sampling_id."""
    helper = LayerHelper("sampling_id")
    out = _out(helper, dtype, stop_gradient=True)
    helper.append_op(
        "sampling_id", {"X": [x.name]}, {"Out": [out.name]}, {"seed": seed}
    )
    return out


def gaussian_random_batch_size_like(input, shape, input_dim_idx=0,
                                    output_dim_idx=0, mean=0.0, std=1.0,
                                    seed=0, dtype="float32"):
    """reference: python/paddle/fluid/layers/nn.py."""
    helper = LayerHelper("gaussian_random_batch_size_like")
    out = _out(helper, dtype)
    helper.append_op(
        "gaussian_random_batch_size_like", {"Input": [input.name]},
        {"Out": [out.name]},
        {"shape": list(shape), "input_dim_idx": input_dim_idx,
         "output_dim_idx": output_dim_idx, "mean": mean, "std": std,
         "seed": seed},
    )
    return out


def random_crop(x, shape, seed=None):
    """reference: python/paddle/fluid/layers/nn.py random_crop."""
    helper = LayerHelper("random_crop")
    out = _out(helper, x.dtype)
    seed_out = _out(helper, "int64", stop_gradient=True)
    helper.append_op(
        "random_crop", {"X": [x.name]},
        {"Out": [out.name], "SeedOut": [seed_out.name]},
        {"shape": list(shape), "seed": seed or 0},
    )
    return out


def similarity_focus(input, axis, indexes, name=None):
    """reference: python/paddle/fluid/layers/nn.py similarity_focus."""
    helper = LayerHelper("similarity_focus", name=name)
    out = _out(helper, input.dtype)
    helper.append_op(
        "similarity_focus", {"X": [input.name]}, {"Out": [out.name]},
        {"axis": axis, "indexes": list(indexes)},
    )
    return out


def psroi_pool(input, rois, output_channels, spatial_scale, pooled_height,
               pooled_width, name=None, rois_num=None):
    """reference: python/paddle/fluid/layers/nn.py:12626."""
    helper = LayerHelper("psroi_pool", name=name)
    out = _out(helper, input.dtype)
    ins = {"X": [input.name], "ROIs": [rois.name]}
    if rois_num is not None:
        ins["RoisNum"] = [rois_num.name]
    helper.append_op(
        "psroi_pool", ins, {"Out": [out.name]},
        {"output_channels": output_channels, "spatial_scale": spatial_scale,
         "pooled_height": pooled_height, "pooled_width": pooled_width},
    )
    return out


def prroi_pool(input, rois, spatial_scale=1.0, pooled_height=1,
               pooled_width=1, name=None, rois_num=None):
    """reference: python/paddle/fluid/layers/nn.py:12692."""
    helper = LayerHelper("prroi_pool", name=name)
    out = _out(helper, input.dtype)
    ins = {"X": [input.name], "ROIs": [rois.name]}
    if rois_num is not None:
        ins["RoisNum"] = [rois_num.name]
    helper.append_op(
        "prroi_pool", ins, {"Out": [out.name]},
        {"spatial_scale": spatial_scale, "pooled_height": pooled_height,
         "pooled_width": pooled_width},
    )
    return out


def deformable_conv(input, offset, mask, num_filters, filter_size,
                    stride=1, padding=0, dilation=1, groups=None,
                    deformable_groups=None, im2col_step=None,
                    param_attr=None, bias_attr=None, modulated=True,
                    name=None):
    """reference: python/paddle/fluid/layers/nn.py:13105 — DCN v2
    (modulated=True, with mask) or v1 (modulated=False)."""
    from paddle_tpu.initializer import NormalInitializer
    from paddle_tpu.layers import nn as nn_layers

    def _pair(v):
        return list(v) if isinstance(v, (list, tuple)) else [v, v]

    helper = LayerHelper("deformable_conv", param_attr=param_attr,
                         name=name)
    C = input.shape[1]
    groups = groups or 1
    deformable_groups = deformable_groups or 1
    k = _pair(filter_size)
    w = helper.create_parameter(
        helper.param_attr if param_attr is not None else ParamAttr(
            initializer=NormalInitializer(
                0.0, 1.0 / float(np.sqrt(C * k[0] * k[1]))
            )
        ),
        shape=[num_filters, C // groups, k[0], k[1]], dtype=input.dtype,
    )
    out = _out(helper, input.dtype)
    ins = {"Input": [input.name], "Offset": [offset.name],
           "Filter": [w.name]}
    op_type = "deformable_conv" if modulated else "deformable_conv_v1"
    if modulated:
        ins["Mask"] = [mask.name]
    helper.append_op(
        op_type, ins, {"Output": [out.name]},
        {"strides": _pair(stride), "paddings": _pair(padding),
         "dilations": _pair(dilation), "groups": groups,
         "deformable_groups": deformable_groups},
    )
    if bias_attr:
        b = helper.create_parameter(
            bias_attr if isinstance(bias_attr, ParamAttr) else ParamAttr(),
            shape=[num_filters], dtype=input.dtype,
        )
        out = nn_layers.elementwise_add(out, b, axis=1)
    return out


def deformable_roi_pooling(input, rois, trans, no_trans=False,
                           spatial_scale=1.0, group_size=(1, 1),
                           pooled_height=1, pooled_width=1, part_size=None,
                           sample_per_part=1, trans_std=0.1, position_sensitive=False,
                           name=None):
    """reference: python/paddle/fluid/layers/nn.py deformable_roi_pooling."""
    helper = LayerHelper("deformable_psroi_pooling", name=name)
    out = _out(helper, input.dtype)
    top_count = _out(helper, "float32", stop_gradient=True)
    C = input.shape[1]
    output_dim = (
        C // (pooled_height * pooled_width) if position_sensitive else C
    )
    helper.append_op(
        "deformable_psroi_pooling",
        {"X": [input.name], "ROIs": [rois.name], "Trans": [trans.name]},
        {"Out": [out.name], "TopCount": [top_count.name]},
        {"no_trans": no_trans, "spatial_scale": spatial_scale,
         "output_dim": output_dim, "pooled_height": pooled_height,
         "pooled_width": pooled_width,
         "sample_per_part": sample_per_part, "trans_std": trans_std},
    )
    return out


def generate_proposals(scores, bbox_deltas, im_info, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0, name=None,
                       return_rois_num=False):
    """reference: python/paddle/fluid/layers/detection.py
    generate_proposals."""
    helper = LayerHelper("generate_proposals", name=name)
    rois = _out(helper, scores.dtype, stop_gradient=True)
    probs = _out(helper, scores.dtype, stop_gradient=True)
    num = _out(helper, "int32", stop_gradient=True)
    helper.append_op(
        "generate_proposals",
        {"Scores": [scores.name], "BboxDeltas": [bbox_deltas.name],
         "ImInfo": [im_info.name], "Anchors": [anchors.name],
         "Variances": [variances.name]},
        {"RpnRois": [rois.name], "RpnRoiProbs": [probs.name],
         "RpnRoisNum": [num.name]},
        {"pre_nms_topN": pre_nms_top_n, "post_nms_topN": post_nms_top_n,
         "nms_thresh": nms_thresh, "min_size": min_size, "eta": eta},
    )
    if return_rois_num:
        return rois, probs, num
    return rois, probs


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, name=None):
    """reference: python/paddle/fluid/layers/detection.py
    distribute_fpn_proposals."""
    helper = LayerHelper("distribute_fpn_proposals", name=name)
    n_lvl = max_level - min_level + 1
    outs = [_out(helper, fpn_rois.dtype, stop_gradient=True)
            for _ in range(n_lvl)]
    restore = _out(helper, "int32", stop_gradient=True)
    counts = _out(helper, "int32", stop_gradient=True)
    helper.append_op(
        "distribute_fpn_proposals", {"FpnRois": [fpn_rois.name]},
        {"MultiFpnRois": [o.name for o in outs],
         "RestoreIndex": [restore.name],
         "MultiLevelRoIsNum": [counts.name]},
        {"min_level": min_level, "max_level": max_level,
         "refer_level": refer_level, "refer_scale": refer_scale},
    )
    return outs, restore


def collect_fpn_proposals(multi_rois, multi_scores, min_level, max_level,
                          post_nms_top_n, name=None):
    """reference: python/paddle/fluid/layers/detection.py
    collect_fpn_proposals."""
    helper = LayerHelper("collect_fpn_proposals", name=name)
    out = _out(helper, multi_rois[0].dtype, stop_gradient=True)
    num = _out(helper, "int32", stop_gradient=True)
    helper.append_op(
        "collect_fpn_proposals",
        {"MultiLevelRois": [r.name for r in multi_rois],
         "MultiLevelScores": [s.name for s in multi_scores]},
        {"FpnRois": [out.name], "RoisNum": [num.name]},
        {"post_nms_topN": post_nms_top_n},
    )
    return out


def retinanet_detection_output(bboxes, scores, anchors, im_info,
                               score_threshold=0.05, nms_top_k=1000,
                               keep_top_k=100, nms_threshold=0.3,
                               nms_eta=1.0):
    """reference: python/paddle/fluid/layers/detection.py
    retinanet_detection_output (concatenated-levels form)."""
    helper = LayerHelper("retinanet_detection_output")
    out = _out(helper, scores.dtype, stop_gradient=True)
    num = _out(helper, "int64", stop_gradient=True)
    helper.append_op(
        "retinanet_detection_output",
        {"BBoxes": [bboxes.name], "Scores": [scores.name],
         "Anchors": [anchors.name], "ImInfo": [im_info.name]},
        {"Out": [out.name], "NumDetections": [num.name]},
        {"score_threshold": score_threshold, "nms_top_k": nms_top_k,
         "keep_top_k": keep_top_k, "nms_threshold": nms_threshold},
    )
    return out


def locality_aware_nms(bboxes, scores, score_threshold, nms_top_k,
                       keep_top_k, nms_threshold=0.3, normalized=True,
                       nms_eta=1.0, background_label=-1, name=None):
    """reference: python/paddle/fluid/layers/detection.py
    locality_aware_nms."""
    helper = LayerHelper("locality_aware_nms", name=name)
    out = _out(helper, scores.dtype, stop_gradient=True)
    num = _out(helper, "int64", stop_gradient=True)
    helper.append_op(
        "locality_aware_nms",
        {"BBoxes": [bboxes.name], "Scores": [scores.name]},
        {"Out": [out.name], "NumDetections": [num.name]},
        {"score_threshold": score_threshold, "nms_top_k": nms_top_k,
         "keep_top_k": keep_top_k, "nms_threshold": nms_threshold,
         "background_label": background_label},
    )
    return out


def proximal_gd(param, grad, learning_rate, l1=0.0, l2=0.0):
    """Direct op access (the reference reaches proximal updates through
    optimizer classes; exposed for parity testing)."""
    helper = LayerHelper("proximal_gd")
    out = _out(helper, param.dtype)
    helper.append_op(
        "proximal_gd",
        {"Param": [param.name], "Grad": [grad.name],
         "LearningRate": [learning_rate.name]},
        {"ParamOut": [out.name]},
        {"l1": l1, "l2": l2},
    )
    return out


def unique(x, dtype="int32"):
    """reference: python/paddle/fluid/layers/nn.py unique — returns
    (Out, Index). Static-shape contract: Out keeps x's length with unique
    values front-compacted (see ops/misc_extra.py _unique)."""
    from paddle_tpu.layer_helper import LayerHelper

    helper = LayerHelper("unique")
    out = _out(helper, x.dtype)
    index = _out(helper, dtype, stop_gradient=True)
    helper.append_op(
        "unique", {"X": [x.name]},
        {"Out": [out.name], "Index": [index.name]},
        {"dtype": dtype},
    )
    return out, index


def unique_with_counts(x, dtype="int32"):
    """reference: python/paddle/fluid/layers/nn.py unique_with_counts —
    returns (Out, Index, Count); same static-shape contract as unique."""
    from paddle_tpu.layer_helper import LayerHelper

    helper = LayerHelper("unique_with_counts")
    out = _out(helper, x.dtype)
    index = _out(helper, dtype, stop_gradient=True)
    count = _out(helper, dtype, stop_gradient=True)
    helper.append_op(
        "unique_with_counts", {"X": [x.name]},
        {"Out": [out.name], "Index": [index.name], "Count": [count.name]},
        {"dtype": dtype},
    )
    return out, index, count
