"""Detection layer API (reference: python/paddle/fluid/layers/detection.py).

Thin builders over ops/detection.py; outputs are fixed-shape (NMS returns a
keep_top_k slate + count instead of a variable-length LoD tensor).
"""

from paddle_tpu.layer_helper import LayerHelper

__all__ = [
    "iou_similarity",
    "box_coder",
    "box_clip",
    "prior_box",
    "anchor_generator",
    "yolo_box",
    "multiclass_nms",
    "bipartite_match",
]


def _one_out(helper, op, inputs, attrs, out_slot="Out", dtype="float32"):
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(op, inputs, {out_slot: [out.name]}, attrs)
    return out


def iou_similarity(x, y, name=None):
    """reference: python/paddle/fluid/layers/detection.py iou_similarity."""
    helper = LayerHelper("iou_similarity", name=name)
    return _one_out(
        helper, "iou_similarity", {"X": [x.name], "Y": [y.name]}, {}
    )


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              name=None, axis=0):
    """reference: python/paddle/fluid/layers/detection.py box_coder."""
    helper = LayerHelper("box_coder", name=name)
    inputs = {"PriorBox": [prior_box.name], "TargetBox": [target_box.name]}
    attrs = {"code_type": code_type, "box_normalized": box_normalized,
             "axis": axis}
    if prior_box_var is not None:
        if isinstance(prior_box_var, (list, tuple)):
            attrs["variance"] = [float(v) for v in prior_box_var]
        else:
            inputs["PriorBoxVar"] = [prior_box_var.name]
    out = helper.create_variable_for_type_inference(target_box.dtype)
    helper.append_op("box_coder", inputs, {"OutputBox": [out.name]}, attrs)
    return out


def box_clip(input, im_info, name=None):
    helper = LayerHelper("box_clip", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "box_clip",
        {"Input": [input.name], "ImInfo": [im_info.name]},
        {"Output": [out.name]},
        {},
    )
    return out


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, name=None):
    """reference: python/paddle/fluid/layers/detection.py prior_box."""
    helper = LayerHelper("prior_box", name=name)
    boxes = helper.create_variable_for_type_inference(input.dtype)
    variances = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "prior_box",
        {"Input": [input.name], "Image": [image.name]},
        {"Boxes": [boxes.name], "Variances": [variances.name]},
        {
            "min_sizes": list(min_sizes),
            "max_sizes": list(max_sizes or []),
            "aspect_ratios": list(aspect_ratios),
            "variances": list(variance),
            "flip": flip,
            "clip": clip,
            "step_w": steps[0],
            "step_h": steps[1],
            "offset": offset,
        },
    )
    return boxes, variances


def anchor_generator(input, anchor_sizes=None, aspect_ratios=None,
                     variance=(0.1, 0.1, 0.2, 0.2), stride=None, offset=0.5,
                     name=None):
    """reference: python/paddle/fluid/layers/detection.py anchor_generator."""
    helper = LayerHelper("anchor_generator", name=name)
    anchors = helper.create_variable_for_type_inference(input.dtype)
    variances = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "anchor_generator",
        {"Input": [input.name]},
        {"Anchors": [anchors.name], "Variances": [variances.name]},
        {
            "anchor_sizes": list(anchor_sizes or [64.0, 128.0, 256.0, 512.0]),
            "aspect_ratios": list(aspect_ratios or [0.5, 1.0, 2.0]),
            "variances": list(variance),
            "stride": list(stride or [16.0, 16.0]),
            "offset": offset,
        },
    )
    return anchors, variances


def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, clip_bbox=True, name=None):
    """reference: python/paddle/fluid/layers/detection.py yolo_box."""
    helper = LayerHelper("yolo_box", name=name)
    boxes = helper.create_variable_for_type_inference(x.dtype)
    scores = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "yolo_box",
        {"X": [x.name], "ImgSize": [img_size.name]},
        {"Boxes": [boxes.name], "Scores": [scores.name]},
        {
            "anchors": list(anchors),
            "class_num": class_num,
            "conf_thresh": conf_thresh,
            "downsample_ratio": downsample_ratio,
            "clip_bbox": clip_bbox,
        },
    )
    return boxes, scores


def multiclass_nms(bboxes, scores, score_threshold=0.0, nms_top_k=400,
                   keep_top_k=100, nms_threshold=0.3, normalized=True,
                   background_label=0, name=None):
    """Fixed-slate NMS: Out [B, keep_top_k, 6] (label, score, box), label=-1
    marks empty slots; NumDetections [B]
    (reference: python/paddle/fluid/layers/detection.py multiclass_nms —
    LoD output there; static slate here, see ops/detection.py)."""
    helper = LayerHelper("multiclass_nms", name=name)
    out = helper.create_variable_for_type_inference(bboxes.dtype)
    num = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        "multiclass_nms",
        {"BBoxes": [bboxes.name], "Scores": [scores.name]},
        {"Out": [out.name], "NumDetections": [num.name]},
        {
            "score_threshold": score_threshold,
            "nms_top_k": nms_top_k,
            "keep_top_k": keep_top_k,
            "nms_threshold": nms_threshold,
            "normalized": normalized,
            "background_label": background_label,
        },
    )
    return out, num


def bipartite_match(dist_matrix, match_type=None, dist_threshold=None,
                    name=None):
    """reference: python/paddle/fluid/layers/detection.py bipartite_match."""
    helper = LayerHelper("bipartite_match", name=name)
    ids = helper.create_variable_for_type_inference("int32")
    dist = helper.create_variable_for_type_inference(dist_matrix.dtype)
    helper.append_op(
        "bipartite_match",
        {"DistMat": [dist_matrix.name]},
        {"ColToRowMatchIndices": [ids.name], "ColToRowMatchDist": [dist.name]},
        {},
    )
    return ids, dist
