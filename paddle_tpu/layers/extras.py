"""Third tranche of layer builders: RoI/vision, norms, CTR helpers,
structured-prediction losses.

reference: python/paddle/fluid/layers/nn.py (roi_align, roi_pool,
grid_sampler, affine_grid, affine_channel, lrn, l2_normalize, data_norm,
spectral_norm, pad_constant_like, im2sequence, row_conv, resize_trilinear,
conv3d_transpose, gather_tree), layers/loss.py (nce, warpctc,
center_loss), layers/nn.py linear_chain_crf/crf_decoding, layers/
detection.py sigmoid_focal_loss, contrib/layers/nn.py (partial_concat,
partial_sum, shuffle_batch), fluid.layers continuous_value_model.
"""

from paddle_tpu.layer_helper import LayerHelper

__all__ = [
    "roi_align", "roi_pool", "grid_sampler", "affine_grid",
    "affine_channel", "lrn", "l2_normalize", "data_norm", "spectral_norm",
    "pad_constant_like", "im2sequence", "row_conv", "resize_trilinear",
    "conv3d_transpose", "gather_tree", "nce", "warpctc", "center_loss",
    "linear_chain_crf", "crf_decoding", "sigmoid_focal_loss",
    "partial_concat", "partial_sum", "shuffle_batch",
    "continuous_value_model", "conv_shift", "unpool", "hinge_loss",
    "max_pool2d_with_index",
]


def _out(helper, dtype="float32", stop_gradient=False):
    return helper.create_variable_for_type_inference(
        dtype, stop_gradient=stop_gradient
    )


def _roi_inputs(input, rois, rois_num, rois_batch_id):
    ins = {"X": [input.name], "ROIs": [rois.name]}
    if rois_batch_id is not None:
        ins["BatchId"] = [rois_batch_id.name]
    elif rois_num is not None:
        ins["RoisNum"] = [rois_num.name]
    return ins


def roi_align(input, rois, pooled_height=1, pooled_width=1,
              spatial_scale=1.0, sampling_ratio=-1, rois_num=None,
              rois_batch_id=None, name=None):
    """reference: python/paddle/fluid/layers/nn.py roi_align. The LoD on
    `rois` becomes an explicit per-image count (`rois_num`) or per-RoI batch
    id (`rois_batch_id`)."""
    helper = LayerHelper("roi_align", name=name)
    out = _out(helper, input.dtype)
    helper.append_op(
        "roi_align", _roi_inputs(input, rois, rois_num, rois_batch_id),
        {"Out": [out.name]},
        {"pooled_height": pooled_height, "pooled_width": pooled_width,
         "spatial_scale": spatial_scale, "sampling_ratio": sampling_ratio},
    )
    return out


def roi_pool(input, rois, pooled_height=1, pooled_width=1,
             spatial_scale=1.0, rois_num=None, rois_batch_id=None,
             name=None):
    """reference: python/paddle/fluid/layers/nn.py roi_pool."""
    helper = LayerHelper("roi_pool", name=name)
    out = _out(helper, input.dtype)
    argmax = _out(helper, "int64", stop_gradient=True)
    helper.append_op(
        "roi_pool", _roi_inputs(input, rois, rois_num, rois_batch_id),
        {"Out": [out.name], "Argmax": [argmax.name]},
        {"pooled_height": pooled_height, "pooled_width": pooled_width,
         "spatial_scale": spatial_scale},
    )
    return out


def grid_sampler(x, grid, name=None):
    """reference: python/paddle/fluid/layers/nn.py grid_sampler."""
    helper = LayerHelper("grid_sampler", name=name)
    out = _out(helper, x.dtype)
    helper.append_op(
        "grid_sampler", {"X": [x.name], "Grid": [grid.name]},
        {"Output": [out.name]}, {},
    )
    return out


def affine_grid(theta, out_shape, name=None):
    """reference: python/paddle/fluid/layers/nn.py affine_grid."""
    helper = LayerHelper("affine_grid", name=name)
    out = _out(helper, theta.dtype)
    ins = {"Theta": [theta.name]}
    attrs = {}
    if hasattr(out_shape, "name"):
        ins["OutputShape"] = [out_shape.name]
    else:
        attrs["output_shape"] = list(out_shape)
    helper.append_op("affine_grid", ins, {"Output": [out.name]}, attrs)
    return out


def affine_channel(x, scale=None, bias=None, data_layout="NCHW", name=None,
                   act=None):
    """reference: python/paddle/fluid/layers/nn.py affine_channel."""
    helper = LayerHelper("affine_channel", name=name, act=act)
    out = _out(helper, x.dtype)
    helper.append_op(
        "affine_channel",
        {"X": [x.name], "Scale": [scale.name], "Bias": [bias.name]},
        {"Out": [out.name]}, {"data_layout": data_layout},
    )
    return helper.append_activation(out)


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None):
    """reference: python/paddle/fluid/layers/nn.py lrn."""
    helper = LayerHelper("lrn", name=name)
    out = _out(helper, input.dtype)
    mid = _out(helper, "float32", stop_gradient=True)
    helper.append_op(
        "lrn", {"X": [input.name]},
        {"Out": [out.name], "MidOut": [mid.name]},
        {"n": n, "k": k, "alpha": alpha, "beta": beta},
    )
    return out


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    """reference: python/paddle/fluid/layers/nn.py l2_normalize (norm op)."""
    helper = LayerHelper("l2_normalize", name=name)
    out = _out(helper, x.dtype)
    norm = _out(helper, "float32", stop_gradient=True)
    helper.append_op(
        "norm", {"X": [x.name]},
        {"Out": [out.name], "Norm": [norm.name]},
        {"axis": 1 if axis is None else axis, "epsilon": epsilon},
    )
    return out


def data_norm(input, act=None, epsilon=1e-5, param_attr=None,
              data_layout="NCHW", in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=True):
    """reference: python/paddle/fluid/layers/nn.py data_norm — batch-stat
    tables (size/sum/square-sum) normalize without learned scale/shift."""
    from paddle_tpu.initializer import ConstantInitializer
    from paddle_tpu.param_attr import ParamAttr

    helper = LayerHelper("data_norm", name=name, act=act)
    C = input.shape[1]
    dtype = "float32"

    def stat(suffix, value):
        # stat tables update via the op's *Out write-back (CentersOut
        # pattern), not via gradients — trainable=False keeps the
        # optimizer's hands off them
        p = helper.create_parameter(
            ParamAttr(name=None, initializer=ConstantInitializer(value),
                      trainable=False),
            shape=[C], dtype=dtype,
        )
        p.stop_gradient = True
        return p

    batch_size = stat("batch_size", 1e4)
    batch_sum = stat("batch_sum", 0.0)
    batch_square_sum = stat("batch_square_sum", 1e4)
    out = _out(helper, input.dtype)
    means = _out(helper, dtype, stop_gradient=True)
    scales = _out(helper, dtype, stop_gradient=True)
    helper.append_op(
        "data_norm",
        {"X": [input.name], "BatchSize": [batch_size.name],
         "BatchSum": [batch_sum.name],
         "BatchSquareSum": [batch_square_sum.name]},
        {"Y": [out.name], "Means": [means.name], "Scales": [scales.name],
         "BatchSizeOut": [batch_size.name],
         "BatchSumOut": [batch_sum.name],
         "BatchSquareSumOut": [batch_square_sum.name]},
        {"epsilon": epsilon},
    )
    return helper.append_activation(out)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """reference: python/paddle/fluid/layers/nn.py spectral_norm."""
    from paddle_tpu.initializer import NormalInitializer
    from paddle_tpu.param_attr import ParamAttr

    helper = LayerHelper("spectral_norm", name=name)
    h = weight.shape[dim]
    w = 1
    for i, d in enumerate(weight.shape):
        if i != dim:
            w *= d
    u = helper.create_parameter(
        ParamAttr(initializer=NormalInitializer(0.0, 1.0), trainable=False),
        shape=[h], dtype="float32",
    )
    v = helper.create_parameter(
        ParamAttr(initializer=NormalInitializer(0.0, 1.0), trainable=False),
        shape=[w], dtype="float32",
    )
    u.stop_gradient = True
    v.stop_gradient = True
    out = _out(helper, weight.dtype)
    helper.append_op(
        "spectral_norm",
        {"Weight": [weight.name], "U": [u.name], "V": [v.name]},
        # UOut/VOut alias back onto U/V so power iterates persist across
        # steps (the reference updates them in place each forward)
        {"Out": [out.name], "UOut": [u.name], "VOut": [v.name]},
        {"dim": dim, "power_iters": power_iters, "eps": eps},
    )
    return out


def pad_constant_like(x, y, pad_value=0.0, name=None):
    """reference: python/paddle/fluid/layers/nn.py pad_constant_like."""
    helper = LayerHelper("pad_constant_like", name=name)
    out = _out(helper, y.dtype)
    helper.append_op(
        "pad_constant_like", {"X": [x.name], "Y": [y.name]},
        {"Out": [out.name]}, {"pad_value": pad_value},
    )
    return out


def im2sequence(input, filter_size=1, stride=1, padding=0, name=None):
    """reference: python/paddle/fluid/layers/nn.py im2sequence."""
    def _pair(v):
        return list(v) if isinstance(v, (list, tuple)) else [v, v]

    helper = LayerHelper("im2sequence", name=name)
    out = _out(helper, input.dtype)
    pads = _pair(padding)
    if len(pads) == 2:
        pads = pads + pads
    helper.append_op(
        "im2sequence", {"X": [input.name]}, {"Out": [out.name]},
        {"kernels": _pair(filter_size), "strides": _pair(stride),
         "paddings": pads},
    )
    return out


def row_conv(input, future_context_size, param_attr=None, act=None):
    """reference: python/paddle/fluid/layers/nn.py row_conv — lookahead
    filter [future_context_size + 1, D] over batched [B, T, D] input."""
    helper = LayerHelper("row_conv", param_attr=param_attr, act=act)
    d = input.shape[-1]
    flt = helper.create_parameter(
        helper.param_attr, shape=[future_context_size + 1, d],
        dtype=input.dtype,
    )
    out = _out(helper, input.dtype)
    helper.append_op(
        "row_conv", {"X": [input.name], "Filter": [flt.name]},
        {"Out": [out.name]}, {},
    )
    return helper.append_activation(out)


def resize_trilinear(input, out_shape=None, scale=None, name=None,
                     actual_shape=None, align_corners=True, align_mode=1,
                     data_format="NCDHW"):
    """reference: python/paddle/fluid/layers/nn.py resize_trilinear."""
    helper = LayerHelper("trilinear_interp", name=name)
    out = _out(helper, input.dtype)
    attrs = {"align_corners": align_corners, "align_mode": align_mode}
    if out_shape is not None:
        attrs["out_d"], attrs["out_h"], attrs["out_w"] = (
            int(out_shape[0]), int(out_shape[1]), int(out_shape[2])
        )
    elif scale is not None:
        attrs["out_d"] = int(input.shape[2] * scale)
        attrs["out_h"] = int(input.shape[3] * scale)
        attrs["out_w"] = int(input.shape[4] * scale)
    helper.append_op(
        "trilinear_interp", {"X": [input.name]}, {"Out": [out.name]}, attrs
    )
    return out


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None):
    """reference: python/paddle/fluid/layers/nn.py conv3d_transpose."""
    def _triple(v):
        return list(v) if isinstance(v, (list, tuple)) else [v, v, v]

    helper = LayerHelper("conv3d_transpose", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    in_c = input.shape[1]
    ks = _triple(filter_size)
    strides = _triple(stride)
    pads = _triple(padding)
    flt = helper.create_parameter(
        helper.param_attr,
        shape=[in_c, num_filters // groups] + ks,
        dtype=input.dtype,
    )
    out = _out(helper, input.dtype)
    helper.append_op(
        "conv3d_transpose",
        {"Input": [input.name], "Filter": [flt.name]},
        {"Output": [out.name]},
        {"strides": strides, "paddings": pads, "groups": groups},
    )
    if helper.bias_attr is not False:
        out = helper.append_bias_op(out, dim_start=1, dim_end=2)
    return helper.append_activation(out)


def gather_tree(ids, parents):
    """reference: python/paddle/fluid/layers/nn.py gather_tree."""
    helper = LayerHelper("gather_tree")
    out = _out(helper, ids.dtype)
    helper.append_op(
        "gather_tree", {"Ids": [ids.name], "Parents": [parents.name]},
        {"Out": [out.name]}, {},
    )
    return out


_SAMPLER_ENUM = {"uniform": 0, "log_uniform": 1}


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=None, name=None,
        sampler="uniform", custom_dist=None, seed=0, is_sparse=False):
    """reference: python/paddle/fluid/layers/loss.py:633 nce. `custom_dist`
    sampling and `is_sparse` SelectedRows grads have no TPU analog (dense
    grads are the design); uniform and log_uniform samplers are native."""
    from paddle_tpu.utils.enforce import enforce

    enforce(sampler in _SAMPLER_ENUM,
            f"nce sampler must be uniform/log_uniform, got {sampler}")
    helper = LayerHelper("nce", param_attr=param_attr, bias_attr=bias_attr,
                         name=name)
    dim = input.shape[1]
    num_neg = num_neg_samples or 10
    w = helper.create_parameter(
        helper.param_attr, shape=[num_total_classes, dim],
        dtype=input.dtype,
    )
    ins = {"Input": [input.name], "Label": [label.name],
           "Weight": [w.name]}
    if helper.bias_attr is not False:
        b = helper.create_parameter(
            helper.bias_attr, shape=[num_total_classes], dtype=input.dtype,
            is_bias=True,
        )
        ins["Bias"] = [b.name]
    if sample_weight is not None:
        ins["SampleWeight"] = [sample_weight.name]
    cost = _out(helper, input.dtype)
    slogits = _out(helper, input.dtype, stop_gradient=True)
    slabels = _out(helper, "int64", stop_gradient=True)
    helper.append_op(
        "nce", ins,
        {"Cost": [cost.name], "SampleLogits": [slogits.name],
         "SampleLabels": [slabels.name]},
        {"num_total_classes": num_total_classes,
         "num_neg_samples": num_neg, "seed": seed,
         "sampler": _SAMPLER_ENUM[sampler]},
    )
    return cost


def warpctc(input, label, blank=0, norm_by_times=False, input_length=None,
            label_length=None):
    """reference: python/paddle/fluid/layers/loss.py:489 warpctc. Padded
    form only (the LoD form has no TPU analog): `input` is
    [max_logit_length, B, V] time-major exactly as the reference's padded
    mode; `label` is [B, max_label_length]."""
    from paddle_tpu.layers.tensor import transpose

    helper = LayerHelper("warpctc")
    logits_btv = transpose(input, [1, 0, 2])
    ins = {"Logits": [logits_btv.name], "Label": [label.name]}
    if input_length is not None:
        ins["LogitsLength"] = [input_length.name]
    if label_length is not None:
        ins["LabelLength"] = [label_length.name]
    loss = _out(helper, "float32")
    grad = _out(helper, "float32", stop_gradient=True)
    helper.append_op(
        "warpctc", ins,
        {"Loss": [loss.name], "WarpCTCGrad": [grad.name]},
        {"blank": blank, "norm_by_times": norm_by_times},
    )
    return loss


def center_loss(input, label, num_classes, alpha, param_attr=None,
                update_center=True):
    """reference: python/paddle/fluid/layers/loss.py center_loss — the
    centers table updates through CentersOut scope write-back (like
    batch_norm's running stats)."""
    from paddle_tpu.initializer import ConstantInitializer
    from paddle_tpu.param_attr import ParamAttr

    helper = LayerHelper("center_loss", param_attr=param_attr)
    dim = input.shape[1]
    centers = helper.create_parameter(
        helper.param_attr if param_attr is not None else ParamAttr(
            initializer=ConstantInitializer(0.0), trainable=False
        ),
        shape=[num_classes, dim], dtype=input.dtype,
    )
    centers.stop_gradient = True
    from paddle_tpu.layers.tensor import fill_constant

    lr = fill_constant([1], "float32", float(alpha))
    loss = _out(helper, input.dtype)
    diff = _out(helper, input.dtype, stop_gradient=True)
    helper.append_op(
        "center_loss",
        {"X": [input.name], "Label": [label.name],
         "Centers": [centers.name], "CenterUpdateRate": [lr.name]},
        {"Loss": [loss.name], "SampleCenterDiff": [diff.name],
         "CentersOut": [centers.name]},
        {"need_update": update_center},
    )
    return loss


def linear_chain_crf(input, label, param_attr=None, length=None):
    """reference: python/paddle/fluid/layers/nn.py:552 linear_chain_crf —
    emits the per-sequence negative log-likelihood; transition param is
    [size + 2, size] (start row, stop row, pairwise)."""
    helper = LayerHelper("linear_chain_crf", param_attr=param_attr)
    size = input.shape[-1]
    transition = helper.create_parameter(
        helper.param_attr, shape=[size + 2, size], dtype=input.dtype,
    )
    ins = {"Emission": [input.name], "Transition": [transition.name],
           "Label": [label.name]}
    if length is not None:
        ins["Length"] = [length.name]
    ll = _out(helper, "float32")
    alpha = _out(helper, "float32", stop_gradient=True)
    eexp = _out(helper, "float32", stop_gradient=True)
    texp = _out(helper, "float32", stop_gradient=True)
    helper.append_op(
        "linear_chain_crf", ins,
        {"LogLikelihood": [ll.name], "Alpha": [alpha.name],
         "EmissionExps": [eexp.name], "TransitionExps": [texp.name]},
        {},
    )
    return ll


def crf_decoding(input, param_attr, label=None, length=None):
    """reference: python/paddle/fluid/layers/nn.py crf_decoding."""
    from paddle_tpu.core.ir import default_main_program

    helper = LayerHelper("crf_decoding", param_attr=param_attr)
    # reuse the transition parameter created by linear_chain_crf via name
    name = param_attr.name if param_attr is not None else None
    block = default_main_program().global_block()
    from paddle_tpu.utils.enforce import enforce

    enforce(name is not None and block._find_var_recursive(name) is not None,
            "crf_decoding needs param_attr naming the trained transition "
            "parameter (create it via linear_chain_crf first)")
    ins = {"Emission": [input.name], "Transition": [name]}
    if label is not None:
        ins["Label"] = [label.name]
    if length is not None:
        ins["Length"] = [length.name]
    path = _out(helper, "int64", stop_gradient=True)
    helper.append_op("crf_decoding", ins, {"ViterbiPath": [path.name]}, {})
    return path


def sigmoid_focal_loss(x, label, fg_num, gamma=2.0, alpha=0.25):
    """reference: python/paddle/fluid/layers/detection.py
    sigmoid_focal_loss."""
    helper = LayerHelper("sigmoid_focal_loss")
    out = _out(helper, x.dtype)
    helper.append_op(
        "sigmoid_focal_loss",
        {"X": [x.name], "Label": [label.name], "FgNum": [fg_num.name]},
        {"Out": [out.name]}, {"gamma": gamma, "alpha": alpha},
    )
    return out


def partial_concat(input, start_index=0, length=-1):
    """reference: python/paddle/fluid/contrib/layers/nn.py partial_concat."""
    helper = LayerHelper("partial_concat")
    out = _out(helper, input[0].dtype)
    helper.append_op(
        "partial_concat", {"X": [v.name for v in input]},
        {"Out": [out.name]},
        {"start_index": start_index, "length": length},
    )
    return out


def partial_sum(input, start_index=0, length=-1):
    """reference: python/paddle/fluid/contrib/layers/nn.py partial_sum."""
    helper = LayerHelper("partial_sum")
    out = _out(helper, input[0].dtype)
    helper.append_op(
        "partial_sum", {"X": [v.name for v in input]},
        {"Out": [out.name]},
        {"start_index": start_index, "length": length},
    )
    return out


def shuffle_batch(x, seed=None):
    """reference: python/paddle/fluid/contrib/layers/nn.py shuffle_batch."""
    helper = LayerHelper("shuffle_batch")
    out = _out(helper, x.dtype)
    idx = _out(helper, "int64", stop_gradient=True)
    seed_out = _out(helper, "int64", stop_gradient=True)
    helper.append_op(
        "shuffle_batch", {"X": [x.name]},
        {"Out": [out.name], "ShuffleIdx": [idx.name],
         "SeedOut": [seed_out.name]},
        {"seed": seed or 0},
    )
    return out


def continuous_value_model(input, cvm, use_cvm=True):
    """reference: python/paddle/fluid/layers/nn.py continuous_value_model."""
    helper = LayerHelper("cvm")
    out = _out(helper, input.dtype)
    helper.append_op(
        "cvm", {"X": [input.name], "CVM": [cvm.name]},
        {"Y": [out.name]}, {"use_cvm": use_cvm},
    )
    return out


def conv_shift(x, y, name=None):
    """reference: python/paddle/fluid/layers/nn.py conv_shift (circular
    correlation)."""
    helper = LayerHelper("conv_shift", name=name)
    out = _out(helper, x.dtype)
    helper.append_op(
        "conv_shift", {"X": [x.name], "Y": [y.name]}, {"Out": [out.name]}, {}
    )
    return out


def unpool(x, indices, unpooled_height, unpooled_width, name=None):
    """Max-unpool from recorded pool indices (reference:
    paddle/fluid/operators/unpool_op.cc)."""
    helper = LayerHelper("unpool", name=name)
    out = _out(helper, x.dtype)
    helper.append_op(
        "unpool", {"X": [x.name], "Indices": [indices.name]},
        {"Out": [out.name]},
        {"unpooled_height": unpooled_height,
         "unpooled_width": unpooled_width},
    )
    return out


def hinge_loss(logits, labels, name=None):
    """reference: paddle/fluid/operators/hinge_loss_op.cc."""
    helper = LayerHelper("hinge_loss", name=name)
    out = _out(helper, logits.dtype)
    helper.append_op(
        "hinge_loss", {"Logits": [logits.name], "Labels": [labels.name]},
        {"Loss": [out.name]}, {},
    )
    return out


def max_pool2d_with_index(x, pool_size, pool_stride=None, pool_padding=0,
                          name=None):
    """Pooling that also emits argmax indices (reference:
    paddle/fluid/operators/pool_with_index_op.cc; pairs with `unpool`)."""
    def _pair(v):
        return list(v) if isinstance(v, (list, tuple)) else [v, v]

    helper = LayerHelper("max_pool2d_with_index", name=name)
    out = _out(helper, x.dtype)
    mask = _out(helper, "int32", stop_gradient=True)
    helper.append_op(
        "max_pool2d_with_index", {"X": [x.name]},
        {"Out": [out.name], "Mask": [mask.name]},
        {"ksize": _pair(pool_size),
         "strides": _pair(pool_stride or pool_size),
         "paddings": _pair(pool_padding)},
    )
    return out, mask
