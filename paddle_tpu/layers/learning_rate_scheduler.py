"""Learning-rate schedules as program ops over a persistable step counter.

Same architecture as the reference (reference: python/paddle/fluid/layers/
learning_rate_scheduler.py — schedules are ops reading @LR_DECAY_COUNTER@),
so the schedule is part of the compiled step and advances with it.
"""

import math

from paddle_tpu.layer_helper import LayerHelper
from paddle_tpu.layers import tensor

__all__ = [
    "noam_decay",
    "exponential_decay",
    "natural_exp_decay",
    "inverse_time_decay",
    "polynomial_decay",
    "piecewise_decay",
    "cosine_decay",
    "linear_lr_warmup",
]

_COUNTER_NAME = "@LR_DECAY_COUNTER@"


def _decay_step_counter(begin=0):
    from paddle_tpu.core.ir import default_main_program

    helper = LayerHelper("global_step_counter")
    already = _COUNTER_NAME in default_main_program().global_block().vars
    counter = tensor.create_global_var(
        shape=[1],
        value=float(begin),
        dtype="float32",
        persistable=True,
        name=_COUNTER_NAME,
    )
    # composed schedules share one counter: only the first creator appends
    # the per-step increment (reference: learning_rate_scheduler.py
    # _decay_step_counter creates the var once)
    if not already:
        helper.append_op(
            "increment",
            {"X": [counter.name]},
            {"Out": [counter.name]},
            # optimize role: the counter must tick once per STEP, not once
            # per microbatch, under PipelineOptimizer's microbatched step
            {"step": 1.0, "op_role": 2},
        )
    return counter


def _floor(x):
    helper = LayerHelper("floor")
    out = helper.create_variable_for_type_inference("float32")
    helper.append_op("floor", {"X": [x.name]}, {"Out": [out.name]})
    return out


def noam_decay(d_model, warmup_steps):
    """lr = d_model^-0.5 * min(step^-0.5, step * warmup^-1.5)
    (reference: python/paddle/fluid/layers/learning_rate_scheduler.py:63)."""
    from paddle_tpu import layers

    step = _decay_step_counter(begin=1)
    a = layers.pow(step, -0.5)
    b = layers.scale(step, scale=warmup_steps ** -1.5)
    lr = layers.scale(layers.elementwise_min(a, b), scale=d_model ** -0.5)
    return lr


def exponential_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    from paddle_tpu import layers

    step = _decay_step_counter()
    div = layers.scale(step, scale=1.0 / decay_steps)
    if staircase:
        div = _floor(div)
    return layers.scale(
        layers.elementwise_pow(
            tensor.fill_constant([1], "float32", decay_rate), div
        ),
        scale=float(learning_rate),
    )


def natural_exp_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    from paddle_tpu import layers

    step = _decay_step_counter()
    div = layers.scale(step, scale=1.0 / decay_steps)
    if staircase:
        div = _floor(div)
    return layers.scale(
        layers.exp(layers.scale(div, scale=-decay_rate)), scale=float(learning_rate)
    )


def inverse_time_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    from paddle_tpu import layers

    step = _decay_step_counter()
    div = layers.scale(step, scale=1.0 / decay_steps)
    if staircase:
        div = _floor(div)
    denom = layers.scale(div, scale=decay_rate, bias=1.0)
    lr = tensor.fill_constant([1], "float32", float(learning_rate))
    return layers.elementwise_div(lr, denom)


def polynomial_decay(
    learning_rate, decay_steps, end_learning_rate=0.0001, power=1.0, cycle=False
):
    from paddle_tpu import layers

    step = _decay_step_counter()
    capped = layers.clip(step, 0.0, float(decay_steps))
    frac = layers.scale(capped, scale=1.0 / decay_steps)
    one_minus = layers.scale(frac, scale=-1.0, bias=1.0)
    poly = layers.pow(one_minus, factor=power)
    return layers.scale(
        poly, scale=float(learning_rate) - end_learning_rate, bias=end_learning_rate
    )


def piecewise_decay(boundaries, values):
    from paddle_tpu import layers

    step = _decay_step_counter()
    lr = tensor.fill_constant([1], "float32", values[-1])
    # build nested where: evaluated right-to-left
    for b, v in zip(reversed(boundaries), reversed(values[:-1])):
        boundary = tensor.fill_constant([1], "float32", float(b))
        is_before = tensor.less_than(step, boundary)
        lr = tensor.where(is_before, tensor.fill_constant([1], "float32", v), lr)
    return lr


def cosine_decay(learning_rate, step_each_epoch, epochs):
    from paddle_tpu import layers

    step = _decay_step_counter()
    epoch = _floor(layers.scale(step, scale=1.0 / step_each_epoch))
    cosv = layers.cos(layers.scale(epoch, scale=math.pi / epochs))
    return layers.scale(cosv, scale=0.5 * learning_rate, bias=0.5 * learning_rate)


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    from paddle_tpu import layers

    step = _decay_step_counter()
    if not hasattr(learning_rate, "name"):
        learning_rate = tensor.fill_constant([1], "float32", float(learning_rate))
    frac = layers.clip(layers.scale(step, scale=1.0 / warmup_steps), 0.0, 1.0)
    warm = layers.scale(frac, scale=end_lr - start_lr, bias=start_lr)
    boundary = tensor.fill_constant([1], "float32", float(warmup_steps))
    in_warmup = tensor.less_than(step, boundary)
    return tensor.where(in_warmup, warm, learning_rate)
