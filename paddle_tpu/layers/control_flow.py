"""Structured control-flow layers: While / cond.

API parity with the reference (reference: python/paddle/fluid/layers/
control_flow.py — While, cond); lowered to lax.while_loop / lax.cond inside
the whole-block XLA computation (see ops/control_flow.py) instead of host-side
sub-block execution.
"""

from paddle_tpu.core.ir import default_main_program
from paddle_tpu.layer_helper import LayerHelper
from paddle_tpu.utils import unique_name

__all__ = ["While", "cond", "array_write", "array_read"]


class While:
    """
    with While(cond_var) as w:   # ops appended inside run in the loop body
        ...
    Variables written in the body that pre-exist outside are loop-carried.
    """

    def __init__(self, cond, name=None):
        self.helper = LayerHelper("while", name=name)
        self.cond_var = cond
        self.program = default_main_program()

    def __enter__(self):
        self.parent_idx = self.program.current_block_idx
        self.sub_block = self.program._create_block()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.program._rollback()
        parent = self.program.block(self.parent_idx)
        parent.append_op(
            "while",
            inputs={"Condition": [self.cond_var.name]},
            outputs={},
            attrs={"sub_block": self.sub_block.idx},
        )
        return False

    def block(self):
        return self


def cond(pred, true_fn=None, false_fn=None, name=None):
    """Two-branch conditional (reference: python/paddle/fluid/layers/
    control_flow.py cond). Both branches are traced into sub-blocks; their
    return variables must match in structure."""
    helper = LayerHelper("cond", name=name)
    program = default_main_program()
    parent_idx = program.current_block_idx

    true_block = program._create_block()
    true_out = true_fn() if true_fn is not None else None
    program._rollback()

    false_idx = -1
    false_out = None
    if false_fn is not None:
        false_block = program._create_block()
        false_out = false_fn()
        program._rollback()
        false_idx = false_block.idx

    def _norm(o):
        if o is None:
            return []
        return list(o) if isinstance(o, (list, tuple)) else [o]

    t_outs, f_outs = _norm(true_out), _norm(false_out)
    if t_outs and (false_fn is None or len(f_outs) != len(t_outs)):
        from paddle_tpu.utils.enforce import EnforceError

        raise EnforceError(
            f"cond: true_fn returns {len(t_outs)} value(s) but false_fn "
            f"returns {len(f_outs)} — both branches must produce the same "
            f"output structure"
        )
    parent = program.block(parent_idx)
    outs = []
    # unify branch outputs through fresh vars written by both branches
    for i, tv in enumerate(t_outs):
        out = parent.create_var(
            name=helper.name + f".out_{i}", dtype=tv.dtype, shape=tv.shape
        )
        program.block(true_block.idx).append_op(
            "assign", {"X": [tv.name]}, {"Out": [out.name]}
        )
        if false_idx >= 0 and i < len(f_outs):
            program.block(false_idx).append_op(
                "assign", {"X": [f_outs[i].name]}, {"Out": [out.name]}
            )
        outs.append(out)
    parent.append_op(
        "conditional_block",
        inputs={"Cond": [pred.name]},
        outputs={"Out": [o.name for o in outs]},
        attrs={"sub_block": true_block.idx, "sub_block_false": false_idx},
    )
    if not outs:
        return None
    return outs[0] if len(outs) == 1 else outs


def array_write(x, i, array=None):
    """TensorArray write (reference: python/paddle/fluid/layers/
    control_flow.py array_write -> write_to_array op). Dense-semantics
    form: indices must be program constants (a fill_constant that nothing
    else writes — resolved at first run, passes.resolve_tensor_array_
    indices); a data-dependent index raises with guidance (ops/tail.py) —
    prefer layers.stack for new code."""
    helper = LayerHelper("array_write")
    out = array
    if out is None:
        out = helper.block.create_var(
            name=unique_name.generate("tensor_array"), shape=None,
            dtype=x.dtype,
        )
    ins = {"X": [x.name], "I": [i.name]}
    if array is not None:
        ins["Array"] = [array.name]
    helper.append_op("write_to_array", ins, {"Out": [out.name]}, {})
    return out


def array_read(array, i):
    """TensorArray read (reference: array_read -> read_from_array op);
    same program-constant index contract as array_write."""
    helper = LayerHelper("array_read")
    out = helper.create_variable_for_type_inference(array.dtype or "float32")
    helper.append_op(
        "read_from_array",
        {"X": [array.name], "I": [i.name]},
        {"Out": [out.name]},
        {},
    )
    return out
