"""Collective ops at the layer level.

The reference inserts c_allreduce/c_allgather ops bound to NCCL rings
(reference: python/paddle/fluid/layers/collective.py:20,108;
paddle/fluid/operators/collective/c_allreduce_op.h:105). Here a collective op
is an annotation in the IR: when the program is compiled for a mesh
(compiler.CompiledProgram / parallel/), the lowering emits jax.lax.psum et al.
over the named mesh axis — XLA maps them onto ICI. Outside a mesh context
they are identity (single-device semantics), mirroring single-trainer runs.
"""

import jax

from paddle_tpu.core.registry import register_op
from paddle_tpu.layer_helper import LayerHelper
from paddle_tpu.ops.common import first
from paddle_tpu.parallel.env import current_mesh_axis

__all__ = ["_allreduce", "_c_allgather", "_c_broadcast", "_c_reducescatter"]


def _make_collective(op_type, lax_fn):
    @register_op(op_type)
    def _lower(ins, attrs, _fn=lax_fn):
        x = first(ins, "X")
        axis = current_mesh_axis(attrs.get("ring_id", 0))
        if axis is None:
            return {"Out": [x]}
        return {"Out": [_fn(x, axis)]}


_make_collective("c_allreduce_sum", lambda x, ax: jax.lax.psum(x, ax))
_make_collective("c_allreduce_max", lambda x, ax: jax.lax.pmax(x, ax))
_make_collective("c_allreduce_min", lambda x, ax: jax.lax.pmin(x, ax))
_make_collective(
    "c_allreduce_prod",
    lambda x, ax: jax.lax.all_gather(x, ax).prod(axis=0),
)
_make_collective(
    "c_allgather", lambda x, ax: jax.lax.all_gather(x, ax, tiled=True)
)
_make_collective(
    "c_broadcast",
    lambda x, ax: jax.lax.all_gather(x, ax)[0],
)


@register_op("c_reducescatter")
def _c_reducescatter(ins, attrs):
    x = first(ins, "X")
    axis = current_mesh_axis(attrs.get("ring_id", 0))
    if axis is None:
        return {"Out": [x]}
    return {"Out": [jax.lax.psum_scatter(x, axis, tiled=True)]}


@register_op("c_sync_calc_stream")
def _c_sync_calc_stream(ins, attrs):
    # stream sync is meaningless under XLA's single-computation schedule
    return {"Out": [first(ins, "X")]}


@register_op("c_sync_comm_stream")
def _c_sync_comm_stream(ins, attrs):
    return {"Out": [first(ins, "X")]}


def _collective_layer(op_type, x, ring_id=0, name=None):
    helper = LayerHelper(op_type, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        op_type, {"X": [x.name]}, {"Out": [out.name]}, {"ring_id": ring_id}
    )
    return out


def _allreduce(x, ring_id=0, use_calc_stream=False, name=None):
    return _collective_layer("c_allreduce_sum", x, ring_id, name)


def _c_allgather(x, nranks=1, ring_id=0, name=None):
    return _collective_layer("c_allgather", x, ring_id, name)


def _c_broadcast(x, root=0, ring_id=0, name=None):
    return _collective_layer("c_broadcast", x, ring_id, name)


def _c_reducescatter_layer(x, nranks=1, ring_id=0, name=None):
    return _collective_layer("c_reducescatter", x, ring_id, name)
