"""Sequence layer API over padded tensors + lengths
(reference: python/paddle/fluid/layers/sequence_lod.py — there LoD-driven;
here every function takes an optional `length` [B] tensor, SURVEY §5.7).
"""

from paddle_tpu.layer_helper import LayerHelper

__all__ = [
    "sequence_pool",
    "sequence_softmax",
    "sequence_reverse",
    "sequence_expand_as",
    "sequence_concat",
    "sequence_slice",
    "sequence_enumerate",
    "sequence_erase",
    "sequence_mask",
    "sequence_pad",
    "sequence_unpad",
    "sequence_conv",
    "sequence_first_step",
    "sequence_last_step",
    "sequence_expand",
    "sequence_reshape",
    "sequence_scatter",
    "lod_reset",
    "chunk_eval",
    "beam_search",
    "beam_search_decode",
]


def _seq_inputs(x, length):
    ins = {"X": [x.name]}
    if length is not None:
        ins["Length"] = [length.name]
    return ins


def _one(helper, op, ins, attrs, dtype, slot="Out"):
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(op, ins, {slot: [out.name]}, attrs)
    return out


def sequence_pool(input, pool_type, length=None, name=None):
    """reference: python/paddle/fluid/layers/sequence_lod.py sequence_pool."""
    helper = LayerHelper("sequence_pool", name=name)
    return _one(
        helper, "sequence_pool", _seq_inputs(input, length),
        {"pooltype": pool_type.upper()}, input.dtype,
    )


def sequence_first_step(input, length=None, name=None):
    return sequence_pool(input, "FIRST", length, name)


def sequence_last_step(input, length=None, name=None):
    return sequence_pool(input, "LAST", length, name)


def sequence_softmax(input, length=None, name=None):
    helper = LayerHelper("sequence_softmax", name=name)
    return _one(
        helper, "sequence_softmax", _seq_inputs(input, length), {},
        input.dtype,
    )


def sequence_reverse(x, length=None, name=None):
    helper = LayerHelper("sequence_reverse", name=name)
    return _one(
        helper, "sequence_reverse", _seq_inputs(x, length), {}, x.dtype, "Y"
    )


def sequence_expand_as(x, y, length=None, name=None):
    helper = LayerHelper("sequence_expand_as", name=name)
    ins = {"X": [x.name], "Y": [y.name]}
    if length is not None:
        ins["Length"] = [length.name]
    return _one(helper, "sequence_expand_as", ins, {}, x.dtype)


def sequence_concat(input, lengths=None, name=None):
    """Row-wise concatenation; returns (out, out_length)."""
    helper = LayerHelper("sequence_concat", name=name)
    ins = {"X": [v.name for v in input]}
    if lengths is not None:
        ins["Length"] = [v.name for v in lengths]
    out = helper.create_variable_for_type_inference(input[0].dtype)
    out_len = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        "sequence_concat", ins,
        {"Out": [out.name], "OutLength": [out_len.name]}, {},
    )
    return out, out_len


def sequence_slice(input, offset, length, name=None):
    helper = LayerHelper("sequence_slice", name=name)
    return _one(
        helper, "sequence_slice",
        {"X": [input.name], "Offset": [offset.name], "Length": [length.name]},
        {}, input.dtype,
    )


def sequence_enumerate(input, win_size, pad_value=0, length=None, name=None):
    helper = LayerHelper("sequence_enumerate", name=name)
    return _one(
        helper, "sequence_enumerate", _seq_inputs(input, length),
        {"win_size": win_size, "pad_value": pad_value}, input.dtype,
    )


def sequence_erase(input, tokens, length=None, name=None):
    """Returns (out, new_length)."""
    helper = LayerHelper("sequence_erase", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    out_len = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        "sequence_erase", _seq_inputs(input, length),
        {"Out": [out.name], "OutLength": [out_len.name]},
        {"tokens": list(tokens)},
    )
    return out, out_len


def sequence_mask(x, maxlen, dtype="int64", name=None):
    """reference: python/paddle/fluid/layers/sequence_lod.py sequence_mask.
    maxlen must be a static int on TPU."""
    helper = LayerHelper("sequence_mask", name=name)
    return _one(
        helper, "sequence_mask", {"X": [x.name]},
        {"maxlen": int(maxlen), "out_dtype": dtype}, dtype, "Y"
    )


def sequence_pad(x, pad_value=0.0, length=None, name=None):
    """Returns (out, length)."""
    helper = LayerHelper("sequence_pad", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    out_len = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        "sequence_pad", _seq_inputs(x, length),
        {"Out": [out.name], "Length": [out_len.name]},
        {"pad_value": float(pad_value)},
    )
    return out, out_len


def sequence_unpad(x, length=None, name=None):
    helper = LayerHelper("sequence_unpad", name=name)
    return _one(
        helper, "sequence_unpad", _seq_inputs(x, length), {}, x.dtype
    )


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=True, padding_start=None, length=None,
                  param_attr=None, bias_attr=None, act=None, name=None):
    """Context-window projection (reference: python/paddle/fluid/layers/
    sequence_lod.py sequence_conv)."""
    from paddle_tpu.utils.enforce import enforce

    enforce(
        filter_stride == 1,
        "sequence_conv supports filter_stride=1 only (the op lowering is "
        "stride-1; a strided variant would change the output length)",
    )
    helper = LayerHelper(
        "sequence_conv", param_attr=param_attr, bias_attr=bias_attr,
        act=act, name=name,
    )
    feat = int(input.shape[-1])
    w = helper.create_parameter(
        helper.param_attr, shape=[filter_size * feat, num_filters],
        dtype=input.dtype,
    )
    ins = _seq_inputs(input, length)
    ins["Filter"] = [w.name]
    start = (
        padding_start
        if padding_start is not None
        else -((filter_size - 1) // 2)
    )
    out = _one(
        helper, "sequence_conv", ins,
        {"contextLength": filter_size, "contextStart": start,
         "contextStride": filter_stride},
        input.dtype,
    )
    if helper.bias_attr is not False:
        b = helper.create_parameter(
            helper.bias_attr, shape=[num_filters], dtype=input.dtype,
            is_bias=True,
        )
        out = helper.append_bias_op(out, b, axis=2)
    return helper.append_activation(out)


def sequence_expand(x, y=None, y_length=None, ref_level=-1, max_repeat=8,
                    name=None):
    """reference: python/paddle/fluid/layers/sequence_lod.py
    sequence_expand — padded form: repeat row i y_length[i] times into a
    [B, max_repeat, ...] slate (see ops/sequence.py)."""
    from paddle_tpu.layer_helper import LayerHelper

    helper = LayerHelper("sequence_expand", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    outl = helper.create_variable_for_type_inference("int32")
    outl.stop_gradient = True
    ins = {"X": [x.name]}
    if y_length is not None:
        ins["YLength"] = [y_length.name]
    elif y is not None:
        ins["Y"] = [y.name]
    helper.append_op(
        "sequence_expand", ins,
        {"Out": [out.name], "OutLength": [outl.name]},
        {"ref_level": ref_level, "max_repeat": max_repeat},
    )
    return out, outl


def sequence_reshape(input, new_dim, name=None):
    """reference: sequence_lod.py sequence_reshape."""
    from paddle_tpu.layer_helper import LayerHelper

    helper = LayerHelper("sequence_reshape", name=name)
    return _one(helper, "sequence_reshape", {"X": [input.name]},
                {"new_dim": new_dim}, input.dtype)


def sequence_scatter(input, index, updates, ids_length=None, name=None):
    """reference: sequence_lod.py sequence_scatter (padded per-row form)."""
    from paddle_tpu.layer_helper import LayerHelper

    helper = LayerHelper("sequence_scatter", name=name)
    ins = {"X": [input.name], "Ids": [index.name],
           "Updates": [updates.name]}
    if ids_length is not None:
        ins["IdsLength"] = [ids_length.name]
    return _one(helper, "sequence_scatter", ins, {}, input.dtype)


def lod_reset(x, y=None, target_lod=None, name=None):
    """reference: python/paddle/fluid/layers/nn.py lod_reset — data passes
    through; new lengths ride as a second output for sequence ops."""
    from paddle_tpu.layer_helper import LayerHelper

    helper = LayerHelper("lod_reset", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    outs = {"Out": [out.name]}
    ins = {"X": [x.name]}
    outl = None
    if y is not None:
        ins["Y"] = [y.name]
        outl = helper.create_variable_for_type_inference("int32")
        outl.stop_gradient = True
        outs["OutLength"] = [outl.name]
    helper.append_op("lod_reset", ins, outs, {})
    return (out, outl) if outl is not None else out


def chunk_eval(input, label, chunk_scheme="IOB", num_chunk_types=1,
               excluded_chunk_types=None, seq_length=None):
    """reference: python/paddle/fluid/layers/nn.py chunk_eval."""
    from paddle_tpu.layer_helper import LayerHelper

    helper = LayerHelper("chunk_eval")

    def mk(dtype):
        v = helper.create_variable_for_type_inference(dtype)
        v.stop_gradient = True
        return v

    precision, recall, f1 = mk("float32"), mk("float32"), mk("float32")
    n_inf, n_lab, n_cor = mk("int64"), mk("int64"), mk("int64")
    ins = {"Inference": [input.name], "Label": [label.name]}
    if seq_length is not None:
        ins["SeqLength"] = [seq_length.name]
    helper.append_op(
        "chunk_eval", ins,
        {"Precision": [precision.name], "Recall": [recall.name],
         "F1-Score": [f1.name], "NumInferChunks": [n_inf.name],
         "NumLabelChunks": [n_lab.name],
         "NumCorrectChunks": [n_cor.name]},
        {"chunk_scheme": chunk_scheme, "num_chunk_types": num_chunk_types,
         "excluded_chunk_types": excluded_chunk_types or []},
    )
    return precision, recall, f1, n_inf, n_lab, n_cor


def beam_search(pre_ids, pre_scores, ids, scores, beam_size, end_id,
                level=0, is_accumulated=True, name=None,
                return_parent_idx=True):
    """reference: python/paddle/fluid/layers/rnn.py beam_search — fixed-
    beam single step (see ops/sequence.py _beam_search for the contract)."""
    from paddle_tpu.layer_helper import LayerHelper

    helper = LayerHelper("beam_search", name=name)
    sel_ids = helper.create_variable_for_type_inference("int64")
    sel_scores = helper.create_variable_for_type_inference("float32")
    parent = helper.create_variable_for_type_inference("int32")
    for v in (sel_ids, sel_scores, parent):
        v.stop_gradient = True
    helper.append_op(
        "beam_search",
        {"pre_ids": [pre_ids.name], "pre_scores": [pre_scores.name],
         "ids": [ids.name], "scores": [scores.name]},
        {"selected_ids": [sel_ids.name],
         "selected_scores": [sel_scores.name],
         "parent_idx": [parent.name]},
        {"beam_size": beam_size, "end_id": end_id, "level": level,
         "is_accumulated": is_accumulated},
    )
    if return_parent_idx:
        return sel_ids, sel_scores, parent
    return sel_ids, sel_scores


def beam_search_decode(ids, parents, scores, beam_size=None, end_id=0,
                       name=None):
    """reference: python/paddle/fluid/layers/rnn.py beam_search_decode —
    stacked [T, B, W] step outputs backtracked to [B, W, T] sentences."""
    from paddle_tpu.layer_helper import LayerHelper

    helper = LayerHelper("beam_search_decode", name=name)
    sent = helper.create_variable_for_type_inference("int64")
    sc = helper.create_variable_for_type_inference("float32")
    sent.stop_gradient = True
    sc.stop_gradient = True
    helper.append_op(
        "beam_search_decode",
        {"Ids": [ids.name], "Parents": [parents.name],
         "Scores": [scores.name]},
        {"SentenceIds": [sent.name], "SentenceScores": [sc.name]},
        {"beam_size": beam_size or 0, "end_id": end_id},
    )
    return sent, sc
