from paddle_tpu.layers.nn import *  # noqa: F401,F403
from paddle_tpu.layers.tensor import *  # noqa: F401,F403
from paddle_tpu.layers.control_flow import *  # noqa: F401,F403
from paddle_tpu.layers.rnn import *  # noqa: F401,F403
from paddle_tpu.layers import rnn  # noqa: F401
from paddle_tpu.layers import learning_rate_scheduler  # noqa: F401
from paddle_tpu.layers import collective  # noqa: F401
