"""User-facing neural-net layer functions.

API surface modeled on the reference's fluid.layers
(reference: python/paddle/fluid/layers/nn.py — fc at :205, ~200 layers).
Every function appends OpDescs to the current block via LayerHelper; no
computation happens at build time.
"""

from paddle_tpu.core.dtypes import convert_dtype
from paddle_tpu.layer_helper import LayerHelper
from paddle_tpu.utils import unique_name
from paddle_tpu.utils.enforce import enforce

__all__ = [
    "fc",
    "conv2d",
    "conv2d_transpose",
    "pool2d",
    "batch_norm",
    "layer_norm",
    "instance_norm",
    "group_norm",
    "embedding",
    "sparse_embedding",
    "distributed_embedding",
    "sharded_embedding",
    "scaled_dot_product_attention",
    "kv_cache_write",
    "masked_write",
    "logits_mask_add",
    "cached_attention",
    "paged_attention",
    "block_gather",
    "block_scatter_write",
    "moe_ffn",
    "dropout",
    "softmax",
    "log_softmax",
    "matmul",
    "mul",
    "relu",
    "relu6",
    "sigmoid",
    "tanh",
    "gelu",
    "leaky_relu",
    "elu",
    "swish",
    "hard_swish",
    "hard_sigmoid",
    "softplus",
    "softsign",
    "prelu",
    "cross_entropy",
    "softmax_with_cross_entropy",
    "sigmoid_cross_entropy_with_logits",
    "square_error_cost",
    "huber_loss",
    "kldiv_loss",
    "mse_loss",
    "accuracy",
    "auc",
    "topk",
    "one_hot",
    "l2_normalize",
    "clip",
    "clip_by_norm",
    "mean",
    "reduce_sum",
    "reduce_mean",
    "reduce_max",
    "reduce_min",
    "reduce_prod",
    "elementwise_op",
    "elementwise_add",
    "elementwise_sub",
    "elementwise_mul",
    "elementwise_div",
    "elementwise_max",
    "elementwise_min",
    "elementwise_pow",
    "scale",
    "sqrt",
    "square",
    "abs",
    "exp",
    "log",
    "sin",
    "cos",
    "erf",
    "pow",
    "argmax",
    "argmin",
    "unsqueeze",
    "squeeze",
]


def _single_op(op_type, x, attrs=None, out_dtype=None, name=None, extra_inputs=None):
    helper = LayerHelper(op_type, name=name)
    out = helper.create_variable_for_type_inference(out_dtype or x.dtype)
    inputs = {"X": [x.name]}
    if extra_inputs:
        inputs.update(extra_inputs)
    helper.append_op(op_type, inputs, {"Out": [out.name]}, attrs or {})
    return out


# -- dense / conv -----------------------------------------------------------


def fc(
    input,
    size,
    num_flatten_dims=1,
    param_attr=None,
    bias_attr=None,
    act=None,
    name=None,
):
    """reference: python/paddle/fluid/layers/nn.py:205."""
    helper = LayerHelper(
        "fc", param_attr=param_attr, bias_attr=bias_attr, act=act, name=name
    )
    dtype = input.dtype
    input_shape = input.shape
    enforce(
        input_shape is not None,
        f"fc input '{input.name}' has no inferred shape, so the weight "
        "size is unknown at build time. Stack fc on layers that propagate "
        "shape, or set the var's .shape explicitly",
    )
    feature_dims = list(input_shape[num_flatten_dims:])
    enforce(
        all(int(d) > 0 for d in feature_dims),
        f"fc input '{input.name}' flattened feature dims {feature_dims} "
        "contain a dynamic -1 dim; fc needs static feature dims (choose "
        "num_flatten_dims so only leading dims are dynamic)",
    )
    in_features = 1
    for d in feature_dims:
        in_features *= d
    w = helper.create_parameter(
        helper.param_attr, shape=[in_features, size], dtype=dtype
    )
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "mul",
        {"X": [input.name], "Y": [w.name]},
        {"Out": [out.name]},
        {"x_num_col_dims": num_flatten_dims, "y_num_col_dims": 1},
    )
    if helper.bias_attr is not False:
        b = helper.create_parameter(
            helper.bias_attr, shape=[size], dtype=dtype, is_bias=True
        )
        out = helper.append_bias_op(out, b, axis=num_flatten_dims)
    return helper.append_activation(out)


def conv2d(
    input,
    num_filters,
    filter_size,
    stride=1,
    padding=0,
    dilation=1,
    groups=1,
    param_attr=None,
    bias_attr=None,
    act=None,
    name=None,
    data_format="NCHW",
):
    """reference: python/paddle/fluid/layers/nn.py conv2d."""
    helper = LayerHelper(
        "conv2d", param_attr=param_attr, bias_attr=bias_attr, act=act, name=name
    )
    dtype = input.dtype
    if isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    if isinstance(stride, int):
        stride = [stride, stride]
    if isinstance(padding, int):
        padding = [padding, padding]
    if isinstance(dilation, int):
        dilation = [dilation, dilation]
    channels = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    enforce(channels % groups == 0, "channels must divide groups")
    filter_shape = [num_filters, channels // groups] + list(filter_size)
    import math

    fan_in = (channels // groups) * filter_size[0] * filter_size[1]
    from paddle_tpu.initializer import NormalInitializer

    default_init = NormalInitializer(0.0, math.sqrt(2.0 / fan_in))
    w = helper.create_parameter(
        helper.param_attr,
        shape=filter_shape,
        dtype=dtype,
        default_initializer=default_init,
    )
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "conv2d",
        {"Input": [input.name], "Filter": [w.name]},
        {"Output": [out.name]},
        {
            "strides": stride,
            "paddings": padding,
            "dilations": dilation,
            "groups": groups,
            "data_format": data_format,
        },
    )
    if helper.bias_attr is not False:
        b = helper.create_parameter(
            helper.bias_attr, shape=[num_filters], dtype=dtype, is_bias=True
        )
        out = helper.append_bias_op(out, b, axis=1 if data_format == "NCHW" else 3)
    return helper.append_activation(out)


def conv2d_transpose(
    input,
    num_filters,
    filter_size,
    stride=1,
    padding=0,
    groups=1,
    param_attr=None,
    bias_attr=None,
    act=None,
    name=None,
):
    helper = LayerHelper(
        "conv2d_transpose",
        param_attr=param_attr,
        bias_attr=bias_attr,
        act=act,
        name=name,
    )
    dtype = input.dtype
    if isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    if isinstance(stride, int):
        stride = [stride, stride]
    if isinstance(padding, int):
        padding = [padding, padding]
    channels = input.shape[1]
    filter_shape = [channels, num_filters // groups] + list(filter_size)
    w = helper.create_parameter(helper.param_attr, shape=filter_shape, dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "conv2d_transpose",
        {"Input": [input.name], "Filter": [w.name]},
        {"Output": [out.name]},
        {"strides": stride, "paddings": padding, "groups": groups},
    )
    if helper.bias_attr is not False:
        b = helper.create_parameter(
            helper.bias_attr, shape=[num_filters], dtype=dtype, is_bias=True
        )
        out = helper.append_bias_op(out, b, axis=1)
    return helper.append_activation(out)


def pool2d(
    input,
    pool_size=-1,
    pool_type="max",
    pool_stride=1,
    pool_padding=0,
    global_pooling=False,
    exclusive=True,
    adaptive=False,
    name=None,
):
    helper = LayerHelper("pool2d", name=name)
    if isinstance(pool_size, int):
        pool_size = [pool_size, pool_size]
    if isinstance(pool_stride, int):
        pool_stride = [pool_stride, pool_stride]
    if isinstance(pool_padding, int):
        pool_padding = [pool_padding, pool_padding]
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "pool2d",
        {"X": [input.name]},
        {"Out": [out.name]},
        {
            "pooling_type": pool_type,
            "ksize": pool_size,
            "strides": pool_stride,
            "paddings": pool_padding,
            "global_pooling": global_pooling,
            "exclusive": exclusive,
            "adaptive": adaptive,
        },
    )
    return out


def batch_norm(
    input,
    act=None,
    is_test=False,
    momentum=0.9,
    epsilon=1e-5,
    param_attr=None,
    bias_attr=None,
    data_layout="NCHW",
    name=None,
    moving_mean_name=None,
    moving_variance_name=None,
    use_global_stats=False,
):
    """reference: python/paddle/fluid/layers/nn.py batch_norm. Running stats
    are persistable non-trainable parameters updated through MeanOut/
    VarianceOut (functionally, via scope write-back)."""
    from paddle_tpu.initializer import ConstantInitializer
    from paddle_tpu.param_attr import ParamAttr

    helper = LayerHelper(
        "batch_norm", param_attr=param_attr, bias_attr=bias_attr, act=act, name=name
    )
    dtype = input.dtype if input.dtype != "float16" else "float32"
    channels = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    scale = helper.create_parameter(
        helper.param_attr,
        shape=[channels],
        dtype=dtype,
        default_initializer=ConstantInitializer(1.0),
    )
    bias = helper.create_parameter(
        helper.bias_attr, shape=[channels], dtype=dtype, is_bias=True
    )
    mean = helper.create_parameter(
        ParamAttr(
            name=moving_mean_name,
            initializer=ConstantInitializer(0.0),
            trainable=False,
        ),
        shape=[channels],
        dtype=dtype,
    )
    variance = helper.create_parameter(
        ParamAttr(
            name=moving_variance_name,
            initializer=ConstantInitializer(1.0),
            trainable=False,
        ),
        shape=[channels],
        dtype=dtype,
    )
    mean.stop_gradient = True
    variance.stop_gradient = True
    out = helper.create_variable_for_type_inference(input.dtype)
    saved_mean = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    saved_var = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    helper.append_op(
        "batch_norm",
        {
            "X": [input.name],
            "Scale": [scale.name],
            "Bias": [bias.name],
            "Mean": [mean.name],
            "Variance": [variance.name],
        },
        {
            "Y": [out.name],
            "MeanOut": [mean.name],
            "VarianceOut": [variance.name],
            "SavedMean": [saved_mean.name],
            "SavedVariance": [saved_var.name],
        },
        {
            "momentum": momentum,
            "epsilon": epsilon,
            "is_test": is_test,
            "data_layout": data_layout,
            "use_global_stats": use_global_stats,
        },
    )
    return helper.append_activation(out)


def layer_norm(
    input,
    scale=True,
    shift=True,
    begin_norm_axis=1,
    epsilon=1e-5,
    param_attr=None,
    bias_attr=None,
    act=None,
    name=None,
):
    from paddle_tpu.initializer import ConstantInitializer

    helper = LayerHelper(
        "layer_norm", param_attr=param_attr, bias_attr=bias_attr, act=act, name=name
    )
    dtype = input.dtype
    import math

    in_shape = list(input.shape) if input.shape is not None else None
    enforce(
        in_shape is not None,
        "layer_norm input has no inferred shape; build it from layers "
        "that propagate shape (fluid.data, fc, elementwise ops)",
    )
    if begin_norm_axis < 0:
        begin_norm_axis += len(in_shape)
    enforce(
        0 < begin_norm_axis < len(in_shape),
        f"begin_norm_axis {begin_norm_axis} out of range for input rank "
        f"{len(in_shape)}",
    )
    norm_dims = in_shape[begin_norm_axis:]
    if scale or shift:
        # the scale/bias parameter is sized by the normalized region —
        # a dynamic (-1) dim there has no buildable parameter shape
        enforce(
            all(int(d) > 0 for d in norm_dims),
            f"layer_norm normalizes over dims {norm_dims} "
            f"(begin_norm_axis={begin_norm_axis}) which contain a dynamic "
            "-1 dim, so the Scale/Bias parameter size is unknown at build "
            "time. Normalize over trailing static dims (e.g. "
            "begin_norm_axis=-1 for the feature axis) or pass "
            "scale=False, shift=False",
        )
    norm_shape = [int(math.prod(norm_dims))]
    inputs = {"X": [input.name]}
    if scale:
        s = helper.create_parameter(
            helper.param_attr,
            shape=norm_shape,
            dtype=dtype,
            default_initializer=ConstantInitializer(1.0),
        )
        inputs["Scale"] = [s.name]
    if shift:
        b = helper.create_parameter(
            helper.bias_attr, shape=norm_shape, dtype=dtype, is_bias=True
        )
        inputs["Bias"] = [b.name]
    out = helper.create_variable_for_type_inference(dtype)
    mean = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    var = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    helper.append_op(
        "layer_norm",
        inputs,
        {"Y": [out.name], "Mean": [mean.name], "Variance": [var.name]},
        {"begin_norm_axis": begin_norm_axis, "epsilon": epsilon},
    )
    # layer_norm is shape-preserving: guarantee the output shape even when
    # abstract evaluation could not run (dynamic dims), so fc and friends
    # stacked on top can always read .shape at build time
    if out.shape is None:
        out.shape = tuple(in_shape)
    if mean.shape is None:
        mean.shape = tuple(in_shape[:begin_norm_axis])
        var.shape = tuple(in_shape[:begin_norm_axis])
    return helper.append_activation(out)


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None, name=None):
    from paddle_tpu.initializer import ConstantInitializer

    helper = LayerHelper(
        "instance_norm", param_attr=param_attr, bias_attr=bias_attr, name=name
    )
    channels = input.shape[1]
    s = helper.create_parameter(
        helper.param_attr,
        shape=[channels],
        dtype=input.dtype,
        default_initializer=ConstantInitializer(1.0),
    )
    b = helper.create_parameter(
        helper.bias_attr, shape=[channels], dtype=input.dtype, is_bias=True
    )
    out = helper.create_variable_for_type_inference(input.dtype)
    sm = helper.create_variable_for_type_inference(input.dtype, stop_gradient=True)
    sv = helper.create_variable_for_type_inference(input.dtype, stop_gradient=True)
    helper.append_op(
        "instance_norm",
        {"X": [input.name], "Scale": [s.name], "Bias": [b.name]},
        {"Y": [out.name], "SavedMean": [sm.name], "SavedVariance": [sv.name]},
        {"epsilon": epsilon},
    )
    return out


def group_norm(
    input, groups, epsilon=1e-5, param_attr=None, bias_attr=None, act=None, name=None
):
    from paddle_tpu.initializer import ConstantInitializer

    helper = LayerHelper(
        "group_norm", param_attr=param_attr, bias_attr=bias_attr, act=act, name=name
    )
    channels = input.shape[1]
    s = helper.create_parameter(
        helper.param_attr,
        shape=[channels],
        dtype=input.dtype,
        default_initializer=ConstantInitializer(1.0),
    )
    b = helper.create_parameter(
        helper.bias_attr, shape=[channels], dtype=input.dtype, is_bias=True
    )
    out = helper.create_variable_for_type_inference(input.dtype)
    m = helper.create_variable_for_type_inference(input.dtype, stop_gradient=True)
    v = helper.create_variable_for_type_inference(input.dtype, stop_gradient=True)
    helper.append_op(
        "group_norm",
        {"X": [input.name], "Scale": [s.name], "Bias": [b.name]},
        {"Y": [out.name], "Mean": [m.name], "Variance": [v.name]},
        {"groups": groups, "epsilon": epsilon},
    )
    return helper.append_activation(out)


def embedding(
    input,
    size,
    is_sparse=False,
    is_distributed=False,
    padding_idx=None,
    param_attr=None,
    dtype="float32",
    name=None,
):
    """reference: python/paddle/fluid/layers/nn.py embedding. is_sparse is
    accepted for API parity; dense gather is the TPU path (the PS stack
    handles the huge-table case)."""
    helper = LayerHelper("embedding", param_attr=param_attr, name=name)
    w = helper.create_parameter(helper.param_attr, shape=list(size), dtype=dtype)
    w.is_distributed = is_distributed
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "lookup_table_v2",
        {"W": [w.name], "Ids": [input.name]},
        {"Out": [out.name]},
        {"padding_idx": -1 if padding_idx is None else padding_idx},
    )
    return out


def scaled_dot_product_attention(q, k, v, bias=None, causal=False,
                                 sm_scale=None, seq_parallel=None,
                                 seq_axis="seq", name=None):
    """Fused attention over [B, H, S, D] tensors; `bias` is an optional
    [B, S] additive key bias (padding mask). Lowers to the Pallas flash
    attention kernel on TPU (ops/pallas/flash_attention.py), or an
    XLA-fused reference implementation otherwise. The reference's analog is
    inference-only (paddle/fluid/operators/fused/multihead_matmul_op.cc);
    this one is differentiable.

    seq_parallel='ring' | 'ulysses' runs attention sequence-sharded over
    mesh axis `seq_axis` when the program is compiled with
    CompiledProgram.with_parallel on a mesh carrying that axis (SURVEY
    §5.7): ring rotates K/V blocks via ppermute, Ulysses head-scatters via
    all_to_all. Off-mesh the plain path runs — identical math."""
    helper = LayerHelper("scaled_dot_product_attention", name=name)
    out = helper.create_variable_for_type_inference(q.dtype)
    inputs = {"Q": [q.name], "K": [k.name], "V": [v.name]}
    if bias is not None:
        inputs["Bias"] = [bias.name]
    attrs = {"causal": causal}
    if sm_scale is not None:
        attrs["sm_scale"] = float(sm_scale)
    if seq_parallel:
        attrs["seq_parallel"] = seq_parallel
        attrs["seq_axis"] = seq_axis
    helper.append_op(
        "scaled_dot_product_attention", inputs, {"Out": [out.name]}, attrs
    )
    return out


def kv_cache_write(cache, new_kv, write_onehot, name=None):
    """Write one new key/value row per sequence into a slotted KV cache,
    functionally: ``out[s, l] = new_kv[s] if write_onehot[s, l] else
    cache[s, l]``. ``cache`` is ``[S, L, H]``, ``new_kv`` ``[S, H]``, and
    ``write_onehot`` a ``[S, L]`` float mask that is one-hot at each
    sequence's write cursor (an all-zero row leaves that sequence's cache
    bit-untouched — how a dense slotted cache freezes inactive slots;
    the serving engine's PAGED arena uses `block_scatter_write` with
    row indices instead, same exactness contract).

    Returns the updated cache; callers persist it with
    ``layers.assign(out, output=cache_var)`` so the lowering donates the
    arena and the update happens in place on device."""
    mask = unsqueeze(write_onehot, [2], name=name)       # [S, L, 1]
    new_row = unsqueeze(new_kv, [1])                     # [S, 1, H]
    return masked_write(cache, new_row, mask)


def masked_write(cache, new, mask, name=None):
    """``cache*(1-mask) + new*mask`` for a 0/1 float ``mask``
    broadcastable against both operands — THE bit-exactness-critical
    masked update for dense slotted-arena writes (`kv_cache_write`'s
    per-position one-hot; the paged decode programs scatter by row
    index instead — `block_scatter_write`).

    Composes multiply/add on existing ops instead of a scatter. Both
    branches are exact in IEEE arithmetic (``x*1.0 == x``,
    ``x + 0.0 == x``), which is what makes continuous-batching decode
    bit-identical to offline decode — positions where the mask is zero
    are never perturbed by writes addressed elsewhere."""
    keep = scale(mask, scale=-1.0, bias=1.0, name=name)  # 1 - mask
    return elementwise_add(
        elementwise_mul(cache, keep),
        elementwise_mul(new, mask),
    )


def block_gather(arena, rows, seqs, length, name=None):
    """Gather a per-sequence KV view out of a flat paged arena:
    ``arena`` ``[R, H]`` + flat row indices ``rows`` ``[seqs * length]``
    -> ``[seqs, length, H]``. The row feed is the device half of a block
    table (vLLM's PagedAttention layout): position ``p`` of sequence
    ``s`` reads arena row ``rows[s * length + p]`` =
    ``block_table[s][p // bs] * bs + p % bs``. Rows at masked positions
    (beyond the sequence's cursor) may point anywhere — the additive
    ``-1e9`` attention bias makes their contribution exactly 0.0, the
    same contract that hides stale rows in the slotted design.

    Gather relocates rows byte-for-byte, so attention over the gathered
    view is bit-identical to attention over a dense per-slot arena
    holding the same rows — the paged rebuild's exactness argument."""
    from paddle_tpu.layers.tensor import gather, reshape

    flat = gather(arena, rows, name=name)              # [seqs*length, H]
    return reshape(flat, [int(seqs), int(length), -1])


def block_scatter_write(arena, rows, new_rows, name=None):
    """Write ``new_rows`` ``[N, H]`` into flat paged arena ``arena``
    ``[R, H]`` at row indices ``rows`` ``[N]``, functionally (callers
    persist with ``assign`` so the lowering donates the arena and XLA
    updates in place). An index >= R means "this row writes NOWHERE"
    (``mode="drop"``) — how retired/inactive batch slots stay
    bit-untouched without changing the compiled shape."""
    from paddle_tpu.layers.tensor import scatter

    return scatter(arena, rows, new_rows, overwrite=True, mode="drop",
                   name=name)


def logits_mask_add(logits, mask, name=None):
    """Additive logits mask for constrained decode: ``logits + mask``
    where ``mask`` is host-built, 0.0 at allowed tokens and ``-1e9`` at
    banned ones (``[S, 1, V]`` against the decode step's logits). The
    same exactness contract as the attention bias: ``x + 0.0 == x`` in
    IEEE float32, so an all-zeros mask (no grammar active) leaves every
    logit bit-untouched, and the host applying the identical float32
    add to prefill-fetched logits reproduces the device result
    byte-for-byte — which is what keeps grammar-constrained decode
    bit-comparable to the offline reference. The mask enters as DATA
    through a fixed-shape feed, so per-step grammar state changes never
    retrace."""
    return elementwise_add(logits, mask, name=name)


def cached_attention(q, k_cache, v_cache, attn_bias, sm_scale=1.0,
                     fused=False, name=None):
    """Single-position attention of ``q`` ``[S, H]`` over a slotted KV
    cache ``[S, L, H]`` — the decode-step half of cached (incremental)
    attention; `kv_cache_write` is the other half. ``attn_bias`` is an
    additive ``[S, 1, L]`` mask fed from the host scheduler: 0.0 at
    positions ``<= cursor``, -1e9 beyond (exp underflows to exactly 0.0,
    the repo-wide padding contract), so stale cache positions are
    bit-invisible. Returns the ``[S, H]`` context vectors.

    ``fused=True`` emits ONE ``cached_attention`` op instead of the
    matmul/softmax composite: the op's reference lowering is the exact
    composite sequence (bit-identical), and the kernel registry
    (paddle_tpu/kernels/) may serve it with a fused Pallas kernel under
    ``PADDLE_TPU_KERNELS``."""
    if fused:
        helper = LayerHelper("cached_attention", name=name)
        out = helper.create_variable_for_type_inference(q.dtype)
        helper.append_op(
            "cached_attention",
            {"Q": [q.name], "KCache": [k_cache.name],
             "VCache": [v_cache.name], "Bias": [attn_bias.name]},
            {"Out": [out.name]},
            {"sm_scale": float(sm_scale)},
        )
        return out
    q3 = unsqueeze(q, [1], name=name)                    # [S, 1, H]
    scores = matmul(q3, k_cache, transpose_y=True, alpha=float(sm_scale))
    att = softmax(elementwise_add(scores, attn_bias), axis=-1)
    return squeeze(matmul(att, v_cache), [1])            # [S, H]


def paged_attention(q, k_arena, v_arena, rows, attn_bias, seqs, length,
                    sm_scale=1.0, name=None):
    """Fused paged attention: ``q`` ``[S, H]`` attends over rows of the
    flat ``[R, H]`` block arenas addressed by the ``[S * L]`` row feed —
    ``block_gather(k) ; block_gather(v) ; cached_attention`` as ONE op.
    The reference lowering is that exact composite (bit-identical for
    any block size); under ``PADDLE_TPU_KERNELS`` the registry serves it
    with the fused Pallas kernel, where the dense ``[S, L, H]`` gather
    views live only in VMEM instead of materializing in HBM (the
    analysis/memory.py accounting difference KERNEL_EVIDENCE commits)."""
    helper = LayerHelper("paged_attention", name=name)
    out = helper.create_variable_for_type_inference(q.dtype)
    helper.append_op(
        "paged_attention",
        {"Q": [q.name], "KArena": [k_arena.name], "VArena": [v_arena.name],
         "Rows": [rows.name], "Bias": [attn_bias.name]},
        {"Out": [out.name]},
        {"sm_scale": float(sm_scale), "seqs": int(seqs),
         "length": int(length)},
    )
    return out


def moe_ffn(input, num_experts, d_ff=None, expert_axis="expert",
            capacity_factor=2.0, capacity=0, activation="gelu",
            param_attr=None, name=None):
    """Top-2 gated mixture-of-experts FFN (expert parallelism on the IR
    path — SURVEY §2.7 new first-class work). `input` [..., H] is routed
    through `num_experts` stacked FFNs; compiled on a mesh whose
    `expert_axis` has size > 1, experts and tokens shard over that axis
    with all_to_all dispatch (ops/moe.py); otherwise the routing runs
    dense. Returns (out, aux_loss) — add aux_loss to the objective for
    load balancing."""
    from paddle_tpu.initializer import ConstantInitializer
    from paddle_tpu.param_attr import ParamAttr

    helper = LayerHelper("moe_ffn", param_attr=param_attr, name=name)
    H = input.shape[-1]
    F = d_ff or 4 * H
    base = helper.param_attr

    def _wattr(suffix):
        # one ParamAttr per weight: sharing a NAMED attr would resolve all
        # three weights to the same variable (create_parameter returns the
        # existing var on a name hit)
        return ParamAttr(
            name=f"{base.name}_{suffix}" if base.name else None,
            initializer=base.initializer,
            regularizer=base.regularizer,
            trainable=base.trainable,
        )

    gate_w = helper.create_parameter(
        _wattr("gate"), shape=[H, num_experts], dtype="float32",
    )
    w1 = helper.create_parameter(_wattr("w1"), shape=[num_experts, H, F],
                                 dtype="float32")
    b1 = helper.create_parameter(
        ParamAttr(initializer=ConstantInitializer(0.0)),
        shape=[num_experts, F], dtype="float32",
    )
    w2 = helper.create_parameter(_wattr("w2"), shape=[num_experts, F, H],
                                 dtype="float32")
    b2 = helper.create_parameter(
        ParamAttr(initializer=ConstantInitializer(0.0)),
        shape=[num_experts, H], dtype="float32",
    )
    out = helper.create_variable_for_type_inference(input.dtype)
    aux = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        "moe_ffn",
        {"X": [input.name], "GateW": [gate_w.name], "W1": [w1.name],
         "B1": [b1.name], "W2": [w2.name], "B2": [b2.name]},
        {"Out": [out.name], "AuxLoss": [aux.name]},
        {"expert_axis": expert_axis, "capacity_factor": capacity_factor,
         "capacity": capacity, "activation": activation},
    )
    return out, aux


def _next_table_id(program):
    """First free PS table id across BOTH registries (host-pull
    `_sparse_tables` and in-graph `_remote_tables`) — one allocation rule
    for every producer (sparse_embedding, distributed_embedding, the
    is_distributed transpiler)."""
    used = {
        t["table_id"]
        for reg in ("_sparse_tables", "_remote_tables")
        for t in getattr(program, reg, {}).values()
    }
    return max(used, default=100) + 1


def sparse_embedding(
    input,
    embedding_dim,
    table_id=None,
    init_range=0.01,
    optimizer="sgd",
    name=None,
):
    """Parameter-server-backed embedding for billion-feature tables
    (reference: distributed_lookup_table / prefetch flow —
    paddle/fluid/operators/distributed/parameter_prefetch.cc; pslib pull in
    fleet_wrapper.h:84). The table never materializes on device: per step
    the PS worker pulls the batch's unique rows (fleet/parameter_server.py
    PSWorker.run), feeds them as `<name>__rows`, and the graph gathers via
    `<name>__idx`; row grads flow back through the gather vjp and are pushed
    to the server. `input` must be an int feed var of ids (any shape)."""
    from paddle_tpu.core.ir import default_main_program
    from paddle_tpu.layers import tensor as tensor_layers

    helper = LayerHelper("sparse_embedding", name=name)
    tname = name or unique_name.generate("sparse_emb")
    program = default_main_program()
    tables = getattr(program, "_sparse_tables", None)
    if tables is None:
        tables = program._sparse_tables = {}
    if table_id is None:
        table_id = _next_table_id(program)
    rows = tensor_layers.data(
        f"{tname}__rows", shape=[-1, embedding_dim],
        dtype="float32", append_batch_size=False,
    )
    rows.stop_gradient = False  # leaf grad target (extra seed in backward)
    idx_shape = [(-1 if d in (-1, None) else d) for d in (input.shape or [-1])]
    idx = tensor_layers.data(
        f"{tname}__idx", shape=idx_shape, dtype="int32",
        append_batch_size=False,
    )
    out = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        "lookup_table_ps",
        {"Rows": [rows.name], "Idx": [idx.name]},
        {"Out": [out.name]},
        {"table_id": table_id},
    )
    tables[tname] = {
        "table_id": table_id,
        "ids": input.name,
        "rows": rows.name,
        "idx": idx.name,
        "dim": embedding_dim,
        "init_range": init_range,
        "optimizer": optimizer,
    }
    return out


def distributed_embedding(
    input,
    size,
    table_name=None,
    table_id=None,
    init_range=0.01,
    optimizer="sgd",
    dtype="float32",
):
    """Embedding whose table lives ONLY on parameter servers, pulled inside
    the compiled step (reference: distributed_lookup_table +
    paddle/fluid/operators/distributed/parameter_prefetch.cc:1). No local
    parameter is created; `size` is [vocab, dim] where vocab is advisory
    (servers grow rows on demand — billion-feature tables never
    materialize). The backward pushes merged row grads to the servers
    (ParameterServerOptimizer wires the push op); fleet.init_worker()
    creates the server tables and activates the lookup context. Use
    `RemoteLookupContext.prefetch` / PSWorker.prefetch for double-buffered
    pulls.

    Id range: in-graph ids ride the XLA int path (int32 under the default
    x64-disabled config), so ids must be < 2^31 — pre-hash larger spaces
    (`id % (2**31 - 1)`, the reference's hash-op recipe) or use
    `sparse_embedding`, whose host-side pull keeps the full uint64 space."""
    from paddle_tpu.core.ir import default_main_program
    from paddle_tpu.utils.enforce import enforce

    enforce(
        dtype == "float32",
        f"distributed_embedding dtype must be float32 (got {dtype}): the "
        "PS wire format and the in-step pull callback are f32",
    )
    helper = LayerHelper("distributed_embedding", name=table_name)
    tname = table_name or unique_name.generate("dist_emb")
    dim = int(size[1])
    program = default_main_program()
    tables = getattr(program, "_remote_tables", None)
    if tables is None:
        tables = program._remote_tables = {}
    if table_id is None:
        table_id = _next_table_id(program)
    out = helper.create_variable_for_type_inference(dtype)
    ids_shape = [d for d in (input.shape or [-1])]
    if len(ids_shape) >= 2 and ids_shape[-1] == 1:
        ids_shape = ids_shape[:-1]
    out.shape = ids_shape + [dim]
    out.stop_gradient = False
    helper.append_op(
        "distributed_lookup_table",
        {"Ids": [input.name]},
        {"Outputs": [out.name]},
        {"table_name": tname, "dim": dim},
    )
    tables[tname] = {
        "table_id": table_id,
        "table_name": tname,  # wire/registration name (entry keys may differ)
        "ids": input.name,
        "out": out.name,
        "dim": dim,
        "init_range": init_range,
        "optimizer": optimizer,
    }
    return out


def sharded_embedding(
    input,
    embedding_dim,
    capacity=65536,
    ep=1,
    name=None,
    init_range=0.01,
    lr=0.1,
    seed=0,
    min_bucket=8,
    vocab_size=None,
):
    """Embedding over the two-tier sharded engine (paddle_tpu/embedding/):
    hot rows live in a device slab row-sharded over the ``ep`` mesh axis,
    the cold tail overflows to host RAM, and the step gathers the slab
    ONCE at the batch's deduplicated unique ids. The TPU-native successor
    to both ``embedding`` (needs a dense [vocab, dim] device table) and
    ``sparse_embedding`` (round-trips every batch's rows host<->device).

    The graph sees only cache-sized tensors: ``<name>__slots`` (unique
    slot indices, bucket-padded) and ``<name>__inv`` (occurrence ->
    unique map), both produced per step by
    ``EmbeddingEngine.prepare_feed``. The slab trains with its OWN
    row-sparse SGD at ``lr`` — the deferred ``sharded_embedding_update``
    pass strips whatever dense optimizer ``minimize`` attached (an Adam
    step on untouched cached rows would drift them, breaking the
    engine's cache-size-invariance contract). ``capacity`` must divide
    evenly by ``ep``; ids span the full u64 space (``vocab_size`` is
    advisory, like the PS tables)."""
    from paddle_tpu.core.ir import default_main_program
    from paddle_tpu.embedding.table import TableConfig
    from paddle_tpu.initializer import ConstantInitializer
    from paddle_tpu.layers import tensor as tensor_layers
    from paddle_tpu.param_attr import ParamAttr

    helper = LayerHelper("sharded_embedding", name=name)
    tname = name or unique_name.generate("sharded_emb")
    cfg = TableConfig(
        tname, embedding_dim, capacity, ep=ep, vocab_size=vocab_size,
        init_range=init_range, lr=lr, seed=seed, min_bucket=min_bucket,
    )
    program = default_main_program()
    tables = getattr(program, "_sharded_tables", None)
    if tables is None:
        tables = program._sharded_tables = {}

    slab = helper.create_parameter(
        ParamAttr(name=cfg.slab_name,
                  initializer=ConstantInitializer(0.0)),
        shape=[cfg.capacity, cfg.dim], dtype="float32",
    )
    slots = tensor_layers.data(
        f"{tname}__slots", shape=[-1], dtype="int32",
        append_batch_size=False,
    )
    ids_shape = [d for d in (input.shape or [-1])]
    if len(ids_shape) >= 2 and ids_shape[-1] == 1:
        ids_shape = ids_shape[:-1]
    idx_shape = [(-1 if d in (-1, None) else d) for d in ids_shape]
    inv = tensor_layers.data(
        f"{tname}__inv", shape=idx_shape, dtype="int32",
        append_batch_size=False,
    )
    out = helper.create_variable_for_type_inference("float32")
    out.shape = idx_shape + [cfg.dim]
    out.stop_gradient = False
    helper.append_op(
        "sharded_embedding_lookup",
        {"Table": [slab.name], "Slots": [slots.name], "Inv": [inv.name]},
        {"Out": [out.name]},
        cfg.to_attrs(),
    )
    program._wants_sharded_embedding_update = True
    tables[tname] = {
        "table_name": tname,
        "ids": input.name,
        "slots": slots.name,
        "inv": inv.name,
        "slab": cfg.slab_name,
        "dim": cfg.dim,
        "capacity": cfg.capacity,
        "ep": cfg.ep,
        "vocab_size": vocab_size,
        "init_range": cfg.init_range,
        "lr": cfg.lr,
        "seed": cfg.seed,
        "min_bucket": cfg.min_bucket,
    }
    return out


def dropout(
    x,
    dropout_prob,
    is_test=False,
    seed=0,
    name=None,
    dropout_implementation="downgrade_in_infer",
):
    helper = LayerHelper("dropout", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    mask = helper.create_variable_for_type_inference(x.dtype, stop_gradient=True)
    helper.append_op(
        "dropout",
        {"X": [x.name]},
        {"Out": [out.name], "Mask": [mask.name]},
        {
            "dropout_prob": dropout_prob,
            "is_test": is_test,
            "seed": seed,
            "dropout_implementation": dropout_implementation,
        },
    )
    return out


# -- activations ------------------------------------------------------------


def _make_act(op_type):
    def act_fn(x, name=None, **attrs):
        return _single_op(op_type, x, attrs, name=name)

    act_fn.__name__ = op_type
    return act_fn


relu = _make_act("relu")
relu6 = _make_act("relu6")
sigmoid = _make_act("sigmoid")
tanh = _make_act("tanh")
leaky_relu = _make_act("leaky_relu")
elu = _make_act("elu")
swish = _make_act("swish")
hard_swish = _make_act("hard_swish")
hard_sigmoid = _make_act("hard_sigmoid")
softplus = _make_act("softplus")
softsign = _make_act("softsign")
sqrt = _make_act("sqrt")
square = _make_act("square")
abs = _make_act("abs")
exp = _make_act("exp")
log = _make_act("log")
sin = _make_act("sin")
cos = _make_act("cos")
erf = _make_act("erf")


def gelu(x, approximate=False, name=None):
    return _single_op("gelu", x, {"approximate": approximate}, name=name)


def pow(x, factor=1.0, name=None):
    return _single_op("pow", x, {"factor": factor}, name=name)


def softmax(input, axis=-1, name=None):
    return _single_op("softmax", input, {"axis": axis}, name=name)


def log_softmax(input, axis=-1, name=None):
    return _single_op("log_softmax", input, {"axis": axis}, name=name)


def prelu(x, mode="all", param_attr=None, name=None):
    from paddle_tpu.initializer import ConstantInitializer

    helper = LayerHelper("prelu", param_attr=param_attr, name=name)
    alpha_shape = [1] if mode == "all" else [x.shape[1]]
    alpha = helper.create_parameter(
        helper.param_attr,
        shape=alpha_shape,
        dtype=x.dtype,
        default_initializer=ConstantInitializer(0.25),
    )
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "prelu",
        {"X": [x.name], "Alpha": [alpha.name]},
        {"Out": [out.name]},
        {"mode": mode},
    )
    return out


# -- elementwise / math -----------------------------------------------------


def elementwise_op(op_type, x, y, axis=-1, act=None, name=None):
    helper = LayerHelper(op_type, act=act, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        op_type, {"X": [x.name], "Y": [y.name]}, {"Out": [out.name]}, {"axis": axis}
    )
    return helper.append_activation(out)


def elementwise_add(x, y, axis=-1, act=None, name=None):
    return elementwise_op("elementwise_add", x, y, axis, act, name)


def elementwise_sub(x, y, axis=-1, act=None, name=None):
    return elementwise_op("elementwise_sub", x, y, axis, act, name)


def elementwise_mul(x, y, axis=-1, act=None, name=None):
    return elementwise_op("elementwise_mul", x, y, axis, act, name)


def elementwise_div(x, y, axis=-1, act=None, name=None):
    return elementwise_op("elementwise_div", x, y, axis, act, name)


def elementwise_max(x, y, axis=-1, act=None, name=None):
    return elementwise_op("elementwise_max", x, y, axis, act, name)


def elementwise_min(x, y, axis=-1, act=None, name=None):
    return elementwise_op("elementwise_min", x, y, axis, act, name)


def elementwise_pow(x, y, axis=-1, act=None, name=None):
    return elementwise_op("elementwise_pow", x, y, axis, act, name)


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    helper = LayerHelper("matmul", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "matmul",
        {"X": [x.name], "Y": [y.name]},
        {"Out": [out.name]},
        {"transpose_X": transpose_x, "transpose_Y": transpose_y, "alpha": alpha},
    )
    return out


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    helper = LayerHelper("mul", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "mul",
        {"X": [x.name], "Y": [y.name]},
        {"Out": [out.name]},
        {"x_num_col_dims": x_num_col_dims, "y_num_col_dims": y_num_col_dims},
    )
    return out


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    helper = LayerHelper("scale", act=act, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "scale",
        {"X": [x.name]},
        {"Out": [out.name]},
        {"scale": scale, "bias": bias, "bias_after_scale": bias_after_scale},
    )
    return helper.append_activation(out)


def mean(x, name=None):
    return _single_op("mean", x, name=name)


def _make_reduce(op_type):
    def fn(input, dim=None, keep_dim=False, name=None):
        attrs = {
            "dim": dim if dim is not None else [0],
            "keep_dim": keep_dim,
            "reduce_all": dim is None,
        }
        return _single_op(op_type, input, attrs, name=name)

    fn.__name__ = op_type
    return fn


reduce_sum = _make_reduce("reduce_sum")
reduce_mean = _make_reduce("reduce_mean")
reduce_max = _make_reduce("reduce_max")
reduce_min = _make_reduce("reduce_min")
reduce_prod = _make_reduce("reduce_prod")


def clip(x, min, max, name=None):
    return _single_op("clip", x, {"min": min, "max": max}, name=name)


def clip_by_norm(x, max_norm, name=None):
    return _single_op("clip_by_norm", x, {"max_norm": max_norm}, name=name)


def l2_normalize(x, axis=-1, epsilon=1e-12, name=None):
    helper = LayerHelper("l2_normalize", name=name)
    sq = square(x)
    ssum = reduce_sum(sq, dim=[axis] if axis is not None else None, keep_dim=True)
    norm = sqrt(elementwise_add(ssum, fill_constant_like(ssum, epsilon)))
    return elementwise_div(x, norm)


def fill_constant_like(x, value):
    from paddle_tpu.layers.tensor import fill_constant

    return fill_constant(shape=[1], dtype=x.dtype, value=value)


# -- losses & metrics -------------------------------------------------------


def cross_entropy(input, label, soft_label=False, ignore_index=-100, name=None):
    helper = LayerHelper("cross_entropy", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "cross_entropy",
        {"X": [input.name], "Label": [label.name]},
        {"Y": [out.name]},
        {"soft_label": soft_label, "ignore_index": ignore_index},
    )
    return out


def softmax_with_cross_entropy(
    logits,
    label,
    soft_label=False,
    ignore_index=-100,
    return_softmax=False,
    axis=-1,
    name=None,
):
    helper = LayerHelper("softmax_with_cross_entropy", name=name)
    softmax_out = helper.create_variable_for_type_inference(logits.dtype)
    loss = helper.create_variable_for_type_inference(logits.dtype)
    helper.append_op(
        "softmax_with_cross_entropy",
        {"Logits": [logits.name], "Label": [label.name]},
        {"Softmax": [softmax_out.name], "Loss": [loss.name]},
        {"soft_label": soft_label, "ignore_index": ignore_index, "axis": axis},
    )
    if return_softmax:
        return loss, softmax_out
    return loss


def sigmoid_cross_entropy_with_logits(
    x, label, ignore_index=-100, normalize=False, name=None
):
    helper = LayerHelper("sigmoid_cross_entropy_with_logits", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "sigmoid_cross_entropy_with_logits",
        {"X": [x.name], "Label": [label.name]},
        {"Out": [out.name]},
        {"ignore_index": ignore_index, "normalize": normalize},
    )
    return out


def square_error_cost(input, label, name=None):
    helper = LayerHelper("square_error_cost", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "square_error_cost",
        {"X": [input.name], "Y": [label.name]},
        {"Out": [out.name]},
    )
    return out


def mse_loss(input, label, name=None):
    return mean(square_error_cost(input, label), name=name)


def huber_loss(input, label, delta, name=None):
    helper = LayerHelper("huber_loss", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    residual = helper.create_variable_for_type_inference(
        input.dtype, stop_gradient=True
    )
    helper.append_op(
        "huber_loss",
        {"X": [input.name], "Y": [label.name]},
        {"Out": [out.name], "Residual": [residual.name]},
        {"delta": delta},
    )
    return out


def kldiv_loss(x, target, reduction="mean", name=None):
    helper = LayerHelper("kldiv_loss", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "kldiv_loss",
        {"X": [x.name], "Target": [target.name]},
        {"Loss": [out.name]},
        {"reduction": reduction},
    )
    return out


def topk(input, k, name=None):
    helper = LayerHelper("top_k", name=name)
    values = helper.create_variable_for_type_inference(input.dtype)
    indices = helper.create_variable_for_type_inference("int64", stop_gradient=True)
    helper.append_op(
        "top_k",
        {"X": [input.name]},
        {"Out": [values.name], "Indices": [indices.name]},
        {"k": k},
    )
    return values, indices


def accuracy(input, label, k=1, name=None):
    """reference: python/paddle/fluid/layers/metric_op.py accuracy."""
    helper = LayerHelper("accuracy", name=name)
    values, indices = topk(input, k)
    acc = helper.create_variable_for_type_inference("float32", stop_gradient=True)
    correct = helper.create_variable_for_type_inference("int32", stop_gradient=True)
    total = helper.create_variable_for_type_inference("int32", stop_gradient=True)
    helper.append_op(
        "accuracy",
        {"Out": [values.name], "Indices": [indices.name], "Label": [label.name]},
        {"Accuracy": [acc.name], "Correct": [correct.name], "Total": [total.name]},
    )
    return acc


def auc(input, label, num_thresholds=4095, name=None):
    """Streaming AUC; stats are persistable state vars
    (reference: python/paddle/fluid/layers/metric_op.py auc)."""
    from paddle_tpu.initializer import ConstantInitializer
    from paddle_tpu.param_attr import ParamAttr

    helper = LayerHelper("auc", name=name)
    stat_pos = helper.create_parameter(
        ParamAttr(initializer=ConstantInitializer(0.0), trainable=False),
        shape=[num_thresholds + 1],
        dtype="int64",
    )
    stat_neg = helper.create_parameter(
        ParamAttr(initializer=ConstantInitializer(0.0), trainable=False),
        shape=[num_thresholds + 1],
        dtype="int64",
    )
    auc_out = helper.create_variable_for_type_inference("float32", stop_gradient=True)
    helper.append_op(
        "auc",
        {
            "Predict": [input.name],
            "Label": [label.name],
            "StatPos": [stat_pos.name],
            "StatNeg": [stat_neg.name],
        },
        {
            "AUC": [auc_out.name],
            "StatPosOut": [stat_pos.name],
            "StatNegOut": [stat_neg.name],
        },
        {"num_thresholds": num_thresholds},
    )
    return auc_out, [stat_pos, stat_neg]


def one_hot(input, depth, name=None):
    return _single_op("one_hot", input, {"depth": depth}, out_dtype="float32", name=name)


def argmax(x, axis=-1, name=None):
    return _single_op("arg_max", x, {"axis": axis}, out_dtype="int64", name=name)


def argmin(x, axis=-1, name=None):
    return _single_op("arg_min", x, {"axis": axis}, out_dtype="int64", name=name)


def unsqueeze(input, axes, name=None):
    helper = LayerHelper("unsqueeze2", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    xshape = helper.create_variable_for_type_inference(input.dtype, stop_gradient=True)
    helper.append_op(
        "unsqueeze2",
        {"X": [input.name]},
        {"Out": [out.name], "XShape": [xshape.name]},
        {"axes": axes},
    )
    return out


def squeeze(input, axes=None, name=None):
    helper = LayerHelper("squeeze2", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    xshape = helper.create_variable_for_type_inference(input.dtype, stop_gradient=True)
    helper.append_op(
        "squeeze2",
        {"X": [input.name]},
        {"Out": [out.name], "XShape": [xshape.name]},
        {"axes": axes or []},
    )
    return out
