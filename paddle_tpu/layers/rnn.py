"""Recurrent layers: fused LSTM/GRU stacks and StaticRNN.

API parity with the reference's RNN surface
(reference: python/paddle/fluid/layers/rnn.py:3049 lstm,
python/paddle/fluid/layers/control_flow.py StaticRNN,
python/paddle/fluid/layers/nn.py dynamic_lstm/dynamic_gru) redesigned for
the TPU: padded [batch, seq, feat] tensors + optional sequence_length
replace LoD ragged batching, and every variant lowers onto `lax.scan`
(ops/rnn.py) instead of per-timestep kernels.
"""

import numpy as np

from paddle_tpu.core.ir import default_main_program
from paddle_tpu.layer_helper import LayerHelper
from paddle_tpu.utils.enforce import enforce

__all__ = ["lstm", "gru", "dynamic_lstm", "dynamic_gru", "StaticRNN"]


def lstm(input, init_h, init_c, hidden_size, num_layers=1, is_bidirec=False,
         sequence_length=None, param_attr=None, bias_attr=None, name=None):
    """Fused multi-layer (bi)LSTM (reference: python/paddle/fluid/layers/
    rnn.py:3049 — there a cuDNN call over [seq, batch, in]; here batch-major
    [batch, seq, in] feeding the lax.scan `lstm` op).

    init_h/init_c: [num_layers * num_directions, batch, hidden_size].
    Returns (out [B, S, H*D], last_h, last_c).
    """
    helper = LayerHelper("lstm", name=name)
    dtype = input.dtype
    n_dir = 2 if is_bidirec else 1
    in_sizes = [int(input.shape[-1])] + [hidden_size * n_dir] * (num_layers - 1)
    shapes = {}
    ws = {"WeightIh": [], "WeightHh": [], "Bias": []}
    from paddle_tpu.param_attr import ParamAttr

    for layer in range(num_layers):
        for d in range(n_dir):
            i = layer * n_dir + d
            for slot, shape, is_bias in (
                ("WeightIh", [in_sizes[layer], 4 * hidden_size], False),
                ("WeightHh", [hidden_size, 4 * hidden_size], False),
                ("Bias", [4 * hidden_size], True),
            ):
                attr = ParamAttr._to_attr(bias_attr if is_bias else param_attr)
                if attr and attr.name:
                    attr = ParamAttr(name=f"{attr.name}.{slot}.{i}",
                                     initializer=attr.initializer)
                p = helper.create_parameter(
                    attr, shape=shape, dtype=dtype, is_bias=is_bias
                )
                ws[slot].append(p)
    # output shapes set explicitly: generic inference can't unify a
    # dynamic-batch input with fixed-batch initial states
    B = input.shape[0] if input.shape else -1
    S = input.shape[1] if input.shape else -1
    out = helper.block.create_var(
        name=helper.name + ".out", dtype=dtype,
        shape=[B, S, hidden_size * n_dir],
    )
    last_h = helper.block.create_var(
        name=helper.name + ".last_h", dtype=dtype,
        shape=[num_layers * n_dir, B, hidden_size],
    )
    last_c = helper.block.create_var(
        name=helper.name + ".last_c", dtype=dtype,
        shape=[num_layers * n_dir, B, hidden_size],
    )
    inputs = {
        "Input": [input.name],
        "InitH": [init_h.name],
        "InitC": [init_c.name],
        "WeightIh": [p.name for p in ws["WeightIh"]],
        "WeightHh": [p.name for p in ws["WeightHh"]],
        "Bias": [p.name for p in ws["Bias"]],
    }
    if sequence_length is not None:
        inputs["SequenceLength"] = [sequence_length.name]
    helper.append_op(
        "lstm",
        inputs,
        {"Out": [out.name], "LastH": [last_h.name], "LastC": [last_c.name]},
        {"num_layers": num_layers, "is_bidirec": is_bidirec,
         "hidden_size": hidden_size},
    )
    return out, last_h, last_c


def gru(input, init_h, hidden_size, num_layers=1, is_bidirec=False,
        sequence_length=None, param_attr=None, bias_attr=None, name=None):
    """Fused multi-layer (bi)GRU (TPU analog of reference
    paddle/fluid/operators/gru_op.h batched over padded tensors).
    Returns (out [B, S, H*D], last_h)."""
    helper = LayerHelper("gru", name=name)
    dtype = input.dtype
    n_dir = 2 if is_bidirec else 1
    in_sizes = [int(input.shape[-1])] + [hidden_size * n_dir] * (num_layers - 1)
    ws = {"WeightIh": [], "WeightHh": [], "BiasIh": [], "BiasHh": []}
    from paddle_tpu.param_attr import ParamAttr

    for layer in range(num_layers):
        for d in range(n_dir):
            i = layer * n_dir + d
            for slot, shape, is_bias in (
                ("WeightIh", [in_sizes[layer], 3 * hidden_size], False),
                ("WeightHh", [hidden_size, 3 * hidden_size], False),
                ("BiasIh", [3 * hidden_size], True),
                ("BiasHh", [3 * hidden_size], True),
            ):
                attr = ParamAttr._to_attr(bias_attr if is_bias else param_attr)
                if attr and attr.name:
                    attr = ParamAttr(name=f"{attr.name}.{slot}.{i}",
                                     initializer=attr.initializer)
                p = helper.create_parameter(
                    attr, shape=shape, dtype=dtype, is_bias=is_bias
                )
                ws[slot].append(p)
    B = input.shape[0] if input.shape else -1
    S = input.shape[1] if input.shape else -1
    out = helper.block.create_var(
        name=helper.name + ".out", dtype=dtype,
        shape=[B, S, hidden_size * n_dir],
    )
    last_h = helper.block.create_var(
        name=helper.name + ".last_h", dtype=dtype,
        shape=[num_layers * n_dir, B, hidden_size],
    )
    inputs = {
        "Input": [input.name],
        "InitH": [init_h.name],
        "WeightIh": [p.name for p in ws["WeightIh"]],
        "WeightHh": [p.name for p in ws["WeightHh"]],
        "BiasIh": [p.name for p in ws["BiasIh"]],
        "BiasHh": [p.name for p in ws["BiasHh"]],
    }
    if sequence_length is not None:
        inputs["SequenceLength"] = [sequence_length.name]
    helper.append_op(
        "gru",
        inputs,
        {"Out": [out.name], "LastH": [last_h.name]},
        {"num_layers": num_layers, "is_bidirec": is_bidirec,
         "hidden_size": hidden_size},
    )
    return out, last_h


def dynamic_lstm(input, size, sequence_length=None, param_attr=None,
                 bias_attr=None, name=None):
    """Single-layer LSTM over a padded batch; parity-named after the
    reference's LoD-driven dynamic_lstm (reference: python/paddle/fluid/
    layers/nn.py dynamic_lstm). `size` is 4*hidden (reference convention).
    Variable lengths come from `sequence_length` [B] instead of LoD offsets.
    Returns (hidden [B, S, H], cell_last [B, H])."""
    from paddle_tpu.layers import tensor as tensor_layers

    hidden_size = size // 4
    B_sym = input.shape[0]
    zeros = tensor_layers.fill_constant_batch_size_like(
        input, shape=[1, -1, hidden_size], dtype=input.dtype, value=0.0,
        input_dim_idx=0, output_dim_idx=1,
    )
    out, last_h, last_c = lstm(
        input, zeros, zeros, hidden_size, num_layers=1,
        sequence_length=sequence_length, param_attr=param_attr,
        bias_attr=bias_attr, name=name,
    )
    # last_c is [num_layers * num_dirs = 1, B, H]; the documented contract
    # is cell_last [B, H]
    return out, tensor_layers.reshape(last_c, [-1, hidden_size])


def dynamic_gru(input, size, sequence_length=None, param_attr=None,
                bias_attr=None, name=None):
    """Single-layer GRU over a padded batch (reference parity:
    python/paddle/fluid/layers/nn.py dynamic_gru; `size` is hidden size).
    Returns hidden [B, S, H]."""
    from paddle_tpu.layers import tensor as tensor_layers

    zeros = tensor_layers.fill_constant_batch_size_like(
        input, shape=[1, -1, size], dtype=input.dtype, value=0.0,
        input_dim_idx=0, output_dim_idx=1,
    )
    out, _ = gru(
        input, zeros, size, num_layers=1, sequence_length=sequence_length,
        param_attr=param_attr, bias_attr=bias_attr, name=name,
    )
    return out


class StaticRNN:
    """Define an RNN cell over a time-major [T, B, ...] sequence by writing
    its step inside a `with rnn.step():` block
    (reference: python/paddle/fluid/layers/control_flow.py StaticRNN).

    with rnn.step():
        x_t = rnn.step_input(x)          # [T, B, I] -> [B, I]
        prev = rnn.memory(init=h0)       # [B, H] carried state
        h = fluid.layers.fc(input=x_t, size=H, ...)  # any graph ops
        rnn.update_memory(prev, h)
        rnn.step_output(h)
    out = rnn()                           # [T, B, H]

    Lowered to ONE `recurrent` op scanning the step block (ops/rnn.py), so
    the whole unroll is a lax.scan in the compiled step — not the
    reference's per-step nested-Executor (recurrent_op.h:189).
    """

    def __init__(self, name=None):
        self.helper = LayerHelper("static_rnn", name=name)
        self.program = default_main_program()
        self._step_inputs = []   # (outer_name, inner_name)
        self._memories = []      # [outer_init_name]
        self._mem_inner = []     # inner mem var names
        self._mem_next = {}      # inner mem name -> inner updated name
        self._outputs = []       # inner names to stack
        self._entered = False
        self._seq_len = None

    # -- step context --------------------------------------------------
    class _Step:
        def __init__(self, rnn):
            self.rnn = rnn

        def __enter__(self):
            self.rnn.parent_idx = self.rnn.program.current_block_idx
            self.rnn.sub_block = self.rnn.program._create_block()
            self.rnn._entered = True
            return self.rnn

        def __exit__(self, exc_type, exc_val, exc_tb):
            self.rnn.program._rollback()
            if exc_type is None:
                self.rnn._complete()
            return False

    def step(self):
        return StaticRNN._Step(self)

    # -- builder API ---------------------------------------------------
    def step_input(self, x):
        enforce(self._entered, "step_input must be called inside rnn.step()")
        enforce(
            x.shape and len(x.shape) >= 2,
            "StaticRNN step input must be [T, B, ...] time-major",
        )
        if self._seq_len is None:
            self._seq_len = x.shape[0]
        inner = self.sub_block.create_var(
            name=f"{self.helper.name}.step_in_{len(self._step_inputs)}",
            shape=list(x.shape[1:]),
            dtype=x.dtype,
        )
        self._step_inputs.append((x.name, inner.name))
        return inner

    def memory(self, init=None, shape=None, batch_ref=None, init_value=0.0,
               init_batch_dim_idx=0, ref_batch_dim_idx=1):
        enforce(self._entered, "memory must be called inside rnn.step()")
        if init is None:
            enforce(
                batch_ref is not None and shape is not None,
                "StaticRNN.memory needs init= or (shape=, batch_ref=)",
            )
            # The boot memory lives OUTSIDE the step block. batch_ref is
            # usually the step_input result (a sub-block var, the standard
            # fluid idiom) — swap it for its outer [T, B, ...] source, whose
            # batch sits one axis later
            # ref_batch_dim_idx names the batch axis of the TIME-MAJOR
            # sequence (default 1 for [T, B, ...]), matching the reference
            ref_var, ref_idx = batch_ref, ref_batch_dim_idx
            for outer_name, inner_name in self._step_inputs:
                if batch_ref.name == inner_name:
                    ref_var = self.program.block(self.parent_idx)._find_var_recursive(outer_name)
                    break
            else:
                enforce(
                    self.program.block(self.parent_idx)._find_var_recursive(
                        batch_ref.name
                    ) is not None,
                    "StaticRNN.memory batch_ref must be a step_input result "
                    "or a variable visible outside the step block, got "
                    f"{batch_ref.name}",
                )
            from paddle_tpu.layers import tensor as tensor_layers

            cur = self.program.current_block_idx
            self.program._rollback()
            try:
                init = tensor_layers.fill_constant_batch_size_like(
                    ref_var,
                    shape=[-1] + list(shape[1:] if len(shape) > 1 else shape),
                    dtype=ref_var.dtype,
                    value=init_value,
                    input_dim_idx=ref_idx,
                    output_dim_idx=init_batch_dim_idx,
                )
            finally:
                # re-enter the step block
                self.program.current_block_idx = cur
        inner = self.sub_block.create_var(
            name=f"{self.helper.name}.mem_{len(self._memories)}",
            shape=list(init.shape) if init.shape else None,
            dtype=init.dtype,
        )
        self._memories.append(init.name)
        self._mem_inner.append(inner.name)
        return inner

    def update_memory(self, mem, var):
        enforce(self._entered, "update_memory must be inside rnn.step()")
        self._mem_next[mem.name] = var.name

    def step_output(self, o):
        enforce(self._entered, "step_output must be inside rnn.step()")
        self._outputs.append((o.name, o.dtype, o.shape))

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    # -- completion ----------------------------------------------------
    def _complete(self):
        enforce(self._step_inputs,
                "StaticRNN needs at least one step_input (it defines the "
                "sequence length the step block scans over)")
        for m in self._mem_inner:
            enforce(
                m in self._mem_next,
                f"StaticRNN memory {m} was never update_memory()'d",
            )
        parent = self.program.block(self.parent_idx)
        # external reads: sub-block reads neither produced in the sub-block
        # nor step inputs/memories, resolvable in an enclosing scope
        produced = set(n for _, n in self._step_inputs) | set(self._mem_inner)
        ex = []
        for sop in self.sub_block.ops:
            for n in sop.input_names():
                if n in produced or n in ex:
                    continue
                if parent._find_var_recursive(n) is not None:
                    ex.append(n)
            produced.update(sop.output_names())
        self._ex_names = ex

        outs = []
        for name, dtype, shape in self._outputs:
            full_shape = [self._seq_len] + list(shape or [])
            outs.append(
                parent.create_var(
                    name=f"{self.helper.name}.out_{len(outs)}",
                    shape=full_shape,
                    dtype=dtype,
                )
            )
        lasts = [
            parent.create_var(
                name=f"{self.helper.name}.last_{i}", shape=None, dtype="float32"
            )
            for i in range(len(self._memories))
        ]
        parent.append_op(
            "recurrent",
            {
                "X": [outer for outer, _ in self._step_inputs],
                "Init": list(self._memories),
                "Ex": list(ex),
            },
            {
                "Out": [o.name for o in outs],
                "LastState": [l.name for l in lasts],
            },
            {
                "sub_block": self.sub_block.idx,
                "inner_input_vars": [n for _, n in self._step_inputs],
                "state_inner_vars": list(self._mem_inner),
                "state_next_vars": [
                    self._mem_next[m] for m in self._mem_inner
                ],
                "step_output_vars": [n for n, _, _ in self._outputs],
                "ex_vars": list(ex),
            },
        )
        self._result_vars = outs

    def __call__(self):
        enforce(hasattr(self, "_result_vars"), "StaticRNN not completed")
        if len(self._result_vars) == 1:
            return self._result_vars[0]
        return self._result_vars
