"""PipelinedStack: build a pipelined layer stack in the Program IR.

The user writes the per-layer body ONCE inside `with stack.layer():` (the
way StaticRNN declares its step); parameters created through
`stack.layer_param` are stacked with a leading [num_layers] axis, and the
whole stack lowers to ONE `pipeline_stack` op (ops/pipeline.py) that runs
the GPipe schedule over the mesh's `stage` axis when compiled with
CompiledProgram.with_parallel — the product-surface path to pipeline
parallelism (reference: python/paddle/fluid/optimizer.py:3414
PipelineOptimizer + section_worker.cc:142; there heterogeneous sections on
threads, here a homogeneous stacked-layer pipeline inside XLA, which is the
shape every pipelined transformer actually has).

    stack = fluid.layers.PipelinedStack(num_layers=12, num_microbatches=4)
    with stack.layer():
        h = stack.input(x)                       # [mb, S, H] per microbatch
        w = stack.layer_param([H, H], spec=(None, "model"))
        h2 = ops using h, w ...
        stack.output(h2)
    out = stack()                                # same shape as x

Pass `stack.param_spec_overrides()` into with_parallel(param_specs=...) so
the stacked parameters are placed stage-major on the mesh.

DESIGN BOUNDARY — homogeneous stages only. Every pipelined layer shares one
body and one stacked param shape; embedding/LM-head-style odd stages live
OUTSIDE the stack in the same program (see models/gpt_ir.py). The
reference's section pipeline cut arbitrary programs into per-device
sections (reference: python/paddle/fluid/optimizer.py:3414 cut_list,
device_worker section_worker.cc:142) because each GPU needed its op range
placed on it; under GSPMD the outside-the-stack ops are sharded over the
whole mesh by the compiler, so the odd stages need no placement — the
homogeneous stack covers exactly the part where the GPipe schedule pays.
"""

import numpy as np

from paddle_tpu.core.ir import default_main_program
from paddle_tpu.layer_helper import LayerHelper
from paddle_tpu.param_attr import ParamAttr
from paddle_tpu.utils.enforce import enforce

__all__ = ["PipelinedStack"]


class PipelinedStack:
    def __init__(self, num_layers, num_microbatches=1, stage_axis="stage",
                 ring_bindings=None, schedule="gpipe", interleave=None,
                 name=None):
        self.helper = LayerHelper("pipelined_stack", name=name)
        self.program = default_main_program()
        self.num_layers = int(num_layers)
        self.num_microbatches = int(num_microbatches)
        self.stage_axis = stage_axis
        # schedule: 'gpipe' | '1f1b' (interleaved; `interleave` chunks per
        # device, default 2). A program attr here is the DEFAULT — the
        # run-time choice `with_parallel(pipeline_schedule=...)` overrides
        # it and joins the compile-cache fingerprint (pipeline_runtime/).
        from paddle_tpu.parallel.pipeline_runtime.schedule import (
            SCHEDULE_KINDS,
        )

        enforce(schedule in SCHEDULE_KINDS,
                f"PipelinedStack schedule must be one of {SCHEDULE_KINDS},"
                f" got {schedule!r}")
        self.schedule = schedule
        self.interleave = int(interleave) if interleave else None
        # ring_id -> mesh axis for collectives inside the body (TP psum)
        self.ring_bindings = dict(ring_bindings or {})
        self._entered = False
        self._input = None        # (outer_name, inner_name)
        self._output = None
        self._params = []         # (outer stacked name, inner name, spec)

    # -- body context ---------------------------------------------------
    class _Layer:
        def __init__(self, stack):
            self.stack = stack

        def __enter__(self):
            st = self.stack
            st.parent_idx = st.program.current_block_idx
            st.sub_block = st.program._create_block()
            st._entered = True
            return st

        def __exit__(self, exc_type, exc_val, exc_tb):
            self.stack.program._rollback()
            if exc_type is None:
                self.stack._complete()
            return False

    def layer(self):
        return PipelinedStack._Layer(self)

    # -- builder API ----------------------------------------------------
    def input(self, x):
        enforce(self._entered, "input() must be called inside stack.layer()")
        enforce(self._input is None, "PipelinedStack takes ONE input")
        shape = list(x.shape) if x.shape else None
        # body sees one microbatch: batch dim shrinks to B/M (dynamic)
        if shape:
            shape = [-1] + shape[1:]
        inner = self.sub_block.create_var(
            name=f"{self.helper.name}.h_in", shape=shape, dtype=x.dtype
        )
        self._input = (x.name, inner.name)
        return inner

    def layer_param(self, shape, dtype="float32", attr=None, spec=None,
                    is_bias=False):
        """A per-layer parameter [*shape]; storage is stacked
        [num_layers, *shape]. `spec` gives the non-stage partition of the
        per-layer dims (e.g. (None, 'model') for a column-parallel matmul);
        the stacked array's spec becomes ('stage', *spec)."""
        enforce(self._entered, "layer_param() must be inside stack.layer()")
        attr = ParamAttr._to_attr(attr)
        if attr is None or attr is False:
            attr = ParamAttr()
        stacked_shape = [self.num_layers] + list(shape)
        # create the stacked parameter in the parent scope
        cur = self.program.current_block_idx
        self.program._rollback()
        try:
            p = self.helper.create_parameter(
                attr, shape=stacked_shape, dtype=dtype, is_bias=is_bias
            )
        finally:
            self.program.current_block_idx = cur
        inner = self.sub_block.create_var(
            name=f"{self.helper.name}.p_{len(self._params)}",
            shape=list(shape),
            dtype=dtype,
        )
        self._params.append(
            (p.name, inner.name, tuple(spec) if spec else ())
        )
        return inner

    def output(self, o):
        enforce(self._entered, "output() must be inside stack.layer()")
        enforce(self._output is None, "PipelinedStack produces ONE output")
        self._output = o.name

    # -- completion -----------------------------------------------------
    def _complete(self):
        enforce(self._input is not None, "PipelinedStack needs input()")
        enforce(self._output is not None, "PipelinedStack needs output()")
        parent = self.program.block(self.parent_idx)
        produced = {self._input[1]} | {inner for _, inner, _ in self._params}
        ex = []
        for sop in self.sub_block.ops:
            for n in sop.input_names():
                if n in produced or n in ex:
                    continue
                if parent._find_var_recursive(n) is not None:
                    ex.append(n)
            produced.update(sop.output_names())
        x_var = parent._find_var_recursive(self._input[0])
        out = parent.create_var(
            name=f"{self.helper.name}.out",
            shape=list(x_var.shape) if x_var.shape else None,
            dtype=x_var.dtype,
        )
        parent.append_op(
            "pipeline_stack",
            {
                "X": [self._input[0]],
                "StackedParams": [n for n, _, _ in self._params],
                "Ex": list(ex),
            },
            {"Out": [out.name]},
            {
                "sub_block": self.sub_block.idx,
                "inner_x": self._input[1],
                "inner_out": self._output,
                "param_inner_vars": [i for _, i, _ in self._params],
                "param_specs": [list(s) for _, _, s in self._params],
                "ex_vars": list(ex),
                "num_microbatches": self.num_microbatches,
                "stage_axis": self.stage_axis,
                "ring_bindings": self.ring_bindings,
                "schedule": self.schedule,
                "interleave": self.interleave,
            },
        )
        self._result = out

    def __call__(self):
        enforce(hasattr(self, "_result"), "PipelinedStack not completed")
        return self._result

    def param_spec_overrides(self):
        """{stacked param name: PartitionSpec('stage', *per-layer spec)} —
        feed to CompiledProgram.with_parallel(param_specs=...)."""
        from jax.sharding import PartitionSpec as P

        return {
            name: P(self.stage_axis, *spec)
            for name, _, spec in self._params
        }
