"""Tensor creation/manipulation layer functions
(reference: python/paddle/fluid/layers/tensor.py)."""

import builtins as _builtins

from paddle_tpu.core.dtypes import convert_dtype

# this module defines a `range` LAYER below, which shadows the builtin for
# any module-level function that runs after import — keep the real one
_builtin_range = _builtins.range
from paddle_tpu.core.ir import default_main_program
from paddle_tpu.layer_helper import LayerHelper

__all__ = [
    "data",
    "fill_constant",
    "fill_constant_batch_size_like",
    "zeros",
    "ones",
    "zeros_like",
    "ones_like",
    "assign",
    "cast",
    "sums",
    "concat",
    "split",
    "reshape",
    "transpose",
    "stack",
    "unstack",
    "slice",
    "expand",
    "gather",
    "batched_gather",
    "gather_nd",
    "scatter",
    "where",
    "cond_select",
    "shape",
    "range",
    "linspace",
    "uniform_random",
    "gaussian_random",
    "create_tensor",
    "create_global_var",
    "cumsum",
    "equal",
    "not_equal",
    "less_than",
    "less_equal",
    "greater_than",
    "greater_equal",
    "logical_and",
    "logical_or",
    "logical_not",
    "isfinite",
    "increment",
    "flatten",
    "pad",
]


def data(name, shape, dtype="float32", append_batch_size=True, lod_level=0):
    """Declare a feed slot (reference: python/paddle/fluid/layers/io.py
    data — append_batch_size prepends the dynamic batch dim)."""
    block = default_main_program().global_block()
    if append_batch_size:
        shape = [-1] + list(shape)
    shape = [-1 if d is None else d for d in shape]
    return block.create_var(
        name=name,
        shape=shape,
        dtype=dtype,
        is_data=True,
        stop_gradient=True,
        lod_level=lod_level,
    )


def data_v2(name, shape, dtype="float32", lod_level=0):
    """The reference's top-level `fluid.data` (python/paddle/fluid/data.py):
    shape taken verbatim, None/-1 marks dynamic dims, NO batch prepend."""
    return data(name, shape, dtype, append_batch_size=False, lod_level=lod_level)


def fill_constant(shape, dtype, value, name=None, out=None):
    helper = LayerHelper("fill_constant", name=name)
    if out is None:
        out = helper.create_variable_for_type_inference(convert_dtype(dtype))
    helper.append_op(
        "fill_constant",
        {},
        {"Out": [out.name]},
        {"shape": list(shape), "dtype": convert_dtype(dtype), "value": value},
    )
    out.stop_gradient = True
    return out


def zeros(shape, dtype="float32", name=None):
    return fill_constant(shape, dtype, 0.0, name=name)


def ones(shape, dtype="float32", name=None):
    return fill_constant(shape, dtype, 1.0, name=name)


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0,
                                  name=None):
    """reference: python/paddle/fluid/layers/tensor.py
    fill_constant_batch_size_like — `shape[output_dim_idx]` is replaced by
    `input.shape[input_dim_idx]` at run time."""
    helper = LayerHelper("fill_constant_batch_size_like", name=name)
    dtype = convert_dtype(dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "fill_constant_batch_size_like",
        {"Input": [input.name]},
        {"Out": [out.name]},
        {
            "shape": list(shape),
            "dtype": dtype,
            "value": value,
            "input_dim_idx": input_dim_idx,
            "output_dim_idx": output_dim_idx,
        },
    )
    return out


def zeros_like(x, name=None):
    helper = LayerHelper("zeros_like", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("fill_zeros_like", {"X": [x.name]}, {"Out": [out.name]})
    return out


def ones_like(x, name=None):
    helper = LayerHelper("ones_like", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "fill_constant_batch_size_like",
        {"Input": [x.name]},
        {"Out": [out.name]},
        {"shape": list(x.shape), "dtype": x.dtype, "value": 1.0},
    )
    return out


def assign(input, output=None, name=None):
    helper = LayerHelper("assign", name=name)
    if output is None:
        output = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("assign", {"X": [input.name]}, {"Out": [output.name]})
    return output


def cast(x, dtype, name=None):
    helper = LayerHelper("cast", name=name)
    dtype = convert_dtype(dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "cast", {"X": [x.name]}, {"Out": [out.name]}, {"out_dtype": dtype}
    )
    return out


def sums(input, out=None, name=None):
    """Elementwise sum of a list of tensors (reference: python/paddle/fluid/
    layers/tensor.py sums -> sum op)."""
    helper = LayerHelper("sum", name=name)
    if out is None:
        out = helper.create_variable_for_type_inference(input[0].dtype)
    helper.append_op(
        "sum", {"X": [v.name for v in input]}, {"Out": [out.name]}, {}
    )
    return out


def concat(input, axis=0, name=None):
    helper = LayerHelper("concat", name=name)
    out = helper.create_variable_for_type_inference(input[0].dtype)
    helper.append_op(
        "concat", {"X": [v.name for v in input]}, {"Out": [out.name]}, {"axis": axis}
    )
    return out


def split(input, num_or_sections, dim=-1, name=None):
    helper = LayerHelper("split", name=name)
    if isinstance(num_or_sections, int):
        num = num_or_sections
        sections = []
        n_out = num
    else:
        num = 0
        sections = list(num_or_sections)
        n_out = len(sections)
    outs = [
        helper.create_variable_for_type_inference(input.dtype)
        for _ in _builtin_range(n_out)
    ]
    helper.append_op(
        "split",
        {"X": [input.name]},
        {"Out": [o.name for o in outs]},
        {"num": num, "sections": sections, "axis": dim},
    )
    return outs


def reshape(x, shape, inplace=False, name=None):
    helper = LayerHelper("reshape2", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    xshape = helper.create_variable_for_type_inference(x.dtype, stop_gradient=True)
    helper.append_op(
        "reshape2",
        {"X": [x.name]},
        {"Out": [out.name], "XShape": [xshape.name]},
        {"shape": list(shape)},
    )
    return out


def transpose(x, perm, name=None):
    helper = LayerHelper("transpose2", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    xshape = helper.create_variable_for_type_inference(x.dtype, stop_gradient=True)
    helper.append_op(
        "transpose2",
        {"X": [x.name]},
        {"Out": [out.name], "XShape": [xshape.name]},
        {"axis": list(perm)},
    )
    return out


def flatten(x, axis=1, name=None):
    helper = LayerHelper("flatten2", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    xshape = helper.create_variable_for_type_inference(x.dtype, stop_gradient=True)
    helper.append_op(
        "flatten2",
        {"X": [x.name]},
        {"Out": [out.name], "XShape": [xshape.name]},
        {"axis": axis},
    )
    return out


def stack(x, axis=0, name=None):
    helper = LayerHelper("stack", name=name)
    out = helper.create_variable_for_type_inference(x[0].dtype)
    helper.append_op(
        "stack", {"X": [v.name for v in x]}, {"Y": [out.name]}, {"axis": axis}
    )
    return out


def unstack(x, axis=0, num=None, name=None):
    helper = LayerHelper("unstack", name=name)
    if num is None:
        num = x.shape[axis]
    outs = [helper.create_variable_for_type_inference(x.dtype) for _ in _builtin_range(num)]
    helper.append_op(
        "unstack",
        {"X": [x.name]},
        {"Y": [o.name for o in outs]},
        {"axis": axis, "num": num},
    )
    return outs


def slice(input, axes, starts, ends, name=None):
    helper = LayerHelper("slice", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "slice",
        {"Input": [input.name]},
        {"Out": [out.name]},
        {"axes": list(axes), "starts": list(starts), "ends": list(ends)},
    )
    return out


def expand(x, expand_times, name=None):
    helper = LayerHelper("expand", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "expand",
        {"X": [x.name]},
        {"Out": [out.name]},
        {"expand_times": list(expand_times)},
    )
    return out


def batched_gather(x, index, name=None):
    """X [B, S, ...] + Index [B, P] -> [B, P, ...] (rows per batch)."""
    helper = LayerHelper("batched_gather", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "batched_gather",
        {"X": [x.name], "Index": [index.name]},
        {"Out": [out.name]},
        {},
    )
    return out


def gather(input, index, axis=0, name=None):
    helper = LayerHelper("gather", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "gather",
        {"X": [input.name], "Index": [index.name]},
        {"Out": [out.name]},
        {"axis": axis},
    )
    return out


def gather_nd(input, index, name=None):
    helper = LayerHelper("gather_nd", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "gather_nd",
        {"X": [input.name], "Index": [index.name]},
        {"Out": [out.name]},
    )
    return out


def scatter(input, index, updates, overwrite=True, mode=None, name=None):
    """Row scatter. ``mode="drop"`` skips out-of-range indices instead
    of clamping — the paged KV arena's "write nowhere" encoding."""
    helper = LayerHelper("scatter", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    attrs = {"overwrite": overwrite}
    if mode is not None:
        attrs["mode"] = mode
    helper.append_op(
        "scatter",
        {"X": [input.name], "Ids": [index.name], "Updates": [updates.name]},
        {"Out": [out.name]},
        attrs,
    )
    return out


def where(condition, x, y, name=None):
    helper = LayerHelper("where", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "where",
        {"Condition": [condition.name], "X": [x.name], "Y": [y.name]},
        {"Out": [out.name]},
    )
    return out


cond_select = where


def shape(input, name=None):
    helper = LayerHelper("shape", name=name)
    out = helper.create_variable_for_type_inference("int32", stop_gradient=True)
    helper.append_op("shape", {"Input": [input.name]}, {"Out": [out.name]})
    return out


def range(start, end, step, dtype="float32", name=None):
    helper = LayerHelper("range", name=name)
    vals = []
    for v, nm in ((start, "start"), (end, "end"), (step, "step")):
        if not hasattr(v, "name"):
            v = fill_constant([1], dtype, float(v))
        vals.append(v)
    out = helper.create_variable_for_type_inference(convert_dtype(dtype))
    helper.append_op(
        "range",
        {"Start": [vals[0].name], "End": [vals[1].name], "Step": [vals[2].name]},
        {"Out": [out.name]},
    )
    return out


def linspace(start, stop, num, dtype="float32", name=None):
    helper = LayerHelper("linspace", name=name)
    vals = []
    for v, d in ((start, dtype), (stop, dtype), (num, "int32")):
        if not hasattr(v, "name"):
            v = fill_constant([1], d, float(v))
        vals.append(v)
    out = helper.create_variable_for_type_inference(convert_dtype(dtype))
    helper.append_op(
        "linspace",
        {"Start": [vals[0].name], "Stop": [vals[1].name], "Num": [vals[2].name]},
        {"Out": [out.name]},
    )
    return out


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0, name=None):
    helper = LayerHelper("uniform_random", name=name)
    out = helper.create_variable_for_type_inference(convert_dtype(dtype))
    helper.append_op(
        "uniform_random",
        {},
        {"Out": [out.name]},
        {"shape": list(shape), "dtype": convert_dtype(dtype), "min": min, "max": max, "seed": seed},
    )
    return out


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32", name=None):
    helper = LayerHelper("gaussian_random", name=name)
    out = helper.create_variable_for_type_inference(convert_dtype(dtype))
    helper.append_op(
        "gaussian_random",
        {},
        {"Out": [out.name]},
        {"shape": list(shape), "dtype": convert_dtype(dtype), "mean": mean, "std": std, "seed": seed},
    )
    return out


def create_tensor(dtype, name=None, persistable=False):
    helper = LayerHelper("create_tensor", name=name)
    return helper.block.create_var(
        name=name or helper.name, dtype=dtype, persistable=persistable, shape=None
    )


def create_global_var(
    shape, value, dtype, persistable=False, force_cpu=False, name=None
):
    """reference: python/paddle/fluid/layers/tensor.py create_global_var —
    value lives in the startup program, var in the main program."""
    from paddle_tpu.core.ir import default_startup_program
    from paddle_tpu.utils import unique_name

    name = name or unique_name.generate("global_var")
    sblock = default_startup_program().global_block()
    svar = sblock.create_var(
        name=name, shape=shape, dtype=dtype, persistable=persistable
    )
    sblock.append_op(
        "fill_constant",
        {},
        {"Out": [name]},
        {"shape": list(shape), "dtype": convert_dtype(dtype), "value": value},
    )
    mblock = default_main_program().global_block()
    var = mblock.create_var(
        name=name, shape=shape, dtype=dtype, persistable=persistable
    )
    var.stop_gradient = True
    return var


def cumsum(x, axis=-1, exclusive=False, reverse=False, name=None):
    helper = LayerHelper("cumsum", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "cumsum",
        {"X": [x.name]},
        {"Out": [out.name]},
        {"axis": axis, "exclusive": exclusive, "reverse": reverse},
    )
    return out


def _make_compare(op_type):
    def fn(x, y, cond=None, name=None):
        # `cond` names an existing output var — the reference uses this to
        # rewrite the loop condition inside While blocks
        # (reference: python/paddle/fluid/layers/control_flow.py less_than)
        helper = LayerHelper(op_type, name=name)
        out = cond if cond is not None else helper.create_variable_for_type_inference(
            "bool", stop_gradient=True
        )
        helper.append_op(
            op_type, {"X": [x.name], "Y": [y.name]}, {"Out": [out.name]}
        )
        return out

    fn.__name__ = op_type
    return fn


equal = _make_compare("equal")
not_equal = _make_compare("not_equal")
less_than = _make_compare("less_than")
less_equal = _make_compare("less_equal")
greater_than = _make_compare("greater_than")
greater_equal = _make_compare("greater_equal")
logical_and = _make_compare("logical_and")
logical_or = _make_compare("logical_or")


def logical_not(x, name=None):
    helper = LayerHelper("logical_not", name=name)
    out = helper.create_variable_for_type_inference("bool", stop_gradient=True)
    helper.append_op("logical_not", {"X": [x.name]}, {"Out": [out.name]})
    return out


def isfinite(x, name=None):
    helper = LayerHelper("isfinite", name=name)
    out = helper.create_variable_for_type_inference("bool", stop_gradient=True)
    helper.append_op("isfinite", {"X": [x.name]}, {"Out": [out.name]})
    return out


def increment(x, value=1.0, in_place=True, name=None):
    helper = LayerHelper("increment", name=name)
    if in_place:
        out = x
    else:
        out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "increment", {"X": [x.name]}, {"Out": [out.name]}, {"step": value}
    )
    return out


def pad(x, paddings, pad_value=0.0, name=None):
    helper = LayerHelper("pad", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "pad",
        {"X": [x.name]},
        {"Out": [out.name]},
        {"paddings": list(paddings), "pad_value": pad_value},
    )
    return out
