"""Second tranche of layer functions (reference: python/paddle/fluid/
layers/nn.py + loss.py — one builder per op in ops/nn_extra.py)."""

from paddle_tpu.layer_helper import LayerHelper

__all__ = [
    "py_func",
    "selu", "brelu", "soft_relu", "stanh", "sign", "maxout",
    "argsort", "eye", "diag", "expand_as", "strided_slice", "reverse",
    "scatter_nd_add", "pad2d", "shard_index", "rank", "size", "multiplex",
    "crop_tensor",
    "log_loss", "rank_loss", "margin_rank_loss", "dice_loss", "bpr_loss",
    "label_smooth", "cos_sim", "npair_loss", "mean_iou",
    "resize_nearest", "resize_bilinear", "image_resize", "pixel_shuffle",
    "space_to_depth", "shuffle_channel", "temporal_shift", "unfold",
    "add_position_encoding", "bilinear_tensor_product", "pool3d", "conv3d",
    "adaptive_pool2d",
]


def _simple(op, ins, attrs, dtype="float32", outs=("Out",), name=None):
    helper = LayerHelper(op, name=name)
    out_vars = [helper.create_variable_for_type_inference(dtype) for _ in outs]
    helper.append_op(
        op, ins, {slot: [v.name] for slot, v in zip(outs, out_vars)}, attrs
    )
    return out_vars[0] if len(out_vars) == 1 else tuple(out_vars)


def _x_op(op, x, attrs=None, name=None, out_slot="Out"):
    return _simple(op, {"X": [x.name]}, attrs or {}, x.dtype,
                   (out_slot,), name)


# -- activations ---------------------------------------------------------
def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return _x_op("selu", x, {"scale": scale, "alpha": alpha}, name)


def brelu(x, t_min=0.0, t_max=24.0, name=None):
    return _x_op("brelu", x, {"t_min": t_min, "t_max": t_max}, name)


def soft_relu(x, threshold=40.0, name=None):
    return _x_op("soft_relu", x, {"threshold": threshold}, name)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return _x_op("stanh", x, {"scale_a": scale_a, "scale_b": scale_b}, name)


def sign(x, name=None):
    return _x_op("sign", x, {}, name)


def maxout(x, groups, name=None):
    return _x_op("maxout", x, {"groups": groups}, name)


# -- tensor utilities ----------------------------------------------------
def argsort(x, axis=-1, descending=False, name=None):
    helper = LayerHelper("argsort", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    ids = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        "argsort", {"X": [x.name]},
        {"Out": [out.name], "Indices": [ids.name]},
        {"axis": axis, "descending": descending},
    )
    return out, ids


def eye(num_rows, num_columns=None, dtype="float32", name=None):
    attrs = {"num_rows": num_rows, "dtype": dtype}
    if num_columns is not None:
        attrs["num_columns"] = num_columns
    return _simple("eye", {}, attrs, dtype, name=name)


def diag(diagonal, name=None):
    return _simple("diag", {"Diagonal": [diagonal.name]}, {},
                   diagonal.dtype, name=name)


def expand_as(x, target_tensor, name=None):
    return _simple(
        "expand_as",
        {"X": [x.name], "target_tensor": [target_tensor.name]}, {},
        x.dtype, name=name,
    )


def strided_slice(input, axes, starts, ends, strides, name=None):
    return _simple(
        "strided_slice", {"Input": [input.name]},
        {"axes": list(axes), "starts": list(starts), "ends": list(ends),
         "strides": list(strides)},
        input.dtype, name=name,
    )


def reverse(x, axis, name=None):
    if isinstance(axis, int):
        axis = [axis]
    return _x_op("reverse", x, {"axis": list(axis)}, name)


def scatter_nd_add(ref, index, updates, name=None):
    return _simple(
        "scatter_nd_add",
        {"X": [ref.name], "Index": [index.name], "Updates": [updates.name]},
        {}, ref.dtype, name=name,
    )


def pad2d(input, paddings=(0, 0, 0, 0), mode="constant", pad_value=0.0,
          data_format="NCHW", name=None):
    return _x_op(
        "pad2d", input,
        {"paddings": list(paddings), "mode": mode, "pad_value": pad_value},
        name,
    )


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1,
                name=None):
    return _x_op(
        "shard_index", input,
        {"index_num": index_num, "nshards": nshards, "shard_id": shard_id,
         "ignore_value": ignore_value},
        name,
    )


def rank(input, name=None):
    return _simple("rank", {"Input": [input.name]}, {}, "int32", name=name)


def size(input, name=None):
    return _simple("size", {"Input": [input.name]}, {}, "int64", name=name)


def multiplex(inputs, index, name=None):
    return _simple(
        "multiplex",
        {"X": [v.name for v in inputs], "Ids": [index.name]}, {},
        inputs[0].dtype, name=name,
    )


def crop_tensor(x, shape, offsets, name=None):
    return _x_op(
        "crop_tensor", x,
        {"shape": list(shape), "offsets": list(offsets)}, name,
    )


# -- losses --------------------------------------------------------------
def log_loss(input, label, epsilon=1e-4, name=None):
    return _simple(
        "log_loss",
        {"Predicted": [input.name], "Labels": [label.name]},
        {"epsilon": epsilon}, input.dtype, ("Loss",), name,
    )


def rank_loss(label, left, right, name=None):
    return _simple(
        "rank_loss",
        {"Label": [label.name], "Left": [left.name], "Right": [right.name]},
        {}, left.dtype, name=name,
    )


def margin_rank_loss(label, left, right, margin=0.1, name=None):
    helper = LayerHelper("margin_rank_loss", name=name)
    out = helper.create_variable_for_type_inference(left.dtype)
    act = helper.create_variable_for_type_inference(left.dtype)
    helper.append_op(
        "margin_rank_loss",
        {"Label": [label.name], "X1": [left.name], "X2": [right.name]},
        {"Out": [out.name], "Activated": [act.name]},
        {"margin": margin},
    )
    return out


def dice_loss(input, label, epsilon=1e-5, name=None):
    return _simple(
        "dice_loss_op",
        {"X": [input.name], "Label": [label.name]},
        {"epsilon": epsilon}, input.dtype, name=name,
    )


def bpr_loss(input, label, name=None):
    return _simple(
        "bpr_loss", {"X": [input.name], "Label": [label.name]}, {},
        input.dtype, name=name,
    )


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    ins = {"X": [label.name]}
    if prior_dist is not None:
        ins["PriorDist"] = [prior_dist.name]
    return _simple("label_smooth", ins, {"epsilon": epsilon},
                   label.dtype, name=name)


def cos_sim(X, Y, name=None):
    helper = LayerHelper("cos_sim", name=name)
    out = helper.create_variable_for_type_inference(X.dtype)
    xn = helper.create_variable_for_type_inference(X.dtype)
    yn = helper.create_variable_for_type_inference(X.dtype)
    helper.append_op(
        "cos_sim", {"X": [X.name], "Y": [Y.name]},
        {"Out": [out.name], "XNorm": [xn.name], "YNorm": [yn.name]}, {},
    )
    return out


def npair_loss(anchor, positive, labels, l2_reg=0.002, name=None):
    return _simple(
        "npair_loss",
        {"anchor": [anchor.name], "positive": [positive.name],
         "labels": [labels.name]},
        {"l2_reg": l2_reg}, anchor.dtype, name=name,
    )


def mean_iou(input, label, num_classes, name=None):
    helper = LayerHelper("mean_iou", name=name)
    miou = helper.create_variable_for_type_inference("float32")
    wrong = helper.create_variable_for_type_inference("float32")
    correct = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        "mean_iou",
        {"Predictions": [input.name], "Labels": [label.name]},
        {"OutMeanIou": [miou.name], "OutWrong": [wrong.name],
         "OutCorrect": [correct.name]},
        {"num_classes": num_classes},
    )
    return miou, wrong, correct


# -- vision --------------------------------------------------------------
def resize_nearest(input, out_shape, align_corners=True, name=None):
    return _x_op(
        "nearest_interp", input,
        {"out_h": int(out_shape[0]), "out_w": int(out_shape[1]),
         "align_corners": align_corners}, name,
    )


def resize_bilinear(input, out_shape, align_corners=True, name=None):
    return _x_op(
        "bilinear_interp", input,
        {"out_h": int(out_shape[0]), "out_w": int(out_shape[1]),
         "align_corners": align_corners}, name,
    )


def image_resize(input, out_shape, resample="BILINEAR", align_corners=True,
                 name=None):
    resample = resample.upper()
    if resample == "BILINEAR":
        return resize_bilinear(input, out_shape, align_corners, name=name)
    if resample == "NEAREST":
        return resize_nearest(input, out_shape, align_corners, name=name)
    raise ValueError(
        f"image_resize: unsupported resample method {resample!r} "
        "(BILINEAR or NEAREST)"
    )


def pixel_shuffle(x, upscale_factor, name=None):
    return _x_op("pixel_shuffle", x, {"upscale_factor": upscale_factor}, name)


def space_to_depth(x, blocksize, name=None):
    return _x_op("space_to_depth", x, {"blocksize": blocksize}, name)


def shuffle_channel(x, group, name=None):
    return _x_op("shuffle_channel", x, {"group": group}, name)


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None):
    return _x_op(
        "temporal_shift", x,
        {"seg_num": seg_num, "shift_ratio": shift_ratio}, name,
    )


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    def _pair(v):
        return [v, v] if isinstance(v, int) else list(v)

    pads = _pair(paddings)
    if len(pads) == 2:
        pads = pads + pads
    return _x_op(
        "unfold", x,
        {"kernel_sizes": _pair(kernel_sizes), "strides": _pair(strides),
         "paddings": pads, "dilations": _pair(dilations)},
        name, out_slot="Y",
    )


def add_position_encoding(input, alpha=1.0, beta=1.0, name=None):
    return _x_op("add_position_encoding", input,
                 {"alpha": alpha, "beta": beta}, name)


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    helper = LayerHelper(
        "bilinear_tensor_product", param_attr=param_attr,
        bias_attr=bias_attr, act=act, name=name,
    )
    w = helper.create_parameter(
        helper.param_attr,
        shape=[size, int(x.shape[-1]), int(y.shape[-1])], dtype=x.dtype,
    )
    ins = {"X": [x.name], "Y": [y.name], "Weight": [w.name]}
    if helper.bias_attr is not False:
        b = helper.create_parameter(
            helper.bias_attr, shape=[size], dtype=x.dtype, is_bias=True
        )
        ins["Bias"] = [b.name]
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("bilinear_tensor_product", ins, {"Out": [out.name]}, {})
    return helper.append_activation(out)


def pool3d(input, pool_size, pool_type="max", pool_stride=None,
           pool_padding=0, global_pooling=False, name=None):
    ks = [pool_size] * 3 if isinstance(pool_size, int) else list(pool_size)
    strides = (
        ks if pool_stride is None
        else [pool_stride] * 3 if isinstance(pool_stride, int)
        else list(pool_stride)
    )
    pads = (
        [pool_padding] * 3 if isinstance(pool_padding, int)
        else list(pool_padding)
    )
    return _x_op(
        "pool3d", input,
        {"ksize": ks, "strides": strides, "paddings": pads,
         "pooling_type": pool_type, "global_pooling": global_pooling},
        name,
    )


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None, name=None):
    helper = LayerHelper(
        "conv3d", param_attr=param_attr, bias_attr=bias_attr, act=act,
        name=name,
    )
    ks = [filter_size] * 3 if isinstance(filter_size, int) else list(filter_size)
    c_in = int(input.shape[1])
    w = helper.create_parameter(
        helper.param_attr,
        shape=[num_filters, c_in // groups] + ks, dtype=input.dtype,
    )
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "conv3d",
        {"Input": [input.name], "Filter": [w.name]},
        {"Output": [out.name]},
        {
            "strides": [stride] * 3 if isinstance(stride, int) else list(stride),
            "paddings": [padding] * 3 if isinstance(padding, int) else list(padding),
            "dilations": [dilation] * 3 if isinstance(dilation, int) else list(dilation),
            "groups": groups,
        },
    )
    if helper.bias_attr is not False:
        b = helper.create_parameter(
            helper.bias_attr, shape=[num_filters], dtype=input.dtype,
            is_bias=True,
        )
        out = helper.append_bias_op(out, b, axis=1)
    return helper.append_activation(out)


def adaptive_pool2d(input, pool_size, pool_type="avg", name=None):
    ps = [pool_size] * 2 if isinstance(pool_size, int) else list(pool_size)
    return _x_op(
        "adaptive_pool2d", input,
        {"pooled_height": ps[0], "pooled_width": ps[1],
         "pooling_type": pool_type},
        name,
    )


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None,
            name=None):
    """User Python inside the step via host callback (reference:
    python/paddle/fluid/layers/nn.py py_func). `out` var(s) must be
    pre-created with concrete shape+dtype (as in the reference);
    `skip_vars_in_backward_input` lists input vars OMITTED from
    backward_func's argument list."""
    from paddle_tpu.ops.py_func import PyFuncToken
    from paddle_tpu.utils.enforce import enforce

    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = out if isinstance(out, (list, tuple)) else [out]
    for o in outs:
        enforce(
            o.shape is not None and all(d >= 0 for d in o.shape),
            f"py_func output {o.name} needs a concrete shape (got {o.shape})",
        )
    skip_idx = []
    if skip_vars_in_backward_input:
        skip_names = {
            v if isinstance(v, str) else v.name
            for v in (
                skip_vars_in_backward_input
                if isinstance(skip_vars_in_backward_input, (list, tuple))
                else [skip_vars_in_backward_input]
            )
        }
        skip_idx = [i for i, v in enumerate(xs) if v.name in skip_names]
    token = PyFuncToken(func, backward_func, skip_idx)
    if backward_func is None:
        # no backward_func -> the op is non-differentiable: mark outputs
        # stop_gradient so append_backward never emits py_func_grad (the
        # io_callback path cannot be vjp'd; same contract as the reference,
        # which only appends a grad op when backward_func is given)
        for o in outs:
            o.stop_gradient = True
    helper = LayerHelper("py_func", name=name)
    helper.append_op(
        "py_func",
        {"X": [v.name for v in xs]},
        {"Out": [o.name for o in outs]},
        {
            "_pyfunc_token": token,
            "out_shapes": [list(o.shape) for o in outs],
            "out_dtypes": [o.dtype for o in outs],
        },
    )
    return out
