"""Parameter initializers — emitted as startup-program ops.

Same architecture as the reference (reference: python/paddle/fluid/
initializer.py — initializers append fill_constant/gaussian_random/... ops to
the startup program); identical initializer streams are a prerequisite for
loss-curve parity with the reference.
"""

import math

from paddle_tpu.utils.enforce import enforce


class Initializer:
    def __call__(self, var, block):
        raise NotImplementedError


class ConstantInitializer(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, var, block):
        block.append_op(
            "fill_constant",
            outputs={"Out": [var.name]},
            attrs={"shape": list(var.shape), "dtype": var.dtype, "value": self.value},
        )


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self.low, self.high, self.seed = low, high, seed

    def __call__(self, var, block):
        block.append_op(
            "uniform_random",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(var.shape),
                "dtype": var.dtype,
                "min": self.low,
                "max": self.high,
                "seed": self.seed,
            },
        )


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        block.append_op(
            "gaussian_random",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(var.shape),
                "dtype": var.dtype,
                "mean": self.loc,
                "std": self.scale,
                "seed": self.seed,
            },
        )


class TruncatedNormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        block.append_op(
            "truncated_gaussian_random",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(var.shape),
                "dtype": var.dtype,
                "mean": self.loc,
                "std": self.scale,
                "seed": self.seed,
            },
        )


def _fan_in_out(var):
    """reference: python/paddle/fluid/initializer.py _compute_fans — FC
    weights are [in, out]; conv filters are [out_c, in_c, *receptive]."""
    shape = var.shape
    enforce(len(shape) >= 1, "initializer needs a shaped variable")
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    receptive = 1
    for d in shape[2:]:
        receptive *= d
    return shape[1] * receptive, shape[0] * receptive


class XavierInitializer(Initializer):
    """reference: python/paddle/fluid/initializer.py XavierInitializer."""

    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self.uniform, self.fan_in, self.fan_out, self.seed = (
            uniform,
            fan_in,
            fan_out,
            seed,
        )

    def __call__(self, var, block):
        fin, fout = _fan_in_out(var)
        fin = self.fan_in if self.fan_in is not None else fin
        fout = self.fan_out if self.fan_out is not None else fout
        if self.uniform:
            limit = math.sqrt(6.0 / (fin + fout))
            UniformInitializer(-limit, limit, self.seed)(var, block)
        else:
            std = math.sqrt(2.0 / (fin + fout))
            NormalInitializer(0.0, std, self.seed)(var, block)


class MSRAInitializer(Initializer):
    """Kaiming He init (reference: python/paddle/fluid/initializer.py
    MSRAInitializer)."""

    def __init__(self, uniform=True, fan_in=None, seed=0):
        self.uniform, self.fan_in, self.seed = uniform, fan_in, seed

    def __call__(self, var, block):
        fin, _ = _fan_in_out(var)
        fin = self.fan_in if self.fan_in is not None else fin
        if self.uniform:
            limit = math.sqrt(6.0 / fin)
            UniformInitializer(-limit, limit, self.seed)(var, block)
        else:
            std = math.sqrt(2.0 / fin)
            NormalInitializer(0.0, std, self.seed)(var, block)


class NumpyArrayInitializer(Initializer):
    def __init__(self, value):
        import numpy as np

        self.value = np.asarray(value)

    def __call__(self, var, block):
        block.append_op(
            "assign_value",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(self.value.shape),
                "dtype": var.dtype,
                "values": self.value.reshape(-1).tolist(),
            },
        )


class BilinearInitializer(Initializer):
    """For upsample deconv filters."""

    def __call__(self, var, block):
        import numpy as np

        shape = var.shape
        f = math.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        weight = np.zeros(shape, dtype="float32")
        size = shape[2] * shape[3]
        for i in range(np.prod(shape)):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            idx = np.unravel_index(i, shape)
            weight[idx] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        NumpyArrayInitializer(weight)(var, block)


# public aliases matching the reference API surface
Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
TruncatedNormal = TruncatedNormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer
Bilinear = BilinearInitializer
