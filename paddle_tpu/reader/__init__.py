"""Reader stack: decorators + device-prefetching DataLoader.

Reference: python/paddle/reader/ (decorators) and
python/paddle/fluid/reader.py (DataLoader/PyReader).
"""

from paddle_tpu.reader.dataloader import DataLoader, PyReader
from paddle_tpu.reader.decorator import (
    batch,
    buffered,
    cache,
    chain,
    compose,
    firstn,
    map_readers,
    shuffle,
    xmap_readers,
)

__all__ = [
    "DataLoader",
    "PyReader",
    "batch",
    "buffered",
    "cache",
    "chain",
    "compose",
    "firstn",
    "map_readers",
    "shuffle",
    "xmap_readers",
]
