"""Reader decorators: composable generator transforms.

Reference: python/paddle/reader/decorator.py — shuffle, batch (creator),
buffered (background thread), cache, chain, compose, map_readers,
xmap_readers (parallel map), firstn. A "reader" is a zero-arg callable
returning an iterator of samples; decorators wrap readers into new readers.
These are host-side and framework-agnostic, so the design carries over
unchanged — the TPU-specific work (device prefetch) lives in
paddle_tpu/reader/dataloader.py.
"""

import itertools
import logging
import queue
import random
import threading
import time

__all__ = [
    "cache",
    "map_readers",
    "buffered",
    "compose",
    "chain",
    "shuffle",
    "firstn",
    "xmap_readers",
    "batch",
    "robust",
]


def cache(reader):
    """Materialize the reader once; replay from memory afterwards."""
    all_data = []
    filled = []

    def cached_reader():
        if not filled:
            all_data.extend(reader())
            filled.append(True)
        return iter(all_data)

    return cached_reader


def map_readers(func, *readers):
    """Sample-wise map over zipped readers."""

    def reader():
        rs = [r() for r in readers]
        for items in zip(*rs):
            yield func(*items)

    return reader


def shuffle(reader, buf_size, seed=None):
    """Pool-based shuffling (reference: decorator.py shuffle).

    `seed` makes the shuffle deterministic via a LOCAL
    ``random.Random(seed)`` — fresh per iteration, so every epoch (and
    every rerun) of a seeded reader replays the identical order, and
    nothing perturbs or reads the module-global RNG. Default (seed=None)
    keeps the reference behavior: the process-global ``random`` state."""

    def data_reader():
        rng = random if seed is None else random.Random(seed)
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                rng.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            rng.shuffle(buf)
            yield from buf

    return data_reader


def chain(*readers):
    def reader():
        return itertools.chain(*[r() for r in readers])

    return reader


def compose(*readers, **kwargs):
    """Zip readers into tuple samples; check_alignment verifies equal
    lengths."""
    check_alignment = kwargs.pop("check_alignment", True)

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        rs = [r() for r in readers]
        if not check_alignment:
            for outputs in zip(*rs):
                yield sum(list(map(make_tuple, outputs)), ())
        else:
            for outputs in itertools.zip_longest(*rs):
                if any(o is None for o in outputs):
                    raise RuntimeError("readers have different lengths")
                yield sum(list(map(make_tuple, outputs)), ())

    return reader


def _abortable_put(q, item, stop):
    """Bounded put that gives up when the consumer abandoned iteration, so
    producer threads never block forever on a full queue."""
    while not stop.is_set():
        try:
            q.put(item, timeout=0.1)
            return True
        except queue.Full:
            continue
    return False


def buffered(reader, size):
    """Decouple producer/consumer with a background thread + bounded queue
    (reference: decorator.py buffered)."""

    class _End:
        pass

    def data_reader():
        r = reader()
        q = queue.Queue(maxsize=size)
        err = []
        stop = threading.Event()

        def produce():
            try:
                for d in r:
                    if not _abortable_put(q, d, stop):
                        return
            except BaseException as e:  # propagate into consumer
                err.append(e)
            finally:
                _abortable_put(q, _End, stop)

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        try:
            while True:
                e = q.get()
                if e is _End:
                    if err:
                        raise err[0]
                    return
                yield e
        finally:
            stop.set()

    return data_reader


def firstn(reader, n):
    def data_reader():
        return itertools.islice(reader(), n)

    return data_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map with worker threads (reference: decorator.py
    xmap_readers). order=True preserves input order."""

    class _End:
        pass

    def data_reader():
        in_q = queue.Queue(buffer_size)
        out_q = queue.Queue(buffer_size)
        err = []
        stop = threading.Event()

        def feed():
            try:
                for i, d in enumerate(reader()):
                    if not _abortable_put(in_q, (i, d), stop):
                        return
            except BaseException as e:
                err.append(e)
            finally:
                for _ in range(process_num):
                    if not _abortable_put(in_q, _End, stop):
                        return

        def work():
            while not stop.is_set():
                try:
                    item = in_q.get(timeout=0.1)
                except queue.Empty:
                    continue
                if item is _End:
                    _abortable_put(out_q, _End, stop)
                    return
                i, d = item
                try:
                    if not _abortable_put(out_q, (i, mapper(d)), stop):
                        return
                except BaseException as e:
                    err.append(e)
                    _abortable_put(out_q, _End, stop)
                    return

        threading.Thread(target=feed, daemon=True).start()
        for _ in range(process_num):
            threading.Thread(target=work, daemon=True).start()

        try:
            finished = 0
            if order:
                pending, want = {}, 0
                while finished < process_num:
                    item = out_q.get()
                    if item is _End:
                        finished += 1
                        continue
                    i, d = item
                    pending[i] = d
                    while want in pending:
                        yield pending.pop(want)
                        want += 1
                for i in sorted(pending):
                    yield pending[i]
            else:
                while finished < process_num:
                    item = out_q.get()
                    if item is _End:
                        finished += 1
                        continue
                    yield item[1]
            if err:
                raise err[0]
        finally:
            stop.set()

    return data_reader


def robust(reader, max_skips=16, max_restarts=4, backoff_s=0.0,
           retry_on=(Exception,)):
    """Skip-and-log bad records instead of killing the epoch (opt-in;
    also exposed as ``fluid.io.robust``).

    Wraps each `next()` on the underlying iterator: a transient exception
    (matching `retry_on`) is logged and counted as one skipped record —
    bounded by `max_skips`, after which the error propagates (a reader
    that is ALL bad records must still fail loudly). Class-based
    iterators simply continue past the bad record. A plain generator
    dies on its first raise (Python semantics), so the decorator
    recreates the reader and fast-forwards past everything already
    consumed plus the bad record — bounded by `max_restarts`, assuming
    the deterministic re-iteration a replayable reader provides
    (file/dataset readers; NOT one-shot streams). Fast-forward
    re-executes earlier records, so a generator record that fails
    DETERMINISTICALLY cannot be skipped — the restart budget exhausts
    and the error is re-raised (never a silent truncation); use a
    class-based iterator for true skip-past-bad-record semantics.
    `backoff_s` sleeps before each recovery for readers whose failures
    are time-transient (e.g. remote storage).

    Skip logging is rate-limited through the observability layer: the
    first `log_first_n` skips log individually, the rest are counted
    silently (``reader_skipped_records_total`` in the metrics registry
    keeps the live rate), and one summary line reports totals when the
    epoch ends — a 10%-bad dataset does not turn the log into noise."""
    log = logging.getLogger("paddle_tpu.reader.robust")
    log_first_n = 8

    def _recreate(position):
        return itertools.islice(reader(), position, None)

    def data_reader():
        import inspect

        from paddle_tpu.observability import registry
        from paddle_tpu.observability.logger import RateLimitedLogger

        limited = RateLimitedLogger(log, max_records=log_first_n)
        skip_counter = registry().counter(
            "reader_skipped_records_total",
            "records skipped by fluid.io.robust readers",
        )

        consumed = 0
        skips = 0
        restarts = 0
        last_error = None  # last next() raised: detect a dead generator
        it = reader()
        # only a GENERATOR dies on raise; a class-based iterator that
        # raised and then ends simply reached end-of-data
        mortal = inspect.isgenerator(it)
        while True:
            try:
                sample = next(it)
            except StopIteration:
                if last_error is None or not mortal:
                    limited.summarize(what="skipped records")
                    return
                # the previous raise killed a generator: StopIteration
                # here is death, not end-of-data — restart past the bad
                # record (position = consumed good + skipped bad)
                if restarts >= max_restarts:
                    log.error(
                        "reader died %d times at record ~%d; raising",
                        restarts + 1, consumed + skips,
                    )
                    limited.summarize(what="skipped records")
                    raise last_error
                restarts += 1
                last_error = None
                if backoff_s:
                    time.sleep(backoff_s)
                it = _recreate(consumed + skips)
            except retry_on as e:
                skips += 1
                skip_counter.inc()
                if skips > max_skips:
                    log.error(
                        "reader exceeded max_skips=%d; re-raising", max_skips
                    )
                    limited.summarize(what="skipped records")
                    raise
                limited.warning(
                    "skipping bad record %d (skip %d/%d): %s: %s",
                    consumed + skips, skips, max_skips,
                    type(e).__name__, e,
                )
                last_error = e
                if backoff_s:
                    time.sleep(backoff_s)
            else:
                last_error = None
                consumed += 1
                yield sample

    return data_reader


def batch(reader, batch_size, drop_last=False):
    """Group samples into lists of batch_size (reference:
    python/paddle/batch.py)."""

    def batch_reader():
        b = []
        for d in reader():
            b.append(d)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return batch_reader
