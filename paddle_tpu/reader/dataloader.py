"""DataLoader: background-prefetching iterator feeding device memory.

Reference: python/paddle/fluid/reader.py — DataLoader.from_generator :168
backed by a C++ blocking queue (reader/lod_tensor_blocking_queue.h) with
double-buffer prefetch to GPU (reader/buffered_reader.cc). TPU-native
equivalent: a bounded host queue drained by the training loop, with each
batch asynchronously `jax.device_put` ahead of use — device transfer overlaps
the current step's compute (XLA dispatch is async), which is the
double-buffer effect without explicit CUDA streams.
"""

import queue
import threading

import numpy as np

import jax

from paddle_tpu.data_feeder import DataFeeder
from paddle_tpu.utils.enforce import enforce

__all__ = ["DataLoader", "PyReader"]

_END = object()


class _GeneratorLoader:
    def __init__(self, feed_list, capacity, return_list):
        self._feed_list = feed_list
        self._capacity = capacity
        self._return_list = return_list
        self._reader = None
        self._places = None
        self._feeder = None
        self._batch_reader = None

    # -- configuration (reference: reader.py set_sample_generator etc.) ----
    def set_sample_generator(self, reader, batch_size, drop_last=True, places=None):
        from paddle_tpu.reader import decorator

        self.set_sample_list_generator(
            decorator.batch(reader, batch_size, drop_last=drop_last), places
        )
        return self

    def set_sample_list_generator(self, reader, places=None):
        feeder = DataFeeder(self._feed_list)

        def batch_reader():
            for samples in reader():
                yield feeder.feed(samples)

        self._batch_reader = batch_reader
        self._places = places
        return self

    def set_batch_generator(self, reader, places=None):
        names = [
            v if isinstance(v, str) else v.name for v in self._feed_list
        ]

        def batch_reader():
            for batch in reader():
                if isinstance(batch, dict):
                    yield batch
                else:
                    yield dict(zip(names, batch))

        self._batch_reader = batch_reader
        self._places = places
        return self

    # -- iteration ---------------------------------------------------------
    def __iter__(self):
        enforce(self._batch_reader is not None, "no generator set on DataLoader")
        q = queue.Queue(maxsize=self._capacity)
        err = []
        stop = threading.Event()

        def _put(item):
            # bounded put that aborts when the consumer abandoned iteration —
            # otherwise the producer blocks forever holding `capacity`
            # device-resident batches
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def produce():
            try:
                for feed in self._batch_reader():
                    # async H2D: device transfer of batch N overlaps step N-1
                    dev = {k: jax.device_put(np.asarray(v)) for k, v in feed.items()}
                    if not _put(dev):
                        return
            except BaseException as e:
                err.append(e)
            finally:
                _put(_END)

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        names = [v if isinstance(v, str) else v.name for v in self._feed_list]
        try:
            while True:
                item = q.get()
                if item is _END:
                    if err:
                        raise err[0]
                    return
                if self._return_list:
                    yield [item[n] for n in names]
                else:
                    yield item
        finally:
            stop.set()
            while not q.empty():  # unblock producer, drop device buffers
                try:
                    q.get_nowait()
                except queue.Empty:
                    break


class DataLoader:
    @staticmethod
    def from_generator(
        feed_list=None,
        capacity=16,
        use_double_buffer=True,
        iterable=True,
        return_list=False,
        use_multiprocess=False,
    ):
        """Reference: python/paddle/fluid/reader.py:168. use_double_buffer /
        use_multiprocess are accepted for parity: prefetch is always on (the
        producer thread device-puts ahead), and multiprocessing is
        unnecessary for numpy-producing readers under the GIL-releasing
        device transfer."""
        return _GeneratorLoader(feed_list or [], capacity, return_list)

    @staticmethod
    def from_dataset(dataset, places=None, drop_last=True):
        """Iterate a Dataset (paddle_tpu/dataset.py) as feed dicts."""

        class _DatasetLoader:
            def __iter__(self):
                return dataset._iter_batches(drop_last=drop_last)

        return _DatasetLoader()


class PyReader(_GeneratorLoader):
    """Non-iterable start/reset flavor (reference: reader.py:971 PyReader)."""

    def __init__(self, feed_list=None, capacity=16, use_double_buffer=True,
                 iterable=True, return_list=False):
        super().__init__(feed_list or [], capacity, return_list)
        self._iter = None

    def decorate_sample_list_generator(self, reader, places=None):
        return self.set_sample_list_generator(reader, places)

    def decorate_batch_generator(self, reader, places=None):
        return self.set_batch_generator(reader, places)

    def start(self):
        self._iter = iter(self)

    def reset(self):
        self._iter = None

    def next(self):
        enforce(self._iter is not None, "call start() first")
        try:
            return next(self._iter)
        except StopIteration:
            self._iter = None
            raise
