"""DataLoader: multi-worker batch assembly + device-prefetching iterator.

Reference: python/paddle/fluid/reader.py — DataLoader.from_generator :168
backed by a C++ blocking queue (reader/lod_tensor_blocking_queue.h) with
double-buffer prefetch to GPU (reader/buffered_reader.cc). TPU-native
equivalent: the host pipeline rides ``paddle_tpu/dataio`` —
``num_workers`` batches are assembled concurrently by the deterministic
ordered worker pool (round-robin reassembly: output order is independent
of worker timing), and ``DevicePrefetcher`` stages each batch with
``jax.device_put`` ahead of use so device transfer overlaps the current
step's compute (the double-buffer effect without explicit CUDA streams).

Fed batches are validated against their feed vars here (dtype/shape by
name, data_feeder.check_feed_array) — a mismatched feed fails at the
loader with the variable named instead of as an opaque downstream XLA
error.
"""

from paddle_tpu.data_feeder import DataFeeder, check_feed_array
from paddle_tpu.utils.enforce import enforce

__all__ = ["DataLoader", "PyReader"]


class _GeneratorLoader:
    def __init__(self, feed_list, capacity, return_list, num_workers=0):
        self._feed_list = feed_list
        self._capacity = capacity
        self._return_list = return_list
        self._num_workers = int(num_workers)
        self._reader = None
        self._places = None
        self._feeder = None
        self._batch_reader = None
        self._sample_transform = None

    def _var_specs(self):
        """(name, dtype, shape) per feed var; dtype/shape None for bare
        string entries (no declaration to check against)."""
        specs = []
        for v in self._feed_list:
            if isinstance(v, str):
                specs.append((v, None, None))
            else:
                specs.append((v.name, v.dtype, v.shape))
        return specs

    # -- configuration (reference: reader.py set_sample_generator etc.) ----
    def set_sample_generator(self, reader, batch_size, drop_last=True,
                             places=None, sample_transform=None):
        """`sample_transform` (optional) is a per-sample preprocess
        (decode/augment) applied on the worker pool when num_workers > 0
        — the CPU-bound stage tools/bench_input.py measures."""
        from paddle_tpu.reader import decorator

        self._sample_transform = sample_transform
        self.set_sample_list_generator(
            decorator.batch(reader, batch_size, drop_last=drop_last), places
        )
        return self

    def set_sample_list_generator(self, reader, places=None):
        feeder = DataFeeder(self._feed_list)
        transform = self._sample_transform
        num_workers = self._num_workers

        def assemble(samples):
            if transform is not None:
                samples = [transform(s) for s in samples]
            return feeder.feed(samples)

        def batch_reader():
            from paddle_tpu.dataio.engine import parallel_map_ordered

            # num_workers=0 runs the pool's synchronous path: same
            # ordering/error contract, same spans and queue metrics
            yield from parallel_map_ordered(
                reader(), assemble, num_workers, name="dataloader",
            )

        self._batch_reader = batch_reader
        self._places = places
        return self

    def set_batch_generator(self, reader, places=None):
        specs = self._var_specs()
        names = [s[0] for s in specs]

        def check(batch):
            if not isinstance(batch, dict):
                batch = dict(zip(names, batch))
            missing = [n for n in names if n not in batch]
            enforce(
                not missing,
                f"fed batch is missing feed variable(s) {missing}; "
                f"expected {names}",
            )
            # validate declared vars in place; keys beyond the feed list
            # (auxiliary feeds) pass through untouched
            out = dict(batch)
            for n, dtype, shape in specs:
                if dtype is not None or shape is not None:
                    out[n] = check_feed_array(n, batch[n], dtype, shape)
            return out

        def batch_reader():
            from paddle_tpu.dataio.engine import parallel_map_ordered

            yield from parallel_map_ordered(
                reader(), check, self._num_workers, name="dataloader",
            )

        self._batch_reader = batch_reader
        self._places = places
        return self

    # -- iteration ---------------------------------------------------------
    def __iter__(self):
        from paddle_tpu.dataio.prefetch import DevicePrefetcher

        enforce(self._batch_reader is not None, "no generator set on DataLoader")
        names = [v if isinstance(v, str) else v.name for v in self._feed_list]
        # async H2D double buffer: device transfer of batch N overlaps
        # step N-1 (the producer thread device-puts ahead)
        # distinct pipeline label: the pool's reassembly wait and the
        # training loop's prefetch wait are different stalls
        prefetcher = DevicePrefetcher(
            self._batch_reader(), depth=self._capacity,
            name="dataloader-prefetch",
        )
        for item in prefetcher:
            if self._return_list:
                yield [item[n] for n in names]
            else:
                yield item


class DataLoader:
    @staticmethod
    def from_generator(
        feed_list=None,
        capacity=16,
        use_double_buffer=True,
        iterable=True,
        return_list=False,
        use_multiprocess=False,
        num_workers=0,
    ):
        """Reference: python/paddle/fluid/reader.py:168. use_double_buffer /
        use_multiprocess are accepted for parity: prefetch is always on (the
        producer thread device-puts ahead). `num_workers > 0` assembles
        batches on the dataio ordered worker pool — same batch order as
        num_workers=0 (round-robin reassembly), more throughput when the
        per-batch work (sample_transform + numpy stacking) is CPU-bound."""
        return _GeneratorLoader(feed_list or [], capacity, return_list,
                                num_workers=num_workers)

    @staticmethod
    def from_dataset(dataset, places=None, drop_last=True):
        """Iterate a Dataset (paddle_tpu/dataset.py) as feed dicts."""

        class _DatasetLoader:
            def __iter__(self):
                return dataset._iter_batches(drop_last=drop_last)

        return _DatasetLoader()


class PyReader(_GeneratorLoader):
    """Non-iterable start/reset flavor (reference: reader.py:971 PyReader)."""

    def __init__(self, feed_list=None, capacity=16, use_double_buffer=True,
                 iterable=True, return_list=False, num_workers=0):
        super().__init__(feed_list or [], capacity, return_list,
                         num_workers=num_workers)
        self._iter = None

    def decorate_sample_list_generator(self, reader, places=None):
        return self.set_sample_list_generator(reader, places)

    def decorate_batch_generator(self, reader, places=None):
        return self.set_batch_generator(reader, places)

    def start(self):
        self._iter = iter(self)

    def reset(self):
        self._iter = None

    def next(self):
        enforce(self._iter is not None, "call start() first")
        try:
            return next(self._iter)
        except StopIteration:
            self._iter = None
            raise
