"""Shared retry policy: capped exponential backoff with jitter + deadline.

One policy object serves every hardened IO path — PS client RPCs,
in-graph lookup pulls/pushes, and checkpoint file IO — so retry behavior
is tuned (and fault-injection-tested) in one place instead of ad-hoc
sleep loops. Jitter is drawn from a per-policy seeded RNG: under the
deterministic fault harness a replayed schedule sees identical backoff
sequences (`PADDLE_TPU_RETRY_SEED` pins it globally for chaos runs).

    policy = RetryPolicy(max_attempts=4, base_delay_s=0.05, deadline_s=10)
    rows = policy.call(client.pull_sparse, table, ids, dim)

Retries ConnectionError/TimeoutError/OSError and the fault harness's
TransientFault by default; everything else propagates immediately.
`on_retry` lets callers repair state between attempts (the PS client
reconnects its socket there).
"""

import logging
import os
import random
import threading
import time

from paddle_tpu.resilience.faults import TransientFault

__all__ = ["RetryPolicy", "DEFAULT_RETRYABLE"]

log = logging.getLogger("paddle_tpu.resilience.retry")

DEFAULT_RETRYABLE = (ConnectionError, TimeoutError, OSError, TransientFault)


class RetryPolicy:
    """Immutable backoff schedule + the `call` driver.

    max_attempts  total tries (1 = no retry).
    base_delay_s  first backoff; doubles each retry, capped at max_delay_s.
    jitter        fraction of the delay drawn uniformly at random and
                  added (0.5 -> delay * [1.0, 1.5)).
    deadline_s    wall-clock budget across ALL attempts; when the budget
                  is exhausted the last error is raised even if attempts
                  remain.
    retry_on      exception classes worth retrying.
    """

    def __init__(self, max_attempts=4, base_delay_s=0.05, max_delay_s=2.0,
                 jitter=0.5, deadline_s=None, retry_on=DEFAULT_RETRYABLE,
                 seed=None, sleep=time.sleep):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.max_attempts = int(max_attempts)
        self.base_delay_s = float(base_delay_s)
        self.max_delay_s = float(max_delay_s)
        self.jitter = float(jitter)
        self.deadline_s = deadline_s
        self.retry_on = tuple(retry_on)
        if seed is None:
            env = os.environ.get("PADDLE_TPU_RETRY_SEED")
            seed = int(env) if env else None
        self._rng = random.Random(seed)
        self._rng_lock = threading.Lock()
        self._sleep = sleep

    def delay(self, attempt):
        """Backoff before retry number `attempt` (1-based), jittered."""
        d = min(self.base_delay_s * (2 ** (attempt - 1)), self.max_delay_s)
        if self.jitter:
            with self._rng_lock:
                d *= 1.0 + self.jitter * self._rng.random()
        return d

    def call(self, fn, *args, retry_on=None, on_retry=None, **kwargs):
        """Run fn(*args, **kwargs) under the policy; returns its value or
        raises the final error. `on_retry(exc, attempt)` runs before each
        retry (reconnect hooks); its own errors abort the retry loop."""
        retry_on = tuple(retry_on) if retry_on is not None else self.retry_on
        start = time.monotonic()
        attempt = 0
        while True:
            attempt += 1
            try:
                return fn(*args, **kwargs)
            except retry_on as e:
                if attempt >= self.max_attempts:
                    raise
                d = self.delay(attempt)
                if (self.deadline_s is not None
                        and time.monotonic() - start + d > self.deadline_s):
                    log.warning(
                        "retry deadline (%.2fs) exhausted after %d attempts: %s",
                        self.deadline_s, attempt, e,
                    )
                    raise
                log.warning(
                    "attempt %d/%d failed (%s: %s); retrying in %.3fs",
                    attempt, self.max_attempts, type(e).__name__, e, d,
                )
                if on_retry is not None:
                    on_retry(e, attempt)
                self._sleep(d)

    def wrap(self, fn, on_retry=None):
        """Decorator form of call()."""

        def wrapped(*args, **kwargs):
            return self.call(fn, *args, on_retry=on_retry, **kwargs)

        wrapped.__name__ = getattr(fn, "__name__", "wrapped")
        return wrapped
