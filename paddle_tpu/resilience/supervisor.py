"""Supervised gang restarts: keep a training job alive across worker death.

On a TPU pod a preemption or single-host failure kills the whole gang —
the reference's recovery story (SURVEY §5.3) is checkpoint-based
restart, and this module is the piece that presses the restart button
without a human: poll every rank, and on the first nonzero exit or a
heartbeat-declared hang, terminate the gang, validate the checkpoint
chain (quarantining corrupt entries so workers resume from the newest
VALID checkpoint, incubate/checkpoint.py), and relaunch every worker —
under a restart budget with backoff between attempts. Every decision is
recorded as a structured event (``supervisor.events``) and fanned out
through the observability layer: an instant event on the tracer (visible
in the chrome timeline), a ``resilience_events_total{kind=...}`` counter
in the metrics registry, and the legacy profiler counters
(``resilience.rank_exit`` / ``resilience.hang`` / ``resilience.restart``
/ ``resilience.gang_ok`` / ``resilience.gang_failed``).

Workers announce liveness by calling ``heartbeat_tick()`` once per step;
the supervisor injects ``PADDLE_RESILIENCE_HEARTBEAT_DIR`` so the helper
knows where to touch. Hang detection is opt-in via ``hang_timeout_s``.

    sup = GangSupervisor(["train.py"], nproc=4, max_restarts=2,
                         checkpoint_dirs=["/ckpt"], hang_timeout_s=300)
    codes = sup.run()   # [0, 0, 0, 0] or raises GangFailedError

Replica-grained restarts (serving fleets, not SPMD gangs): a training
gang is all-or-nothing — one dead rank wedges every collective, so
``run()`` restarts the WHOLE gang. A fleet of serving replicas is the
opposite: replicas share nothing, so killing the survivors to revive
one is an outage invented by the supervisor. ``launch()`` spawns the
gang without the watch loop and ``restart(rank)`` terminates +
respawns exactly ONE rank into the same endpoint slot (same
PADDLE_TRAINER_ID, same port), leaving the rest undisturbed — each
restart is a structured ``rank_restart`` event and a
``resilience_events_total{kind=rank_restart}`` counter, with per-rank
counts in ``rank_restarts``.
"""

import logging
import os
import tempfile
import time

from paddle_tpu import observability, profiler

__all__ = ["GangSupervisor", "GangFailedError", "heartbeat_tick",
           "HEARTBEAT_DIR_ENV"]

log = logging.getLogger("paddle_tpu.resilience.supervisor")

HEARTBEAT_DIR_ENV = "PADDLE_RESILIENCE_HEARTBEAT_DIR"


def heartbeat_tick(rank=None, hb_dir=None):
    """Worker-side liveness tick (call once per training step). No-op
    when no supervisor injected a heartbeat dir — safe to leave in
    production training loops."""
    hb_dir = hb_dir or os.environ.get(HEARTBEAT_DIR_ENV)
    if not hb_dir:
        return False
    if rank is None:
        rank = os.environ.get("PADDLE_TRAINER_ID", "0")
    path = os.path.join(hb_dir, f"hb_{rank}")
    with open(path, "w") as f:
        f.write(str(time.time()))
    return True


class GangFailedError(RuntimeError):
    """The restart budget is exhausted; `events` holds the full timeline
    and `codes` the final gang exit codes."""

    def __init__(self, message, events=None, codes=None):
        super().__init__(message)
        self.events = events or []
        self.codes = codes


class GangSupervisor:
    def __init__(self, script_args, nproc=1, max_restarts=2,
                 restart_backoff_s=1.0, backoff_multiplier=2.0,
                 heartbeat_dir=None, hang_timeout_s=None,
                 poll_interval_s=0.1, grace_s=5.0, checkpoint_dirs=None,
                 on_restart=None, extra_env=None, devices_per_proc=None,
                 started_port=None):
        self.script_args = list(script_args)
        self.nproc = int(nproc)
        self.max_restarts = int(max_restarts)
        self.restart_backoff_s = float(restart_backoff_s)
        self.backoff_multiplier = float(backoff_multiplier)
        self.hang_timeout_s = hang_timeout_s
        self.poll_interval_s = float(poll_interval_s)
        self.grace_s = float(grace_s)
        self.checkpoint_dirs = list(checkpoint_dirs or [])
        self.on_restart = on_restart  # fn(attempt, events) before relaunch
        self.extra_env = dict(extra_env or {})
        self.devices_per_proc = devices_per_proc
        self.started_port = started_port
        if hang_timeout_s and not heartbeat_dir:
            heartbeat_dir = tempfile.mkdtemp(prefix="paddle_hb_")
        self.heartbeat_dir = heartbeat_dir
        self.events = []
        self.restarts = 0
        self.rank_restarts = {}   # rank -> replica-grained restart count
        self._procs = None
        self._spawn_port = None

    # -- events ----------------------------------------------------------
    def _emit(self, kind, **fields):
        ev = dict(kind=kind, time=time.time(), **fields)
        self.events.append(ev)
        observability.registry().counter(
            "resilience_events_total", "gang supervisor decisions",
            labels={"kind": kind},
        ).inc()
        observability.instant(f"resilience.{kind}", cat="resilience",
                              **fields)
        profiler.incr_counter(f"resilience.{kind}")
        log.warning("supervisor: %s %s", kind, fields)
        return ev

    # -- heartbeat -------------------------------------------------------
    def _clear_heartbeats(self):
        if not self.heartbeat_dir:
            return
        os.makedirs(self.heartbeat_dir, exist_ok=True)
        for f in os.listdir(self.heartbeat_dir):
            if f.startswith("hb_"):
                try:
                    os.remove(os.path.join(self.heartbeat_dir, f))
                except OSError:
                    pass

    def _stale_rank(self, attempt_start, codes):
        """Live rank whose last tick (or launch, if it never ticked) is
        older than hang_timeout_s, else None."""
        if not self.hang_timeout_s:
            return None, 0.0
        now = time.monotonic()
        wall_delta = time.time() - (now - attempt_start)  # wall at start
        for rank in range(self.nproc):
            if codes[rank] is not None:  # already exited cleanly
                continue
            path = os.path.join(self.heartbeat_dir, f"hb_{rank}")
            try:
                last_wall = os.path.getmtime(path)
            except OSError:
                last_wall = wall_delta
            age = time.time() - last_wall
            if age > self.hang_timeout_s:
                return rank, age
        return None, 0.0

    # -- checkpoint validation ------------------------------------------
    def _validate_checkpoints(self):
        """Quarantine corrupt/torn checkpoint entries so the relaunched
        workers resume from the newest VALID one; returns what each dir
        will resume from."""
        if not self.checkpoint_dirs:
            return {}
        from paddle_tpu.incubate.checkpoint import newest_valid_checkpoint

        resume = {}
        for d in self.checkpoint_dirs:
            try:
                resume[d] = newest_valid_checkpoint(d, quarantine=True)
            except OSError as e:
                resume[d] = None
                log.warning("checkpoint dir %s unreadable: %s", d, e)
        return resume

    # -- spawning --------------------------------------------------------
    def _gang_env(self):
        env = dict(self.extra_env)
        if self.heartbeat_dir:
            env[HEARTBEAT_DIR_ENV] = self.heartbeat_dir
        return env

    def launch(self, attempt=0):
        """Spawn the gang WITHOUT the watch/relaunch loop — the
        fleet-router usage: replicas are supervised individually via
        `restart(rank)` rather than gang-atomically. Returns the Popen
        list (also kept as `self._procs`)."""
        from paddle_tpu.distributed.launch import _free_port, spawn_gang

        if self.heartbeat_dir:
            self._clear_heartbeats()
        if self._spawn_port is None:
            # pin the endpoint layout now so a respawned rank rejoins
            # the SAME slot later
            self._spawn_port = self.started_port or _free_port()
        self._procs = spawn_gang(
            self.script_args, nproc=self.nproc,
            started_port=self._spawn_port, extra_env=self._gang_env(),
            devices_per_proc=self.devices_per_proc,
        )
        self._emit("gang_start", attempt=attempt,
                   pids=[p.pid for p in self._procs])
        return self._procs

    def restart(self, rank):
        """Replica-grained restart: terminate + respawn exactly ONE
        rank into its original endpoint slot, leaving every other rank
        undisturbed. Clears only that rank's heartbeat, counts the
        restart per-rank, and emits a structured `rank_restart` event
        (mirrored to the metrics registry and profiler like every
        supervisor decision)."""
        from paddle_tpu.distributed.launch import spawn_gang, terminate_gang

        if self._procs is None:
            raise RuntimeError("no gang launched; call launch()/run() first")
        rank = int(rank)
        old = self._procs[rank]
        if old.poll() is None:
            terminate_gang([old], grace_s=self.grace_s)
        exit_code = old.poll()
        if self.heartbeat_dir:
            try:
                os.remove(os.path.join(self.heartbeat_dir, f"hb_{rank}"))
            except OSError:
                pass
        new = spawn_gang(
            self.script_args, nproc=self.nproc,
            started_port=self._spawn_port, extra_env=self._gang_env(),
            devices_per_proc=self.devices_per_proc, ranks=[rank],
        )[0]
        self._procs[rank] = new
        self.rank_restarts[rank] = self.rank_restarts.get(rank, 0) + 1
        self._emit("rank_restart", rank=rank, old_code=exit_code,
                   pid=new.pid, count=self.rank_restarts[rank])
        return new

    def procs(self):
        return list(self._procs or [])

    def terminate(self):
        """Stop every live rank (fleet shutdown path)."""
        from paddle_tpu.distributed.launch import terminate_gang

        if self._procs:
            terminate_gang(self._procs, grace_s=self.grace_s)

    # -- the loop --------------------------------------------------------
    def run(self):
        from paddle_tpu.distributed.launch import terminate_gang

        backoff = self.restart_backoff_s
        attempt = 0
        while True:
            attempt_start = time.monotonic()
            procs = self.launch(attempt=attempt)
            failure = self._watch(procs, attempt_start)
            if failure is None:
                codes = [p.poll() for p in procs]
                self._emit("gang_ok", attempt=attempt, codes=codes)
                return codes
            terminate_gang(procs, grace_s=self.grace_s)
            codes = [p.poll() for p in procs]
            attempt += 1
            if attempt > self.max_restarts:
                self._emit("gang_failed", attempt=attempt, codes=codes)
                raise GangFailedError(
                    f"gang failed after {self.max_restarts} restarts "
                    f"(last failure: {failure}); final codes {codes}",
                    events=self.events, codes=codes,
                )
            self.restarts = attempt
            if self.on_restart is not None:  # test hooks mutate state here
                self.on_restart(attempt, self.events)
            resume = self._validate_checkpoints()
            self._emit("restart", attempt=attempt, backoff_s=backoff,
                       resume_from=resume, failure=failure)
            if self.started_port is None:
                # whole-gang restart: take a FRESH port layout per
                # attempt (the crashed gang's listeners may sit in
                # TIME_WAIT). Pinning is only for replica-grained
                # restart(rank), which rejoins a LIVE gang's slots.
                self._spawn_port = None
            time.sleep(backoff)
            backoff *= self.backoff_multiplier

    def _watch(self, procs, attempt_start):
        """Poll until the gang succeeds (returns None) or fails (returns
        the failure event dict): first nonzero rank exit, or a
        heartbeat-declared hang."""
        while True:
            codes = [p.poll() for p in procs]
            for rank, c in enumerate(codes):
                if c is not None and c != 0:
                    return self._emit("rank_exit", rank=rank, code=c)
            if all(c == 0 for c in codes):
                return None
            rank, age = self._stale_rank(attempt_start, codes)
            if rank is not None:
                return self._emit("hang", rank=rank, age_s=round(age, 3))
            time.sleep(self.poll_interval_s)
