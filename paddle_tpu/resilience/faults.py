"""Deterministic fault injection: the failure model the runtime is tested
against.

Large-scale training systems earn their recovery story by rehearsing it
(CheckFreq, Mohan et al. FAST'21; Check-N-Run, Eisenman et al. NSDI'22
both validate against injected crashes and torn files). This module is
the single schedule-driven harness the framework's hardened paths call
into: a worker can be killed at an exact step, a checkpoint file can be
corrupted or truncated, file IO can fail or stall, and PS/lookup RPCs
can raise transient errors — all deterministically, from a JSON schedule
supplied by API or environment variable, so the chaos tool
(tools/chaos_train.py) and the tests replay identical failure timelines.

Instrumented call sites (`faults.fire(site, ...)`) are inert when no
schedule is configured: the fast path is one global None-check.

Schedule format (``PADDLE_TPU_FAULTS`` env var — a JSON list, or
``@/path/to/plan.json``):

    [{"site": "train.step", "action": "kill", "at_step": 5, "rank": 1},
     {"site": "checkpoint.io", "action": "raise", "times": 2},
     {"site": "ps.rpc", "action": "raise", "at_call": 3},
     {"site": "checkpoint.before_latest", "action": "kill"},
     {"site": "lookup.pull", "action": "stall", "delay_s": 0.2}]

Rule fields: ``site`` (required); ``action`` in kill | term | raise |
stall | corrupt | truncate (default raise); ``at_step`` / ``at_call``
(1-based nth matching call) / ``rank`` / ``prob`` (+ ``seed``) select
WHEN it fires; ``times`` bounds how often (default 1, -1 = unlimited);
``exc`` = "transient" (retryable TransientFault, the default) or
"fault"; ``path`` overrides the file target for corrupt/truncate;
``delay_s``, ``exit_code``, ``id`` as expected. With a ``state_dir``
(``PADDLE_TPU_FAULT_STATE``), one-shot rules record firing in a marker
file so a RESTARTED process replaying the same steps does not re-fire
them — that is what makes kill-at-step-N schedules convergent under a
supervised restart loop.

``kill`` vs ``term``: ``kill`` is a hard crash (``os._exit`` — no
atexit handlers, no flushes, torn files possible), the failure a dying
host produces. ``term`` is a PREEMPTION: the process sends itself
SIGTERM — the polite, catchable signal (a worker that installs a
handler can land in-flight durable state before exiting; unhandled it
terminates with code -SIGTERM). Cloud TPU/VM preemption notices are
exactly this shape; the elastic supervisor treats both as capacity
loss, but only ``kill`` can tear files.

Elastic-training sites (r14, ``resilience/elastic.py`` +
tools/chaos_elastic.py):

* ``worker.preempt`` — fired by training workers once per step
  (immediately after ``train.step``). The conventional site for
  preemption-shaped failure: ``action: "term"`` SIGTERMs the worker
  with grace mid-run, ``action: "kill"`` is the hard variant. The
  chaos scenario drives both shrink (hard kill) and grow (preempt as
  the capacity-returns signal) through these.
* ``elastic.resize`` — fired by ``ElasticGangSupervisor`` immediately
  BEFORE each resize relaunch decision commits (``step`` = the new
  gang generation, ``rank`` = the new world size). ``raise`` makes the
  resize attempt itself fail (the supervisor counts it against the
  restart budget and retries its decision loop); ``stall`` delays it —
  so resize-path failure is injectable like any other hardened path.

Decode-engine sites (r13/r17, ``serving/decode/`` +
tools/stress_concurrency.py):

* ``decode.step`` / ``decode.prefill`` / ``decode.inject`` — fired
  before each decode iteration / prompt prefill / warm-slot KV inject.
  ``raise`` exercises the arena-loss recovery path (every in-flight
  request rejected, arena rebuilt); ``stall`` perturbs scheduler-thread
  interleavings for the concurrency stress harness.
* ``decode.sample`` — fired before each committed-threefry sampled
  token draw (r17; greedy requests never reach it). ``stall`` shifts
  WHEN a sampled request's host-side policy runs relative to its
  batchmates — the stress harness uses it to prove the stall schedule
  cannot change a byte of the sampled stream (it is keyed purely on
  request seed + emitted-token index). Unlike the three sites above,
  the draw is host arithmetic on already-fetched logits, not a device
  boundary, so ``raise`` models no real failure here: use ``stall``
  schedules at this site.
* ``decode.spill`` / ``decode.resume`` — fired when the scheduler
  PARKS an in-flight session under arena exhaustion (its private KV
  rows spill to the host tier, the slot frees) and when a parked
  session RESUMES (rows re-injected — or recomputed from the committed
  tokens when the tier entry was evicted/quarantined). ``stall``
  perturbs park/resume interleavings against admissions and decode
  steps; the stress harness proves no schedule changes a byte of any
  preempted-then-resumed stream. Like ``decode.sample``, the spill is
  host bookkeeping (the device reads are plain fetches), so ``stall``
  is the modeled failure mode here.

Fleet failover sites (r12, ``serving/fleet/`` + tools/chaos_serve.py):

* ``fleet.dispatch`` — fired before every router->replica dispatch
  (``rank`` = target replica index). ``raise`` makes THIS dispatch
  attempt fail: the router fails over to another replica, invisible to
  the caller.
* ``fleet.health``  — fired before every router heartbeat probe
  (``rank`` = probed replica index). ``raise`` is a failed probe:
  consecutive ones open the replica's circuit breaker (quarantine).
* ``replica.kill``  — the replica-death site. In-process replicas fire
  it on every heartbeat: ``action: "raise"`` latches the handle DEAD
  (simulated crash — the router re-dispatches its in-flight work).
  Subprocess workers fire it at the top of every RPC they serve:
  ``action: "kill"`` hard-exits the worker process (``os._exit``)
  mid-traffic, the real thing. ``rank`` selects WHICH replica dies;
  note ``at_call`` counts ALL calls at the site across ranks, so pair
  it with ``rank`` only in single-replica-firing setups (e.g. one
  worker process counting its own RPCs).
"""

import json
import logging
import os
import random
import threading
import time

from paddle_tpu.observability import lockdep

__all__ = [
    "InjectedFault",
    "TransientFault",
    "FaultInjector",
    "configure",
    "reset",
    "get_injector",
    "fire",
    "corrupt_file",
    "FAULTS_ENV",
    "STATE_ENV",
]

log = logging.getLogger("paddle_tpu.resilience.faults")

FAULTS_ENV = "PADDLE_TPU_FAULTS"
STATE_ENV = "PADDLE_TPU_FAULT_STATE"


class InjectedFault(RuntimeError):
    """An error raised by the fault harness (never by real code)."""


class TransientFault(InjectedFault):
    """A retryable injected error — retry.RetryPolicy retries these by
    default, so schedules can distinguish 'flaky' from 'broken'."""


def corrupt_file(path, mode="flip", offset=None, nbytes=16, truncate_to=None):
    """Deterministically damage a file in place.

    mode="flip"     XOR-flips `nbytes` bytes at `offset` (default: the
                    middle of the file — past any format magic, inside
                    real payload).
    mode="truncate" cuts the file to `truncate_to` bytes (default: half).
    Returns the number of bytes damaged/removed.
    """
    size = os.path.getsize(path)
    if mode == "truncate":
        keep = truncate_to if truncate_to is not None else size // 2
        with open(path, "r+b") as f:
            f.truncate(keep)
        return size - keep
    if mode != "flip":
        raise ValueError(f"unknown corruption mode {mode!r}")
    if size == 0:
        return 0
    off = offset if offset is not None else size // 2
    off = max(0, min(off, size - 1))
    n = min(nbytes, size - off)
    with open(path, "r+b") as f:
        f.seek(off)
        chunk = f.read(n)
        f.seek(off)
        f.write(bytes(b ^ 0xFF for b in chunk))
    return n


class _Rule:
    _FIELDS = ("site", "action", "at_step", "at_call", "rank", "prob",
               "seed", "times", "exc", "path", "delay_s", "exit_code",
               "id", "mode")

    def __init__(self, spec, index):
        unknown = set(spec) - set(self._FIELDS)
        if unknown:
            raise ValueError(f"fault rule has unknown fields {sorted(unknown)}")
        if "site" not in spec:
            raise ValueError("fault rule needs a 'site'")
        self.site = spec["site"]
        self.action = spec.get("action", "raise")
        if self.action not in ("kill", "term", "raise", "stall", "corrupt",
                               "truncate"):
            raise ValueError(f"unknown fault action {self.action!r}")
        self.at_step = spec.get("at_step")
        self.at_call = spec.get("at_call")
        self.rank = spec.get("rank")
        self.prob = spec.get("prob")
        self.times = int(spec.get("times", 1))
        self.exc = spec.get("exc", "transient")
        self.path = spec.get("path")
        self.delay_s = float(spec.get("delay_s", 0.1))
        self.exit_code = int(spec.get("exit_code", 43))
        self.mode = spec.get("mode", "flip")
        self.id = spec.get("id") or f"{self.site}:{index}"
        self._rng = random.Random(spec.get("seed", 0))
        self.calls = 0
        self.fired = 0


class FaultInjector:
    """One parsed schedule; thread-safe; process-global via configure()."""

    def __init__(self, rules, state_dir=None):
        if isinstance(rules, (str, bytes)):
            rules = json.loads(rules)
        self._rules = [
            r if isinstance(r, _Rule) else _Rule(r, i)
            for i, r in enumerate(rules)
        ]
        self._sites = {r.site for r in self._rules}
        self._state_dir = state_dir
        if state_dir:
            os.makedirs(state_dir, exist_ok=True)
        # named lockdep class: fire() runs inside arbitrary hardened
        # paths, so any nesting against subsystem locks must be
        # witnessed. Rule-matching happens under the lock; _act (sleep /
        # kill / corrupt) runs OUTSIDE it, keeping this a leaf.
        self._lock = lockdep.named_lock("resilience.faults")

    # -- cross-process one-shot state (times=1 rules only: a multi-fire
    # rule is meant to keep firing after a restart) ----------------------
    def _already_fired(self, rule):
        if not self._state_dir or rule.times != 1:
            return False
        return os.path.exists(os.path.join(self._state_dir, rule.id + ".fired"))

    def _mark_fired(self, rule):
        if self._state_dir and rule.times == 1:
            marker = os.path.join(self._state_dir, rule.id + ".fired")
            with open(marker, "w") as f:
                f.write(str(time.time()))
                f.flush()
                os.fsync(f.fileno())

    # -- the instrumented entry point -----------------------------------
    def fire(self, site, step=None, path=None, rank=None):
        """Evaluate every matching rule; act on the first that triggers.
        Called from instrumented sites; cheap when the site has no rules."""
        if site not in self._sites:
            return
        if rank is None:
            rank = os.environ.get("PADDLE_TRAINER_ID")
        with self._lock:
            rule = self._match(site, step, rank)
            if rule is None:
                return
            rule.fired += 1
            self._mark_fired(rule)
        self._act(rule, site, step, path)

    def _match(self, site, step, rank):
        site_rules = [r for r in self._rules if r.site == site]
        # every site call counts against EVERY rule's at_call counter —
        # an earlier rule firing must not hide the call from later rules
        # (the written schedule IS the replayed timeline)
        for rule in site_rules:
            rule.calls += 1
        for rule in site_rules:
            if rule.times >= 0 and rule.fired >= rule.times:
                continue
            if rule.rank is not None and (
                rank is None or int(rank) != int(rule.rank)
            ):
                continue
            if rule.at_step is not None and step != rule.at_step:
                continue
            if rule.at_call is not None and rule.calls != rule.at_call:
                continue
            if rule.prob is not None and rule._rng.random() >= rule.prob:
                continue
            if self._already_fired(rule):
                continue
            return rule
        return None

    def _act(self, rule, site, step, path):
        log.warning(
            "FAULT %s at site=%s step=%s (rule %s)",
            rule.action, site, step, rule.id,
        )
        if rule.action == "kill":
            # simulate a hard crash: no atexit handlers, no flushes
            os._exit(rule.exit_code)
        if rule.action == "term":
            # preemption: SIGTERM to self — the polite, CATCHABLE
            # signal (a worker with a handler can land its in-flight
            # durable state first; unhandled it terminates with code
            # -SIGTERM). Contrast "kill" = os._exit: uncatchable-shaped,
            # can leave torn files.
            import signal

            os.kill(os.getpid(), signal.SIGTERM)
            # delivery is asynchronous; hold here so the "preempted"
            # worker never races past the site
            time.sleep(rule.delay_s)
            return
        if rule.action == "stall":
            time.sleep(rule.delay_s)
            return
        if rule.action in ("corrupt", "truncate"):
            target = rule.path or path
            if target and os.path.exists(target):
                corrupt_file(
                    target,
                    mode="truncate" if rule.action == "truncate" else rule.mode,
                )
            return
        msg = f"injected fault at {site} (rule {rule.id}, step {step})"
        if rule.exc == "transient":
            raise TransientFault(msg)
        raise InjectedFault(msg)

    def rule_stats(self):
        with self._lock:
            return {r.id: {"calls": r.calls, "fired": r.fired}
                    for r in self._rules}


_injector = None
_env_checked = False
_glock = threading.Lock()


def configure(spec, state_dir=None):
    """Install a process-global schedule. `spec` is a JSON string or a
    list of rule dicts; state_dir enables cross-process one-shot rules."""
    global _injector, _env_checked
    inj = FaultInjector(spec, state_dir=state_dir
                        or os.environ.get(STATE_ENV) or None)
    with _glock:
        _injector = inj
        _env_checked = True
    return inj


def reset():
    global _injector, _env_checked
    with _glock:
        _injector = None
        _env_checked = False


def get_injector():
    """The active injector, lazily parsing the env schedule; None when no
    faults are configured."""
    global _injector, _env_checked
    if _env_checked:
        return _injector
    with _glock:
        if not _env_checked:
            spec = os.environ.get(FAULTS_ENV)
            if spec:
                if spec.startswith("@"):
                    with open(spec[1:]) as f:
                        spec = f.read()
                _injector = FaultInjector(
                    spec, state_dir=os.environ.get(STATE_ENV) or None
                )
            _env_checked = True
    return _injector


def fire(site, step=None, path=None, rank=None):
    """The one-line instrumentation hook. Near-zero cost when inert."""
    inj = get_injector()
    if inj is not None:
        inj.fire(site, step=step, path=path, rank=rank)
