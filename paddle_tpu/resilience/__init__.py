"""Fault-tolerant training runtime: the failure model and its defenses.

The ROADMAP's production north star means a training job must survive
worker death, torn writes, and corrupt checkpoint files without a human
pressing restart. This package supplies the three shared pieces every
hardened path builds on:

* `faults`     — deterministic, schedule-driven fault injection (kill a
  worker at step N, corrupt/truncate a checkpoint file, fail or stall
  file IO, raise transient RPC errors), configured by API or the
  ``PADDLE_TPU_FAULTS`` env var. The tests and ``tools/chaos_train.py``
  replay identical failure timelines through it.
* `retry`      — one capped-exponential-backoff-with-jitter-and-deadline
  policy used by the PS client, the in-graph lookup pull/push path, and
  checkpoint file IO.
* `supervisor` — gang supervision: poll all ranks, on first failure or
  heartbeat-declared hang terminate + relaunch the whole gang from the
  newest VALID checkpoint, under a restart budget with backoff.
* `elastic`    — elastic gang supervision atop `supervisor`: relaunch at
  whatever world size capacity allows (shrink on loss, grow back when it
  returns), pinning every rank to one validated sync checkpoint and
  stamping a monotone gang generation into every manifest; the data
  stream re-shards exactly via `dataio.state.elastic_resume`.

Crash-consistent checkpoint integrity itself (per-array CRC32 manifests,
fallback chain walking, `*.corrupt` quarantine) lives with the
checkpoint code in `paddle_tpu/incubate/checkpoint.py`; the serving
replica circuit breaker lives with the engine in
`paddle_tpu/serving/engine.py`. Both are driven by this package's
harness in tests.
"""

from paddle_tpu.resilience import faults
from paddle_tpu.resilience.elastic import (
    ElasticGangSupervisor,
    elastic_resume_step,
    gang_generation,
)
from paddle_tpu.resilience.faults import (
    FaultInjector,
    InjectedFault,
    TransientFault,
    corrupt_file,
)
from paddle_tpu.resilience.retry import RetryPolicy
from paddle_tpu.resilience.supervisor import (
    GangFailedError,
    GangSupervisor,
    heartbeat_tick,
)

__all__ = [
    "ElasticGangSupervisor",
    "FaultInjector",
    "GangFailedError",
    "GangSupervisor",
    "InjectedFault",
    "RetryPolicy",
    "TransientFault",
    "corrupt_file",
    "elastic_resume_step",
    "faults",
    "gang_generation",
    "heartbeat_tick",
]
