"""Elastic gang training: shrink on capacity loss, grow when it returns.

`GangSupervisor` (supervisor.py) keeps a job alive by relaunching the
gang at the SAME world size — so losing a host for good kills the job
once the restart budget drains. This module adds the elasticity layer
the reference's whole L6 tier gestures at (Fleet API + multi-process
launcher) and ROADMAP item 4 needs for DCNxICI multi-host training:
``ElasticGangSupervisor`` relaunches the gang at whatever world size the
environment can actually supply, M in [min_nproc, nproc], and grows back
toward N when capacity returns — each incarnation a new, monotonically
increasing GANG GENERATION stamped into every checkpoint manifest the
workers write (incubate/checkpoint.py, ``GANG_GENERATION_ENV``).

What makes a resize SAFE is that both halves of training state are
geometry-portable by construction:

* **Parameters / optimizer slots** — format-2 sharded checkpoints
  restore shard-wise onto a DIFFERENT mesh factorization bit-identically
  (PR 7, ``AutoCheckpoint.resume(shardings=...)``). The supervisor's
  job is picking the SYNC STEP: the newest step for which EVERY active
  rank holds a verifiable checkpoint (corrupt entries are quarantined on
  the walk, exactly like the base class). The step is pinned via
  ``RESUME_STEP_ENV`` so no rank can silently walk back to a different
  entry and desync the gang.
* **Data position** — ``dataio/state.py`` records the shard geometry its
  cursor is valid under, and ``elastic_resume()`` projects the per-rank
  cursor to the epoch-GLOBAL stream position; a
  ``DataEngine(elastic=True)`` re-bases the new geometry's shards on the
  remaining stream suffix. Zero samples lost or double-consumed across
  the resize — the replay-determinism property tools/chaos_elastic.py
  gates: an elastic run's loss sequence and consumed-stream digest are
  bit-identical to a fresh run driven by the same (world-size,
  step-range) schedule.

Capacity model: ``capacity_fn()`` (no args -> currently available worker
count) is the environment probe — a cluster scheduler query, a
preemption-notice watcher, or a test closure. Without one, the default
policy shrinks by one rank per failure and re-probes full capacity after
``grow_after_s``. Grow is NOT a failure: the running (healthy, shrunk)
gang is terminated with grace at a checkpoint boundary and relaunched
larger; it never charges the restart budget.

Every decision is observable: ``resilience_events_total{kind=
gang_resize}``, the ``elastic_world_size`` gauge, and the
``elastic_resize_seconds`` histogram (failure detection -> resized gang
spawned). The resize path itself is fault-injectable at the
``elastic.resize`` site (faults.py): an injected raise degrades that
resize to a same-size restart, an injected stall delays it.

    sup = ElasticGangSupervisor(
        ["train.py"], nproc=4, min_nproc=2, max_restarts=4,
        checkpoint_dirs=[f"/ckpt/rank{r}" for r in range(4)],
        capacity_fn=scheduler.available_workers)
    codes = sup.run()

Workers read their marching orders from the environment:
``elastic_resume_step()`` (the pinned sync step, None on a fresh
start) and ``gang_generation()``; ranks joining mid-job (grow) pull the
chief's data blob via ``incubate.checkpoint.load_data_state`` and let
``DataEngine(elastic=True)`` translate it.
"""

import os
import time

from paddle_tpu import observability
from paddle_tpu.resilience import faults
from paddle_tpu.resilience.supervisor import GangFailedError, GangSupervisor

__all__ = [
    "ElasticGangSupervisor",
    "elastic_resume_step",
    "gang_generation",
    "RESUME_STEP_ENV",
    "GANG_GENERATION_ENV",
]

RESUME_STEP_ENV = "PADDLE_ELASTIC_RESUME_STEP"
# the literal is repeated (not imported) because incubate/checkpoint.py
# imports this package at module load — tests/test_elastic.py pins the
# two definitions equal
GANG_GENERATION_ENV = "PADDLE_ELASTIC_GANG_GENERATION"


def elastic_resume_step(env=None):
    """The sync step the supervisor pinned for this incarnation, or None
    on a fresh start / outside an elastic supervisor. Workers pass it to
    ``AutoCheckpoint.resume(step=...)`` so every rank restores the SAME
    validated entry."""
    env = env if env is not None else os.environ
    raw = env.get(RESUME_STEP_ENV)
    return int(raw) if raw not in (None, "") else None


def gang_generation(env=None):
    """This incarnation's gang generation (stamped into every manifest
    the worker writes via the same env var), or None outside an elastic
    supervisor."""
    env = env if env is not None else os.environ
    raw = env.get(GANG_GENERATION_ENV)
    return int(raw) if raw not in (None, "") else None


class ElasticGangSupervisor(GangSupervisor):
    """GangSupervisor that resizes instead of merely restarting.

    nproc        the FULL world size (the grow target)
    min_nproc    the floor: fewer available workers than this fails the
                 resize (the gang restarts same-size and burns budget)
    capacity_fn  () -> currently available worker count; None = default
                 policy (shrink by one per failure, grow to nproc after
                 `grow_after_s` seconds at reduced world)
    grow_after_s default-policy grow delay (ignored with capacity_fn)
    capacity_poll_s  how often the watch loop probes for grow capacity
    on_resize    fn(old_world, new_world, supervisor) before the resized
                 relaunch — e.g. repartition local devices per rank
    """

    def __init__(self, script_args, nproc=1, min_nproc=1, capacity_fn=None,
                 grow_after_s=30.0, capacity_poll_s=0.5, on_resize=None,
                 **kwargs):
        super().__init__(script_args, nproc=nproc, **kwargs)
        self.max_nproc = int(nproc)
        self.min_nproc = int(min_nproc)
        if not 1 <= self.min_nproc <= self.max_nproc:
            raise ValueError(
                f"min_nproc must be in [1, nproc], got {self.min_nproc} "
                f"with nproc {self.max_nproc}")
        self.capacity_fn = capacity_fn
        self.grow_after_s = grow_after_s
        self.capacity_poll_s = float(capacity_poll_s)
        self.on_resize = on_resize
        # the live geometry: self.nproc tracks it so every inherited
        # mechanism (spawn width, heartbeat scan, restart(rank)) sees
        # the CURRENT world, while max_nproc remembers the grow target
        self.world = self.max_nproc
        self.generation = 0
        self.resizes = []          # [(old_world, new_world, generation)]
        self._shrunk_at = None     # monotonic time of the last shrink
        self._resize_started = None
        self._resume_step = None   # sync step pinned for the NEXT launch
        reg = observability.registry()
        self._world_gauge = reg.gauge(
            "elastic_world_size",
            "current world size of the elastic training gang")
        self._resize_hist = reg.histogram(
            "elastic_resize_seconds",
            "failure/capacity detection to resized-gang spawn")

    # -- env contract ----------------------------------------------------
    def _gang_env(self):
        env = super()._gang_env()
        env[GANG_GENERATION_ENV] = str(self.generation)
        if self._resume_step is not None:
            env[RESUME_STEP_ENV] = str(self._resume_step)
        else:
            env.pop(RESUME_STEP_ENV, None)
        return env

    # -- capacity --------------------------------------------------------
    def _capacity(self):
        """Available worker count right now. With no probe installed,
        the default policy reports full capacity once `grow_after_s` has
        elapsed since the last shrink (preemptions are usually
        transient), else no opinion (= current world)."""
        if self.capacity_fn is not None:
            try:
                return int(self.capacity_fn())
            except Exception as e:
                self._emit("capacity_probe_failed", error=str(e))
                return self.world
        if (self.world < self.max_nproc and self._shrunk_at is not None
                and self.grow_after_s is not None
                and time.monotonic() - self._shrunk_at >= self.grow_after_s):
            return self.max_nproc
        return self.world

    # -- sync-step selection ---------------------------------------------
    def _active_checkpoint_dirs(self):
        """The dirs the CURRENT (failed/terminating) generation was
        writing: per-rank layouts are sliced to the live world; a
        shared-dir layout (fewer dirs than ranks) is used whole."""
        if len(self.checkpoint_dirs) >= self.world:
            return self.checkpoint_dirs[:self.world]
        return list(self.checkpoint_dirs)

    def _sync_step(self):
        """The newest step for which EVERY active rank dir holds a
        verifiable checkpoint — the one entry a resized gang can restore
        identically everywhere. Corrupt candidates are quarantined
        (same contract as the base class's pre-relaunch validation) and
        the next-newest common step is tried. None = no common valid
        checkpoint: the resized gang starts fresh."""
        from paddle_tpu.incubate.checkpoint import (
            CheckpointCorruptError,
            _ckpt_step,
            _quarantine,
            newest_valid_checkpoint,
            verify_checkpoint,
        )

        dirs = self._active_checkpoint_dirs()
        if not dirs:
            return None
        per_dir = []
        for d in dirs:
            # walk each chain once: quarantines corrupt newest entries
            # so the listings below only name plausible candidates
            try:
                newest_valid_checkpoint(d, quarantine=True)
            except OSError:
                pass
            steps = set()
            try:
                entries = os.listdir(d)
            except OSError:
                entries = []
            for name in entries:
                if name.startswith("ckpt_") and _ckpt_step(name) is not None:
                    steps.add(_ckpt_step(name))
            per_dir.append(steps)
        common = set.intersection(*per_dir) if per_dir else set()
        for s in sorted(common, reverse=True):
            ok = True
            for d in dirs:
                entry = os.path.join(d, f"ckpt_{s}")
                try:
                    verify_checkpoint(entry, level="file")
                except CheckpointCorruptError as e:
                    _quarantine(entry, str(e))
                    ok = False
            if ok:
                return s
        return None

    # -- the loop --------------------------------------------------------
    def launch(self, attempt=0):
        procs = super().launch(attempt=attempt)
        self._world_gauge.set(self.world)
        if self._resize_started is not None:
            self._resize_hist.observe(
                time.monotonic() - self._resize_started)
            self._resize_started = None
        return procs

    def _watch(self, procs, attempt_start):
        """Base watch (first nonzero exit / heartbeat hang) plus the
        grow probe: when the gang runs below full size and the capacity
        probe reports more workers available, return a synthetic
        ``capacity_ready`` event — run() treats it as a graceful resize,
        not a failure."""
        last_probe = time.monotonic()
        while True:
            codes = [p.poll() for p in procs]
            for rank, c in enumerate(codes):
                if c is not None and c != 0:
                    return self._emit("rank_exit", rank=rank, code=c)
            if all(c == 0 for c in codes):
                return None
            rank, age = self._stale_rank(attempt_start, codes)
            if rank is not None:
                return self._emit("hang", rank=rank, age_s=round(age, 3))
            now = time.monotonic()
            if (self.world < self.max_nproc
                    and now - last_probe >= self.capacity_poll_s):
                last_probe = now
                cap = self._capacity()
                if cap > self.world:
                    return self._emit("capacity_ready", capacity=cap,
                                      world=self.world)
            time.sleep(self.poll_interval_s)

    def _decide_world(self, failure):
        """The next generation's world size, clamped to
        [min_nproc, max_nproc]. Grow: whatever capacity reported.
        Failure: the capacity probe's answer, or (default policy) one
        rank fewer than the world that just failed."""
        if failure["kind"] == "capacity_ready":
            target = failure["capacity"]
        elif self.capacity_fn is not None:
            target = self._capacity()
        else:
            target = self.world - 1
        return max(self.min_nproc, min(self.max_nproc, int(target)))

    def run(self):
        from paddle_tpu.distributed.launch import terminate_gang

        backoff = self.restart_backoff_s
        attempt = 0
        while True:
            attempt_start = time.monotonic()
            procs = self.launch(attempt=attempt)
            failure = self._watch(procs, attempt_start)
            if failure is None:
                codes = [p.poll() for p in procs]
                self._emit("gang_ok", attempt=attempt, codes=codes,
                           world=self.world, generation=self.generation)
                return codes
            self._resize_started = time.monotonic()
            grow = failure["kind"] == "capacity_ready"
            terminate_gang(procs, grace_s=self.grace_s)
            codes = [p.poll() for p in procs]
            if not grow:
                attempt += 1
                if attempt > self.max_restarts:
                    self._emit("gang_failed", attempt=attempt, codes=codes)
                    raise GangFailedError(
                        f"gang failed after {self.max_restarts} restarts "
                        f"(last failure: {failure}); final codes {codes}",
                        events=self.events, codes=codes,
                    )
                self.restarts = attempt
                if self.on_restart is not None:
                    self.on_restart(attempt, self.events)
            old_world = self.world
            new_world = self._decide_world(failure)
            # the resize decision is itself a hardened path: an injected
            # fault here degrades THIS resize to a same-size restart (the
            # classic recovery story), an injected stall delays it
            try:
                faults.fire("elastic.resize", step=self.generation + 1,
                            rank=new_world)
            except faults.InjectedFault as e:
                self._emit("resize_fault", error=str(e),
                           wanted_world=new_world)
                new_world = old_world
            # sync BEFORE the geometry changes: the failed generation's
            # active dirs define the common restorable step
            sync = self._sync_step()
            self._resume_step = sync
            self.generation += 1
            if new_world != old_world:
                self.resizes.append((old_world, new_world, self.generation))
                if new_world < old_world:
                    self._shrunk_at = time.monotonic()
                self._emit("gang_resize", old_world=old_world,
                           new_world=new_world,
                           direction="grow" if new_world > old_world
                           else "shrink",
                           generation=self.generation, sync_step=sync,
                           reason=failure["kind"])
            self.world = new_world
            self.nproc = new_world
            if self.on_resize is not None:
                self.on_resize(old_world, new_world, self)
            self._emit("restart", attempt=attempt, backoff_s=backoff,
                       resume_step=sync, failure=failure,
                       world=new_world, generation=self.generation)
            if self.started_port is None:
                # fresh port layout per generation (see base class note)
                self._spawn_port = None
            if not grow:
                time.sleep(backoff)
                backoff *= self.backoff_multiplier
