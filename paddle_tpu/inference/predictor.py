"""AnalysisPredictor analog: load → analyze → AOT-compile → zero-copy serve.

reference: paddle/fluid/inference/api/analysis_predictor.h:47 (class
AnalysisPredictor), paddle_api.h (PaddlePredictor/ZeroCopyTensor),
paddle_analysis_config.h (AnalysisConfig). The reference pipeline was
load → 30+ ir fusion passes → NaiveExecutor op loop with zero-copy scope
tensors. The TPU-native pipeline is load → semantic passes (passes.py) →
jax.jit AOT lowering of the WHOLE pruned program into one XLA executable per
input-shape bucket; weights live on device across calls, feeds are
device_put once, outputs stay on device until copy_to_cpu.
"""

import json
import os
import threading

import numpy as np

from paddle_tpu.core.scope import Scope
from paddle_tpu.utils.enforce import EnforceError, enforce

__all__ = ["Config", "PrecisionType", "Predictor", "Tensor", "create_predictor"]


class PrecisionType:
    """reference: paddle_api.h PaddleDType/Precision. kHalf maps to bf16 —
    the TPU's native low-precision dtype."""

    Float32 = "float32"
    Bfloat16 = "bfloat16"
    Half = "bfloat16"
    Int8 = "int8"  # accepted; executed as bf16 (no TPU int8 matmul path here)


class Config:
    """reference: paddle/fluid/inference/api/paddle_analysis_config.h:61
    (AnalysisConfig). Construction mirrors the reference: Config(model_dir)
    for the __model__/__params__ layout, or Config(prog_file, params_file)."""

    def __init__(self, model_dir=None, params_file=None):
        if model_dir is not None and params_file is not None:
            self._prog_file = model_dir
            self._params_file = params_file
            self._model_dir = os.path.dirname(model_dir)
        else:
            self._model_dir = model_dir
            self._prog_file = None
            self._params_file = None
        self._use_tpu = True
        self._device_id = 0
        self._ir_optim = True
        self._memory_optim = True
        self._precision = PrecisionType.Float32
        self._passes = None  # None = default pipeline
        self._deleted_passes = set()
        self._verify_each_pass = False
        self._options = {}
        self._serving_buckets = None

    # -- model location (reference: AnalysisConfig::SetModel — updates only
    # the paths; previously configured options must survive) ---------------
    def set_model(self, model_dir_or_prog, params_file=None):
        if model_dir_or_prog is not None and params_file is not None:
            self._prog_file = model_dir_or_prog
            self._params_file = params_file
            self._model_dir = os.path.dirname(model_dir_or_prog)
        else:
            self._model_dir = model_dir_or_prog
            self._prog_file = None
            self._params_file = None

    def model_dir(self):
        return self._model_dir

    # -- device (reference: EnableUseGpu/DisableGpu — re-targeted to TPU) --
    def enable_tpu(self, device_id=0):
        self._use_tpu = True
        self._device_id = device_id

    def disable_tpu(self):
        self._use_tpu = False

    def use_tpu(self):
        return self._use_tpu

    # GPU-era spellings kept callable for porting ease
    def enable_use_gpu(self, memory_pool_init_size_mb=0, device_id=0):
        self.enable_tpu(device_id)

    def disable_gpu(self):
        self.disable_tpu()

    # -- analysis (reference: SwitchIrOptim / pass_builder) ----------------
    def switch_ir_optim(self, x=True):
        self._ir_optim = x

    def enable_program_verification(self, x=True):
        """Run the IR verifier (analysis/verify.py) after every analysis
        pass; a pass that breaks a program invariant raises naming the
        pass instead of serving a silently-corrupted model."""
        self._verify_each_pass = x

    def ir_optim(self):
        return self._ir_optim

    def enable_memory_optim(self, x=True):
        """Donation-based buffer reuse inside the executable (XLA owns the
        actual memory plan; reference: EnableMemoryOptim)."""
        self._memory_optim = x

    def enable_bf16(self):
        """Serve matmul/conv regions in bfloat16 (the reference's
        EnableMkldnnBfloat16/TensorRT-fp16 analog on TPU)."""
        self._precision = PrecisionType.Bfloat16

    def set_precision(self, precision):
        if precision == PrecisionType.Int8:
            # no silent mode degradation: the caller asked for an int8
            # engine (reference: TensorRT int8 calibration path) and gets
            # bf16 execution instead — say so loudly
            import warnings

            warnings.warn(
                "PrecisionType.Int8 requested but this build serves bf16: "
                "there is no int8 matmul path here (weights are not "
                "quantized). Use contrib.quantize QAT for int8-simulated "
                "training, or set Bfloat16 to silence this warning.",
                stacklevel=2,
            )
        self._precision = precision

    def precision(self):
        return self._precision

    def delete_pass(self, name):
        """reference: pass_builder()->DeletePass."""
        self._deleted_passes.add(name)

    def set_passes(self, names):
        self._passes = list(names)

    def analysis_passes(self):
        if self._passes is not None:
            names = list(self._passes)
        else:
            # pattern fusions run AFTER test-mode flip (multihead matching
            # needs is_test dropout) and BEFORE the precision cast (the
            # fused fc/sdpa ops are AMP-white-listed)
            names = ["strip_debug_ops", "flip_test_mode",
                     "dead_code_elimination", "fold_constants",
                     "conv_bn_fuse", "fc_fuse", "multihead_matmul_fuse"]
            if self._precision == PrecisionType.Float32:
                pass
            else:
                names.append("bf16_cast")
        return [n for n in names if n not in self._deleted_passes]

    # -- serving (paddle_tpu/serving: bucket lattice + warmup) -------------
    def set_serving_buckets(self, batch_sizes, seq_lens=None, pad_axis=1):
        """Declare the serving shape lattice: every served batch will be
        one of (batch, seq) with batch from `batch_sizes` and seq from
        `seq_lens` (None = the model has no variable-length axis).
        Predictor.warmup() pre-compiles every lattice point so first-
        request latency never includes a trace, and ServingEngine batches
        only onto these shapes so the compile cache never misses."""
        self._serving_buckets = {
            "batch_sizes": tuple(sorted(int(b) for b in batch_sizes)),
            "seq_lens": (tuple(sorted(int(s) for s in seq_lens))
                         if seq_lens else None),
            "pad_axis": int(pad_axis),
        }

    def serving_buckets(self):
        return self._serving_buckets

    # -- parity shims (accepted, no TPU meaning) ---------------------------
    def set_cpu_math_library_num_threads(self, n):
        self._options["cpu_math_threads"] = n

    def switch_use_feed_fetch_ops(self, x=False):
        self._options["use_feed_fetch_ops"] = x

    def switch_specify_input_names(self, x=True):
        self._options["specify_input_names"] = x


class Tensor:
    """Zero-copy I/O handle (reference: paddle_api.h ZeroCopyTensor:
    copy_from_cpu/copy_to_cpu/Reshape). Input handles hold the next feed;
    output handles hold the last run's device array (fetched lazily)."""

    def __init__(self, name, var, place):
        self.name = name
        self._var = var
        self._place = place
        self._value = None  # np array (pending feed) or jax array (output)
        self._declared_shape = None  # set by reshape()

    def shape(self):
        if self._value is not None:
            return list(np.shape(self._value))
        return list(self._var.shape) if self._var is not None else []

    def reshape(self, shape):
        """Declare the upcoming feed's shape (reference: ZeroCopyTensor::
        Reshape): the next copy_from_cpu may then pass a flat buffer, which
        is viewed through this shape (and thereby selects the compile
        bucket, since buckets key on the concrete feed shapes)."""
        self._declared_shape = list(shape)

    def copy_from_cpu(self, data):
        arr = np.ascontiguousarray(data)
        if self._declared_shape is not None and (
            list(arr.shape) != self._declared_shape
        ):
            arr = arr.reshape(self._declared_shape)
        self._value = arr

    def share_external_data(self, data):
        """Zero-copy variant: keep the caller's buffer (no copy here; the
        single host→device transfer happens inside run())."""
        self._value = np.asarray(data)

    def copy_to_cpu(self):
        enforce(self._value is not None, f"tensor '{self.name}' has no value")
        return np.asarray(self._value)

    def value(self):
        return self._value


class Predictor:
    """reference: analysis_predictor.h:47. Loads the inference program,
    runs the analysis pipeline, and serves through AOT-compiled XLA
    executables keyed on input shapes. clone() shares weights and the
    compile cache (reference: AnalysisPredictor::Clone shares params via the
    parent scope)."""

    def __init__(self, config, _shared=None):
        import jax

        from paddle_tpu.core.places import CPUPlace, TPUPlace

        self._config = config
        self._place = (
            TPUPlace(config._device_id) if config._use_tpu else CPUPlace()
        )
        if _shared is not None:
            # clone: share scope (weights), program, compiled cache, and
            # the cache hit/miss counters (serving replicas report one
            # compile-cache hit rate, not per-clone fragments)
            (self._program, self._feed_names, self._fetch_names,
             self._scope, self._cache, self._analysis_stats,
             self._cache_stats, self._cache_lock) = _shared
        else:
            self._scope = Scope()
            self._program, self._feed_names, self._fetch_names = self._load()
            self._analysis_stats = {}
            if config.ir_optim():
                self._analyze()
            self._cache = {}
            self._cache_stats = {"hits": 0, "misses": 0, "compile_s": 0.0,
                                 "persistent_hits": 0}
            # clones run in concurrent serving workers; counter updates
            # and cache writes need the shared lock (compiles run outside
            # it — the shared lowering single-flights duplicate compiles
            # for the same signature instead of serializing everything)
            self._cache_lock = threading.Lock()
        self._rng0 = None
        self._inputs = {}
        self._outputs = {}
        block = self._program.global_block()
        for n in self._feed_names:
            self._inputs[n] = Tensor(n, block._find_var_recursive(n), self._place)
        for n in self._fetch_names:
            self._outputs[n] = Tensor(n, block._find_var_recursive(n), self._place)

    # -- loading (reference: AnalysisPredictor::LoadProgramDesc/Parameters) -
    def _load(self):
        import jax
        import jax.numpy as jnp

        from paddle_tpu.core.ir import Program
        from paddle_tpu.io import _read_combined

        cfg = self._config
        if cfg._prog_file:
            model_path, params_path = cfg._prog_file, cfg._params_file
        else:
            enforce(cfg._model_dir, "Config has no model location")
            model_path = os.path.join(cfg._model_dir, "__model__")
            params_path = os.path.join(cfg._model_dir, "__params__")
        enforce(os.path.exists(model_path), f"{model_path} not found")
        with open(model_path, "rb") as f:
            desc = json.loads(f.read().decode("utf-8"))
        program = Program.from_bytes(
            json.dumps(
                {k: v for k, v in desc.items()
                 if k not in ("feed_var_names", "fetch_var_names")}
            ).encode()
        )
        feed_names = desc.get("feed_var_names", [])
        fetch_names = desc.get("fetch_var_names", [])
        dev = self._place.jax_device()
        for name, arr in _read_combined(params_path).items():
            # weights go device-resident ONCE; every run() reuses them
            self._scope.set(name, jax.device_put(jnp.asarray(arr), dev))
        return program, feed_names, fetch_names

    # -- analysis (reference: AnalysisPredictor::OptimizeInferenceProgram) -
    def _analyze(self):
        from paddle_tpu.passes import PassContext, PassManager

        ctx = PassContext(
            scope=self._scope,
            feed_names=self._feed_names,
            fetch_names=self._fetch_names,
            bf16_white_list=self._config._options.get("bf16_white_list"),
            bf16_black_list=self._config._options.get("bf16_black_list"),
        )
        pm = PassManager(
            self._config.analysis_passes(),
            verify_each_pass=self._config._verify_each_pass,
        )
        self._program = pm.run(self._program, ctx)
        if self._config.precision() != PrecisionType.Float32:
            self._fold_param_casts()
        self._analysis_stats = ctx.stats

    def _fold_param_casts(self):
        """Pre-cast device weights that only flow through a leading cast op,
        deleting the cast from the program — bf16 weights then live on
        device at half the HBM footprint and no per-call cast runs."""
        import jax.numpy as jnp

        block = self._program.global_block()
        kept = []
        folded_srcs = []
        for op in block.ops:
            if op.type == "cast":
                src = op.inputs.get("X", [None])[0]
                dst = op.outputs.get("Out", [None])[0]
                var = block._find_var_recursive(src) if src else None
                if (
                    var is not None
                    and var.persistable
                    and self._scope.has_var(src)
                    and src not in self._feed_names
                ):
                    w = self._scope.find_var(src)
                    self._scope.set(
                        dst, jnp.asarray(w).astype(op.attrs.get("out_dtype"))
                    )
                    dvar = block._find_var_recursive(dst)
                    if dvar is not None:
                        dvar.persistable = True
                    folded_srcs.append(src)
                    continue
            kept.append(op)
        if len(kept) != len(block.ops):
            block.ops = kept
            # drop an original-precision weight only when NOTHING still reads
            # it (tied weights may feed another op directly, e.g. a lookup
            # table shared with an MLM output matmul)
            still_read = {
                n
                for b in self._program.blocks
                for op in b.ops
                for n in op.input_names()
            } | set(self._fetch_names)
            self._scope.erase([n for n in folded_srcs if n not in still_read])
            self._program._bump_version()

    # -- surface (reference: GetInputNames/GetOutputNames/GetInputTensor) --
    def get_input_names(self):
        return list(self._feed_names)

    def get_output_names(self):
        return list(self._fetch_names)

    def get_input_handle(self, name):
        enforce(name in self._inputs, f"no input named '{name}'")
        return self._inputs[name]

    def get_output_handle(self, name):
        enforce(name in self._outputs, f"no output named '{name}'")
        return self._outputs[name]

    # reference spellings
    get_input_tensor = get_input_handle
    get_output_tensor = get_output_handle

    def get_input_tensor_shape(self):
        block = self._program.global_block()
        out = {}
        for n in self._feed_names:
            v = block._find_var_recursive(n)
            out[n] = list(v.shape) if v is not None else []
        return out

    # -- execution (reference: AnalysisPredictor::ZeroCopyRun) -------------
    def run(self, inputs=None):
        """Run one inference. Either set input handles first (zero-copy
        style) and call run(), or pass `inputs` as {name: np.ndarray} /
        [np.ndarray, ...] (reference: PaddlePredictor::Run). Returns the
        list of output np.ndarrays AND fills the output handles."""
        if inputs is not None:
            if isinstance(inputs, dict):
                for n, v in inputs.items():
                    self.get_input_handle(n).copy_from_cpu(v)
            else:
                enforce(
                    len(inputs) == len(self._feed_names),
                    f"expected {len(self._feed_names)} inputs, "
                    f"got {len(inputs)}",
                )
                for n, v in zip(self._feed_names, inputs):
                    self._inputs[n].copy_from_cpu(v)
        feed_vals = []
        for n in self._feed_names:
            v = self._inputs[n].value()
            enforce(v is not None, f"input '{n}' was never set")
            feed_vals.append(np.asarray(v))
        outs = self._execute_feeds(feed_vals)
        results = []
        for n, o in zip(self._fetch_names, outs):
            self._outputs[n]._value = o
            results.append(np.asarray(o))
        return results

    # compatibility alias (reference: ZeroCopyRun)
    def zero_copy_run(self):
        self.run()
        return True

    @staticmethod
    def _cache_key(sig):
        """Cheap bucket key: the resolved kernel mode
        (paddle_tpu/kernels/) joins it (see core/executor.py) — a
        PADDLE_TPU_KERNELS flip must reach the content-addressed tier,
        never a stale per-object executable."""
        from paddle_tpu.kernels import registry as _kernel_registry

        return (sig, _kernel_registry.resolved_mode())

    def _compiled(self, sig):
        """AOT-compile the pruned program for one input-shape bucket,
        through the shared lowering (core/lowering.py): mandatory verifier
        pass, process-wide + persistent compile cache, and single-flight
        dedupe — N clones warming the same bucket concurrently share ONE
        compile instead of racing N duplicate traces (the lock-free
        duplicate-compile window this replaces multiplied under replica
        warmup). The serving hot path still calls a fixed AOT executable:
        committed same-layout args, no per-call jit dispatch."""
        from paddle_tpu.observability import metrics as obs_metrics

        cache_key = self._cache_key(sig)
        reg = obs_metrics.registry()
        with self._cache_lock:
            hit = self._cache.get(cache_key)
            if hit is not None:
                self._cache_stats["hits"] += 1
                reg.counter("predictor_cache_hits_total",
                            "AOT executable cache hits").inc()
                return hit
            self._cache_stats["misses"] += 1
            reg.counter("predictor_cache_misses_total",
                        "AOT executable cache misses (bucket lookups that "
                        "went to the shared lowering)").inc()
        import time as _time

        from paddle_tpu import profiler
        from paddle_tpu.core import lowering

        feed_sig = tuple(
            (n, tuple(s), str(d)) for n, (s, d) in zip(self._feed_names, sig)
        )
        t0 = _time.perf_counter()
        with profiler.RecordEvent("predictor::aot_compile"):
            entry, source = lowering.lower_step(
                self._program, self._scope, feed_sig, self._fetch_names,
                donate=False, label="predictor",
            )
            executable = entry.aot_compile(
                lowering.abstract_signature(entry, feed_sig, self._scope)
            )
        dt = _time.perf_counter() - t0
        if source == "trace":
            # only the single-flight leader counts a compile; waiters,
            # memory-tier hits, and persistent-cache loads don't
            profiler.incr_counter("predictor.aot_compiles")
            reg.histogram("predictor_compile_seconds",
                          "AOT bucket compile latency").observe(dt)
        elif source == "disk":
            profiler.incr_counter("predictor.persistent_cache_hits")
        with self._cache_lock:
            if source == "trace":
                self._cache_stats["compile_s"] += dt
            elif source == "disk":
                self._cache_stats["persistent_hits"] += 1
            self._cache[cache_key] = (executable, entry.scope_names)
        return self._cache[cache_key]

    def cache_stats(self):
        """Compile-cache counters, shared across clones: {hits, misses,
        compile_s, persistent_hits}. A warmed serving fleet holds misses
        constant while hits grow — the hit-rate metric
        ServingEngine.stats() reports; persistent_hits counts buckets a
        cold replica loaded from PADDLE_TPU_CACHE_DIR instead of
        compiling."""
        with self._cache_lock:
            return dict(self._cache_stats)

    def _rng_arg(self):
        # the lowered step takes the rng key as an argument (shared 4-arg
        # contract); inference programs are deterministic, so one
        # committed zero key serves every call (lowering.zero_rng_key is
        # flags-aware so the dtype matches the AOT executable's rng aval)
        if self._rng0 is None:
            from paddle_tpu.core.lowering import zero_rng_key

            self._rng0 = zero_rng_key(self._place.jax_device())
        return self._rng0

    def _execute_feeds(self, feed_vals):
        """Shared execution tail for run()/run_batch(): signature,
        compile-cache lookup, device transfer, call. ONE place defines
        the cache-signature format the warmup/bucket machinery matches."""
        import jax

        from paddle_tpu.observability.tracer import trace_scope

        sig = tuple((v.shape, str(v.dtype)) for v in feed_vals)
        executable, scope_names = self._compiled(sig)
        dev = self._place.jax_device()
        with trace_scope("predictor::execute", cat="serving"):
            feed_dev = [jax.device_put(v, dev) for v in feed_vals]
            weights = [self._scope.find_var(n) for n in scope_names]
            fetches, _updates = executable(
                tuple(feed_dev), (), tuple(weights), self._rng_arg()
            )
            return fetches

    # -- batched serving (paddle_tpu/serving drives these) -----------------
    def run_batch(self, feeds):
        """Dict-in/dict-out single-shot run that bypasses the zero-copy
        handles — the serving hot path. Each engine worker owns a clone,
        so nothing here touches shared mutable state (the compile cache
        dict is append-only and shared deliberately)."""
        feed_vals = []
        for n in self._feed_names:
            enforce(n in feeds, f"run_batch feed missing input '{n}'")
            feed_vals.append(np.ascontiguousarray(feeds[n]))
        outs = self._execute_feeds(feed_vals)
        return {n: np.asarray(o) for n, o in zip(self._fetch_names, outs)}

    def _bucket_signature(self, batch, seq):
        """Concrete feed signature for one lattice point: each feed var's
        first -1 dim takes the batch bucket, every later -1 takes the
        length bucket (a fixed-shape var serves as declared)."""
        block = self._program.global_block()
        sig = []
        for n in self._feed_names:
            v = block._find_var_recursive(n)
            enforce(v is not None, f"feed var '{n}' not in program")
            shape, saw_batch = [], False
            for d in v.shape:
                if int(d) != -1:
                    shape.append(int(d))
                elif not saw_batch:
                    shape.append(int(batch))
                    saw_batch = True
                else:
                    enforce(
                        seq is not None,
                        f"feed '{n}' has a variable non-batch dim "
                        f"{list(v.shape)}: set_serving_buckets needs "
                        "seq_lens to warm it",
                    )
                    shape.append(int(seq))
            sig.append((tuple(shape), str(v.dtype)))
        return tuple(sig)

    def warmup(self, buckets=None):
        """Pre-compile every serving bucket so no request ever pays a
        trace (reference: the engine-build-on-first-run latency cliff
        this removes). `buckets` overrides Config.set_serving_buckets.
        Returns [(signature, seconds)] per newly compiled bucket; each
        compile is logged through the profiler event machinery."""
        import time as _time

        from paddle_tpu import profiler

        spec = buckets if buckets is not None else \
            self._config.serving_buckets()
        enforce(
            spec is not None,
            "warmup needs buckets: call Config.set_serving_buckets first",
        )
        seqs = spec["seq_lens"] or (None,)
        compiled = []
        for b in spec["batch_sizes"]:
            for s in seqs:
                sig = self._bucket_signature(b, s)
                if self._cache_key(sig) in self._cache:
                    continue
                t0 = _time.perf_counter()
                with profiler.RecordEvent("predictor::warmup_bucket"):
                    self._compiled(sig)
                compiled.append((sig, _time.perf_counter() - t0))
                profiler.incr_counter("predictor.warmup_buckets")
        return compiled

    # -- management --------------------------------------------------------
    def clone(self):
        """Share weights + compiled executables; independent I/O handles
        (reference: AnalysisPredictor::Clone — thread-per-predictor
        serving)."""
        return Predictor(
            self._config,
            _shared=(self._program, self._feed_names, self._fetch_names,
                     self._scope, self._cache, self._analysis_stats,
                     self._cache_stats, self._cache_lock),
        )

    def get_serialized_program(self):
        """reference: AnalysisPredictor::GetSerializedProgram."""
        return self._program.to_bytes()

    def save_optim_model(self, dirname):
        """Persist the analyzed program + (possibly precision-cast) weights
        (reference: AnalysisPredictor::SaveOptimModel)."""
        os.makedirs(dirname, exist_ok=True)
        desc = json.loads(self._program.to_bytes().decode("utf-8"))
        desc["feed_var_names"] = self._feed_names
        desc["fetch_var_names"] = self._fetch_names
        with open(os.path.join(dirname, "__model__"), "wb") as f:
            f.write(json.dumps(desc).encode("utf-8"))
        from paddle_tpu.io import _write_combined

        block = self._program.global_block()
        arrays = {}
        for n in sorted(self._scope.var_names()):
            v = block._find_var_recursive(n)
            if v is not None and v.persistable:
                arrays[n] = np.asarray(self._scope.find_var(n))
        _write_combined(os.path.join(dirname, "__params__"), arrays)

    def analysis_stats(self):
        """Per-pass statistics from the analysis pipeline (debugging aid)."""
        return dict(self._analysis_stats)

    def clear_intermediate_tensor(self):
        """reference: AnalysisPredictor::ClearIntermediateTensor. XLA owns
        intermediates inside the executable; nothing survives a run."""

    def try_shrink_memory(self):
        """Drop compiled executables for unused shape buckets."""
        self._cache.clear()
        return True


def create_predictor(config):
    """reference: CreatePaddlePredictor<AnalysisConfig> /
    paddle_infer::CreatePredictor."""
    return Predictor(config)
