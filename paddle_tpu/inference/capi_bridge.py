"""Python side of the inference C ABI (csrc/capi/capi.cc).

The C library embeds CPython and calls ONLY the flat functions here with
primitive types (str/int/bool/memoryview/bytes) — keeping the C side small
and the conversion logic testable from Python.
reference: paddle/fluid/inference/capi/c_api.cc + pd_predictor.cc (there the
C API wrapped the C++ predictor directly; here it bridges to the Python
predictor that owns the XLA executables).
"""

import numpy as np

from paddle_tpu.inference.predictor import Config, Predictor

_DTYPES = ["float32", "int32", "int64", "uint8"]  # index = PD_DataType enum


def new_predictor(model_dir, prog_file, params_file, use_tpu, device_id,
                  ir_optim, bf16):
    if prog_file:
        config = Config(prog_file, params_file)
    else:
        config = Config(model_dir)
    if use_tpu:
        config.enable_tpu(device_id)
    else:
        config.disable_tpu()
    config.switch_ir_optim(bool(ir_optim))
    if bf16:
        config.enable_bf16()
    return Predictor(config)


def clone_predictor(pred):
    return pred.clone()


def input_names(pred):
    return pred.get_input_names()


def output_names(pred):
    return pred.get_output_names()


def set_input(pred, name, dtype_idx, shape, data):
    """`data` is a memoryview over the caller's buffer; copy out of it
    immediately — the C caller may free it after this returns."""
    arr = np.frombuffer(data, dtype=_DTYPES[dtype_idx]).reshape(shape).copy()
    pred.get_input_handle(name).copy_from_cpu(arr)


def run(pred):
    pred.run()
    return True


def get_output(pred, name):
    """Returns (dtype_enum, shape_tuple, raw_bytes)."""
    arr = np.ascontiguousarray(pred.get_output_handle(name).copy_to_cpu())
    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    if str(arr.dtype) == "bfloat16":
        arr = arr.astype(np.float32)
    dt = str(arr.dtype)
    if dt not in _DTYPES:
        raise TypeError(f"output '{name}' has non-C-ABI dtype {dt}")
    return _DTYPES.index(dt), tuple(arr.shape), arr.tobytes()
