"""Python side of the inference C ABI (csrc/capi/capi.cc).

The C library embeds CPython and calls ONLY the flat functions here with
primitive types (str/int/bool/memoryview/bytes) — keeping the C side small
and the conversion logic testable from Python.
reference: paddle/fluid/inference/capi/c_api.cc + pd_predictor.cc (there the
C API wrapped the C++ predictor directly; here it bridges to the Python
predictor that owns the XLA executables).
"""

import numpy as np

from paddle_tpu.inference.predictor import Config, Predictor

_DTYPES = ["float32", "int32", "int64", "uint8"]  # index = PD_DataType enum


def new_predictor(model_dir, prog_file, params_file, use_tpu, device_id,
                  ir_optim, bf16):
    if prog_file:
        config = Config(prog_file, params_file)
    else:
        config = Config(model_dir)
    if use_tpu:
        config.enable_tpu(device_id)
    else:
        config.disable_tpu()
    config.switch_ir_optim(bool(ir_optim))
    if bf16:
        config.enable_bf16()
    return Predictor(config)


def clone_predictor(pred):
    return pred.clone()


def input_names(pred):
    return pred.get_input_names()


def output_names(pred):
    return pred.get_output_names()


def set_input(pred, name, dtype_idx, shape, data):
    """`data` is a memoryview over the caller's buffer; copy out of it
    immediately — the C caller may free it after this returns."""
    arr = np.frombuffer(data, dtype=_DTYPES[dtype_idx]).reshape(shape).copy()
    pred.get_input_handle(name).copy_from_cpu(arr)


def run(pred):
    pred.run()
    return True


def get_output(pred, name):
    """Returns (dtype_enum, shape_tuple, raw_bytes)."""
    arr = np.ascontiguousarray(pred.get_output_handle(name).copy_to_cpu())
    if arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    if str(arr.dtype) == "bfloat16":
        arr = arr.astype(np.float32)
    dt = str(arr.dtype)
    if dt not in _DTYPES:
        raise TypeError(f"output '{name}' has non-C-ABI dtype {dt}")
    return _DTYPES.index(dt), tuple(arr.shape), arr.tobytes()


# ---------------------------------------------------------------------------
# C train API bridge (reference: paddle/fluid/train/ - the C++ train demo;
# here PD_Trainer in csrc/capi/capi.cc drives these)
# ---------------------------------------------------------------------------


class _Trainer:
    def __init__(self, model_dir, use_tpu):
        import paddle_tpu as fluid
        from paddle_tpu import io as pio

        self.main, self.startup, self.loss = pio.load_train_model(model_dir)
        place = fluid.TPUPlace(0) if use_tpu else fluid.CPUPlace()
        self.exe = fluid.Executor(place)
        self.scope = fluid.Scope()
        import os

        with fluid.scope_guard(self.scope):
            self.exe.run(self.startup)
            params_dir = os.path.join(model_dir, "params")
            if os.path.isdir(params_dir):
                pio.load_persistables(
                    self.exe, params_dir, main_program=self.main
                )
        self.feeds = {}


def new_trainer(model_dir, use_tpu):
    return _Trainer(model_dir, bool(use_tpu))


def trainer_loss_name(tr):
    return tr.loss or ""


def trainer_set_input(tr, name, dtype_idx, shape, data):
    """`data` is a memoryview over the caller's buffer; copy immediately -
    the C host may free/reuse it after this returns (same contract as
    set_input above)."""
    tr.feeds[name] = (
        np.frombuffer(data, dtype=_DTYPES[dtype_idx]).reshape(shape).copy()
    )
    return 0


def trainer_run(tr, fetch_name):
    """One training step with the accumulated feeds; returns the fetched
    var as (dtype_idx, shape, bytes). Empty fetch_name = the saved loss."""
    import paddle_tpu as fluid

    fetch = fetch_name or tr.loss
    with fluid.scope_guard(tr.scope):
        out = tr.exe.run(
            tr.main, feed=dict(tr.feeds), fetch_list=[fetch] if fetch else []
        )
    if not fetch:
        return (0, (), b"")
    arr = np.ascontiguousarray(np.asarray(out[0]))
    if arr.dtype == np.float64 or str(arr.dtype) == "bfloat16":
        arr = arr.astype(np.float32)
    dt = str(arr.dtype)
    if dt not in _DTYPES:
        raise TypeError(f"fetch '{fetch}' has non-C-ABI dtype {dt}")
    return _DTYPES.index(dt), tuple(int(d) for d in arr.shape), arr.tobytes()


def trainer_save(tr, dirname):
    import paddle_tpu as fluid
    from paddle_tpu import io as pio

    with fluid.scope_guard(tr.scope):
        pio.save_persistables(tr.exe, dirname, main_program=tr.main)
    return 0


# -- ProgramDesc-level C surface (reference: paddle/fluid/framework/c/
# c_api.cc - minimal ProgramDesc IO) ---------------------------------------


def program_load(path):
    from paddle_tpu.core.ir import Program

    with open(path, "rb") as f:
        return Program.from_bytes(f.read())


def program_save(prog, path):
    with open(path, "wb") as f:
        f.write(prog.to_bytes())
    return 0


def program_op_count(prog):
    return len(prog.global_block().ops)


def program_op_type(prog, i):
    return prog.global_block().ops[i].type
