"""Python side of the inference C ABI (csrc/capi/capi.cc).

The C library embeds CPython and calls ONLY the flat functions here with
primitive types (str/int/bool/memoryview/bytes) — keeping the C side small
and the conversion logic testable from Python.
reference: paddle/fluid/inference/capi/c_api.cc + pd_predictor.cc (there the
C API wrapped the C++ predictor directly; here it bridges to the Python
predictor that owns the XLA executables).
"""

import numpy as np

from paddle_tpu.inference.predictor import Config, Predictor

_DTYPES = ["float32", "int32", "int64", "uint8"]  # index = PD_DataType enum


def new_predictor(model_dir, prog_file, params_file, use_tpu, device_id,
                  ir_optim, bf16):
    if prog_file:
        config = Config(prog_file, params_file)
    else:
        config = Config(model_dir)
    if use_tpu:
        config.enable_tpu(device_id)
    else:
        config.disable_tpu()
    config.switch_ir_optim(bool(ir_optim))
    if bf16:
        config.enable_bf16()
    return Predictor(config)


def clone_predictor(pred):
    return pred.clone()


def input_names(pred):
    return pred.get_input_names()


def output_names(pred):
    return pred.get_output_names()


def set_input(pred, name, dtype_idx, shape, data):
    """`data` is a memoryview over the caller's buffer; copy out of it
    immediately — the C caller may free it after this returns."""
    arr = np.frombuffer(data, dtype=_DTYPES[dtype_idx]).reshape(shape).copy()
    pred.get_input_handle(name).copy_from_cpu(arr)


def run(pred):
    pred.run()
    return True


def _pack_array(arr, name):
    """One C-ABI tensor marshalling rule for every output path
    (predictor get_output AND serving poll): downcast float64/bfloat16
    to float32, return (dtype_enum, shape_tuple, raw_bytes)."""
    arr = np.ascontiguousarray(arr)
    if arr.dtype == np.float64 or str(arr.dtype) == "bfloat16":
        arr = arr.astype(np.float32)
    dt = str(arr.dtype)
    if dt not in _DTYPES:
        raise TypeError(f"output '{name}' has non-C-ABI dtype {dt}")
    return _DTYPES.index(dt), tuple(arr.shape), arr.tobytes()


def get_output(pred, name):
    """Returns (dtype_enum, shape_tuple, raw_bytes)."""
    return _pack_array(pred.get_output_handle(name).copy_to_cpu(), name)


# ---------------------------------------------------------------------------
# C serving bridge (csrc/capi PD_ServingEngine): submit/poll over
# paddle_tpu.serving.ServingEngine, so C/Go front-ends get the admission
# queue + dynamic batcher instead of one-request-at-a-time PD_PredictorRun.
# Tickets are plain ints; the handle owns ticket -> Response resolution.
# ---------------------------------------------------------------------------


class _ServingHandle:
    def __init__(self, engine):
        import threading

        self.engine = engine
        self.tickets = {}
        self.next_ticket = 0
        self.lock = threading.Lock()


def new_serving_engine(model_dir, prog_file, params_file, use_tpu, device_id,
                       max_batch, max_seq, queue_depth, max_wait_ms,
                       num_replicas):
    """Build + warm + start an engine. max_seq=0 means the model has no
    variable-length axis (batch-bucketing only); ladders are power-of-two
    up to the maxima."""
    from paddle_tpu.serving import BucketLattice, ServingEngine

    if prog_file:
        config = Config(prog_file, params_file)
    else:
        config = Config(model_dir)
    if use_tpu:
        config.enable_tpu(device_id)
    else:
        config.disable_tpu()
    # the C header promises "<= 0 picks the default" — negative values
    # must not leak through (queue_depth=-1 would reject everything)
    lattice = BucketLattice.pow2(max_batch if max_batch > 0 else 8,
                                 max_seq if max_seq > 0 else None)
    config.set_serving_buckets(lattice.batch_sizes, lattice.seq_lens,
                               lattice.pad_axis)
    engine = ServingEngine(
        config, lattice=lattice, num_replicas=max(num_replicas, 1),
        queue_depth=queue_depth if queue_depth > 0 else 256,
        max_wait_ms=max_wait_ms if max_wait_ms > 0 else 5.0,
    )
    engine.start()
    return _ServingHandle(engine)


def serving_submit(handle, names, dtype_idxs, shapes, buffers, priority,
                   deadline_ms):
    """One request: parallel per-input lists. Buffers are memoryviews
    over caller memory — copied immediately (the C caller may free them
    after this returns). Raises RejectedError (backpressure/invalid);
    the C side maps that to ticket -1 + PD_GetLastError."""
    inputs = {}
    for name, di, shape, data in zip(names, dtype_idxs, shapes, buffers):
        inputs[name] = (
            np.frombuffer(data, dtype=_DTYPES[di]).reshape(shape).copy()
        )
    resp = handle.engine.submit(
        inputs, priority=priority,
        deadline_ms=deadline_ms if deadline_ms and deadline_ms > 0 else None,
    )
    with handle.lock:
        handle.next_ticket += 1
        ticket = handle.next_ticket
        handle.tickets[ticket] = resp
    return ticket


def serving_poll(handle, ticket, output_name):
    """None while pending; (dtype_idx, shape, bytes) for the named output
    when served. A FAILED REQUEST raises its structured ServingError and
    consumes the ticket; caller errors (bad ticket, unknown output name)
    raise WITHOUT consuming — the served outputs stay pollable/releasable.
    Successful tickets stay until serving_release so multi-output models
    can poll each output."""
    with handle.lock:
        resp = handle.tickets.get(ticket)
    if resp is None:
        raise KeyError(f"unknown or released ticket {ticket}")
    if not resp.done():
        return None
    err = resp.error()
    if err is not None:
        with handle.lock:
            handle.tickets.pop(ticket, None)
        raise err
    outputs = resp.result()
    if output_name not in outputs:
        raise KeyError(
            f"no output named '{output_name}' (have {sorted(outputs)}); "
            "the ticket is NOT consumed — poll again or serving_release it"
        )
    return _pack_array(outputs[output_name], output_name)


def serving_release(handle, ticket):
    with handle.lock:
        handle.tickets.pop(ticket, None)
    return 0


def serving_stats_json(handle):
    import json as _json

    return _json.dumps(handle.engine.stats())


def serving_shutdown(handle):
    handle.engine.shutdown()
    return 0


# ---------------------------------------------------------------------------
# C train API bridge (reference: paddle/fluid/train/ - the C++ train demo;
# here PD_Trainer in csrc/capi/capi.cc drives these)
# ---------------------------------------------------------------------------


class _Trainer:
    def __init__(self, model_dir, use_tpu):
        import paddle_tpu as fluid
        from paddle_tpu import io as pio

        self.main, self.startup, self.loss = pio.load_train_model(model_dir)
        place = fluid.TPUPlace(0) if use_tpu else fluid.CPUPlace()
        self.exe = fluid.Executor(place)
        self.scope = fluid.Scope()
        import os

        with fluid.scope_guard(self.scope):
            self.exe.run(self.startup)
            params_dir = os.path.join(model_dir, "params")
            if os.path.isdir(params_dir):
                pio.load_persistables(
                    self.exe, params_dir, main_program=self.main
                )
        self.feeds = {}


def new_trainer(model_dir, use_tpu):
    return _Trainer(model_dir, bool(use_tpu))


def trainer_loss_name(tr):
    return tr.loss or ""


def trainer_set_input(tr, name, dtype_idx, shape, data):
    """`data` is a memoryview over the caller's buffer; copy immediately -
    the C host may free/reuse it after this returns (same contract as
    set_input above)."""
    tr.feeds[name] = (
        np.frombuffer(data, dtype=_DTYPES[dtype_idx]).reshape(shape).copy()
    )
    return 0


def trainer_run(tr, fetch_name):
    """One training step with the accumulated feeds; returns the fetched
    var as (dtype_idx, shape, bytes). Empty fetch_name = the saved loss."""
    import paddle_tpu as fluid

    fetch = fetch_name or tr.loss
    with fluid.scope_guard(tr.scope):
        out = tr.exe.run(
            tr.main, feed=dict(tr.feeds), fetch_list=[fetch] if fetch else []
        )
    if not fetch:
        return (0, (), b"")
    arr = np.ascontiguousarray(np.asarray(out[0]))
    if arr.dtype == np.float64 or str(arr.dtype) == "bfloat16":
        arr = arr.astype(np.float32)
    dt = str(arr.dtype)
    if dt not in _DTYPES:
        raise TypeError(f"fetch '{fetch}' has non-C-ABI dtype {dt}")
    return _DTYPES.index(dt), tuple(int(d) for d in arr.shape), arr.tobytes()


def trainer_save(tr, dirname):
    import paddle_tpu as fluid
    from paddle_tpu import io as pio

    with fluid.scope_guard(tr.scope):
        pio.save_persistables(tr.exe, dirname, main_program=tr.main)
    return 0


# -- ProgramDesc-level C surface (reference: paddle/fluid/framework/c/
# c_api.cc - minimal ProgramDesc IO) ---------------------------------------


def program_load(path):
    from paddle_tpu.core.ir import Program

    with open(path, "rb") as f:
        return Program.from_bytes(f.read())


def program_save(prog, path):
    with open(path, "wb") as f:
        f.write(prog.to_bytes())
    return 0


def program_op_count(prog):
    return len(prog.global_block().ops)


def program_op_type(prog, i):
    return prog.global_block().ops[i].type
