"""Inference/serving stack: analysis passes + AOT-compiled predictor.

TPU-native replacement for the reference's 27k-LoC inference engine
(reference: paddle/fluid/inference/api/analysis_predictor.h:47,
paddle_inference_api.h): where the reference rewrote the graph with 30+
fusion passes and ran it op-by-op through a NaiveExecutor, here the analysis
passes are semantic rewrites (DCE, test-mode, bf16, constant folding) and
the whole pruned program is AOT-lowered to ONE XLA executable per input
shape — fusion, layout, and scheduling are XLA's job. Zero-copy means feeds
go straight to device buffers and weights stay device-resident across calls.

C/Go bindings over this module live in csrc/capi and go/paddle.
"""

from paddle_tpu.inference.predictor import (
    Config,
    PrecisionType,
    Predictor,
    Tensor,
    create_predictor,
)

__all__ = [
    "Config",
    "PrecisionType",
    "Predictor",
    "Tensor",
    "create_predictor",
]
