"""Build helper for the inference C ABI (csrc/capi/capi.cc).

`build_capi()` compiles libcapi.so (embedding CPython) on first use via the
same compile-on-demand machinery as the other native components, and returns
its path for C/Go hosts to link against. reference:
paddle/fluid/inference/capi/CMakeLists.txt (there: part of the superbuild).
"""

import os
import sysconfig

from paddle_tpu.utils.native import _CSRC, load_native


def python_embed_flags():
    """Compiler/linker flags to embed this interpreter."""
    inc = sysconfig.get_paths()["include"]
    libdir = sysconfig.get_config_var("LIBDIR") or "/usr/local/lib"
    ldver = sysconfig.get_config_var("LDVERSION") or sysconfig.get_config_var(
        "VERSION"
    )
    return [
        f"-I{inc}",
        f"-L{libdir}",
        f"-lpython{ldver}",
        f"-Wl,-rpath,{libdir}",
        "-ldl",
    ]


def build_capi():
    """Compile (if stale) and return the path to libcapi.so."""
    load_native("capi", extra_flags=python_embed_flags())
    return os.path.join(_CSRC, "capi", "libcapi.so")


def header_path():
    return os.path.join(_CSRC, "capi", "paddle_tpu_capi.h")
