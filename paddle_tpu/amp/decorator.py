"""Automatic mixed precision as a program rewrite.

Same architecture as the reference's contrib.mixed_precision
(reference: python/paddle/fluid/contrib/mixed_precision/decorator.py:27
OptimizerWithMixedPrecision, fp16_lists.py white/black lists, fp16_utils.py
program rewrite + loss scaling), retargeted at the TPU: the default compute
dtype is **bfloat16**, which shares float32's exponent range — so loss
scaling is unnecessary in the default configuration and only activates for
float16. Parameters stay float32 (master weights); white-list ops (matmuls,
convs — the MXU ops) get their float inputs cast down; black-list ops
(softmax/norm/reductions) get casts back up. XLA folds the cast chains.
"""

from paddle_tpu.core.dtypes import is_float_dtype
from paddle_tpu.core.ir import Operator, default_main_program
from paddle_tpu.utils.flags import flags

# reference: python/paddle/fluid/contrib/mixed_precision/fp16_lists.py
WHITE_LIST = {
    "matmul",
    "mul",
    "fc",  # the fc_fuse pass target — same MXU dot as mul
    "conv2d",
    "depthwise_conv2d",
    "conv2d_transpose",
    # fused attention: the Pallas kernel dots run in the input dtype with
    # f32 accumulation, so feeding bf16 q/k/v is what puts them on the MXU
    # at full rate (softmax math inside stays f32 regardless)
    "scaled_dot_product_attention",
    "multihead_matmul",
}

# input slots of white-list ops that never feed an MXU dot: casting them
# buys no rate and only quantizes the value (attention biases are added to
# f32 scores inside the kernel)
WHITE_LIST_SKIP_SLOTS = {
    "scaled_dot_product_attention": {"Bias"},
    "multihead_matmul": {"Bias", "BiasQK"},
}
BLACK_LIST = {
    "softmax",
    "log_softmax",
    "softmax_with_cross_entropy",
    "cross_entropy",
    "layer_norm",
    "batch_norm",
    "instance_norm",
    "group_norm",
    "mean",
    "sum",
    "reduce_sum",
    "reduce_mean",
    "exp",
    "log",
    "squared_l2_norm",
    "auc",
    "accuracy",
}


class AutoMixedPrecisionLists:
    def __init__(self, custom_white_list=None, custom_black_list=None):
        self.white_list = set(WHITE_LIST)
        self.black_list = set(BLACK_LIST)
        if custom_white_list:
            self.white_list |= set(custom_white_list)
        if custom_black_list:
            self.black_list |= set(custom_black_list)
        overlap = self.white_list & self.black_list
        if overlap:
            raise ValueError(f"ops in both white and black lists: {overlap}")


def _insert_cast(block, index, src_name, dst_dtype, cache):
    key = (src_name, dst_dtype)
    if key in cache:
        return cache[key], index
    cast_name = f"{src_name}.cast_{dst_dtype}"
    src = block._find_var_recursive(src_name)
    if cast_name not in block.vars:
        block.create_var(
            name=cast_name,
            shape=src.shape if src is not None else None,
            dtype=dst_dtype,
            stop_gradient=src.stop_gradient if src is not None else False,
        )
    block._insert_op(
        index,
        "cast",
        {"X": [src_name]},
        {"Out": [cast_name]},
        {"out_dtype": dst_dtype, "op_role": 0},
    )
    cache[key] = cast_name
    return cast_name, index + 1


def rewrite_program_amp(program=None, amp_lists=None, dest_dtype=None):
    """Insert casts so white-list ops compute in the low-precision dtype and
    black-list ops compute in float32. Must run on the forward-only program
    (before append_backward) so grad ops inherit the casts via vjp."""
    program = program or default_main_program()
    amp_lists = amp_lists or AutoMixedPrecisionLists()
    dest_dtype = dest_dtype or flags.amp_dtype
    block = program.global_block()
    i = 0
    cache = {}
    while i < len(block.ops):
        op = block.ops[i]
        target = None
        if op.type in amp_lists.white_list:
            target = dest_dtype
        elif op.type in amp_lists.black_list:
            target = "float32"
        if target is None:
            i += 1
            continue
        skip_slots = (
            WHITE_LIST_SKIP_SLOTS.get(op.type, ()) if target != "float32" else ()
        )
        for slot, names in list(op.inputs.items()):
            if slot in skip_slots:
                continue
            new_names = []
            for n in names:
                v = block._find_var_recursive(n)
                if v is not None and v.dtype is not None and is_float_dtype(v.dtype):
                    cast_name, i = _insert_cast(block, i, n, target, cache)
                    new_names.append(cast_name)
                else:
                    new_names.append(n)
            op.inputs[slot] = new_names
        i += 1
    program._bump_version()
    return program


class OptimizerWithMixedPrecision:
    """reference: python/paddle/fluid/contrib/mixed_precision/decorator.py:27.
    Wraps an optimizer: rewrites the forward program, optionally scales the
    loss (float16 only), unscales gradients before the update."""

    def __init__(
        self,
        optimizer,
        amp_lists=None,
        init_loss_scaling=1.0,
        use_dynamic_loss_scaling=False,
        incr_every_n_steps=1000,
        decr_ratio=0.5,
        incr_ratio=2.0,
        dest_dtype=None,
    ):
        self._optimizer = optimizer
        self._amp_lists = amp_lists or AutoMixedPrecisionLists()
        self._dest_dtype = dest_dtype or flags.amp_dtype
        self._loss_scaling = init_loss_scaling
        self._use_dynamic = use_dynamic_loss_scaling
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_ratio = decr_ratio
        self._incr_ratio = incr_ratio
        self._scale_var = None

    def __getattr__(self, name):
        return getattr(self._optimizer, name)

    def _needs_scaling(self):
        return self._dest_dtype == "float16" and (
            self._use_dynamic or self._loss_scaling != 1.0
        )

    def backward(self, loss, startup_program=None, parameter_list=None, no_grad_set=None):
        from paddle_tpu import layers
        from paddle_tpu.core.backward import append_backward

        rewrite_program_amp(loss.block.program, self._amp_lists, self._dest_dtype)
        if not self._needs_scaling():
            return append_backward(loss, parameter_list, no_grad_set)
        if not self._use_dynamic:
            scaled = layers.scale(loss, scale=self._loss_scaling)
            pg = append_backward(scaled, parameter_list, no_grad_set)
            inv = 1.0 / self._loss_scaling
            return [(p, layers.scale(g, scale=inv)) for p, g in pg if g is not None]
        return self._dynamic_backward(loss, parameter_list, no_grad_set)

    def _dynamic_backward(self, loss, parameter_list, no_grad_set):
        """Dynamic loss scaling (reference: contrib/mixed_precision/
        decorator.py + fp16_utils.py update_loss_scaling): scale the loss by a
        persistable scale var, unscale grads, zero them on overflow, and adapt
        the scale — all as graph ops compiled into the training step."""
        from paddle_tpu import layers
        from paddle_tpu.core.backward import append_backward
        from paddle_tpu.layers import tensor as tensor_layers
        from paddle_tpu.utils import unique_name

        block = loss.block
        self._scale_var = tensor_layers.create_global_var(
            shape=[1],
            value=float(self._loss_scaling),
            dtype="float32",
            persistable=True,
            name=unique_name.generate("loss_scaling"),
        )
        good = tensor_layers.create_global_var(
            shape=[1], value=0, dtype="int32", persistable=True,
            name=unique_name.generate("loss_scaling_good_steps"),
        )
        bad = tensor_layers.create_global_var(
            shape=[1], value=0, dtype="int32", persistable=True,
            name=unique_name.generate("loss_scaling_bad_steps"),
        )
        scaled = layers.elementwise_mul(loss, self._scale_var)
        pg = [(p, g) for p, g in append_backward(scaled, parameter_list, no_grad_set) if g is not None]
        grad_names = [g.name for _, g in pg]
        found_inf = block.create_var(
            name=unique_name.generate("found_infinite"), shape=[1], dtype="bool"
        )
        block.append_op(
            "check_finite_and_unscale",
            {"X": grad_names, "Scale": [self._scale_var.name]},
            {"Out": grad_names, "FoundInfinite": [found_inf.name]},
            {"op_role": 1},
        )
        block.append_op(
            "update_loss_scaling",
            {
                "X": grad_names,
                "FoundInfinite": [found_inf.name],
                "PrevLossScaling": [self._scale_var.name],
                "InGoodSteps": [good.name],
                "InBadSteps": [bad.name],
            },
            {
                "Out": grad_names,
                "LossScaling": [self._scale_var.name],
                "OutGoodSteps": [good.name],
                "OutBadSteps": [bad.name],
            },
            {
                "incr_every_n_steps": self._incr_every_n_steps,
                "decr_every_n_nan_or_inf": 2,
                "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio,
                "op_role": 1,
            },
        )
        return pg

    def minimize(self, loss, startup_program=None, parameter_list=None, no_grad_set=None):
        self._optimizer.helper = None
        self._optimizer._create_global_learning_rate()
        params_grads = self.backward(
            loss, startup_program, parameter_list, no_grad_set
        )
        optimize_ops = self._optimizer.apply_gradients(params_grads)
        return optimize_ops, params_grads


def decorate(
    optimizer,
    amp_lists=None,
    init_loss_scaling=1.0,
    use_dynamic_loss_scaling=False,
    incr_every_n_steps=1000,
    decr_every_n_nan_or_inf=2,
    incr_ratio=2.0,
    decr_ratio=0.5,
    dest_dtype=None,
):
    """reference: python/paddle/fluid/contrib/mixed_precision/decorator.py:218."""
    return OptimizerWithMixedPrecision(
        optimizer,
        amp_lists=amp_lists,
        init_loss_scaling=init_loss_scaling,
        use_dynamic_loss_scaling=use_dynamic_loss_scaling,
        incr_every_n_steps=incr_every_n_steps,
        decr_ratio=decr_ratio,
        incr_ratio=incr_ratio,
        dest_dtype=dest_dtype,
    )
