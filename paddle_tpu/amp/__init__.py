from paddle_tpu.amp.decorator import (
    AutoMixedPrecisionLists,
    OptimizerWithMixedPrecision,
    decorate,
    rewrite_program_amp,
)
