"""LayerHelper: the bridge from layer functions to IR ops.

Same role as the reference's LayerHelper (reference: python/paddle/fluid/
layer_helper.py) — creates parameters (with their init ops in the startup
program), temp output variables, and appends OpDescs to the current block.
Output shapes/dtypes are inferred by abstractly evaluating the op's jax
lowering rule (jax.eval_shape) — one shape-inference implementation shared
with execution, where the reference maintained 560 hand-written InferShape
functions (reference: paddle/fluid/framework/shape_inference.h).
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core.dtypes import to_numpy_dtype
from paddle_tpu.core.ir import default_main_program, default_startup_program
from paddle_tpu.core.registry import OpRegistry
from paddle_tpu.initializer import ConstantInitializer, XavierInitializer
from paddle_tpu.param_attr import ParamAttr
from paddle_tpu.utils import unique_name


# Sentinel concrete size standing in for dynamic (-1) dims during abstract
# evaluation; a large prime so products involving it stay recognizable.
_DYN_SENTINEL = 1031


def infer_op_shapes(op_type, block, inputs, attrs):
    """Abstractly evaluate an op lowering to get output ShapeDtypeStructs.
    Returns {slot: [(shape, dtype_str), ...]} or None if not inferable
    (e.g. value-dependent shapes). Dynamic (-1) dims are traced with a
    sentinel size and mapped back to -1 in the result."""
    if not OpRegistry.has(op_type):
        return None
    op_def = OpRegistry.get(op_type)
    specs = {}
    had_dynamic = False
    for slot, names in inputs.items():
        slot_specs = []
        for n in names:
            v = block._find_var_recursive(n)
            if v is None or v.shape is None:
                return None
            had_dynamic = had_dynamic or any(d < 0 for d in v.shape)
            shape = tuple(_DYN_SENTINEL if d < 0 else d for d in v.shape)
            slot_specs.append(jax.ShapeDtypeStruct(shape, to_numpy_dtype(v.dtype)))
        specs[slot] = slot_specs
    if op_def.stateful:
        specs["__rng_key__"] = [jax.ShapeDtypeStruct((2,), jnp.uint32)]
    clean_attrs = {
        k: v for k, v in attrs.items() if k not in ("op_callstack",)
    }
    try:
        out = jax.eval_shape(lambda ins: op_def.lower(ins, clean_attrs), specs)
    except Exception:
        return None
    result = {}
    for slot, vals in out.items():
        if not isinstance(vals, (list, tuple)):
            vals = [vals]
        result[slot] = [
            (
                tuple(
                    -1 if had_dynamic and d > 0 and d % _DYN_SENTINEL == 0 else d
                    for d in v.shape
                ),
                str(v.dtype),
            )
            for v in vals
        ]
    return result


class LayerHelper:
    def __init__(self, layer_type, **kwargs):
        self.kwargs = kwargs
        self.layer_type = layer_type
        name = kwargs.get("name")
        self.name = name if name is not None else unique_name.generate(layer_type)
        self.main_program = kwargs.get("main_program") or default_main_program()
        self.startup_program = (
            kwargs.get("startup_program") or default_startup_program()
        )

    @property
    def block(self):
        return self.main_program.current_block()

    @property
    def param_attr(self):
        return ParamAttr._to_attr(self.kwargs.get("param_attr"))

    @property
    def bias_attr(self):
        return ParamAttr._to_attr(self.kwargs.get("bias_attr"))

    def input_dtype(self, var):
        return var.dtype

    def create_parameter(
        self, attr, shape, dtype="float32", is_bias=False, default_initializer=None
    ):
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        suffix = "b" if is_bias else "w"
        name = attr.name or unique_name.generate(f"{self.name}.{suffix}")
        if default_initializer is None:
            default_initializer = (
                ConstantInitializer(0.0) if is_bias else XavierInitializer()
            )
        init = attr.initializer or default_initializer
        # init op goes into the startup program
        sblock = self.startup_program.global_block()
        if name not in sblock.vars:
            svar = sblock.create_var(
                name=name, shape=shape, dtype=dtype, persistable=True
            )
            init(svar, sblock)
        # parameter lives in the main program's global block
        gblock = self.main_program.global_block()
        if name in gblock.vars:
            return gblock.vars[name]
        param = gblock.create_parameter(
            shape,
            dtype,
            name=name,
            trainable=attr.trainable,
            optimize_attr={"learning_rate": attr.learning_rate},
            regularizer=attr.regularizer,
        )
        return param

    def create_variable_for_type_inference(self, dtype="float32", stop_gradient=False):
        return self.block.create_var(
            name=unique_name.generate(self.name + ".tmp"),
            dtype=dtype,
            shape=None,
            persistable=False,
            stop_gradient=stop_gradient,
        )

    def create_global_variable(self, shape, dtype, name=None, persistable=True):
        gblock = self.main_program.global_block()
        return gblock.create_var(
            name=name or unique_name.generate(self.name + ".global"),
            shape=shape,
            dtype=dtype,
            persistable=persistable,
            stop_gradient=True,
        )

    def append_op(self, type, inputs=None, outputs=None, attrs=None):
        op = self.block.append_op(type, inputs, outputs, attrs or {})
        # propagate inferred shapes onto output variables so downstream
        # layers can read .shape at build time
        inferred = infer_op_shapes(type, self.block, op.inputs, op.attrs)
        if inferred:
            for slot, names in op.outputs.items():
                if slot not in inferred:
                    continue
                for (shape, dtype), n in zip(inferred[slot], names):
                    v = self.block.vars.get(n)
                    if v is not None and v.shape is None:
                        v.shape = shape
                        v.dtype = dtype
        return op

    def append_activation(self, out_var):
        act = self.kwargs.get("act")
        if act is None:
            return out_var
        act_out = self.create_variable_for_type_inference(out_var.dtype)
        self.append_op(act, {"X": [out_var.name]}, {"Out": [act_out.name]})
        return act_out

    def append_bias_op(self, out_var, bias, axis=1):
        tmp = self.create_variable_for_type_inference(out_var.dtype)
        self.append_op(
            "elementwise_add",
            {"X": [out_var.name], "Y": [bias.name]},
            {"Out": [tmp.name]},
            {"axis": axis},
        )
        return tmp
