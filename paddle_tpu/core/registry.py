"""Operator registry.

TPU-native analog of the reference's OpInfoMap/OpRegistry
(reference: paddle/fluid/framework/op_registry.h:68, op_info.h). Where the
reference registers per-(place, dtype, layout) *kernels* chosen at run time
(reference: paddle/fluid/framework/operator.cc:1041 ChooseKernel), an op here
registers:

  * ``lower``   — a jax lowering rule: (inputs, attrs) -> outputs, traced into
                  the whole-block XLA computation. Dtype/device dispatch is
                  XLA's job; there is exactly one lowering per op.
  * ``infer_shape`` — static shape/dtype inference used at graph-build time
                  (reference: shape_inference.h), optional.
  * ``grad``    — a custom IR grad maker (reference: grad_op_desc_maker.h),
                  optional: the default grad op is synthesized generically from
                  the lowering rule via jax.vjp (see core/backward.py), which
                  is the TPU-native replacement for per-op hand-written grad
                  kernels.
  * ``pallas``  — optional hand-written Pallas TPU kernel overriding the jnp
                  lowering for ops XLA fuses poorly.

Inputs/outputs are dicts: slot name -> list of jax arrays, mirroring the
reference's named variable lists on OpDesc.
"""

from paddle_tpu.utils.enforce import EnforceError


class OpDef:
    def __init__(
        self,
        type,
        lower,
        infer_shape=None,
        grad=None,
        pallas=None,
        nondiff_inputs=(),
        stateful=False,
        needs_base_rng=False,
        needs_block=False,
        needs_out_counts=False,
        signature=None,
    ):
        self.type = type
        self.lower = lower
        self.infer_shape = infer_shape
        self.grad = grad
        self.pallas = pallas
        # optional static signature (analysis/signatures.py OpSignature):
        # rank/dtype constraints the program verifier checks op descs
        # against without tracing the lowering
        self.signature = signature
        # input slots that never receive gradients (indices, masks, ...)
        self.nondiff_inputs = frozenset(nondiff_inputs)
        # stateful ops (random, print, ...) must not be CSE'd away
        self.stateful = stateful
        # ops replaying other ops (recompute_segment_grad) get the step's
        # UNFOLDED rng key so they can reproduce per-op folds exactly
        self.needs_base_rng = needs_base_rng
        # ops running sub-blocks through the interpreter (recurrent) get the
        # enclosing Block injected as attrs['_ctx_block'] at execution time —
        # the sub_block attr is an index that only resolves against the
        # program actually being run (survives Program.clone)
        self.needs_block = needs_block
        # ops with variable output arity (select_output) get
        # attrs['__out_counts__'] = {slot: len(names)} injected at execution
        self.needs_out_counts = needs_out_counts

    def lowering(self, use_pallas=True):
        if use_pallas and self.pallas is not None:
            return self.pallas
        return self.lower


class OpRegistry:
    _ops = {}

    @classmethod
    def register(cls, op_def):
        if op_def.type in cls._ops:
            raise EnforceError(f"op {op_def.type} registered twice")
        cls._ops[op_def.type] = op_def

    @classmethod
    def get(cls, type):
        try:
            return cls._ops[type]
        except KeyError:
            raise EnforceError(f"op {type} is not registered")

    @classmethod
    def has(cls, type):
        return type in cls._ops

    @classmethod
    def all_types(cls):
        return sorted(cls._ops)


def register_op(type, infer_shape=None, grad=None, pallas=None, nondiff_inputs=(), stateful=False, needs_base_rng=False, needs_block=False, needs_out_counts=False, signature=None):
    """Decorator form:  @register_op("relu")  def _(ins, attrs): ..."""

    def deco(fn):
        OpRegistry.register(
            OpDef(
                type,
                fn,
                infer_shape=infer_shape,
                grad=grad,
                pallas=pallas,
                nondiff_inputs=nondiff_inputs,
                stateful=stateful,
                needs_base_rng=needs_base_rng,
                needs_block=needs_block,
                needs_out_counts=needs_out_counts,
                signature=signature,
            )
        )
        return fn

    return deco


def get_op_def(type):
    return OpRegistry.get(type)


def has_op_def(type):
    return OpRegistry.has(type)


def register_grad(fwd_type):
    """Attach a custom IR grad maker to an already-registered op.

    The maker has signature (op: Operator, grad_out_names: dict, grad_in_names
    factory) and appends grad OpDescs — see core/backward.py for the calling
    convention.
    """

    def deco(fn):
        OpRegistry.get(fwd_type).grad = fn
        return fn

    return deco


def register_pallas(fwd_type):
    """Attach a Pallas TPU kernel as the preferred lowering for an op."""

    def deco(fn):
        OpRegistry.get(fwd_type).pallas = fn
        return fn

    return deco
