"""ONE program->XLA lowering path for the whole framework.

Before this module, three subsystems each carried their own
plan/trace/compile/cache logic — ``Executor._run_compiled``,
``CompiledProgram._run``, and ``Predictor._compiled`` — the reproduction's
analog of the reference's per-executor ExecutorPrepareContext cache
(reference: paddle/fluid/framework/executor.cc), grown three times. Every
hardening PR had to touch all three (ROADMAP open item 5). This module
collapses them: plan (``executor.plan_step``) -> mandatory verifier pass
(analysis/verify.py) -> step closure -> ``jax.jit`` with donation and
shardings -> the content-addressed compile cache (core/compile_cache.py),
with ``jax.export`` serialization to the persistent tier where the
installed jax supports it and a graceful trace-on-miss fallback where it
does not.

The contract every caller shares: a lowered step is a function

    (feed_vals, donated_vals, readonly_vals, rng_key)
        -> (fetches, written_persistable_updates)

Executor, CompiledProgram (with mesh shardings), Predictor (donation off,
fixed rng), and utils/hlo.py (lower-only, no cache) all route through
``lower_step``; ``jit_compile`` is the repo-wide chokepoint for the few
remaining free-function jits (models/, tools/), so compile counts stay
observable from one place.
"""

import time

import numpy as np

from paddle_tpu.observability import lockdep
from paddle_tpu.observability import metrics as obs_metrics
from paddle_tpu.utils.enforce import EnforceError

__all__ = ["LoweredStep", "lower_step", "jit_compile", "verify_for_lowering",
           "abstract_signature", "zero_rng_key"]

_JITS = obs_metrics.registry().counter(
    "lowering_jit_total", "jax.jit computations created via the chokepoint"
)
_PERSIST_HITS = obs_metrics.registry().counter(
    "compile_cache_persistent_hits_total",
    "lowered steps loaded from the persistent cache (no retrace)",
)
_PERSIST_LOAD_SECONDS = obs_metrics.registry().histogram(
    "executor_persistent_load_seconds",
    "deserialize latency for persistent compile-cache hits",
)
_SHARED_HITS = obs_metrics.registry().counter(
    "compile_cache_memory_hits_total",
    "lowered steps served from the process-wide memory cache",
)


def jit_compile(fn, **jit_kwargs):
    """The one place outside ops/ that calls ``jax.jit``: every compiled
    computation in the repo is countable from this chokepoint."""
    import jax

    _JITS.inc()
    return jax.jit(fn, **jit_kwargs)


# ---------------------------------------------------------------------------
# mandatory pre-lowering verification
# ---------------------------------------------------------------------------

_VERIFIED = {}  # (uid, version, feeds, fetches) -> True (errors raise)
_VERIFIED_CAP = 512


def verify_for_lowering(program, feed_names, fetch_names, scope=None):
    """Run the analysis/ verifier before any lowering; error-severity
    diagnostics raise (a malformed program must fail loudly at compile
    time, not trace into a wrong computation). Memoized per program
    version so steady-state steps pay one dict lookup.

    Fetch names are screened against the program's declared vars first:
    fetching a scope-resident var the program never mentions is legal
    executor behavior (plan_step validates it against the scope), not a
    dangling fetch."""
    key = (program._uid, program._version, tuple(feed_names),
           tuple(fetch_names))
    if key in _VERIFIED:
        return
    from paddle_tpu.analysis.verify import verify_program

    declared = {n for b in program.blocks for n in b.vars}
    diags = verify_program(
        program,
        feed_names=feed_names,
        fetch_names=[n for n in fetch_names if n in declared],
        scope=scope,
    )
    errors = [d for d in diags if d.severity == "error"]
    if errors:
        lines = [f"[{d.code}] {d.message}" for d in errors[:5]]
        raise EnforceError(
            "program failed pre-lowering verification "
            f"({len(errors)} error(s)):\n  " + "\n  ".join(lines),
            op_type=errors[0].op_type,
            op_callstack=errors[0].callstack,
        )
    if len(_VERIFIED) >= _VERIFIED_CAP:
        _VERIFIED.clear()
    _VERIFIED[key] = True


# ---------------------------------------------------------------------------
# opt-in static diagnostic stages (FLAGS_static_diagnostics) — run ahead of
# the mandatory verifier so a program with a statically-decidable defect
# (shape mismatch, over-budget collective) fails with op attribution
# before any tracing
# ---------------------------------------------------------------------------

_STATIC_STAGE_NAMES = ("shapes", "sharding", "memory", "cost")


def _static_stages():
    from paddle_tpu.utils.flags import flags

    raw = (flags.static_diagnostics or "").strip().lower()
    if not raw:
        return ()
    if raw == "all":
        return _STATIC_STAGE_NAMES
    parts = tuple(p.strip() for p in raw.split(",") if p.strip())
    unknown = [p for p in parts if p not in _STATIC_STAGE_NAMES]
    if unknown:
        # a silently-dropped typo ("shape") would disarm a gate the
        # operator believes is on — refuse instead
        raise EnforceError(
            f"FLAGS_static_diagnostics: unknown stage(s) {unknown}; "
            f"valid: {', '.join(_STATIC_STAGE_NAMES)} or 'all'"
        )
    return tuple(s for s in _STATIC_STAGE_NAMES if s in parts)


_diag_log = None


def _stage_log():
    global _diag_log
    if _diag_log is None:
        from paddle_tpu.observability.logger import RateLimitedLogger

        _diag_log = RateLimitedLogger("paddle_tpu.static_diagnostics",
                                      max_records=32)
    return _diag_log


def run_static_diagnostics(program, feed_sig, fetch_names, stages, *,
                           mesh=None, placement=None, label=""):
    """Run the requested analysis stages; error diagnostics raise, warnings
    go through the rate-limited logger. ``placement`` carries the
    CompiledProgram's parameter-placement inputs (spec_layout /
    param_rules / param_specs / input_specs) so the sharding stage lints
    the layout the compile will actually use."""
    from paddle_tpu.analysis import shapes as a_shapes
    from paddle_tpu.utils.flags import flags

    feed_shapes = {n: s for n, s, _d in feed_sig}
    feed_dtypes = {n: d for n, _s, d in feed_sig}
    shape_report = None
    errors = []
    if "shapes" in stages or "memory" in stages or "sharding" in stages \
            or "cost" in stages:
        shape_report = a_shapes.infer_shapes(
            program, feed_shapes=feed_shapes, feed_dtypes=feed_dtypes,
        )
    if shape_report is not None:
        for d in shape_report.diagnostics:
            if d.severity == "error":
                # every stage consumes the shape report — a broken shape
                # poisons sharding bytes and HBM estimates, so shape
                # errors gate no matter which stage was armed
                errors.append(d)
            elif "shapes" in stages:
                _stage_log().warning("static[%s]: %s", label, d)
    sharding_report = None
    if ("sharding" in stages or "cost" in stages) and mesh is not None:
        from paddle_tpu.analysis import sharding as a_sharding

        placement = placement or {}
        sharding_report = a_sharding.analyze_sharding(
            program, mesh,
            spec_layout=placement.get("spec_layout"),
            param_rules=placement.get("param_rules"),
            param_specs=placement.get("param_specs"),
            input_specs=placement.get("input_specs"),
            feed_shapes=feed_shapes,
            shape_report=shape_report,
        )
        budget_kb = flags.collective_budget_kb
        if budget_kb and "sharding" in stages:
            from paddle_tpu.analysis.sharding import (
                collective_budget_diagnostics,
            )

            errors.extend(collective_budget_diagnostics(
                sharding_report, budget_kb * 1024,
            ))
    if "memory" in stages:
        from paddle_tpu.analysis.memory import estimate_peak_hbm

        mem = estimate_peak_hbm(
            program, feed_shapes=feed_shapes, fetch_names=fetch_names,
            shape_report=shape_report, sharding_report=sharding_report,
        )
        _stage_log().info(
            "static[%s]: peak HBM estimate %.2f MiB per device "
            "(persistent %.2f MiB + intermediates %.2f MiB at op "
            "#%s <%s>)",
            label, mem.peak_total_bytes / 2**20,
            mem.persistent_bytes / 2**20,
            mem.peak_intermediate_bytes / 2**20,
            mem.peak_op_index, mem.peak_op_type,
        )
    if "cost" in stages:
        from paddle_tpu.analysis.cost import (
            analyze_cost,
            hierarchical_collective_diagnostics,
        )

        placement = placement or {}
        axis_tags = placement.get("axis_tags")
        cost = analyze_cost(
            program, machine=flags.cost_machine or "tpu-v4-8",
            mesh=mesh, axis_tags=axis_tags, feed_shapes=feed_shapes,
            feed_dtypes=feed_dtypes, fetch_names=fetch_names,
            shape_report=shape_report, sharding_report=sharding_report,
        )
        _stage_log().info(
            "static[%s]: predicted step %.3f ms on %s (roofline %.3f ms "
            "+ collectives %.3f ms), MFU %.4f, %d/%d ops compute-bound",
            label, cost.step_seconds * 1e3, cost.cost_model.machine.name,
            cost.roofline_seconds * 1e3, cost.collective_seconds * 1e3,
            cost.mfu, cost.bound_counts()["compute"], len(cost.ops),
        )
        hier = hierarchical_collective_diagnostics(cost)
        if axis_tags and any(t == "dcn" for t in axis_tags.values()):
            # the caller has DECLARED the slow tier — a full-payload
            # all-reduce across it is a layout bug, not a maybe
            errors.extend(hier)
        else:
            for d in hier:
                _stage_log().warning("static[%s]: %s", label, d)
    if errors:
        lines = [f"[{d.code}] {d.message}" for d in errors[:5]]
        raise EnforceError(
            f"static diagnostics failed before lowering ({len(errors)} "
            "error(s)):\n  " + "\n  ".join(lines),
            op_type=errors[0].op_type,
            op_callstack=errors[0].callstack,
        )


# ---------------------------------------------------------------------------
# the lowered-step entry
# ---------------------------------------------------------------------------


class LoweredStep:
    """One compiled step + its I/O plan. ``fn`` has the shared 4-arg
    signature; ``source`` records where it came from ("trace" | "disk" —
    tier-1 memory hits return the same object). ``meta`` carries
    caller-specific extras (CompiledProgram stores shardings there)."""

    __slots__ = (
        "fn", "feed_names", "fetch_names", "donated", "readonly", "written",
        "ops", "fingerprint", "source", "build_seconds", "executed", "meta",
        "_aot", "_aot_lock",
    )

    def __init__(self, fn, plan, fingerprint, source, build_seconds):
        (self.feed_names, self.fetch_names, self.donated, self.readonly,
         self.written, self.ops) = plan
        self.fn = fn
        self.fingerprint = fingerprint
        self.source = source
        self.build_seconds = build_seconds
        self.executed = False
        self.meta = {}
        self._aot = None
        self._aot_lock = lockdep.named_lock("compile.aot")

    @property
    def scope_names(self):
        return self.donated + self.readonly

    def lower(self, *abstract_args):
        """jax ``Lowered`` for HLO evidence (utils/hlo.py)."""
        return self.fn.lower(*abstract_args)

    def aot_compile(self, abstract_args):
        """AOT executable for the serving hot path (Predictor): committed
        same-layout args, no per-call jit dispatch. Cached on the entry —
        clones warming the same bucket share one executable (the lock
        keeps concurrent warmups from compiling it twice)."""
        with self._aot_lock:
            if self._aot is None:
                self._aot = self.fn.lower(*abstract_args).compile()
            return self._aot


def _sds(value):
    import jax

    if hasattr(value, "shape") and hasattr(value, "dtype"):
        return jax.ShapeDtypeStruct(tuple(value.shape), value.dtype)
    arr = np.asarray(value)
    return jax.ShapeDtypeStruct(arr.shape, arr.dtype)


def zero_rng_key(device=None):
    """The fixed zero rng key deterministic (inference/decode) steps pass
    for the shared 4-arg contract's rng slot. MUST be built flags-aware —
    under ``FLAGS_rng_impl != threefry`` a plain PRNGKey would be a dtype
    mismatch against ``_rng_abstract`` on every call. One definition
    (Predictor and the decode engine both commit this key once)."""
    import jax

    from paddle_tpu.utils.flags import flags

    if flags.rng_impl != "threefry":
        key = jax.random.key(0, impl=flags.rng_impl)
    else:
        key = jax.random.PRNGKey(0)
    return jax.device_put(key, device) if device is not None else key


def _rng_abstract():
    """Abstract value of the rng key argument, matching the construction
    in ``Executor._next_rng_key`` (impl-dependent dtype)."""
    import jax

    key = zero_rng_key()
    return jax.ShapeDtypeStruct(key.shape, key.dtype)


def _default_step(block, plan):
    feed_names, fetch_names, donated, readonly, written, ops = plan
    from paddle_tpu.core.executor import _interpret_block

    def step(feed_vals, donated_vals, readonly_vals, rng_key):
        env = dict(zip(feed_names, feed_vals))
        env.update(zip(donated, donated_vals))
        env.update(zip(readonly, readonly_vals))
        _interpret_block(block, env, rng_key, ops=ops)
        fetches = [env[n] for n in fetch_names]
        updates = [env.get(n) for n in written]
        return fetches, updates

    return step


def lower_step(
    program,
    scope,
    feed_sig,
    fetch_names,
    *,
    donate=True,
    make_step=None,
    plan=None,
    mesh=None,
    in_shardings=None,
    out_shardings=None,
    layout_sig=None,
    placement=None,
    extra_fingerprint=(),
    use_cache=True,
    persist=None,
    label="executor",
):
    """The one lowering entrypoint.

    ``feed_sig`` is the ordered tuple of (name, shape, dtype-str) for the
    step's feeds. ``make_step(block, plan) -> step`` overrides the default
    step body (microbatching, DGC shard_map). ``plan`` is an optional
    precomputed ``plan_step`` result ``(donated, readonly, written, ops)``
    — callers that already planned (CompiledProgram derives its shardings
    from the plan) pass it so the ONE plan that ordered their
    in/out_shardings is the one the entry records. ``persist`` defaults to
    single-device lowerings (mesh entries stay in the memory tier: the
    serialized-module format does not carry multi-device sharding safely
    across processes). Returns ``(LoweredStep, source)`` where source says
    how THIS call obtained the entry — "trace" (this call compiled),
    "disk" (persistent-cache load), or "memory" (process-wide tier, incl.
    waiting out another thread's in-flight build) — so callers count
    compiles exactly once. Concurrent calls for the same fingerprint share
    one build (compile_cache single-flight).
    """
    from paddle_tpu.core import compile_cache
    from paddle_tpu.core.executor import plan_step

    block = program.global_block()
    feed_names = [n for n, _s, _d in feed_sig]

    # opt-in static diagnostic stages run FIRST: statically-decidable
    # defects (shape mismatch, over-budget collective) fail with op
    # attribution before the verifier and long before any tracing
    stages = _static_stages()
    if stages:
        run_static_diagnostics(
            program, feed_sig, fetch_names, stages,
            mesh=mesh, placement=placement, label=label,
        )

    # mandatory pre-lowering pass: a program that fails verification never
    # reaches tracing (and never poisons the content-addressed cache)
    verify_for_lowering(program, feed_names, fetch_names, scope=scope)

    with_donation = donate
    if plan is None:
        plan = plan_step(block, feed_names, fetch_names, scope,
                         with_donation)
    donated, readonly, written, ops = plan

    # donation safety is always-on and cheap (O(ops)): a plan that
    # fetches a donated buffer, aliases it twice, or reads it after its
    # in-place update must never reach tracing
    if with_donation and donated:
        from paddle_tpu.analysis.memory import check_donation_safety

        unsafe = check_donation_safety(
            program, donated, readonly, fetch_names, block=block,
        )
        if unsafe:
            lines = [f"[{d.code}] {d.message}" for d in unsafe[:5]]
            raise EnforceError(
                f"donation-safety check failed ({len(unsafe)} error(s)):"
                "\n  " + "\n  ".join(lines),
                op_type=unsafe[0].op_type,
                op_callstack=unsafe[0].callstack,
            )
    plan = (list(feed_names), list(fetch_names), donated, readonly,
            written, ops)

    scope_sig = tuple(
        (n, tuple(np.shape(scope.find_var(n))), _dtype_str(scope.find_var(n)))
        for n in donated + readonly
    )
    sharding_sig = None
    if in_shardings is not None:
        sharding_sig = _sharding_sig(in_shardings, out_shardings)
    # the Pallas kernel registry's selection joins the fingerprint here
    # (the layout_sig pattern): op lowerings consult the registry at
    # trace time, so a mode/registry change MUST miss the cache
    from paddle_tpu.kernels import registry as kernel_registry

    fingerprint = compile_cache.program_fingerprint(
        program, feed_sig, fetch_names, scope_sig,
        donate=with_donation, mesh=mesh, sharding_sig=sharding_sig,
        layout_sig=layout_sig, kernel_sig=kernel_registry.kernel_sig(),
        extra=(label.split(":", 1)[0],) + tuple(extra_fingerprint),
    )

    if persist is None:
        persist = mesh is None and in_shardings is None
    if persist and compile_cache.cache_dir() is None:
        # no cache dir configured: skip the export/serialize work and
        # trace straight into a plain jit (the graceful fallback — and
        # the zero-overhead path when persistence is off)
        persist = False
    step_factory = make_step if make_step is not None else _default_step

    def build():
        import jax

        jit_kwargs = {}
        if donated:
            jit_kwargs["donate_argnums"] = (1,)
        if in_shardings is not None:
            jit_kwargs["in_shardings"] = in_shardings
        if out_shardings is not None:
            jit_kwargs["out_shardings"] = out_shardings

        if persist:
            rec = compile_cache.load_persistent(fingerprint)
            if rec is not None:
                header, payload = rec
                t0 = time.perf_counter()
                entry = _entry_from_payload(header, payload, plan,
                                            fingerprint, jit_kwargs)
                if entry is not None:
                    _PERSIST_HITS.inc()
                    _PERSIST_LOAD_SECONDS.observe(time.perf_counter() - t0)
                    return entry
                # plan drift against the stored header: stale entry —
                # fall through to a fresh trace (never a wrong answer)

        t0 = time.perf_counter()
        step = step_factory(block, plan)
        fn = None
        if persist:
            fn = _trace_and_persist(
                step, plan, _abstract_args(plan, feed_sig, scope),
                fingerprint, jit_kwargs,
            )
        if fn is None:
            _JITS.inc()
            fn = jax.jit(step, **jit_kwargs)
        return LoweredStep(fn, plan, fingerprint, "trace",
                           time.perf_counter() - t0)

    if not use_cache:
        entry = build()
        return entry, entry.source
    entry, source = compile_cache.get_or_build(fingerprint, build)
    if source == "memory":
        _SHARED_HITS.inc()
    return entry, source


def _dtype_str(v):
    return str(getattr(v, "dtype", np.asarray(v).dtype))


def _sharding_sig(in_shardings, out_shardings):
    def spec_of(s):
        if s is None:
            return None
        spec = getattr(s, "spec", s)
        return str(spec)

    import jax

    return [
        [spec_of(s) for s in jax.tree_util.tree_leaves(in_shardings)],
        [spec_of(s) for s in jax.tree_util.tree_leaves(
            out_shardings, is_leaf=lambda x: x is None)],
    ]


def abstract_signature(entry, feed_sig, scope):
    """Abstract (ShapeDtypeStruct) argument tuple for a LoweredStep —
    what ``aot_compile`` wants (Predictor warms buckets without data)."""
    plan = (entry.feed_names, entry.fetch_names, entry.donated,
            entry.readonly, entry.written, entry.ops)
    return _abstract_args(plan, feed_sig, scope)


def _abstract_args(plan, feed_sig, scope):
    import jax

    _f, _F, donated, readonly, _w, _ops = plan
    feed_sds = tuple(
        jax.ShapeDtypeStruct(tuple(s), np.dtype(d)) for _n, s, d in feed_sig
    )
    donated_sds = tuple(_sds(scope.find_var(n)) for n in donated)
    readonly_sds = tuple(_sds(scope.find_var(n)) for n in readonly)
    return (feed_sds, donated_sds, readonly_sds, _rng_abstract())


def _trace_and_persist(step, plan, abstract_sig, fingerprint, jit_kwargs):
    """Trace once through ``jax.export``, persist the serialized module,
    and return a jitted wrapper around the exported call — the EXACT
    module a later process will deserialize, so cache-cold and cache-warm
    runs execute identical StableHLO (bit-identical fetches). Any
    unsupported construct (extended-dtype rng keys, callbacks, version
    skew) returns None and the caller falls back to a plain jit."""
    import jax

    from paddle_tpu.core import compile_cache

    try:
        from jax import export as jax_export
    except ImportError:
        return None
    try:
        _JITS.inc()
        exported = jax_export.export(jax.jit(step, **jit_kwargs))(
            *abstract_sig
        )
        payload = exported.serialize()
    except Exception:
        return None
    feed_names, fetch_names, donated, readonly, written, _ops = plan
    compile_cache.store_persistent(
        fingerprint,
        {
            "feed_names": feed_names,
            "fetch_names": fetch_names,
            "donated": donated,
            "readonly": readonly,
            "written": written,
            "jax": jax.__version__,
        },
        payload,
    )
    _JITS.inc()
    return jax.jit(exported.call, **_wrapper_jit_kwargs(jit_kwargs))


def _wrapper_jit_kwargs(jit_kwargs):
    """The exported module already carries sharding + aliasing attrs;
    the wrapper jit only re-applies donation so caller buffers are
    actually released."""
    out = {}
    if "donate_argnums" in jit_kwargs:
        out["donate_argnums"] = jit_kwargs["donate_argnums"]
    return out


def _entry_from_payload(header, payload, plan, fingerprint, jit_kwargs):
    """Wrap a persisted module for execution, cross-checking the stored
    I/O plan against the freshly computed one — a mismatch means the
    planner or program changed without changing the fingerprint inputs
    (should be impossible; treated as a miss, not trusted)."""
    import jax

    try:
        from jax import export as jax_export
    except ImportError:
        return None
    feed_names, fetch_names, donated, readonly, written, _ops = plan
    if (header.get("feed_names") != feed_names
            or header.get("fetch_names") != fetch_names
            or header.get("donated") != donated
            or header.get("readonly") != readonly
            or header.get("written") != written
            or header.get("jax") != jax.__version__):
        return None
    try:
        exported = jax_export.deserialize(payload)
        _JITS.inc()
        fn = jax.jit(exported.call, **_wrapper_jit_kwargs(jit_kwargs))
    except Exception:
        return None
    return LoweredStep(fn, plan, fingerprint, "disk", 0.0)
