"""Program IR: Program / Block / Operator / Variable.

This is the framework's intermediate representation, with the same structural
surface as the reference's ProgramDesc protobuf + Python mirror
(reference: paddle/fluid/framework/framework.proto:211 — program = blocks;
block = vars + ops; reference: python/paddle/fluid/framework.py:3602 Program,
:2176 Block, :1706 Operator, :806 Variable).

The execution model differs fundamentally from the reference: instead of a C++
executor interpreting one op at a time through a kernel registry, whole blocks
are traced through each op's jax lowering rule and compiled by XLA as a single
fused computation (see core/executor.py). The IR is therefore a *builder and
transform substrate* — autodiff (core/backward.py), AMP (amp/), recompute,
distillation into data-parallel programs (parallel/) are all program rewrites,
keeping Fluid's central idea that training features are program transforms.
"""

import contextlib
import copy
import itertools
import json

import numpy as np

from paddle_tpu.core.dtypes import convert_dtype
from paddle_tpu.utils import unique_name
from paddle_tpu.utils.enforce import EnforceError, enforce, user_callstack

IR_FORMAT_VERSION = 1

_name_scope_stack = []


@contextlib.contextmanager
def name_scope(prefix):
    """Hierarchical name scoping for profiling/visualization
    (reference: python/paddle/fluid/framework.py name_scope)."""
    _name_scope_stack.append(prefix)
    try:
        yield
    finally:
        _name_scope_stack.pop()


def _current_name_scope():
    return "/".join(_name_scope_stack)


def parse_getitem_index(idx):
    """Shared tensor-index parser for Variable/VarBase.__getitem__:
    idx -> (axes, starts, ends, squeeze_axes). Ints squeeze their axis and
    -1 selects from the end (int-max end sentinel); only step-1 slices are
    expressible as one slice op — anything else raises here so BOTH
    surfaces refuse identically."""
    if not isinstance(idx, tuple):
        idx = (idx,)
    axes, starts, ends, squeeze_axes = [], [], [], []
    for ax, s in enumerate(idx):
        if isinstance(s, slice):
            if s.step not in (None, 1):
                raise ValueError(
                    "tensor slicing supports step 1 only "
                    "(use layers.strided_slice)"
                )
            if s.start is None and s.stop is None:
                continue
            axes.append(ax)
            starts.append(s.start or 0)
            ends.append(s.stop if s.stop is not None else int(1e9))
        else:
            import operator

            try:
                # accepts python/numpy ints; a SYMBOLIC tensor index routes
                # through __index__ and hits its loud capture guard
                i = operator.index(s)
            except TypeError:
                raise TypeError(
                    f"unsupported tensor index {type(s).__name__} "
                    "(tensor-valued indices: use layers.gather)"
                ) from None
            axes.append(ax)
            starts.append(i)
            ends.append(i + 1 if i != -1 else int(1e9))
            squeeze_axes.append(ax)
    return axes, starts, ends, squeeze_axes


class Variable:
    """A named tensor slot in a Block.

    Carries static metadata (shape may contain -1 for a dynamic dim, resolved
    at feed time; XLA still sees static shapes per compilation bucket).
    """

    def __init__(
        self,
        block,
        name=None,
        shape=None,
        dtype="float32",
        persistable=False,
        stop_gradient=False,
        is_data=False,
        type=None,
        lod_level=0,
        initializer=None,
        **kwargs,
    ):
        self.block = block
        self.name = name or unique_name.generate("_generated_var")
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = convert_dtype(dtype) if dtype is not None else None
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.is_data = is_data
        self.type = type or "dense_tensor"
        self.lod_level = lod_level
        if initializer is not None:
            initializer(self, block)

    @property
    def program(self):
        return self.block.program

    def desc(self):
        return {
            "name": self.name,
            "shape": list(self.shape) if self.shape is not None else None,
            "dtype": self.dtype,
            "persistable": self.persistable,
            "stop_gradient": self.stop_gradient,
            "is_data": self.is_data,
            "type": self.type,
            "lod_level": self.lod_level,
            "kind": "param" if isinstance(self, Parameter) else "var",
            "trainable": getattr(self, "trainable", None),
        }

    def numel(self):
        if self.shape is None:
            return None
        n = 1
        for d in self.shape:
            n *= max(d, 1)
        return n

    def __repr__(self):
        return f"Variable(name={self.name}, shape={self.shape}, dtype={self.dtype})"

    # arithmetic sugar (reference: python/paddle/fluid/layers/math_op_patch.py)
    def _binary(self, other, op, reverse=False):
        from paddle_tpu import layers

        if not isinstance(other, Variable):
            other = layers.fill_constant(
                shape=[1], dtype=self.dtype, value=float(other)
            )
        a, b = (other, self) if reverse else (self, other)
        return layers.elementwise_op(op, a, b)

    def __add__(self, other):
        return self._binary(other, "elementwise_add")

    def __radd__(self, other):
        return self._binary(other, "elementwise_add", reverse=True)

    def __sub__(self, other):
        return self._binary(other, "elementwise_sub")

    def __rsub__(self, other):
        return self._binary(other, "elementwise_sub", reverse=True)

    def __mul__(self, other):
        return self._binary(other, "elementwise_mul")

    def __rmul__(self, other):
        return self._binary(other, "elementwise_mul", reverse=True)

    def __truediv__(self, other):
        return self._binary(other, "elementwise_div")

    def __rtruediv__(self, other):
        return self._binary(other, "elementwise_div", reverse=True)

    def __neg__(self):
        from paddle_tpu import layers

        return layers.scale(self, scale=-1.0)

    def __matmul__(self, other):
        from paddle_tpu import layers

        return layers.matmul(self, other)

    def __getitem__(self, idx):
        """Slicing sugar on static Variables (reference:
        python/paddle/fluid/framework.py Variable.__getitem__ emitting the
        slice op): ints and step-1 slices per axis; int indices squeeze
        their axis, -1 selects from the end."""
        from paddle_tpu import layers

        axes, starts, ends, squeeze_axes = parse_getitem_index(idx)
        out = (
            layers.slice(self, axes=axes, starts=starts, ends=ends)
            if axes
            else self
        )
        if squeeze_axes:
            out = layers.squeeze(out, axes=squeeze_axes)
        return out

    def __iter__(self):
        """Row iteration over a static leading dim — without this, adding
        __getitem__ would make `for v in x` append slice ops forever
        (Python's fallback protocol stops only on IndexError)."""
        from paddle_tpu.utils.enforce import enforce as _enforce

        shape = self.shape
        _enforce(
            shape is not None and len(shape) > 0,
            f"cannot iterate '{self.name}': 0-d tensors are not iterable",
        )
        _enforce(
            shape[0] is not None and shape[0] >= 0,
            f"cannot iterate '{self.name}': leading dimension is not "
            "statically known",
        )
        return (self[i] for i in range(shape[0]))


class Parameter(Variable):
    """A persistable, trainable Variable
    (reference: python/paddle/fluid/framework.py:4631)."""

    def __init__(self, block, shape, dtype, **kwargs):
        kwargs.setdefault("persistable", True)
        self.trainable = kwargs.pop("trainable", True)
        self.optimize_attr = kwargs.pop("optimize_attr", {"learning_rate": 1.0})
        self.regularizer = kwargs.pop("regularizer", None)
        self.do_model_average = kwargs.pop("do_model_average", None)
        self.is_distributed = kwargs.pop("is_distributed", False)
        super().__init__(block, shape=shape, dtype=dtype, **kwargs)


class Operator:
    """One op node: type + named input/output variable lists + attributes
    (reference: paddle/fluid/framework/framework.proto:42 OpDesc)."""

    def __init__(self, block, type, inputs=None, outputs=None, attrs=None):
        self.block = block
        self.type = type
        self.inputs = {k: list(v) for k, v in (inputs or {}).items()}
        self.outputs = {k: list(v) for k, v in (outputs or {}).items()}
        self.attrs = dict(attrs or {})
        if _current_name_scope():
            self.attrs.setdefault("op_namescope", _current_name_scope())
        self.attrs.setdefault("op_callstack", user_callstack())
        # stable per-op rng id: stateful ops fold the step key with this id,
        # so dropout masks are reproducible across pruning/replay (recompute)
        if "__rng_id__" not in self.attrs:
            self.attrs["__rng_id__"] = block.program._next_rng_id()

    def input_names(self):
        return [n for names in self.inputs.values() for n in names]

    def output_names(self):
        return [n for names in self.outputs.values() for n in names]

    def input(self, slot):
        return self.inputs.get(slot, [])

    def output(self, slot):
        return self.outputs.get(slot, [])

    def has_attr(self, name):
        return name in self.attrs

    def attr(self, name, default=None):
        return self.attrs.get(name, default)

    def set_attr(self, name, value):
        self.attrs[name] = value
        self.block.program._bump_version()

    def desc(self):
        attrs = {
            k: v
            for k, v in self.attrs.items()
            if k not in ("op_callstack",) and _json_safe(v)
        }
        return {
            "type": self.type,
            "inputs": self.inputs,
            "outputs": self.outputs,
            "attrs": attrs,
        }

    def __repr__(self):
        return f"Operator({self.type}, in={self.inputs}, out={self.outputs})"


def _json_safe(v):
    try:
        json.dumps(v)
        return True
    except (TypeError, ValueError):
        return isinstance(v, np.ndarray)


class Block:
    """vars + ops, with parent-chain lookup for sub-blocks (control flow)
    (reference: paddle/fluid/framework/framework.proto:173 BlockDesc,
    reference: paddle/fluid/framework/scope.h:46 parent-chain semantics)."""

    def __init__(self, program, idx, parent_idx=-1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars = {}
        self.ops = []
        self.forward_block_idx = -1

    @property
    def parent_block(self):
        if self.parent_idx < 0:
            return None
        return self.program.block(self.parent_idx)

    def create_var(self, **kwargs):
        name = kwargs.get("name")
        if name is not None and name in self.vars:
            return self.vars[name]
        var = Variable(self, **kwargs)
        self.vars[var.name] = var
        self.program._bump_version()
        return var

    def create_parameter(self, shape, dtype, name=None, **kwargs):
        # parameters live in the top-level (global) block, as in the reference
        global_block = self.program.global_block()
        param = Parameter(global_block, shape, dtype, name=name, **kwargs)
        global_block.vars[param.name] = param
        self.program._bump_version()
        return param

    def var(self, name):
        v = self._find_var_recursive(name)
        if v is None:
            raise EnforceError(f"Variable {name} not found in block {self.idx}")
        return v

    def has_var(self, name):
        return name in self.vars

    def _find_var_recursive(self, name):
        block = self
        while block is not None:
            if name in block.vars:
                return block.vars[name]
            block = block.parent_block
        return None

    def all_parameters(self):
        return [v for v in self.vars.values() if isinstance(v, Parameter)]

    def append_op(self, type, inputs=None, outputs=None, attrs=None):
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.append(op)
        self.program._bump_version()
        return op

    def _prepend_op(self, type, inputs=None, outputs=None, attrs=None):
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.insert(0, op)
        self.program._bump_version()
        return op

    def _insert_op(self, index, type, inputs=None, outputs=None, attrs=None):
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.insert(index, op)
        self.program._bump_version()
        return op

    def _remove_op(self, index):
        self.ops.pop(index)
        self.program._bump_version()

    def desc(self):
        return {
            "idx": self.idx,
            "parent_idx": self.parent_idx,
            "forward_block_idx": self.forward_block_idx,
            "vars": [v.desc() for v in self.vars.values()],
            "ops": [op.desc() for op in self.ops],
        }


class Program:
    """A list of blocks; block 0 is the global block
    (reference: paddle/fluid/framework/program_desc.h:30,
    reference: python/paddle/fluid/framework.py:3602)."""

    # monotonic per-process program ids: compile caches key on this instead
    # of id(program), which CPython reuses after GC
    _uid_counter = itertools.count()

    def __init__(self):
        self._rng_op_counter = 0
        self.blocks = [Block(self, 0)]
        self.current_block_idx = 0
        self._version = 0
        self._uid = next(Program._uid_counter)
        self._seed = 0
        self.random_seed = 0
        self._is_distributed = False
        self._attrs = {}

    # -- structure --------------------------------------------------------
    def global_block(self):
        return self.blocks[0]

    def block(self, idx):
        return self.blocks[idx]

    def current_block(self):
        return self.blocks[self.current_block_idx]

    def num_blocks(self):
        return len(self.blocks)

    def _create_block(self, parent_idx=None):
        new_idx = len(self.blocks)
        parent = self.current_block_idx if parent_idx is None else parent_idx
        self.blocks.append(Block(self, new_idx, parent))
        self.current_block_idx = new_idx
        self._bump_version()
        return self.current_block()

    def _rollback(self):
        self.current_block_idx = self.current_block().parent_idx

    def _bump_version(self):
        self._version += 1

    def _next_rng_id(self):
        self._rng_op_counter += 1
        return self._rng_op_counter

    def all_parameters(self):
        return self.global_block().all_parameters()

    def list_vars(self):
        for block in self.blocks:
            yield from block.vars.values()

    # -- transforms -------------------------------------------------------
    def clone(self, for_test=False):
        """Deep copy; with for_test=True, flip ops into inference mode
        (reference: python/paddle/fluid/framework.py Program.clone)."""
        p = Program.__new__(Program)
        p.__dict__.update(
            {
                k: copy.copy(v)
                for k, v in self.__dict__.items()
                if k not in ("blocks",)
            }
        )
        p._attrs = dict(self._attrs)
        p._uid = next(Program._uid_counter)
        p.blocks = []
        old_params = {
            v.name for v in self.global_block().vars.values() if isinstance(v, Parameter)
        }
        for b in self.blocks:
            nb = Block(p, b.idx, b.parent_idx)
            nb.forward_block_idx = b.forward_block_idx
            for v in b.vars.values():
                if v.name in old_params and b.idx == 0:
                    nv = Parameter(
                        nb, v.shape, v.dtype, name=v.name, trainable=v.trainable
                    )
                    nv.optimize_attr = dict(v.optimize_attr)
                    nv.regularizer = v.regularizer
                else:
                    nv = Variable(
                        nb,
                        name=v.name,
                        shape=v.shape,
                        dtype=v.dtype,
                        persistable=v.persistable,
                        stop_gradient=v.stop_gradient,
                        is_data=v.is_data,
                        type=v.type,
                        lod_level=v.lod_level,
                    )
                nv.stop_gradient = v.stop_gradient
                nb.vars[nv.name] = nv
            for op in b.ops:
                nop = Operator(nb, op.type, op.inputs, op.outputs, dict(op.attrs))
                if for_test and "is_test" in _test_mode_attrs(op.type):
                    nop.attrs["is_test"] = True
                nb.ops.append(nop)
            p.blocks.append(nb)
        if for_test:
            p._prune_backward()
        return p

    def _prune_backward(self):
        """Drop backward/optimizer ops (everything after the last forward op
        marker, or any op whose outputs are all @GRAD)."""
        for block in self.blocks:
            block.ops = [
                op
                for op in block.ops
                if not (
                    op.attrs.get("op_role", 0) in (1, 2)  # backward / optimize
                    or all(n.endswith("@GRAD") for n in op.output_names())
                    and op.output_names()
                )
            ]
        self._bump_version()

    def _prune(self, targets):
        """Prune to the subgraph needed for `targets`
        (reference: paddle/fluid/framework/prune.cc). Reads/writes are
        control-flow aware (analysis/usedef.py): a while op whose BODY
        writes a target survives, and its body's reads stay needed."""
        from paddle_tpu.analysis.usedef import build_usedef

        target_names = {t.name if isinstance(t, Variable) else t for t in targets}
        block = self.global_block()
        usedef = build_usedef(block)
        needed = set(target_names)
        kept = []
        for op in reversed(block.ops):
            if usedef.writes_of(op) & needed:
                kept.append(op)
                needed.update(usedef.reads_of(op))
        block.ops = list(reversed(kept))
        self._bump_version()
        return self

    # -- serialization ----------------------------------------------------
    def desc(self):
        return {
            "format_version": IR_FORMAT_VERSION,
            "random_seed": self.random_seed,
            "blocks": [b.desc() for b in self.blocks],
        }

    def to_bytes(self):
        return json.dumps(self.desc(), sort_keys=True).encode("utf-8")

    @staticmethod
    def from_bytes(data):
        desc = json.loads(data.decode("utf-8"))
        enforce(
            desc.get("format_version", 0) <= IR_FORMAT_VERSION,
            f"program format {desc.get('format_version')} is newer than this "
            f"framework supports ({IR_FORMAT_VERSION})",
        )
        p = Program()
        p.random_seed = desc.get("random_seed", 0)
        p.blocks = []
        for bdesc in desc["blocks"]:
            b = Block(p, bdesc["idx"], bdesc["parent_idx"])
            b.forward_block_idx = bdesc.get("forward_block_idx", -1)
            for vdesc in bdesc["vars"]:
                cls = Parameter if vdesc.get("kind") == "param" else Variable
                if cls is Parameter:
                    v = Parameter(
                        b,
                        vdesc["shape"],
                        vdesc["dtype"],
                        name=vdesc["name"],
                        trainable=vdesc.get("trainable", True),
                    )
                else:
                    v = Variable(
                        b,
                        name=vdesc["name"],
                        shape=vdesc["shape"],
                        dtype=vdesc["dtype"],
                        persistable=vdesc["persistable"],
                        stop_gradient=vdesc.get("stop_gradient", False),
                        is_data=vdesc.get("is_data", False),
                        type=vdesc.get("type", "dense_tensor"),
                        lod_level=vdesc.get("lod_level", 0),
                    )
                b.vars[v.name] = v
            for odesc in bdesc["ops"]:
                b.ops.append(
                    Operator(b, odesc["type"], odesc["inputs"], odesc["outputs"], odesc["attrs"])
                )
            p.blocks.append(b)
        # ops appended after deserialization must not collide with restored
        # __rng_id__s (correlated dropout masks/initializer streams)
        p._rng_op_counter = max(
            (op.attrs.get("__rng_id__", 0) for b in p.blocks for op in b.ops),
            default=0,
        )
        return p

    def to_string(self, throw_on_error=False):
        lines = []
        for b in self.blocks:
            lines.append(f"-- block {b.idx} (parent {b.parent_idx}) --")
            for v in b.vars.values():
                tag = "param" if isinstance(v, Parameter) else "var"
                lines.append(
                    f"  {tag} {v.name}: shape={v.shape} dtype={v.dtype}"
                    f"{' persistable' if v.persistable else ''}"
                )
            for op in b.ops:
                ins = {k: v for k, v in op.inputs.items()}
                outs = {k: v for k, v in op.outputs.items()}
                lines.append(f"  op {op.type}: {ins} -> {outs}")
        return "\n".join(lines)

    __str__ = to_string


def _test_mode_attrs(op_type):
    return {"is_test"} if op_type in _IS_TEST_OPS else set()


_IS_TEST_OPS = {"dropout", "batch_norm", "layer_norm", "data_norm"}


# ---------------------------------------------------------------------------
# process-global default programs
# (reference: python/paddle/fluid/framework.py:4845,4879)
# ---------------------------------------------------------------------------

_main_program = Program()
_startup_program = Program()


def default_main_program():
    return _main_program


def default_startup_program():
    return _startup_program


def switch_main_program(program):
    global _main_program
    old = _main_program
    _main_program = program
    return old


def switch_startup_program(program):
    global _startup_program
    old = _startup_program
    _startup_program = program
    return old


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    old_main = switch_main_program(main_program)
    old_startup = None
    if startup_program is not None:
        old_startup = switch_startup_program(startup_program)
    try:
        yield
    finally:
        switch_main_program(old_main)
        if old_startup is not None:
            switch_startup_program(old_startup)
