"""Scope: name -> device array state, with parent-chain lookup.

TPU-native analog of the reference's Scope
(reference: paddle/fluid/framework/scope.h:46). Instead of type-erased mutable
Variables, a Scope holds immutable jax.Arrays; the executor threads them
functionally through compiled steps and writes the updated arrays back, with
buffer donation standing in for in-place mutation (reference's inplace pass /
eager deletion — paddle/fluid/framework/ir/memory_optimize_pass/).
"""

import contextlib

import numpy as np


class Scope:
    def __init__(self, parent=None):
        self._vars = {}
        self.parent = parent
        self.kids = []
        # name -> {devices} the value is KNOWN to live on — the
        # steady-state dispatch fast path (Executor._committed) is then one
        # dict lookup instead of a per-step jax.Array.devices() call (~5 us
        # each; BERT threads ~600 scope entries per step). Any user-facing
        # set() invalidates; the executor re-marks values it verified or
        # produced itself.
        self._device_verified = {}
        if parent is not None:
            parent.kids.append(self)

    def new_scope(self):
        return Scope(parent=self)

    def set(self, name, value):
        self._vars[name] = value
        self._device_verified.pop(name, None)

    def _set_verified(self, name, value, device):
        """Executor-internal write-back: `value` came out of the compiled
        step (or was just committed), so it is on `device` by construction
        — and ONLY there: the verification set resets (the old value's
        devices do not describe the replacement; a stale entry would hand
        another executor a wrong-device array through the fast path)."""
        self._vars[name] = value
        self._device_verified[name] = {device}

    def _find_owner(self, name):
        scope = self
        while scope is not None:
            if name in scope._vars:
                return scope
            scope = scope.parent
        return None

    def find_var(self, name):
        owner = self._find_owner(name)
        return owner._vars[name] if owner is not None else None

    def has_var(self, name):
        return self.find_var(name) is not None

    def var_names(self):
        return list(self._vars)

    def erase(self, names):
        for n in names:
            self._vars.pop(n, None)
            self._device_verified.pop(n, None)

    def find_var_numpy(self, name):
        v = self.find_var(name)
        return None if v is None else np.asarray(v)

    def drop_kids(self):
        self.kids = []


_global_scope = Scope()


def global_scope():
    return _global_scope


@contextlib.contextmanager
def scope_guard(scope):
    global _global_scope
    old = _global_scope
    _global_scope = scope
    try:
        yield
    finally:
        _global_scope = old
