"""Scope: name -> device array state, with parent-chain lookup.

TPU-native analog of the reference's Scope
(reference: paddle/fluid/framework/scope.h:46). Instead of type-erased mutable
Variables, a Scope holds immutable jax.Arrays; the executor threads them
functionally through compiled steps and writes the updated arrays back, with
buffer donation standing in for in-place mutation (reference's inplace pass /
eager deletion — paddle/fluid/framework/ir/memory_optimize_pass/).
"""

import contextlib

import numpy as np


class Scope:
    def __init__(self, parent=None):
        self._vars = {}
        self.parent = parent
        self.kids = []
        if parent is not None:
            parent.kids.append(self)

    def new_scope(self):
        return Scope(parent=self)

    def set(self, name, value):
        self._vars[name] = value

    def find_var(self, name):
        scope = self
        while scope is not None:
            if name in scope._vars:
                return scope._vars[name]
            scope = scope.parent
        return None

    def has_var(self, name):
        return self.find_var(name) is not None

    def var_names(self):
        return list(self._vars)

    def erase(self, names):
        for n in names:
            self._vars.pop(n, None)

    def find_var_numpy(self, name):
        v = self.find_var(name)
        return None if v is None else np.asarray(v)

    def drop_kids(self):
        self.kids = []


_global_scope = Scope()


def global_scope():
    return _global_scope


@contextlib.contextmanager
def scope_guard(scope):
    global _global_scope
    old = _global_scope
    _global_scope = scope
    try:
        yield
    finally:
        _global_scope = old
