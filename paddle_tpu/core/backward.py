"""Tape-free autodiff by program rewriting.

The same architecture as the reference's append_backward
(reference: python/paddle/fluid/backward.py:1139 — walk forward ops in
reverse, emit grad OpDescs, sum-aggregate repeated gradients :361) with one
TPU-native twist: instead of 560 hand-written grad kernels, the grad op for
any forward op is synthesized from its jax lowering rule via jax.vjp at
lowering time (see synthesize_grad_op_def). Because the whole block compiles
as one XLA computation, the recomputed forward primals inside each vjp are
CSE'd against the forward pass — zero duplicate FLOPs after XLA optimization.
Ops can still override with a hand-written grad lowering
(register_grad, the analog of reference grad_op_desc_maker.h), e.g. dropout
reusing its saved mask.

Grad op calling convention (desc-level):
  type:    f"{fwd_type}_grad"
  inputs:  every forward input slot, every forward output slot, plus
           f"{out_slot}@GRAD" per forward output slot that has a gradient
  outputs: f"{in_slot}@GRAD" per forward input slot needing a gradient
  attrs:   forward attrs + __fwd_inputs__/__fwd_outputs__ slot lists
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core.ir import Parameter
from paddle_tpu.core.registry import OpDef, OpRegistry
from paddle_tpu.utils.enforce import EnforceError, enforce

_OP_ROLE_FORWARD = 0
_OP_ROLE_BACKWARD = 1
_OP_ROLE_OPTIMIZE = 2
_OP_ROLE_LOSS = 256


# ---------------------------------------------------------------------------
# generic grad lowering via jax.vjp
# ---------------------------------------------------------------------------


def _is_diff(x):
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)


def make_generic_grad_lowering(base, use_pallas=False):
    """Differentiate `base`'s lowering via jax.vjp. `use_pallas` selects which
    forward path the vjp traces: the grad op must differentiate the SAME
    lowering the forward ran, or a Pallas custom_vjp (e.g. flash attention's
    blocked backward) silently degrades to re-tracing the unfused reference
    path — recomputing the forward AND materializing the buffers the kernel
    exists to avoid (caught by tests/test_hlo.py)."""

    def lower(ins, attrs):
        fwd_in_slots = [s for s in attrs["__fwd_inputs__"] if s in ins]
        fwd_out_slots = attrs["__fwd_outputs__"]
        fwd_ins = {s: ins[s] for s in fwd_in_slots}
        # a slot participates if ANY member is floating; non-float members
        # (e.g. int32 indices mixed into py_func's X) are frozen per-element
        # and get zero grads, so the emitted @GRAD list stays aligned with
        # the forward member list
        diff_slots = [
            s
            for s in fwd_in_slots
            if s not in base.nondiff_inputs and any(_is_diff(x) for x in fwd_ins[s])
        ]
        if not diff_slots:
            return {}
        diff_idx = {
            s: [i for i, x in enumerate(fwd_ins[s]) if _is_diff(x)]
            for s in diff_slots
        }
        frozen = {s: fwd_ins[s] for s in fwd_in_slots if s not in diff_slots}
        clean_attrs = {k: v for k, v in attrs.items() if not k.startswith("__")}

        def f(diff_part):
            full = dict(frozen)
            for s, vals in diff_part.items():
                members = list(fwd_ins[s])
                for j, i in enumerate(diff_idx[s]):
                    members[i] = vals[j]
                full[s] = members
            if "__rng_key__" in ins:
                full["__rng_key__"] = ins["__rng_key__"]
            outs = base.lowering(use_pallas)(full, clean_attrs)
            result = {}
            for s in fwd_out_slots:
                if s in outs:
                    vals = outs[s]
                    result[s] = list(vals) if isinstance(vals, (list, tuple)) else [vals]
            return result

        primal_in = {
            s: [fwd_ins[s][i] for i in diff_idx[s]] for s in diff_slots
        }
        primal_out, vjp = jax.vjp(f, primal_in)
        cotangents = {}
        for s, primals in primal_out.items():
            gslot = f"{s}@GRAD"
            given = ins.get(gslot)
            cots = []
            for i, p in enumerate(primals):
                if given is not None and i < len(given) and given[i] is not None:
                    cots.append(given[i].astype(p.dtype))
                else:
                    cots.append(jnp.zeros_like(p))
            cotangents[s] = cots
        (gins,) = vjp(cotangents)
        result = {}
        for s in diff_slots:
            idx = set(diff_idx[s])
            it = iter(gins[s])
            result[f"{s}@GRAD"] = [
                next(it) if i in idx else jnp.zeros_like(jnp.asarray(x))
                for i, x in enumerate(fwd_ins[s])
            ]
        return result

    return lower


_GRAD_DEF_CACHE = {}


def resolve_op_def(op_type):
    """Registry lookup that lazily synthesizes `<type>_grad` defs."""
    if OpRegistry.has(op_type):
        return OpRegistry.get(op_type)
    if op_type.endswith("_grad"):
        cached = _GRAD_DEF_CACHE.get(op_type)
        if cached is not None:
            return cached
        base_type = op_type[: -len("_grad")]
        if OpRegistry.has(base_type):
            base = OpRegistry.get(base_type)
            if base.grad is not None:
                lower, pallas_lower = base.grad, None
            else:
                lower = make_generic_grad_lowering(base, use_pallas=False)
                # keep fwd/bwd path selection consistent under the executor's
                # use_pallas toggle: the pallas-variant grad differentiates the
                # pallas forward (whose custom_vjp supplies the blocked bwd)
                pallas_lower = (
                    make_generic_grad_lowering(base, use_pallas=True)
                    if base.pallas is not None
                    else None
                )
            gdef = OpDef(
                op_type, lower, pallas=pallas_lower, stateful=base.stateful,
                needs_block=base.needs_block,
            )
            _GRAD_DEF_CACHE[op_type] = gdef
            return gdef
    raise EnforceError(f"op {op_type} is not registered")


# ---------------------------------------------------------------------------
# append_backward
# ---------------------------------------------------------------------------


def _requires_grad_vars(block, ops, no_grad_set, extra_seeds=()):
    """Forward propagation of the requires-grad property. `extra_seeds` are
    vars the caller wants gradients for even if they are not leafs (the
    gradients() API on intermediate activations)."""
    produced = {n for op in ops for n in op.output_names()}
    requires = set(extra_seeds)
    for v in block.vars.values():
        if v.name in no_grad_set:
            continue
        if isinstance(v, Parameter) and v.trainable:
            requires.add(v.name)
        elif not v.stop_gradient and v.name not in produced:
            # leaf inputs explicitly marked differentiable (gradients() API)
            requires.add(v.name)
    for op in ops:
        if any(n in requires for n in op.input_names()):
            for n in op.output_names():
                v = block._find_var_recursive(n)
                if n in no_grad_set or (v is not None and v.stop_gradient):
                    continue
                requires.add(n)
    return requires


def _is_float_var(block, name, default=True):
    v = block._find_var_recursive(name)
    if v is None or v.dtype is None:
        return default
    return "float" in str(v.dtype)


#: op types that must not be folded into a recompute segment (they run
#: sub-blocks or have host side effects)
_NO_SEGMENT_OPS = {"while", "conditional_block", "recurrent", "print", "py_func"}


def _make_segment_op(block, seg_ops, ckpt_set, loss_name, requires, readers):
    """Collapse `seg_ops` (consecutive forward ops) into one pseudo
    recompute_segment op; its grad op replays the segment at backward time
    (ops/recompute.py). Only the segment's boundary values stay live across
    fwd->bwd — the remat analog of the reference's checkpoint re-emission
    (reference: python/paddle/fluid/backward.py:618). `readers` maps
    name -> set of reader op ids over the whole block (precomputed once so
    segmentation stays linear in block size)."""
    from paddle_tpu.core.ir import Operator

    seg_ids = {id(o) for o in seg_ops}
    in_names, inner_produced = [], set()
    for o in seg_ops:
        for n in o.input_names():
            if n not in inner_produced and n not in in_names:
                in_names.append(n)
        inner_produced.update(o.output_names())

    def read_outside(n):
        return bool(readers.get(n, set()) - seg_ids)
    out_names = []
    for o in seg_ops:
        for n in o.output_names():
            if n in out_names:
                continue
            v = block._find_var_recursive(n)
            if (
                read_outside(n)
                or n in ckpt_set
                or n == loss_name
                or (v is not None and v.persistable)
            ):
                out_names.append(n)
    segment = [
        (
            o.type,
            {k: list(v) for k, v in o.inputs.items()},
            {k: list(v) for k, v in o.outputs.items()},
            {k: v for k, v in o.attrs.items() if k != "op_callstack"},
        )
        for o in seg_ops
    ]
    # IR-keyed remat policy (paddle_tpu/kernels/remat.py): the policy
    # rides in op attrs (so a flip retraces via the content-addressed
    # cache), together with the NAME lists of what each policy would
    # additionally pin across fwd->bwd. analysis/memory.py resolves
    # those names through its feed-bound, shard-aware shape report (the
    # forward ops stay in the program, so the names keep inferred
    # shapes) and adds the chosen policy's bytes to every program point
    # between the segment's end and its grad op — predicting the
    # peak-HBM delta of a policy change before any compile.
    from paddle_tpu.kernels import remat as _remat

    policy = _remat.validate_policy(
        getattr(block.program, "_recompute_policy", None)
        or _remat.DEFAULT_POLICY
    )
    out_set = set(out_names)
    dot_ops = {"mul", "matmul", "matmul_v2", "conv2d", "depthwise_conv2d"}
    dots_saved, all_saved = [], []
    for o in seg_ops:
        for n in o.output_names():
            if n in out_set:
                continue           # boundary values are saved regardless
            all_saved.append(n)
            if o.type in dot_ops:
                dots_saved.append(n)
    attrs = {
        "__segment__": segment,
        "__in_names__": list(in_names),
        "__out_names__": list(out_names),
        "__diff_ins__": [
            n for n in in_names if n in requires and _is_float_var(block, n)
        ],
        "__diff_outs__": [n for n in out_names if _is_float_var(block, n)],
        "__remat_policy__": policy,
        "__segment_saved_names__": {
            "full": [], "dots": dots_saved, "dots_no_batch": dots_saved,
            "save_all": all_saved,
        },
    }
    return Operator(
        block, "recompute_segment", {"X": in_names}, {"Out": out_names}, attrs
    )


def _collapse_segments(block, ops, checkpoints, loss_name, requires):
    """Greedy segmentation of the relevant forward ops: a segment closes at
    each op producing a checkpoint var; control-flow/side-effect ops stay
    outside segments; 1-op segments aren't worth a replay."""
    ckpt_set = set(checkpoints)
    walk, cur = [], []
    # control-flow-aware readers (analysis/usedef.py): a var read inside a
    # while/cond body counts its control-flow op as a reader, so a segment
    # producing it keeps it as a boundary output instead of replay-privat-
    # izing a value a sub-block needs
    from paddle_tpu.analysis.usedef import build_usedef

    readers = {
        n: {id(c) for c in cons}
        for n, cons in build_usedef(block).consumers.items()
    }

    def flush():
        nonlocal cur
        if len(cur) >= 2:
            walk.append(
                _make_segment_op(block, cur, ckpt_set, loss_name, requires, readers)
            )
        else:
            walk.extend(cur)
        cur = []

    for op in ops:
        if op.type in _NO_SEGMENT_OPS:
            flush()
            walk.append(op)
            continue
        cur.append(op)
        if any(n in ckpt_set for n in op.output_names()):
            flush()
    flush()
    return walk


def _create_grad_var(block, fwd_name, grad_name):
    if grad_name in block.vars:
        return block.vars[grad_name]
    fwd = block._find_var_recursive(fwd_name)
    return block.create_var(
        name=grad_name,
        shape=fwd.shape if fwd is not None else None,
        dtype=fwd.dtype if fwd is not None else "float32",
        persistable=False,
        stop_gradient=True,
    )


def append_backward(
    loss, parameter_list=None, no_grad_set=None, callbacks=None, extra_seeds=()
):
    """Append grad ops for `loss` to its program; returns [(param, grad)].

    reference: python/paddle/fluid/backward.py:1139.
    """
    block = loss.block
    program = block.program
    no_grad_set = set(no_grad_set or ())
    enforce(
        loss.shape is None or all(d == 1 or d == -1 for d in loss.shape),
        f"loss must be scalar-like, got shape {loss.shape}",
    )

    fwd_ops = list(block.ops)
    # find the op producing the loss; everything after it is irrelevant
    loss_op_idx = None
    for i in reversed(range(len(fwd_ops))):
        if loss.name in fwd_ops[i].output_names():
            loss_op_idx = i
            break
    enforce(loss_op_idx is not None, f"loss var {loss.name} has no producer op")
    fwd_ops = fwd_ops[: loss_op_idx + 1]
    if fwd_ops:
        fwd_ops[-1].attrs["op_role"] = _OP_ROLE_LOSS

    requires = _requires_grad_vars(block, fwd_ops, no_grad_set, extra_seeds)

    # relevance: ops on a path from requires-grad vars to the loss
    pending = {loss.name}
    relevant = []
    for op in reversed(fwd_ops):
        if op.type in ("feed", "fetch"):
            continue
        if any(n in pending for n in op.output_names()) and any(
            n in requires for n in op.input_names()
        ):
            relevant.append(op)
            pending.update(n for n in op.input_names() if n in requires)
    relevant_set = set(id(op) for op in relevant)

    # partial-gradient bookkeeping: var -> list of partial grad var names
    partials = {}

    def finalize(name):
        """Collapse partial grads for `name` into the canonical `name@GRAD`,
        inserting a sum op when there are multiple contributions
        (reference: python/paddle/fluid/backward.py:361)."""
        canonical = name + "@GRAD"
        plist = partials.get(name)
        if not plist:
            return None
        if len(plist) == 1:
            if plist[0] != canonical:
                _create_grad_var(block, name, canonical)
                block.append_op(
                    "assign",
                    inputs={"X": [plist[0]]},
                    outputs={"Out": [canonical]},
                    attrs={"op_role": _OP_ROLE_BACKWARD},
                )
            partials[name] = [canonical]
            return canonical
        _create_grad_var(block, name, canonical)
        block.append_op(
            "sum",
            inputs={"X": list(plist)},
            outputs={"Out": [canonical]},
            attrs={"op_role": _OP_ROLE_BACKWARD},
        )
        partials[name] = [canonical]
        return canonical

    def add_partial(name, producing_op_hint):
        canonical = name + "@GRAD"
        existing = partials.setdefault(name, [])
        pname = canonical if not existing else f"{name}@GRAD@RENAME@{len(existing)}"
        existing.append(pname)
        _create_grad_var(block, name, pname)
        return pname

    # seed: d loss / d loss = 1
    loss_grad_name = loss.name + "@GRAD"
    _create_grad_var(block, loss.name, loss_grad_name)
    block.append_op(
        "fill_constant",
        inputs={},
        outputs={"Out": [loss_grad_name]},
        attrs={
            "shape": list(loss.shape) if loss.shape else [1],
            "dtype": loss.dtype,
            "value": 1.0,
            "op_role": _OP_ROLE_BACKWARD,
        },
    )
    partials[loss.name] = [loss_grad_name]

    ordered_relevant = [op for op in fwd_ops if id(op) in relevant_set]
    checkpoints = getattr(program, "_recompute_checkpoints", None)
    if checkpoints:
        walk_ops = _collapse_segments(
            block, ordered_relevant, checkpoints, loss.name, requires
        )
    else:
        walk_ops = ordered_relevant

    for op in reversed(walk_ops):
        # outputs' grads must be finalized before this op's grad runs
        out_grad_slots = {}
        has_any = False
        for slot, names in op.outputs.items():
            gnames = []
            for n in names:
                g = finalize(n)
                gnames.append(g)
                if g is not None:
                    has_any = True
            out_grad_slots[slot] = gnames
        if not has_any:
            continue
        grad_inputs = {}
        for slot, names in op.inputs.items():
            grad_inputs[slot] = list(names)
        for slot, names in op.outputs.items():
            grad_inputs[slot] = list(names)
            gnames = out_grad_slots[slot]
            if any(g is not None for g in gnames):
                filled = []
                for i, g in enumerate(gnames):
                    if g is None:
                        # zero-fill grads for unused sibling outputs so the
                        # slot stays well-formed in the desc
                        zname = f"{names[i]}@GRAD@ZERO"
                        _create_grad_var(block, names[i], zname)
                        block.append_op(
                            "fill_zeros_like",
                            inputs={"X": [names[i]]},
                            outputs={"Out": [zname]},
                            attrs={"op_role": _OP_ROLE_BACKWARD},
                        )
                        filled.append(zname)
                    else:
                        filled.append(g)
                grad_inputs[f"{slot}@GRAD"] = filled
        grad_outputs = {}
        for slot, names in op.inputs.items():
            gnames = []
            for n in names:
                v = block._find_var_recursive(n)
                if (
                    n in requires
                    and n not in no_grad_set
                    and not (v is not None and v.stop_gradient and not isinstance(v, Parameter))
                ):
                    gnames.append(add_partial(n, op))
                else:
                    gnames.append(None)
            if any(g is not None for g in gnames):
                grad_outputs[f"{slot}@GRAD"] = [
                    g if g is not None else f"{names[i]}@GRAD@UNUSED"
                    for i, g in enumerate(gnames)
                ]
                for i, g in enumerate(gnames):
                    if g is None:
                        _create_grad_var(block, names[i], f"{names[i]}@GRAD@UNUSED")
        if not grad_outputs:
            continue
        grad_attrs = {
            k: v for k, v in op.attrs.items() if k != "op_callstack"
        }
        grad_attrs["__fwd_inputs__"] = list(op.inputs.keys())
        grad_attrs["__fwd_outputs__"] = list(op.outputs.keys())
        grad_attrs["op_role"] = _OP_ROLE_BACKWARD
        block.append_op(
            f"{op.type}_grad",
            inputs=grad_inputs,
            outputs=grad_outputs,
            attrs=grad_attrs,
        )

    # multi-consumer extra seeds (gradients() on intermediates) may still
    # hold unsummed partials — their producer op need not be relevant
    for name in extra_seeds:
        finalize(name)

    # finalize any leaf grads never finalized (params consumed once)
    params_and_grads = []
    if parameter_list is not None:
        params = [
            block._find_var_recursive(p) if isinstance(p, str) else p
            for p in parameter_list
        ]
    else:
        params = [
            v
            for v in block.program.global_block().vars.values()
            if isinstance(v, Parameter) and v.trainable
        ]
    for p in params:
        if p.name in no_grad_set:
            continue
        g = finalize(p.name)
        if g is not None:
            params_and_grads.append((p, block.vars[g]))
    return params_and_grads


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """Compute d(targets)/d(inputs) (reference: python/paddle/fluid/
    backward.py:1672). Currently supports a single scalar target."""
    target = targets[0] if isinstance(targets, (list, tuple)) else targets
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    block = target.block
    for v in inputs:
        v.stop_gradient = False
    append_backward(
        target,
        parameter_list=None,
        no_grad_set=no_grad_set,
        extra_seeds=[v.name for v in inputs],
    )
    out = []
    for v in inputs:
        # intermediate (non-leaf) targets never hit the param finalize loop;
        # collapse their partial grads explicitly
        gname = v.name + "@GRAD"
        grad_var = block.vars.get(gname)
        out.append(grad_var)
    return out
