"""Variable type taxonomy and dtype conversion.

Mirrors the surface of the reference's VarType proto
(reference: paddle/fluid/framework/framework.proto:104) mapped onto numpy/jax
dtypes. bfloat16 is first-class — it is the TPU-native low-precision type.
"""

import numpy as np

try:
    import jax.numpy as jnp

    _BF16 = jnp.bfloat16
except Exception:  # pragma: no cover
    _BF16 = None


class VarType:
    # tensor element types
    BOOL = "bool"
    INT8 = "int8"
    UINT8 = "uint8"
    INT16 = "int16"
    INT32 = "int32"
    INT64 = "int64"
    FP16 = "float16"
    BF16 = "bfloat16"
    FP32 = "float32"
    FP64 = "float64"
    # variable kinds (reference framework.proto:122-140)
    DENSE_TENSOR = "dense_tensor"
    SELECTED_ROWS = "selected_rows"
    READER = "reader"
    STEP_SCOPES = "step_scopes"
    RAW = "raw"


_ALIASES = {
    "float": "float32",
    "double": "float64",
    "half": "float16",
    "bf16": "bfloat16",
    "int": "int32",
    "long": "int64",
}

_FLOAT_TYPES = {"float16", "bfloat16", "float32", "float64"}


def convert_dtype(dtype):
    """Normalize any dtype spec (str / np.dtype / jnp dtype) to a canonical
    string name."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        name = _ALIASES.get(dtype, dtype)
    elif _BF16 is not None and dtype == _BF16:
        name = "bfloat16"
    else:
        name = np.dtype(dtype).name
    name = _ALIASES.get(name, name)
    return name


def to_numpy_dtype(dtype):
    name = convert_dtype(dtype)
    if name == "bfloat16":
        return _BF16
    return np.dtype(name)


def is_float_dtype(dtype):
    return convert_dtype(dtype) in _FLOAT_TYPES


def is_integer_dtype(dtype):
    return convert_dtype(dtype) in {"int8", "uint8", "int16", "int32", "int64"}


_DTYPE_BYTES = {
    "float64": 8, "float32": 4, "bfloat16": 2, "float16": 2,
    "int64": 8, "uint64": 8, "int32": 4, "uint32": 4, "int16": 2,
    "uint16": 2, "int8": 1, "uint8": 1, "bool": 1,
}


def dtype_size(dtype, default=4):
    """Bytes per element for a framework dtype name. The ONE size table the
    static analyzers (analysis/sharding.py, analysis/memory.py) share —
    byte predictions cross-validated against utils/hlo.py must not drift
    because two hand-copies disagree. (utils/hlo.py keeps its own table
    keyed by HLO shorthand: f32/s32/pred is a different name universe.)"""
    try:
        name = convert_dtype(dtype)
    except Exception:
        return default
    return _DTYPE_BYTES.get(name, default)
