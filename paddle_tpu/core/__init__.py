from paddle_tpu.core.places import TPUPlace, CPUPlace, Place, is_compiled_with_tpu
from paddle_tpu.core.dtypes import VarType, convert_dtype
from paddle_tpu.core.ir import (
    Program,
    Block,
    Operator,
    Variable,
    Parameter,
    program_guard,
    default_main_program,
    default_startup_program,
    switch_main_program,
    switch_startup_program,
    name_scope,
)
from paddle_tpu.core.scope import Scope, global_scope, scope_guard
from paddle_tpu.core.registry import OpDef, register_op, get_op_def, has_op_def, OpRegistry
