"""Content-addressed compile cache: one fingerprint, three tiers.

The reference keeps one ExecutorPrepareContext cache per executor
(reference: paddle/fluid/framework/executor.cc) — in-memory, per-object,
gone on restart. Here the unit of caching is the LOWERED STEP (the whole
block compiled to one XLA computation), keyed by a content-addressed
**program fingerprint** so train (Executor), data-parallel train
(CompiledProgram), and serving (Predictor) share entries, and a restarted
process re-enters its step without a retrace:

- tier 1: a process-wide in-memory map fingerprint -> LoweredStep, shared
  by every Executor/Predictor/CompiledProgram in the process;
- tier 2: an on-disk persistent cache (``PADDLE_TPU_CACHE_DIR``) holding
  ``jax.export``-serialized StableHLO, written atomically with a CRC32
  like incubate/checkpoint.py — a corrupt or truncated entry is
  quarantined and silently falls back to a fresh trace, never a crash or
  a wrong answer;
- tier 3: XLA's own persistent compilation cache (enabled under the same
  directory) so even the StableHLO->executable compile is reused across
  processes.

The fingerprint covers everything that can change the compiled artifact:
the serialized block desc, feed/fetch signature, scope-input
shapes/dtypes, the donation plan, the lowering-relevant flags, the mesh
and sharding specs, and the jax version + backend — so a jax upgrade or a
backend switch misses cleanly instead of deserializing a stale module.

Concurrent lowerings of the SAME fingerprint are single-flighted: the
first caller traces (or loads), the rest wait and share the result — the
replica-warmup compile storm (N clones x same bucket) collapses to one
compile.
"""

import hashlib
import json
import os
import struct
import threading
import time
import zlib

from paddle_tpu.observability import lockdep as _lockdep

__all__ = [
    "program_fingerprint",
    "cache_dir",
    "get_or_build",
    "load_persistent",
    "store_persistent",
    "clear_memory_cache",
    "stats",
]

_MAGIC = b"PTCC1\n"
_ENTRY_SUFFIX = ".ptcc"

# tier-1 memory cache + single-flight registry (process-wide). LRU with
# a cap: unlike the old per-Executor/Predictor caches (freed with their
# owner), this map outlives every caller — a model-cycling server must
# not accumulate executables forever. Eviction only costs a recompile
# (or a disk-tier reload).
_MEM_CAP = 512
_mem = {}  # insertion/use-ordered: dict move-to-end via pop+reinsert
_inflight = {}
_lock = _lockdep.named_lock("compile.cache")

# lazily-created metric handles: avoid registering registry series in
# processes that never build an entry (the lockdep import above pulls
# the observability package at module import, so availability is no
# longer the concern — series hygiene is)
_counters = {}


def _counter(name, help_):
    c = _counters.get(name)
    if c is None:
        from paddle_tpu.observability import metrics as obs_metrics

        c = obs_metrics.registry().counter(name, help_)
        _counters[name] = c
    return c


def cache_dir():
    """The persistent cache directory, or None when disabled. Read per
    call (not latched at import) so tests and launchers can flip
    ``PADDLE_TPU_CACHE_DIR`` per process without re-importing."""
    d = os.environ.get("PADDLE_TPU_CACHE_DIR", "").strip()
    return d or None


_xla_cache_wired = set()


def _wire_xla_cache(d):
    """Point jax's own persistent compilation cache at our directory so a
    disk hit skips the XLA compile too, not just the Python trace. Best
    effort: unsupported knobs on an older/newer jax just leave tier 3
    off."""
    if d in _xla_cache_wired:
        return
    _xla_cache_wired.add(d)
    import jax

    for knob, val in (
        ("jax_compilation_cache_dir", os.path.join(d, "xla")),
        ("jax_persistent_cache_min_compile_time_secs", 0.0),
        ("jax_persistent_cache_min_entry_size_bytes", 0),
    ):
        try:
            jax.config.update(knob, val)
        except Exception:
            pass


# ---------------------------------------------------------------------------
# fingerprint
# ---------------------------------------------------------------------------

#: flags that change the emitted computation (ops/ lowering rules read
#: these); check_nan_inf/benchmark route to the interpreted path and never
#: reach the compiled cache
_LOWERING_FLAGS = (
    "use_donation",
    "amp_dtype",
    "rng_impl",
    "sparse_embedding_update",
    "pallas_sparse_update",
    "pallas_dgc_topk",
    "dgc_sparse_exchange",
)


def _mesh_desc(mesh):
    if mesh is None:
        return None
    return {
        "axis_names": list(mesh.axis_names),
        "shape": list(mesh.devices.shape),
        "device_kinds": sorted(
            {getattr(d, "device_kind", str(d.platform)) for d in mesh.devices.flat}
        ),
    }


def program_fingerprint(
    program,
    feed_sig,
    fetch_names,
    scope_sig=(),
    *,
    donate=True,
    mesh=None,
    sharding_sig=None,
    layout_sig=None,
    kernel_sig=None,
    extra=(),
):
    """Content-addressed identity of one lowered step.

    ``feed_sig``/``scope_sig`` are (name, shape, dtype) tuples;
    ``sharding_sig`` any JSON-able description of the partition specs;
    ``layout_sig`` the SpecLayout registry fingerprint when placement
    came from the canonical sharding layer (parallel/spec_layout.py) —
    editing a role's spec must retrace even though the per-step
    sharding_sig already covers the RESOLVED specs (the layout also owns
    future placement of vars this step does not touch, and two processes
    with the same layout must agree on the fingerprint without resolving
    first). The jax version and backend are always mixed in: a version
    bump or a backend switch invalidates every persisted entry (fall
    back to retrace — never a wrong answer from a stale module)."""
    import jax

    from paddle_tpu.utils.flags import flags

    payload = {
        "ir": None,  # filled below as raw bytes, hashed separately
        "feed_sig": [[n, list(s), str(d)] for n, s, d in feed_sig],
        "fetch": list(fetch_names),
        "scope_sig": [[n, list(s), str(d)] for n, s, d in scope_sig],
        "donate": bool(donate),
        "flags": {f: getattr(flags, f) for f in _LOWERING_FLAGS},
        "mesh": _mesh_desc(mesh),
        "shardings": sharding_sig,
        "jax": jax.__version__,
        "backend": jax.default_backend(),
        "extra": list(extra),
    }
    if layout_sig is not None:
        # added only when a registry drives placement, so fingerprints of
        # layout-less lowerings (everything the persistent tier holds)
        # are byte-identical to pre-registry revisions — a deploy of this
        # code does not cold-miss an existing PADDLE_TPU_CACHE_DIR
        payload["layout"] = layout_sig
    if kernel_sig is not None:
        # same discipline for the Pallas kernel registry
        # (paddle_tpu/kernels/): None whenever every kernel resolves to
        # its composite fallback, so kernel-less fingerprints stay
        # byte-identical to pre-registry revisions; any active kernel
        # selection (mode x registry content) retraces cleanly
        payload["kernels"] = kernel_sig
    h = hashlib.sha256()
    h.update(program.to_bytes())
    h.update(b"\0")
    h.update(json.dumps(payload, sort_keys=True, default=str).encode("utf-8"))
    return h.hexdigest()


# ---------------------------------------------------------------------------
# tier 2: on-disk entries (atomic write + CRC, checkpoint.py discipline)
# ---------------------------------------------------------------------------


def _entry_path(d, fingerprint):
    return os.path.join(d, fingerprint + _ENTRY_SUFFIX)


def store_persistent(fingerprint, header, payload):
    """Atomically persist one serialized executable. ``header`` is a
    JSON-able dict (plan lists, versions); ``payload`` the jax.export
    bytes. Layout: MAGIC | u32 header_len | header JSON | payload, with
    the payload CRC32 + length recorded in the header so truncation and
    bit-rot are detected before deserialization. Best effort: any IO
    failure leaves the cache cold, never breaks the step."""
    d = cache_dir()
    if d is None:
        return False
    try:
        os.makedirs(d, exist_ok=True)
        header = dict(header)
        header["fingerprint"] = fingerprint
        header["payload_crc32"] = zlib.crc32(payload) & 0xFFFFFFFF
        header["payload_len"] = len(payload)
        header["created"] = time.time()
        hbytes = json.dumps(header, sort_keys=True).encode("utf-8")
        final = _entry_path(d, fingerprint)
        tmp = final + f".tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(_MAGIC)
            f.write(struct.pack(">I", len(hbytes)))
            f.write(hbytes)
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, final)
        _counter("compile_cache_persistent_stores_total",
                 "persisted compile-cache entries written").inc()
        return True
    except OSError:
        _counter("compile_cache_persistent_errors_total",
                 "persistent compile-cache IO/corruption events").inc()
        return False


def _quarantine(path):
    """Keep the bad bytes for forensics, out of the lookup path (the
    checkpoint.py ``*.corrupt`` convention)."""
    try:
        os.replace(path, path + ".corrupt")
    except OSError:
        pass


def load_persistent(fingerprint):
    """Load one entry; returns (header, payload) or None. A missing file
    is a plain miss; a corrupt/truncated/mismatched one is quarantined
    and reported as a miss — the caller falls back to a fresh trace."""
    d = cache_dir()
    if d is None:
        return None
    path = _entry_path(d, fingerprint)
    if not os.path.exists(path):
        return None
    try:
        with open(path, "rb") as f:
            magic = f.read(len(_MAGIC))
            if magic != _MAGIC:
                raise ValueError("bad magic")
            (hlen,) = struct.unpack(">I", f.read(4))
            header = json.loads(f.read(hlen).decode("utf-8"))
            payload = f.read()
        if header.get("fingerprint") != fingerprint:
            raise ValueError("fingerprint mismatch")
        if len(payload) != header.get("payload_len"):
            raise ValueError(
                f"payload is {len(payload)} bytes, header says "
                f"{header.get('payload_len')} (torn write)"
            )
        if (zlib.crc32(payload) & 0xFFFFFFFF) != header.get("payload_crc32"):
            raise ValueError("payload CRC mismatch")
        return header, payload
    except (OSError, ValueError, KeyError, struct.error,
            json.JSONDecodeError) as e:
        _counter("compile_cache_persistent_errors_total",
                 "persistent compile-cache IO/corruption events").inc()
        import logging

        logging.getLogger("paddle_tpu.compile_cache").warning(
            "quarantining corrupt compile-cache entry %s (%s); retracing",
            path, e,
        )
        _quarantine(path)
        return None


# ---------------------------------------------------------------------------
# tier 1 + single-flight
# ---------------------------------------------------------------------------


class _Flight:
    __slots__ = ("event", "result", "exc")

    def __init__(self):
        self.event = threading.Event()
        self.result = None
        self.exc = None


def get_or_build(fingerprint, build):
    """Memory-cache lookup with single-flight build.

    Returns (entry, source) where source is "memory" or whatever
    ``build()`` reported for the entry it produced ("disk"/"trace" — the
    entry's own ``source`` attribute). Concurrent callers with the same
    fingerprint share ONE ``build()``; distinct fingerprints build in
    parallel. A failed build propagates its exception to every waiter and
    leaves the cache cold (the next call retries)."""
    d = cache_dir()
    if d is not None:
        _wire_xla_cache(d)
    while True:
        with _lock:
            entry = _mem.pop(fingerprint, None)
            if entry is not None:
                _mem[fingerprint] = entry  # LRU touch: newest position
                return entry, "memory"
            flight = _inflight.get(fingerprint)
            if flight is None:
                flight = _Flight()
                _inflight[fingerprint] = flight
                leader = True
            else:
                leader = False
        if leader:
            try:
                entry = build()
            except BaseException as e:
                with _lock:
                    _inflight.pop(fingerprint, None)
                flight.exc = e
                flight.event.set()
                raise
            with _lock:
                _mem[fingerprint] = entry
                while len(_mem) > _MEM_CAP:
                    _mem.pop(next(iter(_mem)))  # evict least recently used
                _inflight.pop(fingerprint, None)
            flight.result = entry
            flight.event.set()
            return entry, getattr(entry, "source", "trace")
        flight.event.wait()
        if flight.exc is not None:
            raise flight.exc
        if flight.result is not None:
            return flight.result, "memory"
        # leader failed between registry pop and event set: retry


def clear_memory_cache():
    """Drop tier 1 (tests; also frees executables for long-lived
    processes that served many shapes)."""
    with _lock:
        _mem.clear()


def stats():
    with _lock:
        return {"memory_entries": len(_mem), "inflight": len(_inflight)}
