"""Device identity ("Place") and device discovery.

TPU-native analog of the reference's Place variant
(reference: paddle/fluid/platform/place.h:79 — CUDAPlace/CPUPlace/
CUDAPinnedPlace) with TPUPlace replacing CUDAPlace, and of device discovery in
``InitDevices`` (reference: paddle/fluid/platform/init.cc:116). Discovery here
goes through the PJRT client that jax exposes rather than the CUDA driver.
"""

import functools


class Place:
    _kind = "undefined"

    def __init__(self, device_id=0):
        self.device_id = device_id

    def __eq__(self, other):
        return type(self) is type(other) and self.device_id == other.device_id

    def __hash__(self):
        return hash((self._kind, self.device_id))

    def __repr__(self):
        return f"{type(self).__name__}({self.device_id})"


class CPUPlace(Place):
    _kind = "cpu"

    def __init__(self):
        super().__init__(0)

    def jax_device(self):
        import jax

        return jax.devices("cpu")[0] if "cpu" in _platforms() else jax.devices()[0]


class TPUPlace(Place):
    """One TPU chip, identified by its index in the local PJRT device list."""

    _kind = "tpu"

    def jax_device(self):
        import jax

        devs = _accelerator_devices()
        if not devs:
            # CPU fallback keeps programs runnable on hosts without a TPU
            # (tests force JAX_PLATFORMS=cpu with a virtual 8-device mesh).
            devs = jax.devices()
        return devs[self.device_id % len(devs)]


@functools.lru_cache(maxsize=None)
def _platforms():
    import jax

    return {d.platform for d in jax.devices()}


def _accelerator_devices():
    import jax

    return [d for d in jax.devices() if d.platform != "cpu"]


def is_compiled_with_tpu():
    return True


def tpu_device_count():
    devs = _accelerator_devices()
    if devs:
        return len(devs)
    import jax

    return jax.device_count()


def get_all_places():
    return [TPUPlace(i) for i in range(tpu_device_count())]
