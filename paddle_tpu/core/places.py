"""Device identity ("Place") and device discovery.

TPU-native analog of the reference's Place variant
(reference: paddle/fluid/platform/place.h:79 — CUDAPlace/CPUPlace/
CUDAPinnedPlace) with TPUPlace replacing CUDAPlace, and of device discovery in
``InitDevices`` (reference: paddle/fluid/platform/init.cc:116). Discovery here
goes through the PJRT client that jax exposes rather than the CUDA driver.
"""

import functools
import os


class Place:
    _kind = "undefined"

    def __init__(self, device_id=0):
        self.device_id = device_id

    def __eq__(self, other):
        return type(self) is type(other) and self.device_id == other.device_id

    def __hash__(self):
        return hash((self._kind, self.device_id))

    def __repr__(self):
        return f"{type(self).__name__}({self.device_id})"


class CPUPlace(Place):
    _kind = "cpu"

    def __init__(self):
        super().__init__(0)

    def jax_device(self):
        import jax

        # local_devices: in a multi-controller job jax.devices() lists every
        # process's devices; an executor must target one THIS process owns
        return (
            jax.local_devices(backend="cpu")[0]
            if "cpu" in _platforms()
            else jax.local_devices()[0]
        )


class TPUPlace(Place):
    """One TPU chip, identified by its index in the local PJRT device list."""

    _kind = "tpu"

    def jax_device(self):
        import jax

        devs = _accelerator_devices()
        if not devs:
            # CPU fallback keeps programs runnable on hosts without a TPU
            # (tests force JAX_PLATFORMS=cpu with a virtual 8-device mesh).
            # local only: a multi-controller peer's devices are not valid
            # device_put targets here
            devs = jax.local_devices()
        return devs[self.device_id % len(devs)]


@functools.lru_cache(maxsize=None)
def _platforms():
    import os

    import jax

    if os.environ.get("PADDLE_TPU_FORCE_CPU"):
        jax.config.update("jax_platforms", "cpu")
        return {"cpu"}
    return {d.platform for d in jax.devices()}


def _accelerator_devices():
    import os

    import jax

    if os.environ.get("PADDLE_TPU_FORCE_CPU"):
        # Honor the force-CPU escape hatch everywhere: a stalled TPU tunnel
        # makes a bare jax.devices() hang, so never probe accelerators.
        jax.config.update("jax_platforms", "cpu")
        return []
    return [d for d in jax.local_devices() if d.platform != "cpu"]


_PROBE_SRC = (
    "import jax; d = jax.devices(); "
    "print(d[0].platform, getattr(d[0], 'device_kind', ''))"
)


def probe_accelerator(timeout=150, retries=2):
    """Check — in a subprocess, so a hung backend cannot take this process
    down — whether an accelerator backend initializes. A stalled TPU tunnel
    makes a bare jax.devices() hang >10 min. Returns (ok, diagnostic)."""
    import subprocess
    import sys
    import time

    if os.environ.get("PADDLE_TPU_FORCE_CPU"):
        return False, "PADDLE_TPU_FORCE_CPU set"
    last = ""
    for attempt in range(retries):
        if attempt:
            time.sleep(5)
        try:
            proc = subprocess.run(
                [sys.executable, "-c", _PROBE_SRC],
                capture_output=True,
                text=True,
                timeout=timeout,
            )
        except subprocess.TimeoutExpired:
            last = f"backend probe timed out after {timeout}s (attempt {attempt + 1})"
            continue
        out = proc.stdout.strip()
        if proc.returncode == 0 and out and not out.startswith("cpu"):
            return True, out
        last = (proc.stderr.strip().splitlines() or [out or "no output"])[-1]
    return False, last


def force_cpu_platform():
    """Pin this process to the CPU backend. Must run before the first backend
    probe; the axon plugin ignores JAX_PLATFORMS so the config API is used."""
    import jax

    jax.config.update("jax_platforms", "cpu")


def ensure_backend_or_cpu(timeout=150, retries=2):
    """Probe the accelerator; on failure pin this process to CPU.
    Returns (on_accelerator, diagnostic)."""
    ok, diag = probe_accelerator(timeout=timeout, retries=retries)
    if not ok:
        force_cpu_platform()
    return ok, diag


def is_compiled_with_tpu():
    return True


def tpu_device_count():
    devs = _accelerator_devices()
    if devs:
        return len(devs)
    import jax

    return jax.device_count()


def get_all_places():
    return [TPUPlace(i) for i in range(tpu_device_count())]
