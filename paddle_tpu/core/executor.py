"""Executor: runs Programs by compiling whole blocks to XLA.

This replaces the reference's per-op interpretive executors
(reference: paddle/fluid/framework/executor.cc:195 Executor::Run — a loop
dispatching one kernel per op) with the design the TPU demands: the entire
block is traced through each op's jax lowering rule into ONE XLA computation,
compiled once per (program version, feed signature) and cached — the analog of
the reference's ExecutorPrepareContext cache (executor.cc) but at whole-graph
granularity, letting XLA fuse elementwise chains into matmuls and schedule the
MXU instead of a host hot-loop dispatching kernels.

State threading: a Scope maps names to jax.Arrays. The compiled step takes
(feeds, scope-resident inputs, rng key) and returns (fetches, updated
persistables); parameter buffers are donated so optimizer updates are
in-place at the XLA level — the donation discipline replaces the reference's
inplace/eager-deletion passes (paddle/fluid/framework/ir/memory_optimize_pass/).

A per-op interpretive mode remains as the debug path (FLAGS_check_nan_inf),
mirroring the reference's NaN/Inf sanitizer hooked into op dispatch
(reference: paddle/fluid/framework/operator.cc:1029).
"""

import warnings

import numpy as np

import jax
import jax.numpy as jnp

from paddle_tpu.core.dtypes import to_numpy_dtype
from paddle_tpu.core.ir import Program
from paddle_tpu.core.places import CPUPlace, TPUPlace
from paddle_tpu.core.backward import resolve_op_def as get_op_def
from paddle_tpu.core.scope import global_scope
from paddle_tpu.observability import metrics as obs_metrics
from paddle_tpu.observability import sanitizer as obs_sanitizer
from paddle_tpu.observability.tracer import trace_scope
from paddle_tpu.profiler import RecordEvent
from paddle_tpu.utils.enforce import EnforceError
from paddle_tpu.utils.flags import flags

# always-on executor telemetry (one scrape shows compile-cache behavior
# next to serving stats and supervisor events); counter inc is the only
# per-step registry cost on the hot compiled path
_CACHE_HITS = obs_metrics.registry().counter(
    "executor_cache_hits_total", "compiled-step cache hits"
)
_CACHE_MISSES = obs_metrics.registry().counter(
    "executor_cache_misses_total", "compiled-step cache misses (traces)"
)
_COMPILE_SECONDS = obs_metrics.registry().histogram(
    "executor_compile_seconds", "trace+compile latency on cache miss"
)

# op types handled structurally by the interpreter (they run sub-blocks);
# `recurrent` is NOT here: it is a regular op whose lowering scans its
# sub-block (ops/rnn.py), so autodiff works through the generic vjp path
CONTROL_FLOW_OPS = {"while", "conditional_block"}
# pseudo-ops that the executor elides (feed/fetch are direct env access here)
ELIDED_OPS = {"feed", "fetch"}


# the use-def/liveness computation lives in the shared static-analysis
# layer (one control-flow-aware implementation for the executor's planner,
# the DCE/fusion passes, and the verifier); re-exported here because this
# module is its historical home
from paddle_tpu.analysis.usedef import live_ops  # noqa: E402


class _OpStep:
    """One op's pre-resolved execution plan: op-def lookup, baked attrs
    (with `_ctx_block`/`__out_counts__` already applied — lowerings only
    read attrs), and the non-empty input/output slot lists. Resolving
    these once per (program version, op list) instead of every `run()`
    call removes the dominant per-op Python dispatch cost the PR-4
    observability spans showed on the interpreted path, and shrinks trace
    time on the compiled path the same way."""

    __slots__ = ("op", "op_def", "attrs", "inputs", "outputs",
                 "control_flow", "rng_id")

    def __init__(self, op, op_def, attrs, inputs, outputs, control_flow,
                 rng_id):
        self.op = op
        self.op_def = op_def
        self.attrs = attrs
        self.inputs = inputs
        self.outputs = outputs
        self.control_flow = control_flow
        self.rng_id = rng_id


# (program uid, program version, block idx, op-list identity) -> [_OpStep];
# version bumps on every program mutation, so stale plans can't be served.
# Bounded: cleared wholesale at the cap (plans are cheap to rebuild).
_PLAN_CACHE = {}
_PLAN_CACHE_CAP = 256


def _block_plan(block, ops=None):
    prog = block.program
    key = (prog._uid, prog._version, block.idx,
           None if ops is None else tuple(map(id, ops)))
    plan = _PLAN_CACHE.get(key)
    if plan is not None:
        return plan
    plan = []
    for op_index, op in enumerate(block.ops if ops is None else ops):
        if op.type in ELIDED_OPS:
            continue
        if op.type in CONTROL_FLOW_OPS:
            plan.append(_OpStep(op, None, None, None, None, True, 0))
            continue
        op_def = get_op_def(op.type)
        attrs = op.attrs
        if op_def.needs_block:
            attrs = dict(attrs)
            attrs["_ctx_block"] = block
        if op_def.needs_out_counts:
            if attrs is op.attrs:
                attrs = dict(attrs)
            attrs["__out_counts__"] = {
                s: len(ns) for s, ns in op.outputs.items()
            }
        plan.append(_OpStep(
            op, op_def, attrs,
            [(slot, names) for slot, names in op.inputs.items() if names],
            list(op.outputs.items()),
            False,
            op.attrs.get("__rng_id__", op_index),
        ))
    if len(_PLAN_CACHE) >= _PLAN_CACHE_CAP:
        _PLAN_CACHE.clear()
    _PLAN_CACHE[key] = plan
    return plan


def _run_op_step(step, env, rng_key, use_pallas):
    """Execute one planned op against `env` (shared by the tracing and
    interpretive paths)."""
    op_def = step.op_def
    ins = {
        slot: [env[n] for n in names]
        for slot, names in step.inputs
        if all(n in env for n in names)
    }
    if op_def.stateful:
        ins["__rng_key__"] = [jax.random.fold_in(rng_key, step.rng_id)]
    if op_def.needs_base_rng:
        ins["__base_rng__"] = [rng_key]
    try:
        outs = op_def.lowering(use_pallas)(ins, step.attrs)
    except EnforceError:
        raise
    except Exception as e:
        raise EnforceError(
            f"lowering failed: {e}",
            op_type=step.op.type,
            op_callstack=step.op.attrs.get("op_callstack"),
        ) from e
    return outs


def _store_outputs(step, outs, env):
    for slot, names in step.outputs:
        if slot not in outs:
            continue
        vals = outs[slot]
        if not isinstance(vals, (list, tuple)):
            vals = [vals]
        for name, val in zip(names, vals):
            if val is not None:
                env[name] = val


def _interpret_block(block, env, rng_key, use_pallas=True, ops=None):
    """Trace every op in `block` through its lowering rule, mutating `env`.

    Called under jax tracing for the compiled path, or with concrete arrays
    for the interpretive debug path. Per-op resolution comes from the
    cached block plan, so repeated traces (and every interpreted step)
    skip the op-def/attrs re-resolution work.
    """
    from paddle_tpu.ops import control_flow as cf  # late import, avoids cycle

    for step in _block_plan(block, ops):
        if step.control_flow:
            cf.run_control_flow_op(step.op, block, env, rng_key,
                                   _interpret_block)
            continue
        outs = _run_op_step(step, env, rng_key, use_pallas)
        _store_outputs(step, outs, env)
    return env


def plan_step(block, feed_names, fetch_names, scope, use_donation):
    """Classify step I/O: validate fetches, split scope-resident inputs into
    donated (rewritten by the step — donation makes the update in-place at
    the XLA level) and read-only. Dead ops are pruned first (live_ops).
    Shared by Executor and CompiledProgram."""
    produced = set(feed_names)
    for op in block.ops:
        produced.update(op.output_names())
    bad_fetch = [
        n for n in fetch_names if n not in produced and not scope.has_var(n)
    ]
    if bad_fetch:
        raise EnforceError(
            f"fetch variables {bad_fetch} are not produced by the program, "
            f"fed, or present in scope"
        )
    ops = live_ops(block, fetch_names)
    scope_inputs, written_persistable = _block_io(block, feed_names, ops)
    # fetching a scope-resident var the block never reads (e.g. a parameter)
    # still needs that var as a step input
    for n in fetch_names:
        if n not in produced and n not in scope_inputs:
            scope_inputs.append(n)
    missing = [n for n in scope_inputs if not scope.has_var(n)]
    if missing:
        raise EnforceError(
            f"variables {missing} are read by the program but not "
            f"initialized in scope (run the startup program first?)"
        )
    overwritten = set(written_persistable) - set(fetch_names)
    donated = (
        [n for n in scope_inputs if n in overwritten] if use_donation else []
    )
    readonly = [n for n in scope_inputs if n not in set(donated)]
    return donated, readonly, written_persistable, ops


def _block_io(block, feed_names, ops=None):
    """Statically classify variables: which must come from the scope, which
    persistables get written back."""
    if ops is None:
        ops = block.ops
    produced = set(feed_names)
    scope_inputs = []
    for op in ops:
        if op.type in ELIDED_OPS:
            continue
        for name in op.input_names():
            if name not in produced and name not in scope_inputs:
                scope_inputs.append(name)
        # conservatively pull sub-block reads from scope too
        if op.type in CONTROL_FLOW_OPS and "sub_block" in op.attrs:
            sub = block.program.block(op.attrs["sub_block"])
            sub_produced = set()
            for sop in sub.ops:
                for n in sop.input_names():
                    if (
                        n not in produced
                        and n not in sub_produced
                        and n not in scope_inputs
                        and sub._find_var_recursive(n) is not None
                    ):
                        scope_inputs.append(n)
                sub_produced.update(sop.output_names())
        produced.update(op.output_names())
    written_persistable = []
    for op in ops:
        for name in op.output_names():
            v = block._find_var_recursive(name)
            if v is not None and v.persistable and name not in written_persistable:
                written_persistable.append(name)
    return scope_inputs, written_persistable


_OP_ROLE_OPTIMIZE = 2


def _make_microbatched_step(block, ops, feed_names, donated, readonly,
                            written_persistable, fetch_names, num_mb):
    """Microbatched step for PipelineOptimizer: the forward+backward region
    runs once per microbatch (feed dim 0 split into num_mb chunks) with
    gradients averaged across microbatches, then the optimizer region runs
    once. The TPU analog of the reference's section pipeline
    (reference: python/paddle/fluid/optimizer.py:3414 PipelineOptimizer +
    section_worker.cc:142 — there microbatches flow through scope queues
    between device sections; here the schedule is unrolled into one XLA
    computation, and with stage-sharded params under with_parallel the
    per-stage overlap is GSPMD's to exploit)."""
    fwd_ops = [
        op for op in ops if op.attrs.get("op_role", 0) != _OP_ROLE_OPTIMIZE
    ]
    opt_ops = [
        op for op in ops if op.attrs.get("op_role", 0) == _OP_ROLE_OPTIMIZE
    ]
    # gradients consumed by optimizer ops get accumulated across microbatches
    fwd_produced = {n for op in fwd_ops for n in op.output_names()}
    acc_names = sorted(
        {
            n
            for op in opt_ops
            for n in op.input_names()
            if n.endswith("@GRAD") and n in fwd_produced
        }
    )

    # float fetches produced per-microbatch (losses/metrics) are averaged
    # across microbatches so they describe the WHOLE fed batch
    fwd_fetches = [n for n in fetch_names if n in fwd_produced]

    def step(feed_vals, donated_vals, readonly_vals, rng_key):
        base_env = dict(zip(donated, donated_vals))
        base_env.update(zip(readonly, readonly_vals))
        feeds = dict(zip(feed_names, feed_vals))
        for n, v in feeds.items():
            if hasattr(v, "ndim") and v.ndim and v.shape[0] % num_mb != 0:
                raise EnforceError(
                    f"feed '{n}' batch dim {v.shape[0]} is not divisible by "
                    f"num_microbatches={num_mb} — remainder rows would be "
                    f"silently dropped"
                )
        acc = {}
        fetch_parts = {n: [] for n in fwd_fetches}
        last_env = None
        mb_size = 0
        for m in range(num_mb):
            env = dict(base_env)
            for n, v in feeds.items():
                mb = v.shape[0] // num_mb if hasattr(v, "ndim") and v.ndim else 0
                env[n] = v[m * mb:(m + 1) * mb] if mb else v
                mb_size = mb or mb_size
            _interpret_block(
                block, env, jax.random.fold_in(rng_key, m), ops=fwd_ops
            )
            for n in acc_names:
                g = env[n]
                acc[n] = g if m == 0 else acc[n] + g
            for n in fwd_fetches:
                fetch_parts[n].append(env[n])
            # forward-written persistables (batch-norm moving stats, streaming
            # metric accumulators) must chain across microbatches, not reset
            # to base_env each time — the reference's section pipeline updates
            # shared-scope persistables every microbatch
            for n in written_persistable:
                if n in env:
                    base_env[n] = env[n]
            last_env = env
        env = last_env
        for n in acc_names:
            env[n] = acc[n] / num_mb
        _interpret_block(block, env, rng_key, ops=opt_ops)
        for n, parts in fetch_parts.items():
            v0 = jnp.asarray(parts[0])
            if v0.ndim and mb_size and v0.shape[0] == mb_size:
                env[n] = jnp.concatenate(parts, axis=0)  # per-example fetch
            elif jnp.issubdtype(v0.dtype, jnp.floating):
                env[n] = sum(parts) / num_mb  # scalar metric: batch mean
        fetches = [env[n] for n in fetch_names]
        updates = [env.get(n) for n in written_persistable]
        return fetches, updates

    return step


class Executor:
    """Feed/fetch driver (reference: python/paddle/fluid/executor.py:432)."""

    def __init__(self, place=None):
        self.place = place if place is not None else TPUPlace(0)
        self._cache = {}
        self._rng_counter = 0

    # ------------------------------------------------------------------
    def run(
        self,
        program=None,
        feed=None,
        fetch_list=None,
        scope=None,
        return_numpy=True,
        use_program_cache=True,
    ):
        from paddle_tpu.compiler import CompiledProgram

        if program is None:
            from paddle_tpu.core.ir import default_main_program

            program = default_main_program()
        if isinstance(program, CompiledProgram):
            return program._run(self, feed, fetch_list, scope, return_numpy)
        feed = feed or {}
        fetch_list = fetch_list or []
        scope = scope or global_scope()
        fetch_names = [
            f.name if not isinstance(f, str) else f for f in fetch_list
        ]

        block = program.global_block()
        with trace_scope("executor::feed", nfeeds=len(feed)):
            feed_arrays = {
                name: self._to_device(value, block, name)
                for name, value in feed.items()
            }

        if flags.check_nan_inf or flags.benchmark:
            return self._run_interpreted(
                program, feed_arrays, fetch_names, scope, return_numpy
            )
        return self._run_compiled(
            program, feed_arrays, fetch_names, scope, return_numpy
        )

    # ------------------------------------------------------------------
    def train_from_dataset(
        self,
        program=None,
        dataset=None,
        scope=None,
        thread=0,
        debug=False,
        fetch_list=None,
        fetch_info=None,
        print_period=100,
        fetch_handler=None,
        is_infer=False,
    ):
        """Dataset-mode training loop (reference: python/paddle/fluid/
        executor.py:1124 train_from_dataset -> C++ Executor::RunFromDataset
        with thread-per-core DeviceWorkers). TPU-native: the whole step is
        one XLA computation, so the worker-thread pool collapses into the
        native data-feed producing batches (csrc/datafeed) while the chip
        runs the compiled step. The per-batch driver comes from the
        program's `_fleet_opt` via TrainerFactory (device_worker.py):
        Hogwild = plain step, DownpourSGD = the PS pull/step/push loop;
        its run configuration (fetch/debug/infer) travels on the
        TrainerDesc."""
        from paddle_tpu.utils.enforce import enforce as _enforce

        _enforce(dataset is not None, "dataset is required")
        import time as _time

        from paddle_tpu.device_worker import TrainerFactory

        prog_obj = getattr(program, "program", program)
        if is_infer:
            # evaluation must not update state: a program still carrying
            # optimizer ops (or in-graph grad pushes) would train on the
            # eval data — demand the test clone, like the reference's
            # infer-trainer contract
            bad = [
                op.type
                for op in prog_obj.global_block().ops
                if op.attrs.get("op_role", 0) == _OP_ROLE_OPTIMIZE
                or op.type == "distributed_push_sparse"
            ]
            _enforce(
                not bad,
                "infer_from_dataset got a TRAINING program (contains "
                f"{sorted(set(bad))[:3]}...): pass the "
                "clone(for_test=True) inference program instead",
            )
        trainer = TrainerFactory()._create_trainer(
            getattr(prog_obj, "_fleet_opt", None)
        )
        trainer._set_thread(thread)
        trainer._set_debug(debug)
        trainer._set_infer(is_infer)
        trainer._set_fetch_var_and_info(fetch_list, fetch_info, print_period)
        trainer._set_program(prog_obj)
        worker = trainer._device_worker
        worker.prepare(self, prog_obj, scope)

        fetch_list = trainer._fetch_vars
        fetch_info = trainer._fetch_info or [str(f) for f in fetch_list]
        print_period = trainer._print_period
        debug = trainer._debug
        step = 0
        last = None
        last_handled = _time.monotonic()
        # background=True on the FetchHandler moves delivery off the
        # training loop onto a period-driven monitor thread (reference:
        # FetchHandlerMonitor) — a long epoch reports on schedule even
        # when single steps are slow
        monitor = None
        if fetch_list and fetch_handler is not None and getattr(
                fetch_handler, "background", False):
            from paddle_tpu.observability.fetcher import FetchHandlerMonitor

            monitor = FetchHandlerMonitor(fetch_handler).start()
        # lookahead iteration ONLY for programs with in-graph remote tables
        # (distributed_embedding): the NEXT batch's ids are announced before
        # the current step runs, so the PS pull overlaps device compute —
        # the dataset-mode analog of the reference's prefetch thread
        # (reference: paddle/fluid/operators/distributed/parameter_prefetch.cc).
        # Other programs keep strict one-batch-at-a-time iteration: eagerly
        # demanding batch N+1 from a streaming producer would stall batch N.
        lookahead = bool(
            getattr(getattr(program, "program", program), "_remote_tables", None)
        )
        host_feed = lookahead or bool(
            getattr(prog_obj, "_sparse_tables", None)
        )
        if host_feed:
            # PS paths read feed ids on the HOST (PSWorker.run / the
            # lookahead pull): keep the raw iterator — device-staging
            # first would force a device->host copy per batch
            it = iter(dataset._iter_batches())
        else:
            # dataio double-buffer: batch N+1 is device_put while batch N
            # computes (the buffered_reader.cc overlap)
            from paddle_tpu.dataio.prefetch import DevicePrefetcher

            it = iter(DevicePrefetcher(dataset._iter_batches(), depth=2,
                                       device=self.place.jax_device(),
                                       name="train_from_dataset"))
        feed = next(it, None)
        nxt = None
        try:
            while feed is not None:
                if lookahead:
                    nxt = next(it, None)
                    if nxt is not None:
                        from paddle_tpu.distributed import lookup as _rl

                        _rl.prefetch_for_program(program, nxt)
                out = worker.run_batch(
                    self, program, feed, fetch_list=fetch_list, scope=scope
                )
                last = out
                if fetch_list and fetch_handler is not None:
                    names = [
                        f if isinstance(f, str) else f.name
                        for f in fetch_list
                    ]
                    if monitor is not None:
                        # background monitor owns the cadence; the loop
                        # only publishes the newest values (one dict swap)
                        monitor.update(dict(zip(names, out)))
                    else:
                        # in-loop cadence (reference: FetchHandlerMonitor
                        # wakes every period_secs, executor.py:406) with a
                        # step fallback so short runs still observe fetches
                        now = _time.monotonic()
                        if (
                            now - last_handled >= fetch_handler.period_secs
                            or step % print_period == 0
                        ):
                            fetch_handler.handler(dict(zip(names, out)))
                            last_handled = now
                elif fetch_list and (debug or step % print_period == 0):
                    msgs = [
                        f"{info}={np.asarray(v).reshape(-1)[:1][0]:.6f}"
                        for info, v in zip(fetch_info, out)
                    ]
                    print(f"step {step}: " + ", ".join(msgs))
                step += 1
                feed = nxt if lookahead else next(it, None)
        finally:
            # a mid-epoch raise must not leak the monitor's daemon thread;
            # the final tick delivers the last published fetch either way
            if monitor is not None:
                monitor.stop()
        worker.finish()
        return last

    def infer_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100,
                           fetch_handler=None):
        return self.train_from_dataset(
            program, dataset, scope, thread, debug, fetch_list, fetch_info,
            print_period, fetch_handler, is_infer=True,
        )

    # ------------------------------------------------------------------
    def _to_device(self, value, block, name):
        if isinstance(value, jax.Array):
            return value
        return jax.device_put(np.asarray(value), self.place.jax_device())

    @staticmethod
    def _committed(scope, name, dev, store=True):
        """Scope value as a device-committed array, verifying at most once:
        steady-state training steps hand back the arrays the previous step
        produced (written back via _set_verified, already on `dev`), so the
        common path is ONE dict lookup — not a device_put (the round-2
        profile's biggest host-side line item) and not even a per-step
        `.devices()` call (~5 us x ~600 scope entries on BERT,
        tools/bench_host_overhead.py). User-facing scope.set invalidates
        the verification.

        `store=False` for DONATED inputs: their buffer is consumed by the
        step, so storing the committed copy would leave a deleted array in
        the scope whenever the step fails — the post-step write-back is
        their only legitimate store."""
        owner = scope._find_owner(name)
        v = owner._vars[name] if owner is not None else None
        if isinstance(v, jax.Array):
            ver = owner._device_verified.get(name)
            if ver is not None and dev in ver:
                return v
            devs = v.devices()
            if dev in devs or len(devs) > 1:  # right chip, or sharded: keep
                owner._device_verified.setdefault(name, set()).add(dev)
                return v
        arr = jax.device_put(v, dev)
        if store:
            scope._set_verified(name, arr, dev)
        return arr

    def _next_rng_key(self, program):
        seed = program.random_seed or 0
        self._rng_counter += 1
        if flags.rng_impl != "threefry":
            # rbg: hardware-RNG-backed bits on TPU - dropout-heavy steps
            # stop paying threefry's ALU cost. Streams differ from threefry
            # but the distribution is identical.
            return jax.random.fold_in(
                jax.random.key(seed, impl=flags.rng_impl), self._rng_counter
            )
        return jax.random.fold_in(jax.random.PRNGKey(seed), self._rng_counter)

    # ------------------------------------------------------------------
    def _run_compiled(self, program, feed_arrays, fetch_names, scope, return_numpy):
        from paddle_tpu.passes import (
            apply_deferred_sharded_embedding_rewrite,
            apply_deferred_sparse_rewrite,
            resolve_tensor_array_indices,
        )

        apply_deferred_sparse_rewrite(program)
        apply_deferred_sharded_embedding_rewrite(program)
        resolve_tensor_array_indices(program)
        block = program.global_block()
        feed_names = sorted(feed_arrays)
        feed_sig = tuple(
            (n, tuple(feed_arrays[n].shape), str(feed_arrays[n].dtype))
            for n in feed_names
        )
        # per-executor cheap key: steady-state steps never pay the
        # content-addressed fingerprint (which serializes the program);
        # on a miss the shared lowering consults the process-wide and
        # persistent tiers before tracing. The RESOLVED kernel mode
        # (paddle_tpu/kernels/) joins the cheap key — flipping
        # PADDLE_TPU_KERNELS must not serve a stale executable from this
        # per-object tier when the content-addressed one would miss
        from paddle_tpu.kernels import registry as _kernel_registry

        key = (program._uid, program._version, feed_sig,
               tuple(fetch_names), _kernel_registry.resolved_mode())
        entry = self._cache.get(key)
        if entry is None:
            from paddle_tpu.core import lowering

            num_mb = getattr(program, "_num_microbatches", 0)
            make_step = None
            extra = ()
            if num_mb and num_mb > 1:
                if any(op.type == "sgd_sparse" for op in block.ops):
                    raise EnforceError(
                        "sgd_sparse cannot run microbatched: Ids differ per "
                        "microbatch while grads accumulate across them. "
                        "Build the program with "
                        "FLAGS_sparse_embedding_update=0, or apply "
                        "PipelineOptimizer before minimize"
                    )
                extra = (("mb", num_mb),)

                def make_step(blk, plan):
                    f_names, f_fetch, donated, readonly, written, ops = plan
                    return _make_microbatched_step(
                        blk, ops, f_names, donated, readonly, written,
                        f_fetch, num_mb,
                    )

            with trace_scope("executor::plan", ops=len(block.ops)):
                entry, source = lowering.lower_step(
                    program, scope, feed_sig, fetch_names,
                    donate=flags.use_donation, make_step=make_step,
                    extra_fingerprint=extra, label="executor",
                )
            if source == "trace":
                _CACHE_MISSES.inc()
            self._cache[key] = entry
        else:
            _CACHE_HITS.inc()

        compiled = entry.fn
        donated, readonly = entry.donated, entry.readonly
        written_persistable = entry.written
        missing = [n for n in donated + readonly if not scope.has_var(n)]
        if missing:
            raise EnforceError(
                f"variables {missing} are read by the program but not "
                f"initialized in scope (run the startup program first?)"
            )
        # Commit every input to the executor's device: mixing committed and
        # uncommitted arrays makes XLA compile one executable per layout
        # combination (first step vs steady state), doubling compile time.
        # The commit is sticky (written back to the scope) so steady-state
        # steps skip the per-param device_put loop entirely — the step outputs
        # written back below are already committed device arrays.
        dev = self.place.jax_device()
        with trace_scope("executor::commit_inputs"):
            feed_vals = tuple(feed_arrays[n] for n in sorted(feed_arrays))
            donated_vals = tuple(
                self._committed(scope, n, dev, store=False) for n in donated
            )
            readonly_vals = tuple(
                self._committed(scope, n, dev) for n in readonly
            )
        rng_key = self._next_rng_key(program)
        # first call on a freshly traced entry runs the XLA compile; a
        # separate span name keeps compile time out of the execute track,
        # and a persistent-cache load gets its own span (it compiles the
        # deserialized module, it does not retrace)
        if not entry.executed and entry.source == "trace":
            import time as _time

            t0 = _time.perf_counter()
            with trace_scope("executor::trace_compile_execute"), \
                    warnings.catch_warnings():
                warnings.simplefilter("ignore")
                fetches, updates = compiled(
                    feed_vals, donated_vals, readonly_vals, rng_key
                )
            _COMPILE_SECONDS.observe(
                entry.build_seconds + _time.perf_counter() - t0
            )
        elif not entry.executed:
            with trace_scope("executor::persistent_load_execute"), \
                    warnings.catch_warnings():
                warnings.simplefilter("ignore")
                fetches, updates = compiled(
                    feed_vals, donated_vals, readonly_vals, rng_key
                )
        else:
            with trace_scope("executor::execute"), warnings.catch_warnings():
                warnings.simplefilter("ignore")  # donation warnings on CPU
                fetches, updates = compiled(
                    feed_vals, donated_vals, readonly_vals, rng_key
                )
        entry.executed = True
        for name, val in zip(written_persistable, updates):
            if val is not None:
                # write back to the scope the variable LIVES in (reference
                # semantics: persistables update in place through child
                # scopes — and the owner's buffer was donated, so leaving
                # it unreplaced would strand a deleted array there). Step
                # outputs are on `dev` by construction: mark verified so
                # the next dispatch skips the devices() probe.
                target = scope._find_owner(name) or scope
                target._set_verified(name, val, dev)
        if return_numpy:
            with trace_scope("executor::fetch", nfetch=len(fetches)):
                return [np.asarray(f) for f in fetches]
        return list(fetches)

    # ------------------------------------------------------------------
    def _run_interpreted(self, program, feed_arrays, fetch_names, scope, return_numpy):
        """Per-op debug path with NaN/Inf checking
        (reference: paddle/fluid/framework/details/nan_inf_utils_detail.cc)."""
        from paddle_tpu.passes import (
            apply_deferred_sharded_embedding_rewrite,
            resolve_tensor_array_indices,
        )

        apply_deferred_sharded_embedding_rewrite(program)
        resolve_tensor_array_indices(program)
        block = program.global_block()
        env = dict(feed_arrays)
        for name in block.vars:
            v = scope.find_var(name)
            if v is not None and name not in env:
                env[name] = v
        rng_key = self._next_rng_key(program)
        from paddle_tpu.ops import control_flow as cf

        # per-op resolution comes from the cached block plan (shared with
        # the compiled path's tracer): repeated debug/benchmark steps skip
        # the op-def/attrs re-resolution entirely
        for step in _block_plan(block):
            op = step.op
            if step.control_flow:
                cf.run_control_flow_op(op, block, env, rng_key, _interpret_block)
                continue
            if flags.benchmark:
                # per-op timing: block on the op's outputs so device time is
                # attributed to the op (reference: FLAGS_benchmark serializes
                # with dev_ctx->Wait, operator.cc:1006)
                with RecordEvent(op.type):
                    outs = _run_op_step(step, env, rng_key, True)
                    for vals in outs.values():
                        for v in vals if isinstance(vals, (list, tuple)) else [vals]:
                            if hasattr(v, "block_until_ready"):
                                v.block_until_ready()
            else:
                with trace_scope("op::" + op.type, cat="op"):
                    outs = _run_op_step(step, env, rng_key, True)
            for slot, names in step.outputs:
                if slot not in outs:
                    continue
                vals = outs[slot]
                if not isinstance(vals, (list, tuple)):
                    vals = [vals]
                for name, val in zip(names, vals):
                    if val is None:
                        continue
                    env[name] = val
                    if flags.check_nan_inf:
                        # sanitizer mode (reference: nan_inf_utils_detail.cc):
                        # names the op, the output var, value stats, and the
                        # user callstack that built the op
                        obs_sanitizer.check_output(op, name, val)
        for name, val in env.items():
            var = block._find_var_recursive(name)
            if var is not None and var.persistable:
                (scope._find_owner(name) or scope).set(name, val)
        fetches = [env[n] for n in fetch_names]
        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return fetches

    # ------------------------------------------------------------------
    def close(self):
        self._cache.clear()
