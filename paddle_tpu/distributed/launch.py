"""Multi-process launcher.

Reference: python/paddle/distributed/launch.py — parses cluster env and
spawns one worker process per device (start_procs :175), injecting
PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM / PADDLE_TRAINER_ENDPOINTS
(:105-109). The TPU-native difference: JAX is multi-controller SPMD, so the
unit of launch is one process per HOST (each host drives all its local
chips), and rendezvous is the JAX coordinator (PADDLE_DIST_COORDINATOR)
instead of NCCL-id RPC. For CPU-based testing, --nproc emulates multiple
hosts on localhost with virtual devices.

Usage:  python -m paddle_tpu.distributed.launch --nproc 2 train.py [args...]
"""

import argparse
import os
import signal
import subprocess
import sys

__all__ = ["launch_procs", "main"]


def _free_port():
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def launch_procs(
    script_args,
    nproc=1,
    started_port=None,
    coordinator=None,
    extra_env=None,
    devices_per_proc=None,
):
    """Spawn `nproc` worker processes running `script_args`, with the fleet
    env contract injected. Returns the list of exit codes."""
    started_port = started_port or _free_port()
    endpoints = ",".join(
        f"127.0.0.1:{started_port + i}" for i in range(nproc)
    )
    coordinator = coordinator or (
        f"127.0.0.1:{_free_port()}" if nproc > 1 else ""
    )
    # make the framework importable in workers even when not pip-installed
    pkg_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    procs = []
    for rank in range(nproc):
        env = dict(os.environ)
        env["PYTHONPATH"] = os.pathsep.join(
            p for p in (pkg_root, env.get("PYTHONPATH")) if p
        )
        env.update(extra_env or {})
        env.update(
            {
                "TRAINING_ROLE": "TRAINER",
                "PADDLE_TRAINER_ID": str(rank),
                "PADDLE_TRAINERS_NUM": str(nproc),
                "PADDLE_TRAINER_ENDPOINTS": endpoints,
                "PADDLE_CURRENT_ENDPOINT": f"127.0.0.1:{started_port + rank}",
            }
        )
        if coordinator:
            env["PADDLE_DIST_COORDINATOR"] = coordinator
        if devices_per_proc:
            env["XLA_FLAGS"] = (
                env.get("XLA_FLAGS", "")
                + f" --xla_force_host_platform_device_count={devices_per_proc}"
            ).strip()
            env["JAX_PLATFORMS"] = "cpu"
        procs.append(
            subprocess.Popen([sys.executable] + list(script_args), env=env)
        )

    def _terminate(signum, frame):
        for p in procs:
            p.terminate()

    old = signal.signal(signal.SIGTERM, _terminate)
    try:
        codes = [p.wait() for p in procs]
    finally:
        signal.signal(signal.SIGTERM, old)
    return codes


def main():
    parser = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    parser.add_argument("--nproc", type=int, default=1,
                        help="processes (hosts) to launch on this machine")
    parser.add_argument("--started_port", type=int, default=None)
    parser.add_argument("--devices_per_proc", type=int, default=None,
                        help="virtual CPU devices per process (testing)")
    parser.add_argument("script", type=str)
    parser.add_argument("script_args", nargs=argparse.REMAINDER)
    args = parser.parse_args()
    codes = launch_procs(
        [args.script] + args.script_args,
        nproc=args.nproc,
        started_port=args.started_port,
        devices_per_proc=args.devices_per_proc,
    )
    bad = [i for i, c in enumerate(codes) if c != 0]
    if bad:
        sys.exit(f"workers {bad} exited nonzero: {[codes[i] for i in bad]}")


if __name__ == "__main__":
    main()
