"""Multi-process launcher.

Reference: python/paddle/distributed/launch.py — parses cluster env and
spawns one worker process per device (start_procs :175), injecting
PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM / PADDLE_TRAINER_ENDPOINTS
(:105-109). The TPU-native difference: JAX is multi-controller SPMD, so the
unit of launch is one process per HOST (each host drives all its local
chips), and rendezvous is the JAX coordinator (PADDLE_DIST_COORDINATOR)
instead of NCCL-id RPC. For CPU-based testing, --nproc emulates multiple
hosts on localhost with virtual devices.

A gang is all-or-nothing: one crashed rank wedges every collective, so
`wait_gang` POLLS the whole gang and fail-fast terminates the survivors
the moment any rank exits nonzero (instead of the old sequential
[p.wait() ...], where a dead rank 3 hung the job until ranks 0-2
finished). Supervised restarts on top of this live in
paddle_tpu.resilience.supervisor (--max-restarts below wires it in).

Usage:  python -m paddle_tpu.distributed.launch --nproc 2 train.py [args...]
"""

import argparse
import os
import signal
import subprocess
import sys
import time

__all__ = ["spawn_gang", "wait_gang", "terminate_gang", "launch_procs",
           "main"]


def _free_port():
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def spawn_gang(
    script_args,
    nproc=1,
    started_port=None,
    coordinator=None,
    extra_env=None,
    devices_per_proc=None,
    ranks=None,
):
    """Spawn worker processes running `script_args` with the fleet env
    contract injected; returns the list of Popen handles (in `ranks`
    order). `ranks` defaults to the whole gang; passing a subset spawns
    only those ranks into the SAME endpoint layout (pin `started_port`
    so a respawned rank rejoins the original endpoints) — the
    supervisor's replica-grained `restart(rank)` relies on this."""
    started_port = started_port or _free_port()
    endpoints = ",".join(
        f"127.0.0.1:{started_port + i}" for i in range(nproc)
    )
    coordinator = coordinator or (
        f"127.0.0.1:{_free_port()}" if nproc > 1 else ""
    )
    # make the framework importable in workers even when not pip-installed
    pkg_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    procs = []
    for rank in (range(nproc) if ranks is None else ranks):
        env = dict(os.environ)
        env["PYTHONPATH"] = os.pathsep.join(
            p for p in (pkg_root, env.get("PYTHONPATH")) if p
        )
        env.update(extra_env or {})
        env.update(
            {
                "TRAINING_ROLE": "TRAINER",
                "PADDLE_TRAINER_ID": str(rank),
                "PADDLE_TRAINERS_NUM": str(nproc),
                "PADDLE_TRAINER_ENDPOINTS": endpoints,
                "PADDLE_CURRENT_ENDPOINT": f"127.0.0.1:{started_port + rank}",
            }
        )
        if coordinator:
            env["PADDLE_DIST_COORDINATOR"] = coordinator
        if devices_per_proc:
            env["XLA_FLAGS"] = (
                env.get("XLA_FLAGS", "")
                + f" --xla_force_host_platform_device_count={devices_per_proc}"
            ).strip()
            env["JAX_PLATFORMS"] = "cpu"
        procs.append(
            subprocess.Popen([sys.executable] + list(script_args), env=env)
        )
    return procs


def terminate_gang(procs, grace_s=5.0):
    """TERM every live rank, give them `grace_s` to exit, then KILL."""
    for p in procs:
        if p.poll() is None:
            p.terminate()
    deadline = time.monotonic() + grace_s
    for p in procs:
        if p.poll() is None:
            try:
                p.wait(timeout=max(deadline - time.monotonic(), 0.1))
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()


def wait_gang(procs, fail_fast=True, poll_interval_s=0.1, grace_s=5.0):
    """Poll ALL ranks until the gang resolves; returns exit codes in rank
    order. With fail_fast, the first nonzero exit terminates the
    survivors immediately (they would otherwise hang on dead
    collectives); their codes then reflect the termination signal."""
    failed = False
    while True:
        codes = [p.poll() for p in procs]
        if all(c is not None for c in codes):
            return codes
        if fail_fast and not failed and any(
            c is not None and c != 0 for c in codes
        ):
            failed = True
            terminate_gang(procs, grace_s=grace_s)
            continue
        time.sleep(poll_interval_s)


def launch_procs(
    script_args,
    nproc=1,
    started_port=None,
    coordinator=None,
    extra_env=None,
    devices_per_proc=None,
    fail_fast=True,
):
    """Spawn a gang and wait for it. Returns the list of exit codes."""
    procs = spawn_gang(
        script_args,
        nproc=nproc,
        started_port=started_port,
        coordinator=coordinator,
        extra_env=extra_env,
        devices_per_proc=devices_per_proc,
    )

    def _terminate(signum, frame):
        for p in procs:
            p.terminate()

    old = signal.signal(signal.SIGTERM, _terminate)
    try:
        codes = wait_gang(procs, fail_fast=fail_fast)
    finally:
        signal.signal(signal.SIGTERM, old)
    return codes


def main():
    parser = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    parser.add_argument("--nproc", type=int, default=1,
                        help="processes (hosts) to launch on this machine")
    parser.add_argument("--started_port", type=int, default=None)
    parser.add_argument("--devices_per_proc", type=int, default=None,
                        help="virtual CPU devices per process (testing)")
    parser.add_argument("--max_restarts", type=int, default=0,
                        help="supervised gang restarts on failure (0 = "
                             "fail fast with no restart)")
    parser.add_argument("--elastic", action="store_true",
                        help="elastic supervision: relaunch at reduced "
                             "world size on capacity loss and grow back "
                             "(implies supervision and a restart budget "
                             "of 4 unless --max_restarts is set; see "
                             "--min_nproc)")
    parser.add_argument("--min_nproc", type=int, default=None,
                        help="elastic world-size floor (implies "
                             "--elastic; default: 1)")
    parser.add_argument("--grow_after", type=float, default=30.0,
                        help="elastic: seconds at reduced world size "
                             "before probing full capacity again")
    parser.add_argument("--restart_backoff", type=float, default=1.0,
                        help="seconds between restart attempts (doubles)")
    parser.add_argument("--hang_timeout", type=float, default=None,
                        help="declare the gang hung when no heartbeat "
                             "tick lands for this many seconds")
    parser.add_argument("--heartbeat_dir", type=str, default=None,
                        help="directory for worker heartbeat files "
                             "(created; implied by --hang_timeout)")
    parser.add_argument("--checkpoint_dir", type=str, action="append",
                        default=None,
                        help="AutoCheckpoint dir(s) to validate (quarantine "
                             "corrupt entries) before each restart")
    parser.add_argument("script", type=str)
    parser.add_argument("script_args", nargs=argparse.REMAINDER)
    args = parser.parse_args()
    script_args = [args.script] + args.script_args
    elastic = args.elastic or args.min_nproc is not None
    if elastic or args.max_restarts > 0 or args.hang_timeout:
        common = dict(
            nproc=args.nproc,
            max_restarts=args.max_restarts,
            restart_backoff_s=args.restart_backoff,
            hang_timeout_s=args.hang_timeout,
            heartbeat_dir=args.heartbeat_dir,
            checkpoint_dirs=args.checkpoint_dir,
            devices_per_proc=args.devices_per_proc,
            started_port=args.started_port,
        )
        if elastic:
            from paddle_tpu.resilience.elastic import ElasticGangSupervisor

            # a zero restart budget would fail the job on the very
            # capacity loss --elastic exists to survive: the first
            # shrink decision needs at least one allowed restart
            if common["max_restarts"] < 1:
                common["max_restarts"] = 4
            sup = ElasticGangSupervisor(
                script_args,
                min_nproc=args.min_nproc or 1,
                grow_after_s=args.grow_after,
                **common,
            )
        else:
            from paddle_tpu.resilience.supervisor import GangSupervisor

            sup = GangSupervisor(script_args, **common)
        try:
            sup.run()
        except Exception as e:
            sys.exit(str(e))
        return
    codes = launch_procs(
        script_args,
        nproc=args.nproc,
        started_port=args.started_port,
        devices_per_proc=args.devices_per_proc,
    )
    bad = [i for i, c in enumerate(codes) if c != 0]
    if bad:
        sys.exit(f"workers {bad} exited nonzero: {[codes[i] for i in bad]}")


if __name__ == "__main__":
    main()
