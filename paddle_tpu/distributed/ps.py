"""Parameter-server client + in-process server host.

Reference: paddle/fluid/operators/distributed/ RPCClient (rpc_client.h) and
framework/fleet/fleet_wrapper.h (PullSparseVarsSync :84, PushSparseVarsAsync
:141, PushDenseVarsAsync :114, LoadModel/SaveModel :199-206, Shrink :226).
The server itself is native C++ (csrc/ps) spoken to over a length-prefixed
TCP protocol; PSServer here hosts it in-process via ctypes for single-host
jobs and tests, and `python -m paddle_tpu.distributed.ps` runs it standalone
for real multi-host clusters.
"""

import ctypes
import socket
import struct
import threading

import numpy as np

from paddle_tpu.resilience import faults
from paddle_tpu.resilience.retry import RetryPolicy
from paddle_tpu.utils.enforce import enforce
from paddle_tpu.utils.native import load_native

__all__ = ["PSServer", "PSClient", "Communicator", "frame_send",
           "frame_recv"]

CMD_CREATE = 1
CMD_PULL_SPARSE = 2
CMD_PUSH_SPARSE = 3
CMD_PULL_DENSE = 4
CMD_PUSH_DENSE = 5
CMD_SAVE = 6
CMD_LOAD = 7
CMD_SHRINK = 8
CMD_BARRIER = 9
CMD_HEARTBEAT = 10
CMD_STOP = 11
CMD_STATS = 12

OPT_SGD = 0
OPT_ADAGRAD = 1


# -- the shared wire framing -------------------------------------------------
# ONE definition of the '<I'-length-prefixed frame protocol: the PS
# client below and the fleet replica transport
# (serving/fleet/{replica,worker}.py) all speak it — a framing fix
# lands once, here.


def _read_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("connection closed mid-frame")
        buf += chunk
    return buf


def frame_send(sock, body):
    """Send one length-prefixed frame."""
    sock.sendall(struct.pack("<I", len(body)) + body)


def frame_recv(sock):
    """Read one length-prefixed frame; ConnectionError on EOF."""
    (blen,) = struct.unpack("<I", _read_exact(sock, 4))
    return _read_exact(sock, blen)


class PSServer:
    """In-process native PS (thread pool lives in the C++ lib)."""

    def __init__(self, port=0):
        self._lib = load_native("ps")
        self._lib.paddle_ps_start.restype = ctypes.c_void_p
        self._lib.paddle_ps_start.argtypes = [ctypes.c_int]
        self._lib.paddle_ps_port.restype = ctypes.c_int
        self._lib.paddle_ps_port.argtypes = [ctypes.c_void_p]
        self._lib.paddle_ps_stop.argtypes = [ctypes.c_void_p]
        self._h = self._lib.paddle_ps_start(port)
        enforce(self._h, f"failed to start PS on port {port}")
        self.port = self._lib.paddle_ps_port(self._h)
        self.endpoint = f"127.0.0.1:{self.port}"

    def stop(self):
        if self._h:
            self._lib.paddle_ps_stop(self._h)
            self._h = None

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass


class PSClient:
    """Blocking client; one TCP connection per client (thread-safe via lock).
    For multi-server sharding, ids are routed by id %% n_servers — the
    analog of the reference's per-parameter block placement
    (reference: python/paddle/fluid/transpiler/distribute_transpiler.py:254
    slice_variable round-robin)."""

    _DEFAULT_RETRY = RetryPolicy(max_attempts=3, base_delay_s=0.05,
                                 max_delay_s=1.0, deadline_s=60.0)

    def __init__(self, endpoints, retry=_DEFAULT_RETRY):
        if isinstance(endpoints, str):
            endpoints = endpoints.split(",")
        self._eps = list(endpoints)
        self._socks = []
        self._lock = threading.Lock()
        # transient transport errors reconnect + resend under the shared
        # policy (requests are single-message, so a fresh socket starts
        # clean; non-idempotent cmds become at-least-once on retry);
        # retry=None disables for raw fail-fast semantics
        self._retry = retry
        for ep in self._eps:
            self._socks.append(self._connect(ep))

    @staticmethod
    def _connect(ep):
        host, port = ep.rsplit(":", 1)
        s = socket.create_connection((host, int(port)), timeout=60)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return s

    def _reconnect(self, server):
        try:
            self._socks[server].close()
        except OSError:
            pass
        self._socks[server] = self._connect(self._eps[server])

    @property
    def n_servers(self):
        return len(self._socks)

    # -- wire helpers ------------------------------------------------------
    def _rpc(self, server, cmd, table_id, payload=b""):
        body = struct.pack("<BI", cmd, table_id) + payload

        def exchange():
            faults.fire("ps.rpc")
            s = self._socks[server]
            frame_send(s, body)
            return frame_recv(s)

        def repair(exc, attempt):
            if isinstance(exc, (ConnectionError, OSError)) and not isinstance(
                exc, faults.InjectedFault
            ):
                try:
                    self._reconnect(server)
                except OSError:
                    # server still down: let the policy's bounded backoff
                    # decide whether another attempt happens — a reconnect
                    # failure must not abort the retry loop early
                    pass

        if self._retry is None:
            body = exchange()
        else:
            try:
                body = self._retry.call(exchange, on_retry=repair)
            except (ConnectionError, OSError) as e:
                # a permanently dead PS exhausts the bounded policy; name
                # the endpoint and the budget instead of surfacing a bare
                # socket error (or, worse, retrying forever)
                raise ConnectionError(
                    f"parameter server {self._eps[server]} unreachable: "
                    f"cmd={cmd} failed after "
                    f"{self._retry.max_attempts} attempts ({e})"
                ) from e
        status = body[0]
        if status != 0:
            raise RuntimeError(
                f"PS rpc cmd={cmd} failed: {body[1:].decode(errors='replace')}"
            )
        return body[1:]

    # -- API ---------------------------------------------------------------
    def create_table(self, table_id, dim=0, dense_size=0, init_range=0.01,
                     optimizer=OPT_SGD, is_dense=False):
        payload = struct.pack(
            "<BIQfB", int(is_dense), dim, dense_size, init_range, optimizer
        )
        with self._lock:
            for srv in range(self.n_servers):
                self._rpc(srv, CMD_CREATE, table_id, payload)

    def _route(self, ids):
        """ids (u64 ndarray) -> per-server (ids, positions)."""
        srv = ids % self.n_servers
        out = []
        for sidx in range(self.n_servers):
            pos = np.nonzero(srv == sidx)[0]
            out.append((ids[pos], pos))
        return out

    def pull_sparse(self, table_id, ids, dim):
        """ids: 1-D uint64; returns [len(ids), dim] float32."""
        ids = np.ascontiguousarray(ids, dtype=np.uint64)
        out = np.empty((len(ids), dim), dtype=np.float32)
        with self._lock:
            for sidx, (sids, pos) in enumerate(self._route(ids)):
                if len(sids) == 0:
                    continue
                payload = struct.pack("<Q", len(sids)) + sids.tobytes()
                resp = self._rpc(sidx, CMD_PULL_SPARSE, table_id, payload)
                out[pos] = np.frombuffer(resp, dtype=np.float32).reshape(
                    len(sids), dim
                )
        return out

    def push_sparse(self, table_id, ids, grads, lr):
        ids = np.ascontiguousarray(ids, dtype=np.uint64)
        grads = np.ascontiguousarray(grads, dtype=np.float32)
        with self._lock:
            for sidx, (sids, pos) in enumerate(self._route(ids)):
                if len(sids) == 0:
                    continue
                payload = (
                    struct.pack("<fQ", lr, len(sids))
                    + sids.tobytes()
                    + grads[pos].tobytes()
                )
                self._rpc(sidx, CMD_PUSH_SPARSE, table_id, payload)

    def pull_dense(self, table_id):
        with self._lock:
            resp = self._rpc(0, CMD_PULL_DENSE, table_id)
        return np.frombuffer(resp, dtype=np.float32).copy()

    def push_dense(self, table_id, grads, lr):
        grads = np.ascontiguousarray(grads, dtype=np.float32)
        payload = struct.pack("<fQ", lr, grads.size) + grads.tobytes()
        with self._lock:
            self._rpc(0, CMD_PUSH_DENSE, table_id, payload)

    def save(self, table_id, path):
        """Checkpoint a table server-side (reference: checkpoint_notify_op —
        snapshots happen where the data lives). With multiple servers each
        saves its shard to <path>.shard<i>."""
        with self._lock:
            for sidx in range(self.n_servers):
                p = path if self.n_servers == 1 else f"{path}.shard{sidx}"
                payload = struct.pack("<I", len(p)) + p.encode()
                self._rpc(sidx, CMD_SAVE, table_id, payload)

    def load(self, table_id, path):
        with self._lock:
            for sidx in range(self.n_servers):
                p = path if self.n_servers == 1 else f"{path}.shard{sidx}"
                payload = struct.pack("<I", len(p)) + p.encode()
                self._rpc(sidx, CMD_LOAD, table_id, payload)

    def shrink(self, table_id, keep_versions=1000):
        dropped = 0
        with self._lock:
            for sidx in range(self.n_servers):
                resp = self._rpc(
                    sidx, CMD_SHRINK, table_id, struct.pack("<Q", keep_versions)
                )
                dropped += struct.unpack("<Q", resp)[0]
        return dropped

    def barrier(self, n_workers):
        with self._lock:
            self._rpc(0, CMD_BARRIER, 0, struct.pack("<I", n_workers))

    def heartbeat(self, worker_id):
        """Returns {worker_id: seconds_since_last_seen} as tracked by the
        chief server (reference: heart_beat_monitor.h:54)."""
        with self._lock:
            resp = self._rpc(0, CMD_HEARTBEAT, 0, struct.pack("<I", worker_id))
        (n,) = struct.unpack("<I", resp[:4])
        out = {}
        off = 4
        for _ in range(n):
            wid, age = struct.unpack("<If", resp[off:off + 8])
            out[wid] = age
            off += 8
        return out

    def table_stats(self):
        """{table_id: total_rows (sparse) / size (dense)} across servers."""
        out = {}
        with self._lock:
            for sidx in range(self.n_servers):
                resp = self._rpc(sidx, CMD_STATS, 0)
                (n,) = struct.unpack("<I", resp[:4])
                off = 4
                for _ in range(n):
                    tid, cnt = struct.unpack("<IQ", resp[off:off + 12])
                    out[tid] = out.get(tid, 0) + cnt
                    off += 12
        return out

    def stop_server(self):
        with self._lock:
            for sidx in range(self.n_servers):
                try:
                    self._rpc(sidx, CMD_STOP, 0)
                except (RuntimeError, ConnectionError, OSError):
                    pass

    def close(self):
        for s in self._socks:
            try:
                s.close()
            except OSError:
                pass
        self._socks = []


class Communicator:
    """Async gradient communicator: trainer threads enqueue sparse grads;
    a background thread merges duplicate ids and pushes batched updates
    (reference: paddle/fluid/operators/distributed/communicator.h:237
    AsyncCommunicator — send queues + merge + batched send; :365 GeoSgd).
    mode='sync' pushes inline; 'async' merges up to `merge_steps` batches."""

    def __init__(self, client, mode="async", merge_steps=4, max_queue=64):
        import queue as _q

        self._client = client
        self._mode = mode
        self._merge_steps = merge_steps
        self._queue = _q.Queue(maxsize=max_queue)
        self._thread = None
        self._stop = threading.Event()
        self._err = []
        if mode == "async":
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()

    def push_sparse(self, table_id, ids, grads, lr):
        import queue as _q

        if self._mode == "sync":
            self._client.push_sparse(table_id, ids, grads, lr)
            return
        item = (table_id, np.asarray(ids), np.asarray(grads), lr)
        # bounded put that keeps checking for a dead background thread —
        # blocking forever on a full queue would hide the PS failure
        while True:
            if self._err:
                raise self._err[0]
            try:
                self._queue.put(item, timeout=0.2)
                return
            except _q.Full:
                continue

    def _loop(self):
        import queue as _q

        while not self._stop.is_set():
            try:
                item = self._queue.get(timeout=0.05)
            except _q.Empty:
                continue
            batch = [item]
            for _ in range(self._merge_steps - 1):
                try:
                    batch.append(self._queue.get_nowait())
                except _q.Empty:
                    break
            try:
                self._flush(batch)
            except BaseException as e:
                # lockdep: ok(single append from the one loop thread before it exits; list.append is atomic under the GIL and readers only probe emptiness then index 0)
                self._err.append(e)
                return

    def _flush(self, batch):
        by_table = {}
        for table_id, ids, grads, lr in batch:
            by_table.setdefault((table_id, lr), []).append((ids, grads))
        for (table_id, lr), items in by_table.items():
            ids = np.concatenate([i for i, _ in items])
            grads = np.concatenate([g for _, g in items])
            # merge duplicate ids: sum grads (matches allreduce-sum semantics)
            uniq, inv = np.unique(ids, return_inverse=True)
            merged = np.zeros((len(uniq), grads.shape[1]), dtype=np.float32)
            np.add.at(merged, inv, grads)
            self._client.push_sparse(table_id, uniq, merged, lr)

    def flush(self):
        """Drain pending async pushes (barrier before save/eval)."""
        import queue as _q

        if self._mode != "async":
            return
        pending = []
        while True:
            try:
                pending.append(self._queue.get_nowait())
            except _q.Empty:
                break
        if pending:
            self._flush(pending)
        if self._err:
            raise self._err[0]

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.flush()


def main():
    """Standalone server: python -m paddle_tpu.distributed.ps --port 7164"""
    import argparse
    import time

    parser = argparse.ArgumentParser("paddle_tpu parameter server")
    parser.add_argument("--port", type=int, default=7164)
    args = parser.parse_args()
    srv = PSServer(args.port)
    print(f"PS listening on {srv.endpoint}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.stop()


if __name__ == "__main__":
    main()
