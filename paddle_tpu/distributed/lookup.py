"""In-graph remote sparse lookup: the PS pull/push INSIDE the compiled step.

Reference: paddle/fluid/operators/distributed/parameter_prefetch.cc:1 and
distributed_ops/prefetch_op.cc:1 — the reference splits ids by table shard,
RPCs the rows in, and merges them back *inside the operator*, so a huge
embedding table exists only on the parameter servers. The TPU translation:
`distributed_lookup_table` lowers to a `jax.experimental.io_callback` into
the PSClient (pull), and the backward wires a `distributed_push_sparse`
callback pushing the merged row grads. The step stays ONE XLA computation;
the callbacks ride the host-callback channel at the exact graph positions
where the reference ran its RPCs.

Double-buffered prefetch (the reference's prefetch thread): the data driver
announces the NEXT batch's ids via `RemoteLookupContext.prefetch`; the pull
callback then finds the rows already in flight and never blocks on the
network. `PSWorker.prefetch` / `train_from_dataset` call it one batch ahead.

The context is process-global (activated by fleet.init_worker): lowering a
remote lookup with NO active context raises instead of silently computing a
local-dense answer — a ported PS program must fail loudly, not train a
different model.
"""

import hashlib
import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from paddle_tpu.observability import lockdep
from paddle_tpu.resilience import faults
from paddle_tpu.resilience.retry import RetryPolicy
from paddle_tpu.utils.enforce import EnforceError, enforce

__all__ = [
    "RemoteLookupContext",
    "activate",
    "deactivate",
    "active_context",
    "set_retry_policy",
]

# Transient PS failures (connection blips, injected TransientFault) on the
# in-graph pull/push callbacks retry under the shared policy instead of
# killing the compiled step. Pulls are idempotent; a retried push is
# at-least-once (the server may double-apply a grad when the error struck
# after the apply) — the same trade the reference's async PS mode makes.
_retry = RetryPolicy(max_attempts=3, base_delay_s=0.05, max_delay_s=1.0,
                     deadline_s=30.0)


def set_retry_policy(policy):
    """Swap the pull/push retry policy (None disables retries)."""
    global _retry
    old, _retry = _retry, policy
    return old


def _with_retry(fn, *args):
    if _retry is None:
        return fn(*args)
    return _retry.call(fn, *args)

_active = None
_lock = threading.Lock()

# intended hierarchy: prefetch-map lock before the push fence — today
# every use is sequential (scan under one, wait under the other), and
# the declaration keeps a future nesting honest
lockdep.declare_order("lookup.prefetch", "lookup.push")


def activate(ctx):
    global _active
    with _lock:
        _active = ctx
    return ctx


def deactivate():
    global _active
    with _lock:
        ctx, _active = _active, None
    if ctx is not None:
        ctx.close()


def active_context():
    return _active


class RemoteLookupContext:
    """Host-side bridge between compiled-step callbacks and the PSClient."""

    def __init__(self, client, sparse_lr=0.1):
        self.client = client
        self.sparse_lr = sparse_lr
        self._tables = {}  # table_name -> {"table_id", "dim"}
        self._pending = {}  # (name, ids digest) -> Future
        self._pool = ThreadPoolExecutor(max_workers=8)
        self._plock = lockdep.named_lock("lookup.prefetch")
        self._push_cv = lockdep.named_condition("lookup.push")
        self._last_fence = 0
        self._closed = False
        # observability: sync pulls vs prefetch hits (tests assert on these)
        self.stats = {
            "pulls": 0, "prefetch_hits": 0, "pushes": 0, "stale_prefetch": 0,
        }

    def register(self, name, table_id, dim):
        self._tables[name] = {"table_id": int(table_id), "dim": int(dim)}

    def has(self, name):
        return name in self._tables

    # -- host callbacks ----------------------------------------------------
    @staticmethod
    def _digest(ids):
        """Content key for prefetch matching, canonicalized to a FLAT
        uint64 view: the in-graph callback sees int32 (x64 disabled) and
        sometimes a squeezed/unsqueezed shape, while the prefetching
        driver holds the original int64 feed — dtype, memory order, and
        trailing-1 shape differences must all hash identically or the
        prefetch silently misses (the rows are content-addressed; the
        requesting shape is reapplied at delivery in pull())."""
        a = np.ascontiguousarray(
            np.asarray(ids).astype(np.uint64).reshape(-1)
        )
        return (a.size, hashlib.sha1(a.tobytes()).hexdigest())

    def _pull_now(self, name, ids):
        t = self._tables[name]
        flat = np.asarray(ids).reshape(-1).astype(np.uint64)
        uniq, inv = np.unique(flat, return_inverse=True)

        def do_pull():
            faults.fire("lookup.pull")
            return self.client.pull_sparse(t["table_id"], uniq, t["dim"])

        rows = _with_retry(do_pull)
        return (
            rows[inv]
            .reshape(tuple(np.shape(ids)) + (t["dim"],))
            .astype(np.float32)
        )

    def pull(self, name, ids):
        """The in-graph pull callback (ordered: by the time it fires, every
        push of every earlier step has executed — that observed push count
        is the freshness requirement for a prefetched future)."""
        key = (name, self._digest(ids))
        with self._plock:
            fence_fut = self._pending.pop(key, None)
        if fence_fut is not None:
            fence, fut = fence_fut
            with self._push_cv:
                observed = self.stats["pushes"]
            if fence == observed:
                pulled_at, rows = fut.result()
                if pulled_at >= fence:
                    self.stats["prefetch_hits"] += 1
                    # the future was announced under the DRIVER's shape;
                    # reshape to the requesting callback's (same content
                    # by digest, possibly squeezed differently)
                    return rows.reshape(
                        tuple(np.shape(ids)) + (rows.shape[-1],)
                    )
                # the background pull timed out waiting for the fence and
                # read PRE-push rows; the pushes landed afterwards, so the
                # current count looks right but the rows are stale —
                # validate the count recorded AT pull time, never the
                # count now (ADVICE r5 low)
                self.stats["stale_prefetch"] += 1
            else:
                # mispredicted fence: the future either pulled too early
                # (stale rows) or waits on pushes this very step must
                # produce (would deadlock) — drop it and pull fresh
                self.stats["stale_prefetch"] += 1
        self.stats["pulls"] += 1
        return self._pull_now(name, ids)

    def prefetch(self, name, ids, min_push_count=0):
        """Start pulling `ids`' rows in the background; the step's pull
        callback collects the future by content digest.

        `min_push_count`: the pull waits until that many pushes have
        completed — announcing batch N+1's ids while step N is still in
        flight must NOT read rows that step N's backward is about to
        update (one-step-stale rows silently change the training
        trajectory). prefetch_for_program computes the fence; the pull
        callback re-validates it against the pushes actually observed and
        discards a mispredicted future."""
        ids = np.asarray(ids)
        key = (name, self._digest(ids))
        with self._plock:
            if key not in self._pending:
                self._pending[key] = (
                    min_push_count,
                    self._pool.submit(
                        self._pull_after, name, ids, min_push_count
                    ),
                )

    def next_fence(self, n_push):
        """Push count that must land before the NEXT step's pull may read:
        every earlier announced step contributes its n_push pushes even
        when they haven't executed yet (async dispatch)."""
        with self._push_cv:
            base = max(self.stats["pushes"], self._last_fence)
            fence = base + n_push
            self._last_fence = fence
        return fence

    def _pull_after(self, name, ids, min_pushes):
        """Returns (pushes_observed_at_pull_time, rows). The observed count
        is recorded BEFORE the pull so it is a lower bound on the rows'
        freshness — pull() accepts the future only when that recorded count
        has reached the fence (a 60s-timeout early pull records a smaller
        count and is rejected instead of being served as a fresh hit)."""
        with self._push_cv:
            if min_pushes:
                # timeout fallback: a failed step would otherwise wedge
                # every later prefetch behind a push that never comes
                self._push_cv.wait_for(
                    lambda: self._closed
                    or self.stats["pushes"] >= min_pushes,
                    timeout=60,
                )
                if self._closed:
                    raise RuntimeError("remote lookup context closed")
            observed = self.stats["pushes"]
        return observed, self._pull_now(name, ids)

    def push(self, name, ids, grad):
        """Merge duplicate-id grads (sum — dense scatter-add semantics) and
        push; the server applies its optimizer rule at sparse_lr."""
        t = self._tables[name]
        flat = np.asarray(ids).reshape(-1).astype(np.uint64)
        g = np.asarray(grad, dtype=np.float32).reshape(len(flat), t["dim"])
        uniq, inv = np.unique(flat, return_inverse=True)
        merged = np.zeros((len(uniq), t["dim"]), dtype=np.float32)
        np.add.at(merged, inv, g)

        def do_push():
            faults.fire("lookup.push")
            self.client.push_sparse(t["table_id"], uniq, merged,
                                    self.sparse_lr)

        _with_retry(do_push)
        with self._push_cv:
            self.stats["pushes"] += 1
            self._push_cv.notify_all()

    def close(self):
        """Unblock waiting prefetch tasks and release the pool threads —
        a pull racing a closing PSClient must die in its future, not hit a
        closed socket later."""
        with self._push_cv:
            self._closed = True
            self._push_cv.notify_all()
        with self._plock:
            pending = list(self._pending.values())
            self._pending.clear()
        for _, fut in pending:
            fut.cancel()
        self._pool.shutdown(wait=False)


# -- module-level callback targets (resolve the context at CALL time so a
#    compiled step survives worker re-init) --------------------------------


def _require_ctx(name):
    ctx = active_context()
    enforce(
        ctx is not None and ctx.has(name),
        f"remote table '{name}' has no active lookup context — "
        "fleet.init_worker() must run before the step executes",
    )
    return ctx


def prefetch_for_program(program, next_feed):
    """Announce the NEXT batch's ids for every in-graph remote table of
    `program`, fenced behind the in-flight step's pushes (one push per
    distributed_push_sparse op) so the prefetched rows reflect the current
    step's update. The canonical driver for Executor.train_from_dataset,
    PSWorker.prefetch, and hand-rolled training loops."""
    prog = getattr(program, "program", program)  # unwrap CompiledProgram
    tables = getattr(prog, "_remote_tables", None)
    ctx = active_context()
    if not tables or ctx is None:
        return
    n_push = sum(
        1
        for op in prog.global_block().ops
        if op.type == "distributed_push_sparse"
    )
    fence = ctx.next_fence(n_push)
    for tname, t in tables.items():
        ids = next_feed.get(t["ids"])
        if ids is None:
            continue
        ids = np.asarray(ids)
        if ids.ndim >= 2 and ids.shape[-1] == 1:
            ids = ids[..., 0]
        ctx.prefetch(t.get("table_name", tname), ids, min_push_count=fence)


def pull_host(name, ids):
    return _require_ctx(name).pull(name, ids)


def push_host(name, ids, grad):
    _require_ctx(name).push(name, ids, grad)
    return ()
