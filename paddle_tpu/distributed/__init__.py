"""Distributed launch utilities (reference: python/paddle/distributed/)."""

from paddle_tpu.distributed.launch import launch_procs  # noqa: F401
