"""Gradient clipping as program rewrites
(reference: python/paddle/fluid/clip.py — GradientClipByValue,
GradientClipByNorm, GradientClipByGlobalNorm)."""

from paddle_tpu.layer_helper import LayerHelper


class GradientClipBase:
    def __call__(self, params_grads):
        raise NotImplementedError


class GradientClipByValue(GradientClipBase):
    def __init__(self, max, min=None):
        self.max = max
        self.min = -max if min is None else min

    def __call__(self, params_grads):
        from paddle_tpu import layers

        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            out.append((p, layers.clip(g, self.min, self.max)))
        return out


class GradientClipByNorm(GradientClipBase):
    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def __call__(self, params_grads):
        from paddle_tpu import layers

        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            out.append((p, layers.clip_by_norm(g, self.clip_norm)))
        return out


class GradientClipByGlobalNorm(GradientClipBase):
    """g_i *= clip_norm / max(global_norm, clip_norm)."""

    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def __call__(self, params_grads):
        from paddle_tpu import layers
        from paddle_tpu.layers import tensor

        helper = LayerHelper("global_norm_clip")
        sq_norms = []
        for p, g in params_grads:
            if g is None:
                continue
            sq = helper.create_variable_for_type_inference(g.dtype)
            helper.append_op(
                "squared_l2_norm", {"X": [g.name]}, {"Out": [sq.name]}, {"op_role": 1}
            )
            sq_norms.append(sq)
        if not sq_norms:
            return params_grads
        total = helper.create_variable_for_type_inference("float32")
        helper.append_op(
            "sum",
            {"X": [v.name for v in sq_norms]},
            {"Out": [total.name]},
            {"op_role": 1},
        )
        global_norm = layers.sqrt(total)
        clip_var = tensor.fill_constant([1], "float32", self.clip_norm)
        denom = layers.elementwise_max(global_norm, clip_var)
        scale_factor = layers.elementwise_div(clip_var, denom)
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            out.append((p, layers.elementwise_mul(g, scale_factor)))
        return out


def set_gradient_clip(clip, param_list=None, program=None):
    import warnings

    warnings.warn("set_gradient_clip is deprecated; pass grad_clip= to the optimizer")


ErrorClipByValue = GradientClipByValue
