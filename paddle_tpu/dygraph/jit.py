"""Dygraph-to-static: TracedLayer + declarative.

reference: python/paddle/fluid/dygraph/jit.py (TracedLayer traces a dygraph
Layer into a static Program) and dygraph_to_static/ast_transformer.py. The
reference rewrites Python ASTs to turn imperative code into ProgramDesc; here
the SAME forward code traces into a Program via the capture mode in
dygraph/base.py — no AST surgery, mirroring how jax.jit replaces
torch.jit.script on TPU. The captured Program then runs on the whole-block
XLA executor (fast path) and exports via save_inference_model."""

import numpy as np

from paddle_tpu.core.executor import Executor
from paddle_tpu.core.ir import Program, program_guard
from paddle_tpu.core.places import TPUPlace
from paddle_tpu.core.scope import Scope, scope_guard
from paddle_tpu.dygraph.base import no_grad_ctx, static_capture, to_variable
from paddle_tpu.dygraph.varbase import VarBase
from paddle_tpu.utils import unique_name
from paddle_tpu.utils.enforce import enforce


class TracedLayer:
    """Static program captured from a dygraph Layer
    (reference: python/paddle/fluid/dygraph/jit.py TracedLayer)."""

    def __init__(self, main_program, startup_program, feed_vars, fetch_vars):
        self._main = main_program
        self._startup = startup_program
        self._feed = feed_vars
        self._fetch = fetch_vars
        self._scope = Scope()
        self._exe = Executor(TPUPlace(0))
        with scope_guard(self._scope):
            self._exe.run(self._startup)

    @staticmethod
    def trace(layer, inputs):
        """Run `layer` once under capture; returns (dygraph_outputs,
        traced_layer)."""
        inputs = [inputs] if isinstance(inputs, VarBase) else list(inputs)
        # run once eagerly for the dygraph outputs
        dy_outs = layer(*inputs)

        main, startup = Program(), Program()
        with program_guard(main, startup), static_capture(main, startup) as cap:
            feed_vars = []
            proxies = []
            for vb in inputs:
                value = np.asarray(vb.value)
                sv = main.global_block().create_var(
                    name=unique_name.generate("traced_feed"),
                    shape=list(value.shape),
                    dtype=str(value.dtype),
                    is_data=True,
                )
                proxy = VarBase.from_static(sv, stop_gradient=True)
                cap.var_map[id(proxy)] = sv
                feed_vars.append(sv)
                proxies.append(proxy)
            with no_grad_ctx():
                outs = layer(*proxies)
            outs_list = outs if isinstance(outs, (list, tuple)) else [outs]
            fetch_vars = [o.static_var for o in outs_list]
        return dy_outs, TracedLayer(main, startup, feed_vars, fetch_vars)

    def __call__(self, inputs):
        inputs = [inputs] if isinstance(inputs, VarBase) else list(inputs)
        feed = {
            v.name: np.asarray(vb.value) for v, vb in zip(self._feed, inputs)
        }
        with scope_guard(self._scope):
            outs = self._exe.run(self._main, feed=feed, fetch_list=[f.name for f in self._fetch])
        return [to_variable(o) for o in outs]

    @property
    def program(self):
        return self._main

    def save_inference_model(self, dirname, feed=None, fetch=None):
        from paddle_tpu import io

        feed_vars = self._feed if feed is None else [self._feed[i] for i in feed]
        fetch_vars = self._fetch if fetch is None else [self._fetch[i] for i in fetch]
        with scope_guard(self._scope):
            io.save_inference_model(
                dirname,
                [v.name for v in feed_vars],
                fetch_vars,
                self._exe,
                main_program=self._main,
            )


def _signature(args):
    sig = []
    for a in args:
        if isinstance(a, VarBase):
            sig.append(("var", tuple(a.shape), str(a.dtype)))
        elif isinstance(a, np.ndarray):
            sig.append(("np", a.shape, str(a.dtype)))
        else:
            sig.append(("const", a))
    return tuple(sig)


def declarative(fn):
    """Decorator: compile a dygraph function to a static program per input
    signature (reference: dygraph_to_static @declarative). Data-dependent
    `if` statements are AST-converted to both-branch `where` selection
    (dygraph/ast_transform.py, the reference's IfElseTransformer analog);
    non-convertible control flow keeps the loud capture-guard error."""
    from paddle_tpu.dygraph.ast_transform import convert_ifelse

    traced_fn = convert_ifelse(fn)
    cache = {}

    def wrapper(*args):
        vb_args = [
            a if isinstance(a, VarBase) else to_variable(np.asarray(a)) for a in args
        ]
        key = _signature(vb_args)
        if key not in cache:

            class _FnLayer:
                def __call__(self, *xs):
                    return traced_fn(*xs)

            _, traced = TracedLayer.trace(_FnLayer(), vb_args)
            cache[key] = traced
        outs = cache[key](vb_args)
        return outs[0] if len(outs) == 1 else outs

    wrapper.__wrapped__ = fn
    return wrapper


to_static = declarative
