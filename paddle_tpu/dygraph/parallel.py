"""Dygraph data parallelism (reference: python/paddle/fluid/dygraph/
parallel.py:223 DataParallel + :54 ParallelEnv; C++ side paddle/fluid/
imperative/nccl_context.cc).

The reference coalesces gradients after backward and all-reduces them over
NCCL rings. TPU-native: each SPMD process holds its shard of the batch; after
`loss.backward()`, `apply_collective_grads` runs ONE jitted psum over the
global device mesh (XLA lowers it onto ICI/DCN), with all gradients flattened
and concatenated into coalesced buckets exactly like the reference's
coalesce_grad_tensor_pass."""

import functools

import numpy as np

import jax
import jax.numpy as jnp

from paddle_tpu.dygraph.layers import Layer
from paddle_tpu.parallel.env import ParallelEnv


def prepare_context():
    """reference: dygraph/parallel.py prepare_context — under jax SPMD the
    collective bootstrap is jax.distributed.initialize, done at launch."""
    return ParallelEnv()


class DataParallel(Layer):
    def __init__(self, layers, strategy=None):
        super().__init__()
        self._layers = layers
        self._env = ParallelEnv()
        self._nranks = max(self._env.nranks, 1)

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        """Divide by trainer count so the post-allreduce gradient is the
        global mean (reference: parallel.py scale_loss)."""
        if self._nranks <= 1:
            return loss
        return loss * (1.0 / self._nranks)

    def apply_collective_grads(self):
        """Sum gradients across processes (reference: parallel.py
        apply_collective_grads — coalesce + allreduce)."""
        if self._nranks <= 1:
            return
        params = [p for p in self._layers.parameters() if p.grad_value is not None]
        if not params:
            return
        grads = [p.grad_value for p in params]
        summed = _global_psum(grads)
        for p, g in zip(params, summed):
            p.grad_value = g

    def state_dict(self, include_sublayers=True):
        return self._layers.state_dict(include_sublayers)

    def set_dict(self, state_dict, include_sublayers=True):
        return self._layers.set_dict(state_dict, include_sublayers)

    load_dict = set_dict

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def clear_gradients(self):
        self._layers.clear_gradients()


def _global_psum(grads):
    """One coalesced cross-process all-reduce. Buckets all grads into a flat
    buffer (the reference's coalesce_grad_tensor_pass), psums it over every
    device, splits back."""
    shapes = [g.shape for g in grads]
    sizes = [int(np.prod(s)) if s else 1 for s in shapes]
    flat = jnp.concatenate([g.reshape(-1).astype(jnp.float32) for g in grads])

    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devices = jax.devices()
    mesh = Mesh(np.array(devices), ("world",))
    row_sharding = NamedSharding(mesh, P("world"))
    repl = NamedSharding(mesh, P())
    # each process's DIFFERENT local gradients become row 0 of its LOCAL
    # block of a [world, size] global array (a local array cannot be fed to
    # a sharding spanning non-addressable devices); extra local devices
    # carry zero rows so the row-sum — GSPMD's cross-process allreduce over
    # DCN/ICI — counts each process's gradients exactly once. The same
    # construction covers the single-process case.
    n_local = len(jax.local_devices())
    local_rows = np.zeros((n_local, flat.shape[0]), np.float32)
    local_rows[0] = np.asarray(flat)
    stacked = jax.make_array_from_process_local_data(row_sharding, local_rows)

    summed = _row_sum(stacked, repl)
    if jax.process_count() > 1:
        # hand back a LOCAL array: the replicated global result is not a
        # valid input for single-device work downstream (device_put to a
        # local device would try to touch peers' devices)
        summed = jnp.asarray(np.asarray(summed.addressable_data(0)))
    out, off = [], 0
    for shape, size, g in zip(shapes, sizes, grads):
        out.append(summed[off : off + size].reshape(shape).astype(g.dtype))
        off += size
    return out


@functools.partial(jax.jit, static_argnums=1)
def _row_sum(x, out_sharding):
    return jax.lax.with_sharding_constraint(x.sum(axis=0), out_sharding)
