"""Dygraph data parallelism (reference: python/paddle/fluid/dygraph/
parallel.py:223 DataParallel + :54 ParallelEnv; C++ side paddle/fluid/
imperative/nccl_context.cc).

The reference coalesces gradients after backward and all-reduces them over
NCCL rings. TPU-native: each SPMD process holds its shard of the batch; after
`loss.backward()`, `apply_collective_grads` runs ONE jitted psum over the
global device mesh (XLA lowers it onto ICI/DCN), with all gradients flattened
and concatenated into coalesced buckets exactly like the reference's
coalesce_grad_tensor_pass."""

import numpy as np

import jax
import jax.numpy as jnp

from paddle_tpu.dygraph.layers import Layer
from paddle_tpu.parallel.env import ParallelEnv


def prepare_context():
    """reference: dygraph/parallel.py prepare_context — under jax SPMD the
    collective bootstrap is jax.distributed.initialize, done at launch."""
    return ParallelEnv()


class DataParallel(Layer):
    def __init__(self, layers, strategy=None):
        super().__init__()
        self._layers = layers
        self._env = ParallelEnv()
        self._nranks = max(self._env.nranks, 1)

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        """Divide by trainer count so the post-allreduce gradient is the
        global mean (reference: parallel.py scale_loss)."""
        if self._nranks <= 1:
            return loss
        return loss * (1.0 / self._nranks)

    def apply_collective_grads(self):
        """Sum gradients across processes (reference: parallel.py
        apply_collective_grads — coalesce + allreduce)."""
        if self._nranks <= 1:
            return
        params = [p for p in self._layers.parameters() if p.grad_value is not None]
        if not params:
            return
        grads = [p.grad_value for p in params]
        summed = _global_psum(grads)
        for p, g in zip(params, summed):
            p.grad_value = g

    def state_dict(self, include_sublayers=True):
        return self._layers.state_dict(include_sublayers)

    def set_dict(self, state_dict, include_sublayers=True):
        return self._layers.set_dict(state_dict, include_sublayers)

    load_dict = set_dict

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def clear_gradients(self):
        self._layers.clear_gradients()


def _global_psum(grads):
    """One coalesced cross-process all-reduce. Buckets all grads into a flat
    buffer (the reference's coalesce_grad_tensor_pass), psums it over every
    device, splits back."""
    shapes = [g.shape for g in grads]
    sizes = [int(np.prod(s)) if s else 1 for s in shapes]
    flat = jnp.concatenate([g.reshape(-1).astype(jnp.float32) for g in grads])

    devices = jax.devices()
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    mesh = Mesh(np.array(devices), ("world",))

    @jax.jit
    def allreduce(x):
        return shard_map(
            lambda v: jax.lax.psum(v, "world"),
            mesh=mesh,
            in_specs=P(None),
            out_specs=P(None),
        )(x)

    summed = allreduce(flat)
    out, off = [], 0
    for shape, size, g in zip(shapes, sizes, grads):
        out.append(summed[off : off + size].reshape(shape).astype(g.dtype))
        off += size
    return out
