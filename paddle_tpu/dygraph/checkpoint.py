"""save_dygraph / load_dygraph (reference: python/paddle/fluid/dygraph/
checkpoint.py — state-dict persistence). Format: one .npz of arrays plus the
suffix conventions of the reference (.pdparams for layer state, .pdopt for
optimizer state)."""

import os

import numpy as np

from paddle_tpu.utils.enforce import enforce


def _save_state(state_dict, path):
    arrays, meta = {}, {}
    for i, (name, val) in enumerate(state_dict.items()):
        key = f"arr_{i}"
        arrays[key] = np.asarray(val)
        meta[key] = name
    arrays["__names__"] = np.array(
        [meta[f"arr_{i}"] for i in range(len(meta))], dtype=object
    )
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    np.savez(path, **{k: v for k, v in arrays.items() if k != "__names__"},
             __names__=arrays["__names__"])


def _load_state(path):
    with np.load(path, allow_pickle=True) as data:
        names = list(data["__names__"])
        return {
            str(name): data[f"arr_{i}"] for i, name in enumerate(names)
        }


def save_dygraph(state_dict, model_path):
    """reference: python/paddle/fluid/dygraph/checkpoint.py save_dygraph."""
    enforce(bool(state_dict), "empty state_dict")
    # optimizer states carry non-array entries? normalize everything to arrays
    suffix = ".pdparams"
    for v in state_dict.values():
        if np.asarray(v).dtype == object:
            suffix = ".pdopt"
            break
    _save_state(state_dict, model_path + suffix + ".npz")


def load_dygraph(model_path):
    """Returns (param_state_dict, optimizer_state_dict) — either may be None
    (reference: checkpoint.py load_dygraph)."""
    params, opt = None, None
    p = model_path + ".pdparams.npz"
    if os.path.exists(p):
        params = _load_state(p)
    o = model_path + ".pdopt.npz"
    if os.path.exists(o):
        opt = _load_state(o)
    enforce(
        params is not None or opt is not None,
        f"no checkpoint found at {model_path}(.pdparams/.pdopt).npz",
    )
    return params, opt
