"""VarBase: the eager tensor (reference: paddle/fluid/imperative/layer.h:61
VarBase — a refcounted wrapper of framework::Variable with a grad var and
autograd hooks; python surface python/paddle/fluid/dygraph/
varbase_patch_methods.py). Here the payload is a jax.Array; the grad var is
`grad_value`, populated by the tape walk in base.run_backward."""

import numpy as np

import jax.numpy as jnp

from paddle_tpu.utils import unique_name
from paddle_tpu.utils.enforce import EnforceError, enforce


class VarBase:
    def __init__(self, value, name=None, stop_gradient=False, persistable=False):
        self.value = value
        self.name = name or unique_name.generate("generated_tensor")
        self.stop_gradient = stop_gradient
        self.persistable = persistable
        self.grad_value = None
        self.static_var = None  # set when this is a capture-mode proxy

    @classmethod
    def from_static(cls, static_var, stop_gradient=False):
        """Capture-mode proxy bound to an existing static Variable (no
        eager value): ops tracing through it reference `static_var` by
        name. The jit tracer, op capture, and the loop transform all build
        proxies this way."""
        vb = cls.__new__(cls)
        vb.value = None
        vb.name = static_var.name
        vb.stop_gradient = stop_gradient
        vb.persistable = False
        vb.grad_value = None
        vb.static_var = static_var
        return vb

    # -- autograd ------------------------------------------------------
    def backward(self, retain_graph=False):
        from paddle_tpu.dygraph.base import run_backward

        run_backward(self, retain_graph=retain_graph)

    def _accumulate_grad(self, g):
        self.grad_value = g

    def gradient(self):
        return None if self.grad_value is None else np.asarray(self.grad_value)

    @property
    def grad(self):
        return self.grad_value

    def clear_gradient(self):
        self.grad_value = None

    # -- data access ---------------------------------------------------
    def numpy(self):
        enforce(self.value is not None, f"{self.name} has no value (capture proxy)")
        return np.asarray(self.value)

    def detach(self):
        out = VarBase(self.value, name=self.name + ".detach", stop_gradient=True)
        return out

    def item(self):
        return self.numpy().item()

    @property
    def shape(self):
        if self.value is not None:
            return list(self.value.shape)
        return list(self.static_var.shape) if self.static_var is not None else None

    @property
    def dtype(self):
        if self.value is not None:
            return str(self.value.dtype)
        return self.static_var.dtype if self.static_var is not None else None

    def astype(self, dtype):
        from paddle_tpu.dygraph.base import trace_op

        return trace_op("cast", {"X": [self]}, {"out_dtype": str(dtype)})["Out"][0]

    def numel(self):
        return int(np.prod(self.shape)) if self.shape else 1

    def set_value(self, value):
        arr = np.asarray(value.numpy() if isinstance(value, VarBase) else value)
        enforce(
            tuple(arr.shape) == tuple(self.shape),
            f"set_value shape mismatch: {arr.shape} vs {self.shape}",
        )
        self.value = jnp.asarray(arr.astype(np.asarray(self.value).dtype))

    def __len__(self):
        return self.shape[0] if self.shape else 0

    # -- control-flow capture guards -----------------------------------
    # A Python `if`/`while` on a tensor calls __bool__. Eagerly that is
    # fine (the value exists); under dygraph-to-static capture the value
    # is symbolic, and Python would otherwise take the default object
    # truthiness (always True) and SILENTLY bake one branch into the
    # traced program (reference fixes this with AST rewriting,
    # dygraph_to_static/ast_transformer.py; the TPU-native contract is a
    # loud trace-time error instead — use layers.cond / layers.while_loop
    # or keep the code eager).
    def _concrete(self, what):
        if self.value is None:
            raise EnforceError(
                f"cannot convert symbolic tensor '{self.name}' to {what} "
                "during dygraph-to-static capture: a Python branch/loop on "
                "a traced value would silently bake one path into the "
                "program. Rewrite the data-dependent control flow with "
                "fluid.layers.cond / fluid.layers.while_loop (or a "
                "vectorized select like fluid.layers.where), or run the "
                "layer eagerly instead of tracing it"
            )
        return np.asarray(self.value)

    def _scalar(self, what):
        """The single element of a size-1 tensor, extracted explicitly:
        numpy >= 1.25 deprecates the implicit ndim>0 -> scalar conversion
        that ``int(np.array([3]))`` used to do. Multi-element tensors
        keep numpy's error semantics (ambiguous truth / no conversion)."""
        arr = self._concrete(what)
        if arr.ndim and arr.size == 1:
            return arr.reshape(())[()]
        return arr

    def __bool__(self):
        return bool(self._scalar("bool"))

    def __float__(self):
        return float(self._scalar("float"))

    def __int__(self):
        return int(self._scalar("int"))

    def __index__(self):
        return int(self._scalar("index"))

    def __repr__(self):
        tag = "ParamBase" if getattr(self, "trainable", None) is not None else "VarBase"
        return f"{tag}(name={self.name}, shape={self.shape}, dtype={self.dtype})"

    # -- math sugar (reference: math_op_patch applied to VarBase) ------
    def _binary(self, other, op_type, reverse=False):
        from paddle_tpu.dygraph.base import to_variable, trace_op

        if not isinstance(other, VarBase):
            other = to_variable(
                # self.dtype works for capture proxies too (value is None)
                np.full((1,), other, dtype=np.dtype(self.dtype))
            )
        x, y = (other, self) if reverse else (self, other)
        return trace_op(op_type, {"X": [x], "Y": [y]}, {"axis": -1})["Out"][0]

    def __add__(self, o):
        return self._binary(o, "elementwise_add")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binary(o, "elementwise_sub")

    def __rsub__(self, o):
        return self._binary(o, "elementwise_sub", reverse=True)

    def __mul__(self, o):
        return self._binary(o, "elementwise_mul")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binary(o, "elementwise_div")

    def __rtruediv__(self, o):
        return self._binary(o, "elementwise_div", reverse=True)

    def __matmul__(self, o):
        from paddle_tpu.dygraph.base import trace_op

        return trace_op("matmul", {"X": [self], "Y": [o]}, {})["Out"][0]

    # comparisons (reference: math_op_patch monkey-patches these too) —
    # they return TENSORS; a Python `if` on the result goes through
    # __bool__, which is guarded against capture proxies above
    def __gt__(self, o):
        return self._binary(o, "greater_than")

    def __ge__(self, o):
        return self._binary(o, "greater_equal")

    def __lt__(self, o):
        return self._binary(o, "less_than")

    def __le__(self, o):
        return self._binary(o, "less_equal")

    def __neg__(self):
        from paddle_tpu.dygraph.base import trace_op

        return trace_op("scale", {"X": [self]}, {"scale": -1.0})["Out"][0]

    def __iter__(self):
        """Row iteration (`for row in x`), matching the reference's tensor
        iteration. Requires a static leading dim — without this method,
        Python's fallback iteration protocol would call __getitem__ with
        ever-growing indices and never terminate (our slice op cannot
        raise IndexError). The validation runs HERE (not in the generator)
        so iter(x) fails at the call site, not at the first next()."""
        shape = self.shape
        enforce(
            shape is not None and len(shape) > 0,
            f"cannot iterate '{self.name}': 0-d tensors are not iterable",
        )
        enforce(
            shape[0] is not None and shape[0] >= 0,
            f"cannot iterate '{self.name}': leading dimension is not "
            "statically known",
        )
        return (self[i] for i in range(shape[0]))

    def __getitem__(self, idx):
        from paddle_tpu.core.ir import parse_getitem_index
        from paddle_tpu.dygraph.base import trace_op

        axes, starts, ends, squeeze_axes = parse_getitem_index(idx)
        if not axes:
            return self
        out = trace_op(
            "slice",
            {"Input": [self]},
            {"axes": axes, "starts": starts, "ends": ends},
        )["Out"][0]
        if squeeze_axes:
            out = trace_op("squeeze2", {"X": [out]}, {"axes": squeeze_axes})["Out"][0]
        return out


class ParamBase(VarBase):
    """Eager parameter (reference: VarBase with persistable=True +
    python/paddle/fluid/framework.py ParamBase semantics)."""

    def __init__(self, value, name=None, trainable=True, **kwargs):
        super().__init__(value, name=name, persistable=True)
        self.trainable = trainable
        self.stop_gradient = not trainable
        self.optimize_attr = kwargs.pop("optimize_attr", {"learning_rate": 1.0})
        self.regularizer = kwargs.pop("regularizer", None)
        self.do_model_average = kwargs.pop("do_model_average", None)
        self.is_distributed = False
