"""Containers (reference: python/paddle/fluid/dygraph/container.py —
Sequential, ParameterList, LayerList)."""

from paddle_tpu.dygraph.layers import Layer
from paddle_tpu.dygraph.varbase import ParamBase


class Sequential(Layer):
    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], (list, tuple)) and layers and not isinstance(layers[0], Layer):
            layers = layers[0]
        for i, item in enumerate(layers):
            if isinstance(item, (list, tuple)):
                name, layer = item
            else:
                name, layer = str(i), item
            self.add_sublayer(name, layer)

    def __getitem__(self, name):
        return self._sub_layers[str(name)]

    def __setitem__(self, name, layer):
        self.add_sublayer(str(name), layer)

    def __len__(self):
        return len(self._sub_layers)

    def forward(self, input):
        for layer in self._sub_layers.values():
            input = layer(input)
        return input


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        for i, layer in enumerate(sublayers or []):
            self.add_sublayer(str(i), layer)

    def __getitem__(self, idx):
        return self._sub_layers[str(idx)]

    def __setitem__(self, idx, layer):
        self.add_sublayer(str(idx), layer)

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def append(self, layer):
        self.add_sublayer(str(len(self._sub_layers)), layer)
        return self


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        for i, p in enumerate(parameters or []):
            self.add_parameter(str(i), p)

    def __getitem__(self, idx):
        return self._parameters[str(idx)]

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())

    def append(self, parameter):
        self.add_parameter(str(len(self._parameters)), parameter)
        return self
