"""Imperative (dygraph) mode — TPU-native eager execution.

reference: python/paddle/fluid/dygraph/ and paddle/fluid/imperative/.
See base.py for the tracer/tape design."""

from paddle_tpu.dygraph.base import (
    enable_dygraph,
    disable_dygraph,
    guard,
    in_dygraph_mode,
    no_grad,
    to_variable,
    trace_op,
    _dygraph_tracer,
)
from paddle_tpu.dygraph.varbase import ParamBase, VarBase
from paddle_tpu.dygraph.layers import Layer
from paddle_tpu.dygraph.container import LayerList, ParameterList, Sequential
from paddle_tpu.dygraph import nn
from paddle_tpu.dygraph.nn import (
    BatchNorm,
    Conv2D,
    Conv2DTranspose,
    Dropout,
    Embedding,
    GroupNorm,
    GRUUnit,
    InstanceNorm,
    LayerNorm,
    Linear,
    Pool2D,
    PRelu,
)
from paddle_tpu.dygraph.checkpoint import load_dygraph, save_dygraph
from paddle_tpu.dygraph.parallel import DataParallel, ParallelEnv, prepare_context
from paddle_tpu.dygraph.jit import TracedLayer, declarative, to_static
