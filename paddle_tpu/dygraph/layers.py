"""Layer: the dygraph module base class
(reference: python/paddle/fluid/dygraph/layers.py Layer — parameter/sublayer
registration via __setattr__, train/eval mode, state_dict). Parameters are
initialized EAGERLY by running the initializer's op through the same registry
lowering the startup program would use — identical initializer streams in
dygraph and static mode, a prerequisite for static/dygraph loss parity
(the reference tests this in test_imperative_resnet.py)."""

import collections

import numpy as np

import jax.numpy as jnp

from paddle_tpu.core.dtypes import to_numpy_dtype
from paddle_tpu.core.registry import get_op_def
from paddle_tpu.dygraph.base import _dygraph_tracer, in_capture_mode, trace_op
from paddle_tpu.dygraph.varbase import ParamBase, VarBase
from paddle_tpu.initializer import ConstantInitializer, XavierInitializer
from paddle_tpu.param_attr import ParamAttr
from paddle_tpu.utils import unique_name
from paddle_tpu.utils.enforce import enforce


def eager_initialize(shape, dtype, initializer):
    """Run an initializer eagerly: let it append its op(s) to a scratch block,
    then execute those ops through their registry lowerings."""
    from paddle_tpu.core.ir import Program

    prog = Program()
    block = prog.global_block()
    var = block.create_var(name="__init_target__", shape=list(shape), dtype=dtype)
    initializer(var, block)
    tracer = _dygraph_tracer()
    env = {}
    for op in block.ops:
        op_def = get_op_def(op.type)
        ins = {
            slot: [env[n] for n in names]
            for slot, names in op.inputs.items()
            if names and all(n in env for n in names)
        }
        if op_def.stateful:
            key = (
                tracer.next_rng_key()
                if tracer is not None
                else __import__("jax").random.PRNGKey(0)
            )
            ins["__rng_key__"] = [key]
        outs = op_def.lower(ins, op.attrs)
        for slot, names in op.outputs.items():
            vals = outs.get(slot)
            if vals is None:
                continue
            if not isinstance(vals, (list, tuple)):
                vals = [vals]
            for name, val in zip(names, vals):
                env[name] = val
    return env["__init_target__"]


class Layer:
    """reference: python/paddle/fluid/dygraph/layers.py Layer."""

    def __init__(self, name_scope=None, dtype="float32"):
        base = name_scope or self.__class__.__name__.lower()
        self._full_name = unique_name.generate(base)
        self._dtype = dtype
        self.training = True
        self._parameters = collections.OrderedDict()
        self._sub_layers = collections.OrderedDict()
        self._buffers = collections.OrderedDict()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()

    def full_name(self):
        return self._full_name

    # -- parameter management -----------------------------------------
    def create_parameter(
        self,
        shape,
        attr=None,
        dtype=None,
        is_bias=False,
        default_initializer=None,
    ):
        attr = ParamAttr._to_attr(attr)
        dtype = dtype or self._dtype
        init = (
            attr.initializer
            or default_initializer
            or (ConstantInitializer(0.0) if is_bias else XavierInitializer())
        )
        value = eager_initialize(shape, dtype, init)
        name = attr.name or unique_name.generate(f"{self._full_name}.w")
        p = ParamBase(
            value,
            name=name,
            trainable=attr.trainable,
            optimize_attr={"learning_rate": attr.learning_rate},
            regularizer=attr.regularizer,
        )
        return p

    def create_variable(self, name=None, persistable=True, dtype=None, value=None):
        vb = VarBase(
            value if value is None else jnp.asarray(value),
            name=name or unique_name.generate(f"{self._full_name}.b"),
            stop_gradient=True,
            persistable=persistable,
        )
        return vb

    def register_buffer(self, name, value, persistable=True):
        vb = (
            value
            if isinstance(value, VarBase)
            else VarBase(
                jnp.asarray(value),
                name=f"{self._full_name}.{name}",
                stop_gradient=True,
                persistable=persistable,
            )
        )
        self._buffers[name] = vb
        return vb

    def add_parameter(self, name, parameter):
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[name] = sublayer
        return sublayer

    # -- traversal -----------------------------------------------------
    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(include_sublayers)]

    def named_parameters(self, include_sublayers=True, prefix=""):
        out = []
        seen = set()
        for name, p in self._parameters.items():
            if p is not None and id(p) not in seen:
                seen.add(id(p))
                out.append((f"{prefix}{name}" if prefix else name, p))
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                sub_prefix = f"{prefix}{lname}." if prefix else f"{lname}."
                for n, p in layer.named_parameters(True, sub_prefix):
                    if id(p) not in seen:
                        seen.add(id(p))
                        out.append((n, p))
        return out

    def sublayers(self, include_self=False):
        out = [self] if include_self else []
        for layer in self._sub_layers.values():
            out.extend(layer.sublayers(include_self=True))
        return out

    def named_buffers(self, prefix=""):
        out = []
        for name, b in self._buffers.items():
            out.append((f"{prefix}{name}" if prefix else name, b))
        for lname, layer in self._sub_layers.items():
            sub_prefix = f"{prefix}{lname}." if prefix else f"{lname}."
            out.extend(layer.named_buffers(sub_prefix))
        return out

    # -- modes ---------------------------------------------------------
    def train(self):
        self.training = True
        tracer = _dygraph_tracer()
        if tracer is not None:
            tracer._train_mode = True
        for layer in self.sublayers():
            layer.training = True
        return self

    def eval(self):
        self.training = False
        tracer = _dygraph_tracer()
        if tracer is not None:
            tracer._train_mode = False
        for layer in self.sublayers():
            layer.training = False
        return self

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_gradient()

    # -- state dict -----------------------------------------------------
    def state_dict(self, include_sublayers=True):
        out = collections.OrderedDict()
        for _, p in self.named_parameters(include_sublayers):
            out[p.name] = p.numpy()
        for _, b in self.named_buffers():
            if b.persistable and b.value is not None:
                out[b.name] = b.numpy()
        return out

    def set_dict(self, state_dict, include_sublayers=True):
        missing = []
        for _, p in self.named_parameters(include_sublayers):
            if p.name in state_dict:
                p.set_value(np.asarray(state_dict[p.name]))
            else:
                missing.append(p.name)
        for _, b in self.named_buffers():
            if b.name in state_dict and b.value is not None:
                b.set_value(np.asarray(state_dict[b.name]))
        enforce(not missing, f"state_dict missing parameters: {missing[:5]}")

    set_state_dict = set_dict
    load_dict = set_dict

    # -- hooks + call ----------------------------------------------------
    def register_forward_pre_hook(self, hook):
        handle = len(self._forward_pre_hooks)
        self._forward_pre_hooks[handle] = hook
        return handle

    def register_forward_post_hook(self, hook):
        handle = len(self._forward_post_hooks)
        self._forward_post_hooks[handle] = hook
        return handle

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            result = hook(self, inputs, outputs)
            if result is not None:
                outputs = result
        return outputs

    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    # -- attribute capture ----------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        if isinstance(value, ParamBase):
            enforce(params is not None, "call Layer.__init__ first")
            params[name] = value
        elif isinstance(value, Layer):
            enforce(layers is not None, "call Layer.__init__ first")
            layers[name] = value
        else:
            if params is not None and name in params:
                del params[name]
            if layers is not None and name in layers:
                del layers[name]
        object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'"
        )

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)
