"""Dygraph module library (reference: python/paddle/fluid/dygraph/nn.py —
Conv2D, Pool2D, Linear, BatchNorm, Embedding, LayerNorm, Dropout, ...).

Every module's forward is written against `trace_op`, so the same code runs
eagerly in dygraph mode and appends ops under static capture (jit.py) — the
dual-dispatch design the reference implements with tracer-vs-LayerHelper."""

import numpy as np

from paddle_tpu.dygraph.base import trace_op
from paddle_tpu.dygraph.layers import Layer
from paddle_tpu.initializer import ConstantInitializer, NormalInitializer
from paddle_tpu.param_attr import ParamAttr
from paddle_tpu.utils.enforce import enforce


def _pair(v):
    return list(v) if isinstance(v, (list, tuple)) else [v, v]


class Linear(Layer):
    """reference: python/paddle/fluid/dygraph/nn.py Linear."""

    def __init__(self, input_dim, output_dim, param_attr=None, bias_attr=None, act=None, dtype="float32"):
        super().__init__(dtype=dtype)
        self.weight = self.create_parameter([input_dim, output_dim], attr=param_attr, dtype=dtype)
        self.bias = (
            None
            if bias_attr is False
            else self.create_parameter([output_dim], attr=bias_attr, dtype=dtype, is_bias=True)
        )
        self._act = act

    def forward(self, input):
        out = trace_op(
            "mul",
            {"X": [input], "Y": [self.weight]},
            {"x_num_col_dims": len(input.shape) - 1, "y_num_col_dims": 1},
        )["Out"][0]
        if self.bias is not None:
            out = trace_op(
                "elementwise_add",
                {"X": [out], "Y": [self.bias]},
                {"axis": len(out.shape) - 1},
            )["Out"][0]
        return _apply_act(out, self._act)


def _apply_act(x, act):
    if act is None:
        return x
    return trace_op(act, {"X": [x]}, {})["Out"][0]


class Conv2D(Layer):
    """reference: python/paddle/fluid/dygraph/nn.py Conv2D (NCHW)."""

    def __init__(
        self,
        num_channels,
        num_filters,
        filter_size,
        stride=1,
        padding=0,
        dilation=1,
        groups=1,
        param_attr=None,
        bias_attr=None,
        use_cudnn=True,
        act=None,
        dtype="float32",
    ):
        super().__init__(dtype=dtype)
        ksize = _pair(filter_size)
        self._attrs = {
            "strides": _pair(stride),
            "paddings": _pair(padding),
            "dilations": _pair(dilation),
            "groups": groups or 1,
        }
        std = (2.0 / (ksize[0] * ksize[1] * num_channels)) ** 0.5
        self.weight = self.create_parameter(
            [num_filters, num_channels // (groups or 1), *ksize],
            attr=param_attr,
            dtype=dtype,
            default_initializer=NormalInitializer(0.0, std),
        )
        self.bias = (
            None
            if bias_attr is False
            else self.create_parameter([num_filters], attr=bias_attr, dtype=dtype, is_bias=True)
        )
        self._act = act

    def forward(self, input):
        out = trace_op(
            "conv2d", {"Input": [input], "Filter": [self.weight]}, self._attrs
        )["Output"][0]
        if self.bias is not None:
            out = trace_op(
                "elementwise_add", {"X": [out], "Y": [self.bias]}, {"axis": 1}
            )["Out"][0]
        return _apply_act(out, self._act)


class Conv2DTranspose(Layer):
    def __init__(
        self,
        num_channels,
        num_filters,
        filter_size,
        output_size=None,
        padding=0,
        stride=1,
        dilation=1,
        groups=1,
        param_attr=None,
        bias_attr=None,
        act=None,
        dtype="float32",
    ):
        super().__init__(dtype=dtype)
        ksize = _pair(filter_size)
        self._attrs = {
            "strides": _pair(stride),
            "paddings": _pair(padding),
            "dilations": _pair(dilation),
            "groups": groups or 1,
        }
        self.weight = self.create_parameter(
            [num_channels, num_filters // (groups or 1), *ksize],
            attr=param_attr,
            dtype=dtype,
        )
        self.bias = (
            None
            if bias_attr is False
            else self.create_parameter([num_filters], attr=bias_attr, dtype=dtype, is_bias=True)
        )
        self._act = act

    def forward(self, input):
        out = trace_op(
            "conv2d_transpose",
            {"Input": [input], "Filter": [self.weight]},
            self._attrs,
        )["Output"][0]
        if self.bias is not None:
            out = trace_op(
                "elementwise_add", {"X": [out], "Y": [self.bias]}, {"axis": 1}
            )["Out"][0]
        return _apply_act(out, self._act)


class Pool2D(Layer):
    """reference: python/paddle/fluid/dygraph/nn.py Pool2D."""

    def __init__(
        self,
        pool_size=-1,
        pool_type="max",
        pool_stride=1,
        pool_padding=0,
        global_pooling=False,
        use_cudnn=True,
        ceil_mode=False,
        exclusive=True,
    ):
        super().__init__()
        self._attrs = {
            "pooling_type": pool_type,
            "ksize": _pair(pool_size),
            "strides": _pair(pool_stride),
            "paddings": _pair(pool_padding),
            "global_pooling": global_pooling,
            "ceil_mode": ceil_mode,
            "exclusive": exclusive,
        }

    def forward(self, input):
        return trace_op("pool2d", {"X": [input]}, self._attrs)["Out"][0]


class BatchNorm(Layer):
    """reference: python/paddle/fluid/dygraph/nn.py BatchNorm. Running stats
    are buffers; train-mode forward re-binds them to the op's MeanOut/
    VarianceOut (functional update, not mutation)."""

    def __init__(
        self,
        num_channels,
        act=None,
        is_test=False,
        momentum=0.9,
        epsilon=1e-5,
        param_attr=None,
        bias_attr=None,
        dtype="float32",
        data_layout="NCHW",
        use_global_stats=False,
    ):
        super().__init__(dtype=dtype)
        self.weight = self.create_parameter(
            [num_channels], attr=param_attr, dtype=dtype,
            default_initializer=ConstantInitializer(1.0),
        )
        self.bias = self.create_parameter(
            [num_channels], attr=bias_attr, dtype=dtype, is_bias=True
        )
        self._mean = self.register_buffer("_mean", np.zeros(num_channels, dtype))
        self._variance = self.register_buffer("_variance", np.ones(num_channels, dtype))
        self._attrs = {
            "momentum": momentum,
            "epsilon": epsilon,
            "data_layout": data_layout,
            "use_global_stats": use_global_stats,
        }
        self._act = act

    def forward(self, input):
        attrs = dict(self._attrs, is_test=not self.training)
        outs = trace_op(
            "batch_norm",
            {
                "X": [input],
                "Scale": [self.weight],
                "Bias": [self.bias],
                "Mean": [self._mean],
                "Variance": [self._variance],
            },
            attrs,
        )
        if self.training and outs.get("MeanOut") and outs["MeanOut"][0] is not None:
            if outs["MeanOut"][0].value is not None:
                self._mean.value = outs["MeanOut"][0].value
                self._variance.value = outs["VarianceOut"][0].value
        return _apply_act(outs["Y"][0], self._act)


class LayerNorm(Layer):
    def __init__(
        self,
        normalized_shape,
        scale=True,
        shift=True,
        begin_norm_axis=1,
        epsilon=1e-5,
        param_attr=None,
        bias_attr=None,
        act=None,
        dtype="float32",
    ):
        super().__init__(dtype=dtype)
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        n = int(np.prod(normalized_shape))
        self.weight = (
            self.create_parameter([n], attr=param_attr, dtype=dtype,
                                  default_initializer=ConstantInitializer(1.0))
            if scale
            else None
        )
        self.bias = (
            self.create_parameter([n], attr=bias_attr, dtype=dtype, is_bias=True)
            if shift
            else None
        )
        self._attrs = {"begin_norm_axis": begin_norm_axis, "epsilon": epsilon}
        self._act = act

    def forward(self, input):
        ins = {"X": [input]}
        if self.weight is not None:
            ins["Scale"] = [self.weight]
        if self.bias is not None:
            ins["Bias"] = [self.bias]
        out = trace_op("layer_norm", ins, self._attrs)["Y"][0]
        return _apply_act(out, self._act)


class GroupNorm(Layer):
    def __init__(self, channels, groups, epsilon=1e-5, param_attr=None, bias_attr=None, act=None, dtype="float32"):
        super().__init__(dtype=dtype)
        self.weight = self.create_parameter(
            [channels], attr=param_attr, dtype=dtype,
            default_initializer=ConstantInitializer(1.0),
        )
        self.bias = self.create_parameter([channels], attr=bias_attr, dtype=dtype, is_bias=True)
        self._attrs = {"groups": groups, "epsilon": epsilon}
        self._act = act

    def forward(self, input):
        out = trace_op(
            "group_norm",
            {"X": [input], "Scale": [self.weight], "Bias": [self.bias]},
            self._attrs,
        )["Y"][0]
        return _apply_act(out, self._act)


class InstanceNorm(Layer):
    def __init__(self, num_channels, epsilon=1e-5, param_attr=None, bias_attr=None, dtype="float32"):
        super().__init__(dtype=dtype)
        self.weight = self.create_parameter(
            [num_channels], attr=param_attr, dtype=dtype,
            default_initializer=ConstantInitializer(1.0),
        )
        self.bias = self.create_parameter([num_channels], attr=bias_attr, dtype=dtype, is_bias=True)
        self._attrs = {"epsilon": epsilon}

    def forward(self, input):
        return trace_op(
            "instance_norm",
            {"X": [input], "Scale": [self.weight], "Bias": [self.bias]},
            self._attrs,
        )["Y"][0]


class Embedding(Layer):
    """reference: python/paddle/fluid/dygraph/nn.py Embedding."""

    def __init__(self, size, is_sparse=False, is_distributed=False, padding_idx=None, param_attr=None, dtype="float32"):
        super().__init__(dtype=dtype)
        enforce(len(size) == 2, "Embedding size must be [vocab, dim]")
        self.weight = self.create_parameter(list(size), attr=param_attr, dtype=dtype)
        self._attrs = {
            "padding_idx": -1 if padding_idx is None else padding_idx,
            "is_sparse": is_sparse,
        }

    def forward(self, input):
        return trace_op(
            "lookup_table_v2", {"W": [self.weight], "Ids": [input]}, self._attrs
        )["Out"][0]


class Dropout(Layer):
    def __init__(self, p=0.5, dropout_implementation="downgrade_in_infer", is_test=False):
        super().__init__()
        self._attrs = {
            "dropout_prob": p,
            "dropout_implementation": dropout_implementation,
        }

    def forward(self, input):
        attrs = dict(self._attrs, is_test=not self.training)
        return trace_op("dropout", {"X": [input]}, attrs)["Out"][0]


class PRelu(Layer):
    def __init__(self, mode="all", channel=None, input_shape=None, param_attr=None, dtype="float32"):
        super().__init__(dtype=dtype)
        if mode == "all":
            shape = [1]
        elif mode == "channel":
            shape = [channel]
        else:
            shape = list(input_shape)[1:]
        self.weight = self.create_parameter(
            shape, attr=param_attr, dtype=dtype,
            default_initializer=ConstantInitializer(0.25),
        )
        self._mode = mode

    def forward(self, input):
        return trace_op(
            "prelu", {"X": [input], "Alpha": [self.weight]}, {"mode": self._mode}
        )["Out"][0]


class GRUUnit(Layer):
    """Single GRU step (reference: python/paddle/fluid/dygraph/nn.py GRUUnit,
    operators/gru_unit_op.cc). Composed from registry ops so it traces in
    both modes."""

    def __init__(self, size, param_attr=None, bias_attr=None, activation="tanh", gate_activation="sigmoid", dtype="float32"):
        super().__init__(dtype=dtype)
        self._hidden = size // 3
        d = self._hidden
        self.weight = self.create_parameter([d, d * 3], attr=param_attr, dtype=dtype)
        self.bias = (
            None
            if bias_attr is False
            else self.create_parameter([1, d * 3], attr=bias_attr, dtype=dtype, is_bias=True)
        )
        self._activation = activation
        self._gate_activation = gate_activation

    def forward(self, input, hidden):
        d = self._hidden

        def mm(a, b):
            return trace_op("matmul", {"X": [a], "Y": [b]}, {})["Out"][0]

        def sl(x, s, e):
            return trace_op(
                "slice", {"Input": [x]}, {"axes": [1], "starts": [s], "ends": [e]}
            )["Out"][0]

        gate_w = sl(self.weight, 0, d * 2)
        cand_w = sl(self.weight, d * 2, d * 3)
        xu = sl(input, 0, d)
        xr = sl(input, d, d * 2)
        xc = sl(input, d * 2, d * 3)
        hg = mm(hidden, gate_w)
        if self.bias is not None:
            bg = sl(self.bias, 0, d * 2)
            hg = hg + bg
        u = _apply_act(xu + sl(hg, 0, d), self._gate_activation)
        r = _apply_act(xr + sl(hg, d, d * 2), self._gate_activation)
        rh = r * hidden
        c = mm(rh, cand_w)
        if self.bias is not None:
            c = c + sl(self.bias, d * 2, d * 3)
        c = _apply_act(xc + c, self._activation)
        new_h = u * hidden + (1.0 - u) * c
        return new_h, new_h, c
