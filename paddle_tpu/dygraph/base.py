"""Dygraph (imperative) mode: eager op dispatch + tape autograd.

TPU-native analog of the reference's dygraph Tracer/BasicEngine
(reference: paddle/fluid/imperative/tracer.h:44 Tracer, tracer.cc:87 TraceOp,
engine.h:42 BasicEngine). Where the reference runs one pre-selected kernel per
op and records OpBase nodes for a reverse-topo grad walk, here every eager op
dispatches through the SAME registry lowering rule the static executor traces
(core/registry.py), and the tape records the `jax.vjp` pullback computed at
dispatch time — one forward execution yields both the outputs and the exact
backward function, replacing the reference's 560 hand-written grad kernels.

Dual dispatch (the reference's tracer-vs-OpDesc split, tracer.cc:87 vs
python/paddle/fluid/framework.py append_op): `trace_op` either executes
eagerly or, inside a `static_capture` context, appends the op to a Program
block — this powers dygraph-to-static (jit.py) with zero changes to module
code.
"""

import contextlib

import numpy as np

import jax
import jax.numpy as jnp

from paddle_tpu.core.dtypes import convert_dtype, to_numpy_dtype
from paddle_tpu.core.registry import get_op_def
from paddle_tpu.utils import unique_name
from paddle_tpu.utils.enforce import EnforceError, enforce

_tracer = None


class Tracer:
    """Eager-mode execution state: autograd tape + rng stream
    (reference: paddle/fluid/imperative/tracer.h:44)."""

    def __init__(self, seed=0):
        self._has_grad = True
        self._train_mode = True
        self._tape = []
        self._seed = seed
        self._rng_counter = 0

    def next_rng_key(self):
        self._rng_counter += 1
        return jax.random.fold_in(jax.random.PRNGKey(self._seed), self._rng_counter)

    def reset_tape(self):
        self._tape = []


def _dygraph_tracer():
    return _tracer


def in_dygraph_mode():
    return _tracer is not None


@contextlib.contextmanager
def guard(place=None, seed=0):
    """Enter imperative mode (reference: python/paddle/fluid/dygraph/base.py
    guard)."""
    global _tracer
    old = _tracer
    _tracer = Tracer(seed=seed)
    try:
        yield
    finally:
        _tracer = old


def enable_dygraph(place=None):
    global _tracer
    if _tracer is None:
        _tracer = Tracer()


def disable_dygraph():
    global _tracer
    _tracer = None


@contextlib.contextmanager
def no_grad_ctx():
    tracer = _dygraph_tracer()
    if tracer is None:
        yield
        return
    old = tracer._has_grad
    tracer._has_grad = False
    try:
        yield
    finally:
        tracer._has_grad = old


def no_grad(fn=None):
    """Usable as decorator or context manager (reference:
    python/paddle/fluid/dygraph/base.py no_grad)."""
    if fn is None:
        return no_grad_ctx()

    def wrapper(*args, **kwargs):
        with no_grad_ctx():
            return fn(*args, **kwargs)

    return wrapper


# ---------------------------------------------------------------------------
# static capture (dygraph-to-static)
# ---------------------------------------------------------------------------

_capture = None


class _CaptureContext:
    """While active, trace_op appends ops to `main_program` instead of
    executing; eager parameters materialize as static Parameters initialized
    with their current values. This replaces the reference's AST-rewriting
    dygraph_to_static (python/paddle/fluid/dygraph/dygraph_to_static/
    ast_transformer.py) — under jax there is nothing to rewrite, the same
    trace that builds the tape can build the Program."""

    def __init__(self, main_program, startup_program):
        self.main_program = main_program
        self.startup_program = startup_program
        self.var_map = {}  # id(VarBase) -> static Variable
        # id() keys are only stable while the object lives: keep a strong
        # reference to every mapped VarBase, or a freed temporary (e.g. the
        # scalar constant `x * 2.0` materializes) lets a LATER temporary
        # reuse its id and silently alias its static var
        self._retained = []

    def to_static_var(self, vb):
        from paddle_tpu.dygraph.varbase import VarBase
        from paddle_tpu.initializer import NumpyArrayInitializer

        if vb.static_var is not None:
            return vb.static_var
        sv = self.var_map.get(id(vb))
        if sv is not None:
            return sv
        block = self.main_program.global_block()
        value = np.asarray(vb.value)
        if getattr(vb, "trainable", None) is not None:
            # an eager ParamBase: becomes a static Parameter carrying its
            # current value through the startup program
            sv = block.create_parameter(
                shape=list(value.shape),
                dtype=str(value.dtype),
                name=vb.name,
                trainable=vb.trainable,
            )
            sblock = self.startup_program.global_block()
            sblock.create_var(
                name=vb.name,
                shape=list(value.shape),
                dtype=str(value.dtype),
                persistable=True,
            )
            NumpyArrayInitializer(value)(sv, sblock)
        else:
            # a non-parameter eager tensor from outside the capture: freeze
            # it as a constant
            sv = block.create_var(
                name=unique_name.generate(vb.name or "captured"),
                shape=list(value.shape),
                dtype=str(value.dtype),
            )
            block.append_op(
                "assign_value",
                {},
                {"Out": [sv.name]},
                {
                    "shape": list(value.shape),
                    "dtype": str(value.dtype),
                    "values": value.reshape(-1).tolist(),
                },
            )
        self.var_map[id(vb)] = sv
        self._retained.append(vb)
        return sv


@contextlib.contextmanager
def static_capture(main_program, startup_program):
    global _capture
    old = _capture
    _capture = _CaptureContext(main_program, startup_program)
    try:
        yield _capture
    finally:
        _capture = old


def in_capture_mode():
    return _capture is not None


# ---------------------------------------------------------------------------
# eager op dispatch
# ---------------------------------------------------------------------------


def _flatten_outs(outs):
    """Deterministic flattening of a lowering's {slot: [arrays]} output."""
    slots = sorted(outs)
    flat, index = [], []
    for slot in slots:
        vals = outs[slot]
        if not isinstance(vals, (list, tuple)):
            vals = [vals]
        for i, v in enumerate(vals):
            flat.append(v)
            index.append((slot, i))
    return flat, index


def trace_op(op_type, ins, attrs=None, out_slots=("Out",), stop_gradient=False):
    """Run one op eagerly (or append it to the captured program).

    ins: {slot: [VarBase, ...]}; returns {slot: [VarBase, ...]}.
    The tape entry stores the vjp pullback over the differentiable inputs
    (reference analog: Tracer::TraceOp + TraceBackward, tracer.cc:87,136).
    """
    from paddle_tpu.dygraph.varbase import VarBase

    attrs = dict(attrs or {})
    attrs.pop("op_callstack", None)
    if in_capture_mode():
        return _capture_op(op_type, ins, attrs, out_slots)

    tracer = _dygraph_tracer()
    enforce(tracer is not None, "dygraph op outside dygraph mode")
    op_def = get_op_def(op_type)

    raw_ins = {}
    for slot, vals in ins.items():
        if vals is None:
            continue
        vals = vals if isinstance(vals, (list, tuple)) else [vals]
        raw_ins[slot] = [v.value if isinstance(v, VarBase) else jnp.asarray(v) for v in vals]
    if op_def.stateful:
        raw_ins["__rng_key__"] = [tracer.next_rng_key()]
        if not tracer._train_mode:
            attrs.setdefault("is_test", True)

    # which (slot, pos) get gradients
    diff_positions = []
    for slot, vals in ins.items():
        if vals is None or slot in op_def.nondiff_inputs:
            continue
        vals = vals if isinstance(vals, (list, tuple)) else [vals]
        for i, v in enumerate(vals):
            if (
                isinstance(v, VarBase)
                and not v.stop_gradient
                and jnp.issubdtype(raw_ins[slot][i].dtype, jnp.inexact)
            ):
                diff_positions.append((slot, i, v))

    need_grad = bool(diff_positions) and tracer._has_grad and not stop_gradient

    if not need_grad:
        outs = op_def.lowering()(raw_ins, attrs)
        flat, index = _flatten_outs(outs)
        out_vbs = [
            VarBase(v, stop_gradient=True, name=unique_name.generate(f"{op_type}_out"))
            if v is not None
            else None
            for v in flat
        ]
        return _pack_outs(out_vbs, index)

    diff_vals = [raw_ins[slot][i] for slot, i, _ in diff_positions]

    def fn(*dvals):
        local = {s: list(vs) for s, vs in raw_ins.items()}
        for (slot, i, _), dv in zip(diff_positions, dvals):
            local[slot][i] = dv
        outs = op_def.lowering()(local, attrs)
        flat, index = _flatten_outs(outs)
        diff_flat = [
            v if v is not None and jnp.issubdtype(jnp.asarray(v).dtype, jnp.inexact) else None
            for v in flat
        ]
        aux_flat = [None if d is not None else v for v, d in zip(flat, diff_flat)]
        return [d for d in diff_flat if d is not None], (aux_flat, index)

    try:
        diff_outs, vjp_fn, (aux_flat, index) = jax.vjp(fn, *diff_vals, has_aux=True)
    except Exception as e:  # pragma: no cover - surfaced with op context
        raise EnforceError(f"dygraph op failed: {e}", op_type=op_type) from e

    # reassemble the full flat output list
    flat, di = [], 0
    for a in aux_flat:
        if a is None:
            flat.append(diff_outs[di])
            di += 1
        else:
            flat.append(a)

    out_vbs = []
    diff_out_vbs = []
    for v, a in zip(flat, aux_flat):
        if v is None:
            out_vbs.append(None)
            continue
        vb = VarBase(
            v,
            stop_gradient=(a is not None),
            name=unique_name.generate(f"{op_type}_out"),
        )
        out_vbs.append(vb)
        if a is None:
            diff_out_vbs.append(vb)

    tracer._tape.append(
        _TapeEntry(
            op_type=op_type,
            vjp_fn=vjp_fn,
            input_vars=[v for _, _, v in diff_positions],
            output_vars=diff_out_vbs,
        )
    )
    return _pack_outs(out_vbs, index)


class _TapeEntry:
    __slots__ = ("op_type", "vjp_fn", "input_vars", "output_vars")

    def __init__(self, op_type, vjp_fn, input_vars, output_vars):
        self.op_type = op_type
        self.vjp_fn = vjp_fn
        self.input_vars = input_vars
        self.output_vars = output_vars


def _pack_outs(out_vbs, index):
    outs = {}
    for vb, (slot, i) in zip(out_vbs, index):
        outs.setdefault(slot, []).append(vb)
    return outs


def _capture_op(op_type, ins, attrs, out_slots):
    """Append the op to the program under capture; infer output shapes via
    the shared abstract-eval machinery (layer_helper.infer_op_shapes)."""
    from paddle_tpu.dygraph.varbase import VarBase
    from paddle_tpu.layer_helper import infer_op_shapes

    # CURRENT block, not the global one: a converted loop body traces its
    # ops into the `while` op's sub-block (ast_transform LoopTransformer)
    block = _capture.main_program.current_block()
    in_names = {}
    for slot, vals in ins.items():
        if vals is None:
            continue
        vals = vals if isinstance(vals, (list, tuple)) else [vals]
        names = []
        for v in vals:
            enforce(isinstance(v, VarBase), f"capture input must be VarBase, got {type(v)}")
            names.append(_capture.to_static_var(v).name)
        in_names[slot] = names

    specs = infer_op_shapes(op_type, block, in_names, attrs)
    out_names, out_vbs_index = {}, []
    slots = sorted(specs) if specs else list(out_slots)
    for slot in slots:
        n = len(specs[slot]) if specs else 1
        names = []
        for i in range(n):
            name = unique_name.generate(f"{op_type}_{slot.lower()}")
            shape, dtype = (specs[slot][i] if specs else (None, "float32"))
            block.create_var(name=name, shape=shape, dtype=dtype)
            names.append(name)
            out_vbs_index.append((slot, i))
        out_names[slot] = names
    op = block.append_op(op_type, in_names, out_names, attrs)

    outs = {}
    for slot, names in out_names.items():
        outs[slot] = [VarBase.from_static(block.var(n)) for n in names]
    return outs


# ---------------------------------------------------------------------------
# backward engine
# ---------------------------------------------------------------------------


def run_backward(loss, retain_graph=False):
    """Reverse-topo tape walk with gradient accumulation
    (reference: paddle/fluid/imperative/engine.cc BasicEngine,
    gradient_accumulator.cc)."""
    tracer = _dygraph_tracer()
    enforce(tracer is not None, ".backward() outside dygraph mode")
    grads = {id(loss): jnp.ones_like(loss.value)}

    for entry in reversed(tracer._tape):
        cotangents = []
        any_needed = False
        for ov in entry.output_vars:
            g = grads.get(id(ov))
            if g is None:
                g = jnp.zeros_like(ov.value)
            else:
                any_needed = True
            cotangents.append(g)
        if not any_needed:
            continue
        in_grads = entry.vjp_fn(cotangents)
        for iv, g in zip(entry.input_vars, in_grads):
            if g is None:
                continue
            prev = grads.get(id(iv))
            grads[id(iv)] = g if prev is None else prev + g
            iv._accumulate_grad(grads[id(iv)])
    if not retain_graph:
        tracer.reset_tape()


def to_variable(value, name=None, zero_copy=None, dtype=None):
    """numpy / scalar -> eager VarBase (reference: python/paddle/fluid/
    dygraph/base.py to_variable)."""
    from paddle_tpu.dygraph.varbase import VarBase

    if isinstance(value, VarBase):
        return value
    arr = np.asarray(value)
    if dtype is not None:
        arr = arr.astype(to_numpy_dtype(convert_dtype(dtype)))
    elif arr.dtype == np.float64:
        arr = arr.astype(np.float32)
    elif arr.dtype == np.int64:
        arr = arr.astype(np.int32)
    return VarBase(jnp.asarray(arr), name=name or unique_name.generate("generated_tensor"))
