"""Dygraph-to-static AST transform for data-dependent `if`.

reference: python/paddle/fluid/dygraph/dygraph_to_static/ast_transformer.py
(IfElseTransformer) — the reference rewrites Python `if` on tensors into
layers.cond sub-blocks. TPU-native form: the rewritten `if` evaluates BOTH
branches and selects per returned tensor with the `where` op — the
lax.select lowering XLA would pick for cheap branches anyway, and it needs
no sub-block machinery under trace capture. Eager calls keep plain Python
branching (values exist, __bool__ works).

Contract (documented limits, loud failures otherwise):
- only `if`/`elif`/`else` on tensor predicates are transformed; `for`/
  `while` over tensors still raise the capture-guard error (use
  layers.while_loop);
- both branches run under trace: side-effecting branches (py_func, prints,
  state write-backs) are NOT eligible;
- branch variables must be assignable by simple names; `return`/`break`/
  `continue` inside a transformed `if` are rejected at transform time.
"""

import ast
import inspect
import textwrap

__all__ = ["convert_ifelse", "ast_transform"]

_HELPER = "__paddle_tpu_select_if__"


def _assigned_names(stmts):
    """Simple Name targets assigned anywhere in `stmts` — at THIS function
    scope (nested def/lambda bodies have their own locals)."""
    names = []

    class V(ast.NodeVisitor):
        def visit_Assign(self, node):
            for t in node.targets:
                self._collect(t)
            self.generic_visit(node)

        def visit_AugAssign(self, node):
            self._collect(node.target)
            self.generic_visit(node)

        def visit_AnnAssign(self, node):
            self._collect(node.target)
            self.generic_visit(node)

        def visit_For(self, node):
            self._collect(node.target)
            self.generic_visit(node)

        def visit_FunctionDef(self, node):
            pass  # nested scope

        visit_AsyncFunctionDef = visit_FunctionDef
        visit_Lambda = visit_FunctionDef

        def _collect(self, t):
            if isinstance(t, ast.Name):
                if t.id not in names:
                    names.append(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                for e in t.elts:
                    self._collect(e)

    v = V()
    for s in stmts:
        v.visit(s)
    return names


def _has_flow_escape(stmts):
    """Control flow that would escape the `if` being converted: `return`
    at this function scope, or break/continue NOT owned by a loop inside
    the branch. Nested function defs are their own scope."""

    class V(ast.NodeVisitor):
        found = False
        loop_depth = 0

        def visit_Return(self, node):
            self.found = True

        def visit_Break(self, node):
            if self.loop_depth == 0:
                self.found = True

        visit_Continue = visit_Break

        def _loop(self, node):
            self.loop_depth += 1
            self.generic_visit(node)
            self.loop_depth -= 1

        visit_For = _loop
        visit_While = _loop
        visit_AsyncFor = _loop

        def visit_FunctionDef(self, node):
            pass  # own scope

        visit_AsyncFunctionDef = visit_FunctionDef
        visit_Lambda = visit_FunctionDef

    v = V()
    for s in stmts:
        v.visit(s)
    return v.found


class _IfTransformer(ast.NodeTransformer):
    def __init__(self):
        self.count = 0

    def visit_If(self, node):
        self.generic_visit(node)  # innermost-first
        if _has_flow_escape(node.body) or _has_flow_escape(node.orelse):
            # leave THIS `if` as plain Python: static predicates still work
            # (eager bool), data-dependent ones hit the loud capture guard
            return node
        names = _assigned_names(node.body + node.orelse)
        # every assigned name becomes a helper parameter fed by a lazy
        # thunk of its current value (or an _Undefined placeholder): the
        # helpers' return tuple then never references an unbound free
        # variable, and read-before-write inside a branch sees the value
        # from before the `if` (Python closure-write rule workaround)
        params = list(names)
        n = self.count
        self.count += 1
        tname = f"__pt_true_{n}"
        fname = f"__pt_false_{n}"
        ret = ast.Return(
            value=ast.Tuple(
                elts=[ast.Name(id=x, ctx=ast.Load()) for x in names],
                ctx=ast.Load(),
            )
        )
        def fn_args():
            return ast.arguments(
                posonlyargs=[],
                args=[ast.arg(arg=x) for x in params],
                kwonlyargs=[], kw_defaults=[], defaults=[],
            )

        tdef = ast.FunctionDef(
            name=tname,
            args=fn_args(),
            body=list(node.body) + [ret],
            decorator_list=[],
        )
        fdef = ast.FunctionDef(
            name=fname,
            args=fn_args(),
            body=(list(node.orelse) + [ret]) if node.orelse else [ret],
            decorator_list=[],
        )
        # current values of read-write branch vars travel as LAZY thunks:
        # a default argument would evaluate at def time and explode when
        # the name is only assigned inside the `if` itself
        thunks = ast.Tuple(
            elts=[
                ast.Lambda(
                    args=ast.arguments(posonlyargs=[], args=[],
                                       kwonlyargs=[], kw_defaults=[],
                                       defaults=[]),
                    body=ast.Name(id=x, ctx=ast.Load()),
                )
                for x in params
            ],
            ctx=ast.Load(),
        )
        call = ast.Call(
            func=ast.Name(id=_HELPER, ctx=ast.Load()),
            args=[node.test,
                  ast.Name(id=tname, ctx=ast.Load()),
                  ast.Name(id=fname, ctx=ast.Load()),
                  thunks],
            keywords=[],
        )
        if names:
            assign = ast.Assign(
                targets=[ast.Tuple(
                    elts=[ast.Name(id=x, ctx=ast.Store()) for x in names],
                    ctx=ast.Store(),
                )],
                value=call,
            )
        else:
            assign = ast.Expr(value=call)
        return [tdef, fdef, assign]


class _Undefined:
    """Placeholder for a branch variable with no value yet: any use inside
    the branch (before its own assignment) fails loudly."""

    def _boom(self, *a, **k):
        raise RuntimeError(
            "converted `if`: this variable has no value on every path "
            "(it was assigned in only one branch, or not before the "
            "`if`); assign it on all paths before using it"
        )

    __getattr__ = __call__ = __add__ = __radd__ = __mul__ = __rmul__ = \
        __sub__ = __rsub__ = __truediv__ = __rtruediv__ = __bool__ = _boom


def _select_if(pred, true_fn, false_fn, thunks=()):
    """Runtime dispatch: eager bool -> Python branch; symbolic tensor ->
    run BOTH branches and `where`-select each returned value. `thunks`
    lazily read the CURRENT values of read-write branch variables."""
    from paddle_tpu.dygraph.base import trace_op
    from paddle_tpu.dygraph.varbase import VarBase

    vals = []
    for th in thunks:
        try:
            vals.append(th())
        except (NameError, UnboundLocalError):
            vals.append(_Undefined())
    if not isinstance(pred, VarBase) or pred.value is not None:
        return true_fn(*vals) if pred else false_fn(*vals)
    if not thunks:
        raise RuntimeError(
            "a data-dependent `if` whose branches assign no variables is "
            "side-effect-only and cannot be converted to a select; use "
            "layers.cond or restructure"
        )
    tv = true_fn(*vals)
    fv = false_fn(*vals)
    tv = tv if isinstance(tv, tuple) else (tv,)
    fv = fv if isinstance(fv, tuple) else (fv,)
    outs = []
    for t, f in zip(tv, fv):
        if isinstance(t, _Undefined) or isinstance(f, _Undefined):
            # the variable exists on one path only (branch-local temp, loop
            # var, nested def): no select possible. Mirror Python: fine if
            # never used after the `if`, loud on use.
            outs.append(_Undefined())
            continue
        if isinstance(t, VarBase) or isinstance(f, VarBase):
            # mixed tensor/scalar branches (`y = 0.0` before the if, then
            # `y = x * 2` inside): promote the plain value to a constant
            # tensor so the select works
            from paddle_tpu.dygraph.base import to_variable
            import numpy as _np

            if not isinstance(t, VarBase):
                t = to_variable(_np.asarray(t, dtype=_np.dtype(f.dtype)))
            if not isinstance(f, VarBase):
                f = to_variable(_np.asarray(f, dtype=_np.dtype(t.dtype)))
            outs.append(trace_op(
                "where", {"Condition": [pred], "X": [t], "Y": [f]}, {}
            )["Out"][0])
        else:
            raise RuntimeError(
                "converted `if` produced a non-tensor branch value under "
                "trace; only tensor-valued branches can be selected "
                f"(got {type(t).__name__}/{type(f).__name__})"
            )
    # always a tuple: the rewritten assignment unpacks a tuple target
    return tuple(outs)


def ast_transform(fn):
    """Rewrite `fn`'s data-dependent `if` statements. Returns the
    transformed function, or None when the source cannot be transformed
    (caller falls back to plain tracing + the loud capture guard)."""
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return None
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return None
    fdef.decorator_list = []  # avoid re-applying @declarative etc.
    tr = _IfTransformer()
    tr.visit(tree)
    if tr.count == 0:
        return None
    ast.fix_missing_locations(tree)
    try:
        code = compile(tree, f"<ast_transform {fn.__name__}>", "exec")
    except (SyntaxError, ValueError):
        return None
    # exec against the LIVE module globals (not a snapshot): names defined
    # or monkeypatched after decoration, and recursion through the module
    # global, must resolve. The helper key is collision-safe.
    glb = getattr(fn, "__globals__", None)
    if glb is None:
        return None
    glb[_HELPER] = _select_if
    # re-bind the function's closure-free form; closures over outer locals
    # cannot be rebuilt from source -> bail to the fallback
    if getattr(fn, "__closure__", None):
        return None
    loc = {}
    exec(code, glb, loc)
    out = loc.get(fdef.name)
    if out is None:
        return None
    out.__wrapped_original__ = fn
    return out


def convert_ifelse(fn):
    """Public decorator: transform if possible, else return fn unchanged
    (plain trace + loud guard)."""
    return ast_transform(fn) or fn
