"""Dygraph-to-static AST transforms for data-dependent `if` and loops.

reference: python/paddle/fluid/dygraph/dygraph_to_static/ast_transformer.py
(IfElseTransformer) and loop_transformer.py (LoopTransformer) — the
reference rewrites Python `if` on tensors into layers.cond sub-blocks and
`while`/`for` into layers.While. TPU-native forms:

* `if`: the rewritten statement evaluates BOTH branches and selects per
  returned tensor with the `where` op — the lax.select lowering XLA would
  pick for cheap branches anyway, and it needs no sub-block machinery under
  trace capture. Eager calls keep plain Python branching.
* `while` / `for i in range(...)`: carried variables (names assigned in the
  body) become explicit cond/body function parameters; at run time a
  concrete condition keeps the plain Python loop (eager mode, or constant
  trip counts under capture — unrolled exactly as before), while a symbolic
  condition under capture builds a `while` op sub-block (lowered to
  lax.while_loop, ops/control_flow.py) with the carried names written back
  each iteration.

Contract (documented limits, loud failures otherwise):
- both `if` branches run under trace: side-effecting branches (py_func,
  prints, state write-backs) are NOT eligible;
- variables must be assignable by simple names; `return` inside a
  transformed `if`, and `break`/`continue`/`return` inside a transformed
  loop body, are rejected at transform time (those loops stay plain
  Python: static trip counts still work, data-dependent ones hit the loud
  capture guard);
- loop-carried variables must hold tensor values (or numbers promotable
  to tensors); state read before its in-body assignment must be assigned
  BEFORE the loop (write-before-read temps — e.g. a nested loop's counter
  — get a synthesized zero init from their traced shape);
- `for x in <tensor>` iteration is not converted (use layers.while_loop or
  index with a range loop);
- after a ZERO-trip converted `for`, the loop variable holds `start`
  (CPython leaves it unbound/stale) — carried state needs an init value;
  likewise a write-before-read body temp (synthesized zero init) reads as
  ZEROS after a zero-trip `while` where CPython would raise NameError —
  trip counts are run-time values, so the divergence cannot be detected
  at trace time.
"""

import ast
import inspect
import textwrap

__all__ = ["convert_ifelse", "ast_transform"]

_HELPER = "__paddle_tpu_select_if__"
_WHILE_HELPER = "__paddle_tpu_while__"
_CMP_HELPER = "__paddle_tpu_loop_cmp__"


def _assigned_names(stmts):
    """Simple Name targets assigned anywhere in `stmts` — at THIS function
    scope (nested def/lambda bodies have their own locals)."""
    names = []

    class V(ast.NodeVisitor):
        def visit_Assign(self, node):
            for t in node.targets:
                self._collect(t)
            self.generic_visit(node)

        def visit_AugAssign(self, node):
            self._collect(node.target)
            self.generic_visit(node)

        def visit_AnnAssign(self, node):
            self._collect(node.target)
            self.generic_visit(node)

        def visit_For(self, node):
            self._collect(node.target)
            self.generic_visit(node)

        def visit_NamedExpr(self, node):
            # walrus binds at function scope — a converted body must carry it
            self._collect(node.target)
            self.generic_visit(node)

        def visit_With(self, node):
            for item in node.items:
                if item.optional_vars is not None:
                    self._collect(item.optional_vars)
            self.generic_visit(node)

        visit_AsyncWith = visit_With

        def visit_Import(self, node):
            for a in node.names:
                if a.name == "*":
                    continue
                name = (a.asname or a.name).split(".")[0]
                if name not in names:
                    names.append(name)

        visit_ImportFrom = visit_Import

        def visit_FunctionDef(self, node):
            pass  # nested scope

        visit_AsyncFunctionDef = visit_FunctionDef
        visit_Lambda = visit_FunctionDef

        def _collect(self, t):
            if isinstance(t, ast.Name):
                if t.id not in names:
                    names.append(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                for e in t.elts:
                    self._collect(e)
            elif isinstance(t, ast.Starred):
                self._collect(t.value)

    v = V()
    for s in stmts:
        v.visit(s)
    return names


def _has_flow_escape(stmts):
    """Control flow that would escape the `if` being converted: `return`
    at this function scope, or break/continue NOT owned by a loop inside
    the branch. Nested function defs are their own scope."""

    class V(ast.NodeVisitor):
        found = False
        loop_depth = 0

        def visit_Return(self, node):
            self.found = True

        def visit_Break(self, node):
            if self.loop_depth == 0:
                self.found = True

        visit_Continue = visit_Break

        def _loop(self, node):
            self.loop_depth += 1
            self.generic_visit(node)
            self.loop_depth -= 1

        visit_For = _loop
        visit_While = _loop
        visit_AsyncFor = _loop

        def visit_FunctionDef(self, node):
            pass  # own scope

        visit_AsyncFunctionDef = visit_FunctionDef
        visit_Lambda = visit_FunctionDef

    v = V()
    for s in stmts:
        v.visit(s)
    return v.found


def _has_loop_escape(stmts):
    """Constructs that cannot live inside a converted loop body: `return`,
    `break`/`continue` belonging to the loop being converted (depth 0),
    and global/nonlocal declarations (the body becomes a nested def)."""

    class V(ast.NodeVisitor):
        found = False
        loop_depth = 0

        def visit_Return(self, node):
            self.found = True

        def visit_Break(self, node):
            if self.loop_depth == 0:
                self.found = True

        visit_Continue = visit_Break

        def visit_Global(self, node):
            self.found = True

        visit_Nonlocal = visit_Global

        def _loop(self, node):
            self.loop_depth += 1
            self.generic_visit(node)
            self.loop_depth -= 1

        visit_For = _loop
        visit_While = _loop
        visit_AsyncFor = _loop

        def visit_FunctionDef(self, node):
            pass  # own scope

        visit_AsyncFunctionDef = visit_FunctionDef
        visit_Lambda = visit_FunctionDef

    v = V()
    for s in stmts:
        v.visit(s)
    return v.found


def _contains_named_expr(node):
    return any(isinstance(n, ast.NamedExpr) for n in ast.walk(node))


class _IfTransformer(ast.NodeTransformer):
    def __init__(self):
        self.count = 0

    # -- loops (the reference's LoopTransformer,
    #    dygraph_to_static/loop_transformer.py) -------------------------
    def _thunks(self, params):
        return ast.Tuple(
            elts=[
                ast.Lambda(
                    args=ast.arguments(posonlyargs=[], args=[],
                                       kwonlyargs=[], kw_defaults=[],
                                       defaults=[]),
                    body=ast.Name(id=x, ctx=ast.Load()),
                )
                for x in params
            ],
            ctx=ast.Load(),
        )

    def visit_While(self, node):
        self.generic_visit(node)
        if (
            node.orelse
            or _has_loop_escape(node.body)
            or _contains_named_expr(node.test)
        ):
            return node
        names = _assigned_names(node.body)
        if not names:
            # a body assigning nothing can only terminate via side
            # effects — not expressible as carried state; leave plain
            return node
        n = self.count
        self.count += 1
        cname, bname = f"__pt_wcond_{n}", f"__pt_wbody_{n}"

        def fn_args():
            return ast.arguments(
                posonlyargs=[],
                args=[ast.arg(arg=x) for x in names],
                kwonlyargs=[], kw_defaults=[], defaults=[],
            )

        ret = ast.Return(
            value=ast.Tuple(
                elts=[ast.Name(id=x, ctx=ast.Load()) for x in names],
                ctx=ast.Load(),
            )
        )
        cdef = ast.FunctionDef(
            name=cname, args=fn_args(),
            body=[ast.Return(value=node.test)], decorator_list=[],
        )
        bdef = ast.FunctionDef(
            name=bname, args=fn_args(),
            body=list(node.body) + [ret], decorator_list=[],
        )
        call = ast.Call(
            func=ast.Name(id=_WHILE_HELPER, ctx=ast.Load()),
            args=[
                ast.Name(id=cname, ctx=ast.Load()),
                ast.Name(id=bname, ctx=ast.Load()),
                self._thunks(names),
                ast.Tuple(
                    elts=[ast.Constant(value=x) for x in names],
                    ctx=ast.Load(),
                ),
            ],
            keywords=[],
        )
        assign = ast.Assign(
            targets=[ast.Tuple(
                elts=[ast.Name(id=x, ctx=ast.Store()) for x in names],
                ctx=ast.Store(),
            )],
            value=call,
        )
        return [cdef, bdef, assign]

    def visit_For(self, node):
        self.generic_visit(node)
        if node.orelse or not isinstance(node.target, ast.Name):
            return node
        it = node.iter
        if not (
            isinstance(it, ast.Call)
            and isinstance(it.func, ast.Name)
            and it.func.id == "range"
            and not it.keywords
            and 1 <= len(it.args) <= 3
        ):
            return node  # non-range iteration stays plain Python
        if _has_loop_escape(node.body):
            return node
        n = self.count
        self.count += 1
        start = it.args[0] if len(it.args) >= 2 else ast.Constant(value=0)
        stop = it.args[1] if len(it.args) >= 2 else it.args[0]
        step = it.args[2] if len(it.args) == 3 else ast.Constant(value=1)
        s_start, s_stop, s_step, s_i = (
            f"__pt_start_{n}", f"__pt_stop_{n}", f"__pt_step_{n}",
            f"__pt_i_{n}",
        )
        tgt = node.target.id

        def nm(x, ctx=None):
            return ast.Name(id=x, ctx=ctx or ast.Load())

        # a PRIVATE counter advances the loop; the user's loop variable is
        # assigned FROM it each iteration, so a body that reassigns the
        # loop variable (for i in ...: i = 99) still iterates like CPython
        # and the post-loop value of the loop variable is the last body
        # value, not one-step-past. (Zero-trip loops leave the loop var at
        # `start` — the documented divergence from CPython's unbound/stale
        # name, needed because a carried var must have an initial value.)
        pre = [
            ast.Assign(targets=[nm(s_start, ast.Store())], value=start),
            ast.Assign(targets=[nm(s_stop, ast.Store())], value=stop),
            ast.Assign(targets=[nm(s_step, ast.Store())], value=step),
            ast.Assign(targets=[nm(s_i, ast.Store())], value=nm(s_start)),
            ast.Assign(targets=[nm(tgt, ast.Store())], value=nm(s_start)),
        ]
        body = (
            [ast.Assign(targets=[nm(tgt, ast.Store())], value=nm(s_i))]
            + list(node.body)
            + [
                ast.Assign(
                    targets=[nm(s_i, ast.Store())],
                    value=ast.BinOp(
                        left=nm(s_i), op=ast.Add(), right=nm(s_step)
                    ),
                )
            ]
        )
        w = ast.While(
            test=ast.Call(
                func=nm(_CMP_HELPER),
                args=[nm(s_i), nm(s_stop), nm(s_step)],
                keywords=[],
            ),
            body=body,
            orelse=[],
        )
        converted = self.visit_While(w)
        return pre + (converted if isinstance(converted, list) else [converted])

    def visit_If(self, node):
        self.generic_visit(node)  # innermost-first
        if _has_flow_escape(node.body) or _has_flow_escape(node.orelse):
            # leave THIS `if` as plain Python: static predicates still work
            # (eager bool), data-dependent ones hit the loud capture guard
            return node
        names = _assigned_names(node.body + node.orelse)
        # every assigned name becomes a helper parameter fed by a lazy
        # thunk of its current value (or an _Undefined placeholder): the
        # helpers' return tuple then never references an unbound free
        # variable, and read-before-write inside a branch sees the value
        # from before the `if` (Python closure-write rule workaround)
        params = list(names)
        n = self.count
        self.count += 1
        tname = f"__pt_true_{n}"
        fname = f"__pt_false_{n}"
        ret = ast.Return(
            value=ast.Tuple(
                elts=[ast.Name(id=x, ctx=ast.Load()) for x in names],
                ctx=ast.Load(),
            )
        )
        def fn_args():
            return ast.arguments(
                posonlyargs=[],
                args=[ast.arg(arg=x) for x in params],
                kwonlyargs=[], kw_defaults=[], defaults=[],
            )

        tdef = ast.FunctionDef(
            name=tname,
            args=fn_args(),
            body=list(node.body) + [ret],
            decorator_list=[],
        )
        fdef = ast.FunctionDef(
            name=fname,
            args=fn_args(),
            body=(list(node.orelse) + [ret]) if node.orelse else [ret],
            decorator_list=[],
        )
        # current values of read-write branch vars travel as LAZY thunks:
        # a default argument would evaluate at def time and explode when
        # the name is only assigned inside the `if` itself
        thunks = ast.Tuple(
            elts=[
                ast.Lambda(
                    args=ast.arguments(posonlyargs=[], args=[],
                                       kwonlyargs=[], kw_defaults=[],
                                       defaults=[]),
                    body=ast.Name(id=x, ctx=ast.Load()),
                )
                for x in params
            ],
            ctx=ast.Load(),
        )
        call = ast.Call(
            func=ast.Name(id=_HELPER, ctx=ast.Load()),
            args=[node.test,
                  ast.Name(id=tname, ctx=ast.Load()),
                  ast.Name(id=fname, ctx=ast.Load()),
                  thunks],
            keywords=[],
        )
        if names:
            assign = ast.Assign(
                targets=[ast.Tuple(
                    elts=[ast.Name(id=x, ctx=ast.Store()) for x in names],
                    ctx=ast.Store(),
                )],
                value=call,
            )
        else:
            assign = ast.Expr(value=call)
        return [tdef, fdef, assign]


class _Undefined:
    """Placeholder for a branch variable with no value yet: any use inside
    the branch (before its own assignment) fails loudly."""

    def _boom(self, *a, **k):
        raise RuntimeError(
            "converted `if`: this variable has no value on every path "
            "(it was assigned in only one branch, or not before the "
            "`if`); assign it on all paths before using it"
        )

    __getattr__ = __call__ = __add__ = __radd__ = __mul__ = __rmul__ = \
        __sub__ = __rsub__ = __truediv__ = __rtruediv__ = __bool__ = \
        __eq__ = __ne__ = __lt__ = __le__ = __gt__ = __ge__ = \
        __getitem__ = __iter__ = __len__ = __neg__ = __pos__ = \
        __pow__ = __rpow__ = __mod__ = __rmod__ = __matmul__ = \
        __rmatmul__ = __float__ = __int__ = __index__ = _boom
    # __eq__ override kills default hashing; identity hash is the right
    # semantic for a placeholder
    __hash__ = object.__hash__


def _select_if(pred, true_fn, false_fn, thunks=()):
    """Runtime dispatch: eager bool -> Python branch; symbolic tensor ->
    run BOTH branches and `where`-select each returned value. `thunks`
    lazily read the CURRENT values of read-write branch variables."""
    from paddle_tpu.dygraph.base import trace_op
    from paddle_tpu.dygraph.varbase import VarBase

    vals = []
    for th in thunks:
        try:
            vals.append(th())
        except (NameError, UnboundLocalError):
            vals.append(_Undefined())
    if not isinstance(pred, VarBase) or pred.value is not None:
        return true_fn(*vals) if pred else false_fn(*vals)
    if not thunks:
        raise RuntimeError(
            "a data-dependent `if` whose branches assign no variables is "
            "side-effect-only and cannot be converted to a select; use "
            "layers.cond or restructure"
        )
    tv = true_fn(*vals)
    fv = false_fn(*vals)
    tv = tv if isinstance(tv, tuple) else (tv,)
    fv = fv if isinstance(fv, tuple) else (fv,)
    outs = []
    for t, f in zip(tv, fv):
        if isinstance(t, _Undefined) or isinstance(f, _Undefined):
            # the variable exists on one path only (branch-local temp, loop
            # var, nested def): no select possible. Mirror Python: fine if
            # never used after the `if`, loud on use.
            outs.append(_Undefined())
            continue
        if isinstance(t, VarBase) or isinstance(f, VarBase):
            # mixed tensor/scalar branches (`y = 0.0` before the if, then
            # `y = x * 2` inside): promote the plain value to a constant
            # tensor so the select works
            from paddle_tpu.dygraph.base import to_variable
            import numpy as _np

            if not isinstance(t, VarBase):
                t = to_variable(_np.asarray(t, dtype=_np.dtype(f.dtype)))
            if not isinstance(f, VarBase):
                f = to_variable(_np.asarray(f, dtype=_np.dtype(t.dtype)))
            outs.append(trace_op(
                "where", {"Condition": [pred], "X": [t], "Y": [f]}, {}
            )["Out"][0])
        else:
            raise RuntimeError(
                "converted `if` produced a non-tensor branch value under "
                "trace; only tensor-valued branches can be selected "
                f"(got {type(t).__name__}/{type(f).__name__})"
            )
    # always a tuple: the rewritten assignment unpacks a tuple target
    return tuple(outs)


def _loop_cmp(i, stop, step):
    """for-range loop condition: `i < stop` for positive step, `i > stop`
    for a NEGATIVE CONSTANT step. A symbolic (tensor) step is compared as
    positive — documented limit, matching the reference's loop transform."""
    from paddle_tpu.dygraph.varbase import VarBase

    if not isinstance(step, VarBase) and step == 0:
        raise ValueError("range() arg 3 must not be zero")
    neg = not isinstance(step, VarBase) and step < 0
    return (i > stop) if neg else (i < stop)


def _run_while(cond_fn, body_fn, thunks, names):
    """Runtime dispatch for a converted loop: concrete condition -> plain
    Python while (eager mode; constant trip counts under capture unroll
    exactly as an untransformed trace would); symbolic condition under
    capture -> a `while` op whose sub-block runs the traced body and
    writes each carried name back (lowered to lax.while_loop)."""
    import numpy as _np

    from paddle_tpu.dygraph import base
    from paddle_tpu.dygraph.base import to_variable
    from paddle_tpu.dygraph.varbase import VarBase

    vals = []
    for th in thunks:
        try:
            vals.append(th())
        except (NameError, UnboundLocalError):
            vals.append(_Undefined())
    c = cond_fn(*vals)
    if not isinstance(c, VarBase) or c.value is not None:
        while bool(c):
            out = body_fn(*vals)
            vals = list(out) if isinstance(out, tuple) else [out]
            c = cond_fn(*vals)
        return tuple(vals)

    cap = base._capture
    if cap is None:
        raise RuntimeError(
            "converted loop: symbolic condition outside capture mode"
        )
    from paddle_tpu.layers.control_flow import While
    from paddle_tpu.utils import unique_name as _un

    prog = cap.main_program
    svs = []
    undef_slots = []
    for nm, v in zip(names, vals):
        if isinstance(v, _Undefined):
            # a name assigned inside the body but never defined before the
            # loop (e.g. an inner loop's counter re-initialized each outer
            # iteration): its init shape becomes known once the body is
            # traced — materialize a zero init then. Reading it BEFORE its
            # in-body assignment still fails loudly (_Undefined._boom).
            svs.append(None)
            undef_slots.append(len(svs) - 1)
            continue
        if isinstance(v, VarBase):
            vb = v
        else:
            arr = _np.asarray(v)
            if arr.ndim == 0:
                # fluid's scalar convention is shape [1]; a 0-d init would
                # mismatch the [1] the body's arithmetic produces
                arr = arr.reshape(1)
            vb = to_variable(arr)
        sv = vb.static_var
        if sv is None:
            sv = cap.to_static_var(vb)
        svs.append(sv)
    cond_sv = c.static_var
    parent = prog.current_block()
    with While(cond_sv):
        sub = prog.current_block()
        out = body_fn(*[
            VarBase.from_static(sv) if sv is not None else _Undefined()
            for sv in svs
        ])
        out = out if isinstance(out, tuple) else (out,)
        for idx in undef_slots:
            nv = out[idx]
            nsv = nv.static_var if isinstance(nv, VarBase) else None
            shape = (
                list(nsv.shape)
                if nsv is not None and nsv.shape is not None
                else None
            )
            if (
                shape is None
                or any(d is None or d < 0 for d in shape)
            ):
                raise RuntimeError(
                    f"converted loop: variable '{names[idx]}' is loop "
                    "state with no value before the loop and no statically "
                    "known in-body shape; initialize it before the loop"
                )
            init_name = _un.generate(f"__pt_loop_init_{names[idx]}")
            parent.create_var(name=init_name, shape=shape, dtype=nsv.dtype)
            # emitted into the PARENT block; the while op is appended after
            # it on __exit__, so the init dominates the loop
            parent.append_op(
                "fill_constant", {}, {"Out": [init_name]},
                {"shape": shape, "dtype": nsv.dtype, "value": 0.0},
            )
            svs[idx] = parent.var(init_name)
        for nm, sv, nv in zip(names, svs, out):
            if not isinstance(nv, VarBase):
                try:
                    nv = to_variable(_np.asarray(nv))
                except Exception:
                    raise RuntimeError(
                        f"converted loop: variable '{nm}' takes non-tensor "
                        f"value {type(nv).__name__} inside the loop; only "
                        "tensor (or numeric) loop state can be carried"
                    ) from None
            nsv = nv.static_var
            if nsv is None:
                nsv = cap.to_static_var(nv)
            # write the new value back under the carried name: the while
            # lowering carries exactly the pre-existing names the
            # sub-block writes (ops/control_flow.py _run_while)
            sub.append_op("assign", {"X": [nsv.name]}, {"Out": [sv.name]})
        c2 = cond_fn(*[VarBase.from_static(sv) for sv in svs])
        if not isinstance(c2, VarBase) or c2.static_var is None:
            raise RuntimeError(
                "converted loop: the condition must stay tensor-valued "
                "inside the loop"
            )
        sub.append_op(
            "assign", {"X": [c2.static_var.name]}, {"Out": [cond_sv.name]}
        )
    return tuple(VarBase.from_static(sv) for sv in svs)


def ast_transform(fn):
    """Rewrite `fn`'s data-dependent `if` statements. Returns the
    transformed function, or None when the source cannot be transformed
    (caller falls back to plain tracing + the loud capture guard)."""
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return None
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return None
    fdef.decorator_list = []  # avoid re-applying @declarative etc.
    tr = _IfTransformer()
    tr.visit(tree)
    if tr.count == 0:
        return None
    ast.fix_missing_locations(tree)
    try:
        code = compile(tree, f"<ast_transform {fn.__name__}>", "exec")
    except (SyntaxError, ValueError):
        return None
    # exec against the LIVE module globals (not a snapshot): names defined
    # or monkeypatched after decoration, and recursion through the module
    # global, must resolve. The helper key is collision-safe.
    glb = getattr(fn, "__globals__", None)
    if glb is None:
        return None
    glb[_HELPER] = _select_if
    glb[_WHILE_HELPER] = _run_while
    glb[_CMP_HELPER] = _loop_cmp
    # re-bind the function's closure-free form; closures over outer locals
    # cannot be rebuilt from source -> bail to the fallback
    if getattr(fn, "__closure__", None):
        return None
    loc = {}
    exec(code, glb, loc)
    out = loc.get(fdef.name)
    if out is None:
        return None
    out.__wrapped_original__ = fn
    return out


def convert_ifelse(fn):
    """Public decorator: transform if possible, else return fn unchanged
    (plain trace + loud guard)."""
    return ast_transform(fn) or fn
