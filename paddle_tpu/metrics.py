"""Streaming training metrics, updated host-side from fetched batches.

Reference: python/paddle/fluid/metrics.py — MetricBase :62, Accuracy :435,
Auc :699, Precision :535, Recall :610, CompositeMetric :364. These accumulate
across exe.run fetches (the in-graph accuracy/auc ops in layers/nn.py are the
per-batch device-side counterparts)."""

import numpy as np

__all__ = [
    "MetricBase",
    "Accuracy",
    "Precision",
    "Recall",
    "Auc",
    "CompositeMetric",
    "ChunkEvaluator",
]


class MetricBase:
    def __init__(self, name=None):
        self._name = name or self.__class__.__name__

    def reset(self):
        raise NotImplementedError

    def update(self, *args, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError

    def get_config(self):
        return {
            k: v for k, v in self.__dict__.items() if not k.startswith("_")
        }


class Accuracy(MetricBase):
    """Weighted streaming accuracy (reference: metrics.py:435)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight=1):
        if weight < 0:
            raise ValueError("weight must be nonnegative")
        self.value += float(np.asarray(value).reshape(-1)[0]) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError("no data updated into Accuracy")
        return self.value / self.weight


class Precision(MetricBase):
    """Binary precision from (pred label in {0,1}, gold) batches
    (reference: metrics.py:535)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).astype(np.int64).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())

    def eval(self):
        denom = self.tp + self.fp
        return float(self.tp) / denom if denom else 0.0


class Recall(MetricBase):
    """reference: metrics.py:610."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).astype(np.int64).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())

    def eval(self):
        denom = self.tp + self.fn
        return float(self.tp) / denom if denom else 0.0


class Auc(MetricBase):
    """Thresholded streaming AUC, same histogram algorithm as the reference
    (reference: metrics.py:699 and operators/metrics/auc_op.cc): bucket
    positive/negative counts by predicted score, integrate trapezoidally."""

    def __init__(self, name=None, curve="ROC", num_thresholds=4095):
        super().__init__(name)
        self._num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        n = self._num_thresholds + 1
        self._stat_pos = np.zeros(n, dtype=np.int64)
        self._stat_neg = np.zeros(n, dtype=np.int64)

    def update(self, preds, labels):
        """preds: [N, 2] class probabilities (or [N] positive scores)."""
        preds = np.asarray(preds)
        scores = preds[:, 1] if preds.ndim == 2 else preds.reshape(-1)
        labels = np.asarray(labels).astype(np.int64).reshape(-1)
        idx = np.clip(
            (scores * self._num_thresholds).astype(np.int64),
            0, self._num_thresholds,
        )
        np.add.at(self._stat_pos, idx[labels == 1], 1)
        np.add.at(self._stat_neg, idx[labels == 0], 1)

    def eval(self):
        tot_pos = tot_neg = 0.0
        auc = 0.0
        for i in range(self._num_thresholds, -1, -1):
            new_pos = tot_pos + self._stat_pos[i]
            new_neg = tot_neg + self._stat_neg[i]
            auc += (new_pos + tot_pos) * (new_neg - tot_neg) / 2.0
            tot_pos, tot_neg = new_pos, new_neg
        denom = tot_pos * tot_neg
        return float(auc) / denom if denom else 0.0


class CompositeMetric(MetricBase):
    """reference: metrics.py:364."""

    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        if not isinstance(metric, MetricBase):
            raise TypeError("add_metric expects a MetricBase")
        self._metrics.append(metric)

    def reset(self):
        for m in self._metrics:
            m.reset()

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


class ChunkEvaluator(MetricBase):
    """Streaming chunk F1 from per-batch (num_infer, num_label, num_correct)
    counts (reference: metrics.py ChunkEvaluator)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks, num_correct_chunks):
        self.num_infer_chunks += int(np.asarray(num_infer_chunks).reshape(-1)[0])
        self.num_label_chunks += int(np.asarray(num_label_chunks).reshape(-1)[0])
        self.num_correct_chunks += int(
            np.asarray(num_correct_chunks).reshape(-1)[0]
        )

    def eval(self):
        precision = (
            self.num_correct_chunks / self.num_infer_chunks
            if self.num_infer_chunks
            else 0.0
        )
        recall = (
            self.num_correct_chunks / self.num_label_chunks
            if self.num_label_chunks
            else 0.0
        )
        f1 = (
            2 * precision * recall / (precision + recall)
            if precision + recall
            else 0.0
        )
        return precision, recall, f1
