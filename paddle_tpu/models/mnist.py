"""MNIST models — the reference's "recognize_digits" book workloads
(reference: python/paddle/fluid/tests/book/test_recognize_digits.py)."""

import paddle_tpu as fluid


def mlp(img, label, hidden=(200, 200)):
    h = img
    for size in hidden:
        h = fluid.layers.fc(h, size=size, act="relu")
    logits = fluid.layers.fc(h, size=10)
    loss = fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(logits, label)
    )
    acc = fluid.layers.accuracy(fluid.layers.softmax(logits), label)
    return loss, acc, logits


def conv_net(img, label):
    """LeNet-style conv net; img is [N, 1, 28, 28]."""
    c1 = fluid.layers.conv2d(img, num_filters=20, filter_size=5, act="relu")
    p1 = fluid.layers.pool2d(c1, pool_size=2, pool_stride=2)
    c2 = fluid.layers.conv2d(p1, num_filters=50, filter_size=5, act="relu")
    p2 = fluid.layers.pool2d(c2, pool_size=2, pool_stride=2)
    logits = fluid.layers.fc(p2, size=10)
    loss = fluid.layers.mean(
        fluid.layers.softmax_with_cross_entropy(logits, label)
    )
    acc = fluid.layers.accuracy(fluid.layers.softmax(logits), label)
    return loss, acc, logits


def build_mnist_train(use_conv=False):
    """Returns (main_program, startup_program, feeds, fetches)."""
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        if use_conv:
            img = fluid.data("img", shape=[-1, 1, 28, 28])
        else:
            img = fluid.data("img", shape=[-1, 784])
        label = fluid.data("label", shape=[-1, 1], dtype="int64")
        build = conv_net if use_conv else mlp
        loss, acc, logits = build(img, label)
        opt = fluid.optimizer.Adam(learning_rate=1e-3)
        opt.minimize(loss)
    return main, startup, [img, label], [loss, acc]
