"""BERT pretraining — the collective-training flagship
(BASELINE.md config 3: BERT-base pretrain, fleet collective allreduce over ICI).

Transformer encoder built from framework layers; attention is plain
matmul/softmax ops that XLA fuses (the reference needed a hand-fused kernel,
reference: paddle/fluid/operators/fused/multihead_matmul_op.cc — here fusion
is the compiler's job, and a Pallas flash-attention kernel can override the
lowering for long sequences; see ops/pallas/).
"""

import math

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.param_attr import ParamAttr


class BertConfig:
    def __init__(
        self,
        vocab_size=30522,
        hidden_size=768,
        num_hidden_layers=12,
        num_attention_heads=12,
        intermediate_size=3072,
        max_position_embeddings=512,
        type_vocab_size=2,
        hidden_dropout_prob=0.1,
        attention_probs_dropout_prob=0.1,
        initializer_range=0.02,
        use_flash_attention=False,
    ):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.intermediate_size = intermediate_size
        self.max_position_embeddings = max_position_embeddings
        self.type_vocab_size = type_vocab_size
        self.hidden_dropout_prob = hidden_dropout_prob
        self.attention_probs_dropout_prob = attention_probs_dropout_prob
        self.initializer_range = initializer_range
        # flash path: Pallas fused attention; attention-prob dropout is not
        # applied inside the fused kernel (standard flash trade-off)
        self.use_flash_attention = use_flash_attention

    @staticmethod
    def base():
        return BertConfig()

    @staticmethod
    def tiny():
        """For tests and dry runs."""
        return BertConfig(
            vocab_size=1024,
            hidden_size=64,
            num_hidden_layers=2,
            num_attention_heads=4,
            intermediate_size=128,
            max_position_embeddings=128,
        )


def _init(cfg):
    return fluid.initializer.TruncatedNormal(0.0, cfg.initializer_range)


def _dense(x, size, cfg, act=None, name=None, num_flatten_dims=2):
    return fluid.layers.fc(
        x,
        size=size,
        num_flatten_dims=num_flatten_dims,
        act=act,
        param_attr=ParamAttr(initializer=_init(cfg), name=name + ".w" if name else None),
        bias_attr=ParamAttr(name=name + ".b" if name else None),
        name=name,
    )


def multi_head_attention(x, attn_bias, cfg, name):
    """Self-attention over [B, S, H]; attn_bias is additive [B, 1, 1, S]."""
    B_H = cfg.hidden_size
    n_head = cfg.num_attention_heads
    d_head = B_H // n_head
    q = _dense(x, B_H, cfg, name=name + ".q")
    k = _dense(x, B_H, cfg, name=name + ".k")
    v = _dense(x, B_H, cfg, name=name + ".v")

    def split_heads(t):
        t = fluid.layers.reshape(t, [0, 0, n_head, d_head])
        return fluid.layers.transpose(t, [0, 2, 1, 3])  # [B, n, S, d]

    q, k, v = split_heads(q), split_heads(k), split_heads(v)
    if getattr(cfg, "use_flash_attention", False):
        if getattr(cfg, "attention_probs_dropout_prob", 0.0):
            # enforcement, not silent degradation: the fused kernel does not
            # apply attention-prob dropout, so refusing beats training a
            # different model than configured
            from paddle_tpu.utils.enforce import EnforceError

            raise EnforceError(
                "use_flash_attention=True cannot honor "
                f"attention_probs_dropout_prob="
                f"{cfg.attention_probs_dropout_prob}: the fused kernel "
                "applies no attention-prob dropout. Set it to 0 (the "
                "common large-model recipe) or disable the flash path."
            )
        # attn_bias here is [B,1,1,S]; the fused op takes [B,S]
        flat_bias = fluid.layers.reshape(attn_bias, [0, attn_bias.shape[-1]])
        ctx = fluid.layers.scaled_dot_product_attention(
            q, k, v, bias=flat_bias, sm_scale=1.0 / math.sqrt(d_head)
        )
    else:
        scores = fluid.layers.matmul(
            q, k, transpose_y=True, alpha=1.0 / math.sqrt(d_head)
        )  # [B, n, S, S]
        scores = fluid.layers.elementwise_add(scores, attn_bias)
        probs = fluid.layers.softmax(scores)
        if cfg.attention_probs_dropout_prob:
            probs = fluid.layers.dropout(
                probs,
                cfg.attention_probs_dropout_prob,
                dropout_implementation="upscale_in_train",
            )
        ctx = fluid.layers.matmul(probs, v)  # [B, n, S, d]
    ctx = fluid.layers.transpose(ctx, [0, 2, 1, 3])
    ctx = fluid.layers.reshape(ctx, [0, 0, B_H])
    return _dense(ctx, B_H, cfg, name=name + ".out")


def encoder_layer(x, attn_bias, cfg, name):
    attn = multi_head_attention(x, attn_bias, cfg, name + ".attn")
    if cfg.hidden_dropout_prob:
        attn = fluid.layers.dropout(
            attn, cfg.hidden_dropout_prob, dropout_implementation="upscale_in_train"
        )
    x = fluid.layers.layer_norm(
        fluid.layers.elementwise_add(x, attn), begin_norm_axis=2, name=name + ".ln1"
    )
    ffn = _dense(x, cfg.intermediate_size, cfg, act="gelu", name=name + ".ffn1")
    ffn = _dense(ffn, cfg.hidden_size, cfg, name=name + ".ffn2")
    if cfg.hidden_dropout_prob:
        ffn = fluid.layers.dropout(
            ffn, cfg.hidden_dropout_prob, dropout_implementation="upscale_in_train"
        )
    return fluid.layers.layer_norm(
        fluid.layers.elementwise_add(x, ffn), begin_norm_axis=2, name=name + ".ln2"
    )


def bert_encoder(input_ids, token_type_ids, input_mask, cfg, seq_len):
    """Returns (sequence_output [B,S,H], pooled_output [B,H])."""
    word_emb = fluid.layers.embedding(
        input_ids,
        size=[cfg.vocab_size, cfg.hidden_size],
        param_attr=ParamAttr(name="word_embedding", initializer=_init(cfg)),
    )
    pos_ids = _const_i64(np.arange(seq_len).reshape(1, seq_len), "pos_ids")
    pos_emb = fluid.layers.embedding(
        pos_ids,
        size=[cfg.max_position_embeddings, cfg.hidden_size],
        param_attr=ParamAttr(name="pos_embedding", initializer=_init(cfg)),
    )
    type_emb = fluid.layers.embedding(
        token_type_ids,
        size=[cfg.type_vocab_size, cfg.hidden_size],
        param_attr=ParamAttr(name="type_embedding", initializer=_init(cfg)),
    )
    emb = fluid.layers.elementwise_add(
        fluid.layers.elementwise_add(word_emb, pos_emb), type_emb
    )
    emb = fluid.layers.layer_norm(emb, begin_norm_axis=2, name="emb_ln")
    if cfg.hidden_dropout_prob:
        emb = fluid.layers.dropout(
            emb, cfg.hidden_dropout_prob, dropout_implementation="upscale_in_train"
        )
    # additive attention bias [B, 1, 1, S]: 0 keep, -10000 masked
    mask_f = fluid.layers.cast(input_mask, "float32")
    neg = fluid.layers.scale(mask_f, scale=10000.0, bias=-10000.0)
    attn_bias = fluid.layers.reshape(neg, [0, 1, 1, seq_len])
    x = emb
    for i in range(cfg.num_hidden_layers):
        x = encoder_layer(x, attn_bias, cfg, f"layer_{i}")
    first_tok = fluid.layers.slice(x, axes=[1], starts=[0], ends=[1])
    pooled = _dense(
        fluid.layers.reshape(first_tok, [0, cfg.hidden_size]),
        cfg.hidden_size,
        cfg,
        act="tanh",
        name="pooler",
        num_flatten_dims=1,
    )
    return x, pooled


def _const_i64(arr, name):
    from paddle_tpu.layer_helper import LayerHelper

    helper = LayerHelper("const_" + name)
    out = helper.block.create_var(
        name=helper.name, shape=list(arr.shape), dtype="int64", stop_gradient=True
    )
    helper.append_op(
        "assign_value",
        {},
        {"Out": [out.name]},
        {"shape": list(arr.shape), "dtype": "int64", "values": arr.reshape(-1).tolist()},
    )
    return out


def build_bert_pretrain(cfg=None, seq_len=128, lr=1e-4, use_amp=False,
                        max_predictions_per_seq=None):
    """BERT pretraining program: MLM + NSP losses.

    Default feeds: input_ids, token_type_ids, input_mask, mlm_labels
    [-1 = unmasked], nsp_labels. With `max_predictions_per_seq=P` the MLM
    head projects ONLY the gathered masked positions (feeds
    masked_positions [B, P] + mlm_labels [B, P], -1 padded) — the standard
    pretraining recipe: the vocab projection shrinks from [B,S,V] to
    [B,P,V], cutting the head's FLOPs and HBM by S/P (~6x at S=128, P=20).
    Returns (main, startup, feeds, fetches)."""
    cfg = cfg or BertConfig.base()
    main = fluid.Program()
    startup = fluid.Program()
    P = max_predictions_per_seq
    with fluid.program_guard(main, startup):
        input_ids = fluid.data("input_ids", shape=[-1, seq_len], dtype="int64")
        token_type_ids = fluid.data("token_type_ids", shape=[-1, seq_len], dtype="int64")
        input_mask = fluid.data("input_mask", shape=[-1, seq_len], dtype="int64")
        if P:
            masked_positions = fluid.data(
                "masked_positions", shape=[-1, P], dtype="int64"
            )
            mlm_labels = fluid.data("mlm_labels", shape=[-1, P], dtype="int64")
        else:
            mlm_labels = fluid.data(
                "mlm_labels", shape=[-1, seq_len], dtype="int64"
            )
        nsp_labels = fluid.data("nsp_labels", shape=[-1, 1], dtype="int64")

        seq_out, pooled = bert_encoder(input_ids, token_type_ids, input_mask, cfg, seq_len)

        # MLM head: transform + output projection (gathered positions only
        # when P is set)
        mlm_in = (
            fluid.layers.batched_gather(seq_out, masked_positions)
            if P
            else seq_out
        )
        n_pred = P or seq_len
        mlm_t = _dense(mlm_in, cfg.hidden_size, cfg, act="gelu", name="mlm_transform")
        mlm_t = fluid.layers.layer_norm(mlm_t, begin_norm_axis=2, name="mlm_ln")
        mlm_logits = _dense(mlm_t, cfg.vocab_size, cfg, name="mlm_out")
        mlm_loss_tok = fluid.layers.softmax_with_cross_entropy(
            mlm_logits, fluid.layers.reshape(mlm_labels, [0, n_pred, 1]),
            ignore_index=-1, axis=-1,
        )  # [B, n_pred, 1], zeros at ignored
        is_masked = fluid.layers.cast(
            fluid.layers.tensor.not_equal(
                mlm_labels, fluid.layers.tensor.fill_constant([1], "int64", -1)
            ),
            "float32",
        )
        denom = fluid.layers.elementwise_max(
            fluid.layers.reduce_sum(is_masked),
            fluid.layers.tensor.fill_constant([1], "float32", 1.0),
        )
        mlm_loss = fluid.layers.elementwise_div(
            fluid.layers.reduce_sum(mlm_loss_tok), denom
        )

        nsp_logits = _dense(pooled, 2, cfg, name="nsp_out", num_flatten_dims=1)
        nsp_loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(nsp_logits, nsp_labels)
        )
        loss = fluid.layers.elementwise_add(mlm_loss, nsp_loss)

        scheduler = fluid.layers.learning_rate_scheduler.linear_lr_warmup(
            lr, warmup_steps=10000, start_lr=0.0, end_lr=lr
        )
        opt = fluid.optimizer.Adam(learning_rate=scheduler)
        if use_amp:
            from paddle_tpu.amp import decorate

            opt = decorate(opt)
        opt.minimize(loss)
    feeds = [input_ids, token_type_ids, input_mask, mlm_labels, nsp_labels]
    if P:
        feeds.insert(3, masked_positions)
    return main, startup, feeds, [loss, mlm_loss, nsp_loss]


def synthetic_batch(rng, batch, seq_len, cfg, max_predictions_per_seq=None):
    ids = rng.randint(0, cfg.vocab_size, (batch, seq_len)).astype("int64")
    types = np.zeros((batch, seq_len), dtype="int64")
    mask = np.ones((batch, seq_len), dtype="int64")
    nsp = rng.randint(0, 2, (batch, 1)).astype("int64")
    P = max_predictions_per_seq
    if P:
        positions = np.zeros((batch, P), dtype="int64")
        labels = np.full((batch, P), -1, dtype="int64")
        n_mask = min(P, max(1, seq_len // 7))
        for b in range(batch):
            pos = rng.choice(seq_len, n_mask, replace=False)
            positions[b, :n_mask] = pos
            labels[b, :n_mask] = ids[b, pos]
            ids[b, pos] = 103  # [MASK]
        return {
            "input_ids": ids,
            "token_type_ids": types,
            "input_mask": mask,
            "masked_positions": positions,
            "mlm_labels": labels,
            "nsp_labels": nsp,
        }
    mlm = np.full((batch, seq_len), -1, dtype="int64")
    n_mask = max(1, seq_len // 7)
    for b in range(batch):
        pos = rng.choice(seq_len, n_mask, replace=False)
        mlm[b, pos] = ids[b, pos]
        ids[b, pos] = 103  # [MASK]
    return {
        "input_ids": ids,
        "token_type_ids": types,
        "input_mask": mask,
        "mlm_labels": mlm,
        "nsp_labels": nsp,
    }
