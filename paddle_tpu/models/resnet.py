"""ResNet for ImageNet — the static-graph flagship vision workload
(BASELINE.md config 2: ResNet-50 ImageNet, fluid static ProgramDesc → XLA).

Built from framework layers only (conv2d/batch_norm/pool2d); under the
whole-block executor the entire network compiles to one XLA computation, so
conv+BN+relu chains fuse without the reference's fusion passes
(reference: paddle/fluid/framework/ir/conv_bn_fuse_pass.cc etc.).
"""

import paddle_tpu as fluid
from paddle_tpu.param_attr import ParamAttr

DEPTH_CFG = {
    18: ("basic", [2, 2, 2, 2]),
    34: ("basic", [3, 4, 6, 3]),
    50: ("bottleneck", [3, 4, 6, 3]),
    101: ("bottleneck", [3, 4, 23, 3]),
    152: ("bottleneck", [3, 8, 36, 3]),
}


def conv_bn_layer(input, num_filters, filter_size, stride=1, groups=1, act=None, name=None):
    conv = fluid.layers.conv2d(
        input,
        num_filters=num_filters,
        filter_size=filter_size,
        stride=stride,
        padding=(filter_size - 1) // 2,
        groups=groups,
        bias_attr=False,
        param_attr=ParamAttr(name=name + "_weights" if name else None),
        name=name,
    )
    return fluid.layers.batch_norm(
        conv,
        act=act,
        param_attr=ParamAttr(name=name + "_bn_scale" if name else None),
        bias_attr=ParamAttr(name=name + "_bn_offset" if name else None),
        moving_mean_name=name + "_bn_mean" if name else None,
        moving_variance_name=name + "_bn_variance" if name else None,
    )


def shortcut(input, ch_out, stride, name):
    ch_in = input.shape[1]
    if ch_in != ch_out or stride != 1:
        return conv_bn_layer(input, ch_out, 1, stride, name=name)
    return input


def bottleneck_block(input, num_filters, stride, name):
    conv0 = conv_bn_layer(input, num_filters, 1, act="relu", name=name + "_branch2a")
    conv1 = conv_bn_layer(conv0, num_filters, 3, stride, act="relu", name=name + "_branch2b")
    conv2 = conv_bn_layer(conv1, num_filters * 4, 1, name=name + "_branch2c")
    short = shortcut(input, num_filters * 4, stride, name=name + "_branch1")
    return fluid.layers.elementwise_add(short, conv2, act="relu")


def basic_block(input, num_filters, stride, name):
    conv0 = conv_bn_layer(input, num_filters, 3, stride, act="relu", name=name + "_branch2a")
    conv1 = conv_bn_layer(conv0, num_filters, 3, name=name + "_branch2b")
    short = shortcut(input, num_filters, stride, name=name + "_branch1")
    return fluid.layers.elementwise_add(short, conv1, act="relu")


def resnet(input, class_dim=1000, depth=50):
    block_kind, counts = DEPTH_CFG[depth]
    block_fn = bottleneck_block if block_kind == "bottleneck" else basic_block
    conv = conv_bn_layer(input, 64, 7, 2, act="relu", name="res_conv1")
    pool = fluid.layers.pool2d(conv, pool_size=3, pool_stride=2, pool_padding=1)
    filters = [64, 128, 256, 512]
    for stage, count in enumerate(counts):
        for i in range(count):
            stride = 2 if i == 0 and stage > 0 else 1
            pool = block_fn(
                pool, filters[stage], stride, name=f"res{stage + 2}{chr(97 + i)}"
            )
    pool = fluid.layers.pool2d(pool, global_pooling=True)
    import math

    stdv = 1.0 / math.sqrt(pool.shape[1] * 1.0)
    logits = fluid.layers.fc(
        pool,
        size=class_dim,
        param_attr=ParamAttr(
            initializer=fluid.initializer.Uniform(-stdv, stdv), name="fc_0.w"
        ),
    )
    return logits


def build_resnet_train(depth=50, class_dim=1000, image_shape=(3, 224, 224),
                       lr=0.1, use_amp=False):
    """Returns (main, startup, feeds, fetches) for ResNet training with
    momentum + L2 decay (the reference recipe); use_amp runs convs/matmuls
    in bf16 (amp white list)."""
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.data("img", shape=[-1] + list(image_shape))
        label = fluid.data("label", shape=[-1, 1], dtype="int64")
        logits = resnet(img, class_dim, depth)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label)
        )
        acc = fluid.layers.accuracy(fluid.layers.softmax(logits), label)
        opt = fluid.optimizer.Momentum(
            learning_rate=lr,
            momentum=0.9,
            regularization=fluid.regularizer.L2Decay(1e-4),
        )
        if use_amp:
            from paddle_tpu.amp import decorate

            opt = decorate(opt)
        opt.minimize(loss)
    return main, startup, [img, label], [loss, acc]


def build_resnet_infer(depth=50, class_dim=1000, image_shape=(3, 224, 224)):
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.data("img", shape=[-1] + list(image_shape))
        logits = resnet(img, class_dim, depth)
        prob = fluid.layers.softmax(logits)
    return main.clone(for_test=True), startup, [img], [prob]
