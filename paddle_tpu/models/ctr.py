"""Wide&Deep CTR model over PS-backed sparse embeddings.

The reference's flagship PS workload (BASELINE.md Wide&Deep CTR,
1B-feature sparse embedding; reference model shape: ctr_dnn in the dist
tests, python/paddle/fluid/tests/unittests/dist_ctr.py): hashed sparse id
slots -> wide (linear) + deep (embedding + MLP) -> sigmoid CTR.

Sparse tables live on native PS servers; ids can span the full u64 hash
space (no vocab-size dense table anywhere).
"""

import numpy as np

import paddle_tpu as fluid

__all__ = ["build_ctr_train", "synthetic_batch"]


def build_ctr_train(
    num_slots=8,
    ids_per_slot=3,
    deep_dim=16,
    hidden=(64, 32),
    sparse_lr=0.1,
    optimizer=None,
    ps_mode=True,
    vocab_size=None,
):
    """Returns (main, startup, feeds, fetches). ps_mode=True uses
    PS sparse_embedding (host pre-pull, ids unbounded); ps_mode="remote"
    uses distributed_embedding (in-graph io_callback pull/push, the
    reference's parameter_prefetch flow); ps_mode=False uses an on-device
    dense table of `vocab_size` rows (parity baseline for tests)."""
    remote = ps_mode == "remote"
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        slots = [
            fluid.data(f"slot_{i}", shape=[-1, ids_per_slot], dtype="int64")
            for i in range(num_slots)
        ]
        label = fluid.data("click", shape=[-1, 1], dtype="float32")

        wide_parts, deep_parts = [], []
        for i, s in enumerate(slots):
            if remote:
                wide_e = fluid.layers.distributed_embedding(
                    s, [0, 1], table_name=f"wide_{i}", init_range=0.0
                )
                deep_e = fluid.layers.distributed_embedding(
                    s, [0, deep_dim], table_name=f"deep_{i}", init_range=0.0
                )
            elif ps_mode:
                wide_e = fluid.layers.sparse_embedding(
                    s, 1, name=f"wide_{i}", init_range=0.0
                )
                deep_e = fluid.layers.sparse_embedding(
                    s, deep_dim, name=f"deep_{i}", init_range=0.01
                )
            else:
                wide_e = fluid.layers.embedding(
                    s, (vocab_size, 1),
                    param_attr=fluid.ParamAttr(
                        name=f"wide_{i}_w",
                        initializer=fluid.initializer.Constant(0.0),
                    ),
                )
                deep_e = fluid.layers.embedding(
                    s, (vocab_size, deep_dim),
                    param_attr=fluid.ParamAttr(name=f"deep_{i}_w"),
                )
            # sum-pool the slot's ids: [B, ids_per_slot, d] -> [B, d]
            wide_parts.append(fluid.layers.reduce_sum(wide_e, dim=1))
            deep_parts.append(fluid.layers.reduce_sum(deep_e, dim=1))

        wide = fluid.layers.sums(wide_parts)  # [B, 1]
        deep = fluid.layers.concat(deep_parts, axis=1)
        for h in hidden:
            deep = fluid.layers.fc(deep, size=h, act="relu")
        deep_logit = fluid.layers.fc(deep, size=1)
        logit = wide + deep_logit
        loss = fluid.layers.mean(
            fluid.layers.sigmoid_cross_entropy_with_logits(logit, label)
        )
        pred = fluid.layers.sigmoid(logit)
        opt = optimizer or fluid.optimizer.Adam(learning_rate=1e-3)
        if ps_mode:
            from paddle_tpu.fleet import parameter_server as psfleet

            strategy = psfleet.PSDistributedStrategy(
                mode="sync", sparse_lr=sparse_lr
            )
            psfleet.fleet.distributed_optimizer(opt, strategy).minimize(loss)
        else:
            opt.minimize(loss)
    return main, startup, slots + [label], [loss, pred]


def synthetic_batch(rng, batch, num_slots=8, ids_per_slot=3, id_space=2**40):
    """Clicky synthetic CTR data: click probability driven by a hash of the
    first slot's ids, so the model has signal to learn."""
    feed = {}
    base = rng.randint(0, id_space, size=(batch, ids_per_slot), dtype=np.int64)
    for i in range(num_slots):
        ids = rng.randint(0, id_space, size=(batch, ids_per_slot), dtype=np.int64)
        if i == 0:
            ids = base
        feed[f"slot_{i}"] = ids
    p = ((base.sum(axis=1) % 97) / 97.0) * 0.8 + 0.1
    feed["click"] = (rng.rand(batch) < p).astype("float32").reshape(batch, 1)
    return feed
