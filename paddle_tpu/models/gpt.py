"""GPT-style decoder LM — the hybrid-parallelism flagship.

Composes, in ONE shard_map'd train step over a 4-axis
('data','stage','model','seq') mesh, every parallelism family:

  dp  — batch sharded on 'data', grads psum over it
        (reference analog: AllReduceSSAGraphBuilder, paddle/fluid/framework/
        ir/multi_devices_graph_pass/multi_devices_graph_pass.h:110)
  pp  — decoder blocks stacked and sharded on 'stage', GPipe microbatch
        schedule via parallel.pipeline (reference analog: PipelineOptimizer,
        python/paddle/fluid/optimizer.py:3414)
  tp  — Megatron column/row-parallel attention+FFN on 'model'
        (absent in reference, SURVEY §2.7)
  sp  — sequence shards on 'seq', ring attention via parallel.ring
        (absent in reference, SURVEY §5.7)
  ep  — MoE experts sharded over 'data' (DeepSpeed-MoE style: EP group ==
        DP group), all_to_all token dispatch via parallel.moe
        (absent in reference)

The per-parameter PartitionSpecs drive both shard_map in_specs and the
psum axes for gradient reduction: a parameter's gradient is psum'd over
exactly the mesh axes its spec does NOT shard (its replication group).
"""

import dataclasses

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from paddle_tpu.parallel.ring import ring_attention_local

from paddle_tpu.parallel.env import shard_map as _shard_map
from paddle_tpu.parallel.moe import moe_ffn_local
from paddle_tpu.parallel.pipeline import pipeline_apply, split_microbatches

AXES = ("data", "stage", "model", "seq")


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 32000
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    ffn_mult: int = 4
    max_seq_len: int = 1024
    num_experts: int = 0          # 0 => dense FFN in every block
    capacity_factor: float = 2.0
    aux_loss_weight: float = 0.01  # MoE load-balance loss weight
    attention: str = "ring"       # 'ring' | 'ulysses' (sp mechanism)

    @staticmethod
    def tiny(**kw):
        d = dict(vocab_size=256, hidden_size=64, num_layers=4, num_heads=4,
                 ffn_mult=2, max_seq_len=128)
        d.update(kw)
        return GPTConfig(**d)


# ---------------------------------------------------------------------------
# parameters


def init_params(rng, cfg):
    """Returns a pytree of np.float32 arrays. Block params are stacked on a
    leading num_layers dim (pipeline shards it over 'stage')."""
    h, l = cfg.hidden_size, cfg.num_layers
    f = cfg.ffn_mult * h
    std = 0.02

    def w(*shape):
        return (rng.standard_normal(shape) * std).astype(np.float32)

    def zeros(*shape):
        return np.zeros(shape, np.float32)

    def ones(*shape):
        return np.ones(shape, np.float32)

    blocks = dict(
        ln1_s=ones(l, h), ln1_b=zeros(l, h),
        wq=w(l, h, h), bq=zeros(l, h),
        wk=w(l, h, h), bk=zeros(l, h),
        wv=w(l, h, h), bv=zeros(l, h),
        wo=w(l, h, h), bo=zeros(l, h),
        ln2_s=ones(l, h), ln2_b=zeros(l, h),
    )
    if cfg.num_experts:
        e = cfg.num_experts
        blocks.update(
            gate=w(l, h, e),
            we1=w(l, e, h, f), be1=zeros(l, e, f),
            we2=w(l, e, f, h), be2=zeros(l, e, h),
        )
    else:
        blocks.update(
            w1=w(l, h, f), b1=zeros(l, f),
            w2=w(l, f, h), b2=zeros(l, h),
        )
    return dict(
        embed=w(cfg.vocab_size, h),
        pos_emb=w(cfg.max_seq_len, h),
        lnf_s=ones(h), lnf_b=zeros(h),
        blocks=blocks,
    )


def param_specs(cfg):
    """PartitionSpecs mirroring init_params: stage on the stacked-layer dim,
    Megatron model-sharding inside blocks, experts on 'data'."""
    blocks = dict(
        ln1_s=P("stage"), ln1_b=P("stage"),
        wq=P("stage", None, "model"), bq=P("stage", "model"),
        wk=P("stage", None, "model"), bk=P("stage", "model"),
        wv=P("stage", None, "model"), bv=P("stage", "model"),
        wo=P("stage", "model", None), bo=P("stage"),
        ln2_s=P("stage"), ln2_b=P("stage"),
    )
    if cfg.num_experts:
        # experts on 'data' (EP group == DP group), each expert's FFN hidden
        # dim Megatron-sharded on 'model' so tp ranks don't duplicate FLOPs
        blocks.update(
            gate=P("stage"),
            we1=P("stage", "data", None, "model"), be1=P("stage", "data", "model"),
            we2=P("stage", "data", "model", None), be2=P("stage", "data"),
        )
    else:
        blocks.update(
            w1=P("stage", None, "model"), b1=P("stage", "model"),
            w2=P("stage", "model", None), b2=P("stage"),
        )
    return dict(
        embed=P(), pos_emb=P(), lnf_s=P(), lnf_b=P(), blocks=blocks,
    )


def grad_psum_axes(spec):
    """Axes a gradient must be summed over = mesh axes the param is
    replicated across."""
    used = set()
    for entry in spec:
        if entry is None:
            continue
        for ax in (entry if isinstance(entry, tuple) else (entry,)):
            used.add(ax)
    return tuple(ax for ax in AXES if ax not in used)


# ---------------------------------------------------------------------------
# model pieces (all run INSIDE shard_map; [mb, s_local, ...] activations)


def _layer_norm(x, scale, bias, eps=1e-5):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(-1, keepdims=True)
    return ((x32 - mu) * jax.lax.rsqrt(var + eps) * scale + bias).astype(x.dtype)


def _attention(p, x, cfg, model_size):
    """Causal self-attention: heads split on 'model', sequence ring on 'seq'."""
    mb, s_loc, h = x.shape
    n_head_loc = cfg.num_heads // model_size
    d = cfg.hidden_size // cfg.num_heads

    def heads(t):  # [mb, s, h_loc] -> [mb, nh_loc, s, d]
        return t.reshape(mb, s_loc, n_head_loc, d).transpose(0, 2, 1, 3)

    q = heads(x @ p["wq"] + p["bq"])
    k = heads(x @ p["wk"] + p["bk"])
    v = heads(x @ p["wv"] + p["bv"])
    if cfg.attention == "ring":
        ctx = ring_attention_local(q, k, v, "seq", causal=True)
    else:
        from paddle_tpu.parallel.ulysses import ulysses_attention_local

        ctx = ulysses_attention_local(q, k, v, "seq", causal=True)
    ctx = ctx.transpose(0, 2, 1, 3).reshape(mb, s_loc, -1)
    out = lax.psum(ctx @ p["wo"], "model") + p["bo"]
    return out


def _ffn(p, x, cfg):
    y = jax.nn.gelu(x @ p["w1"] + p["b1"])
    return lax.psum(y @ p["w2"], "model") + p["b2"]


def _moe_ffn(p, x, cfg):
    mb, s_loc, h = x.shape
    flat = x.reshape(-1, h)

    def expert(ep, xe):
        y = jax.nn.gelu(xe @ ep["w1"] + ep["b1"])
        return lax.psum(y @ ep["w2"], "model") + ep["b2"]

    ep_params = dict(w1=p["we1"], b1=p["be1"], w2=p["we2"], b2=p["be2"])
    y, aux = moe_ffn_local(
        flat, p["gate"], ep_params, expert, "data",
        capacity_factor=cfg.capacity_factor,
    )
    return y.reshape(mb, s_loc, h), aux


def make_block_fn(cfg, model_size):
    """Block over a (h, aux) carry: aux accumulates the MoE load-balance
    loss as the activation traverses the pipeline stages."""

    def block(p, carry):
        x, aux = carry
        a = _attention(p, _layer_norm(x, p["ln1_s"], p["ln1_b"]), cfg, model_size)
        x = x + a
        y = _layer_norm(x, p["ln2_s"], p["ln2_b"])
        if cfg.num_experts:
            y, layer_aux = _moe_ffn(p, y, cfg)
            aux = aux + layer_aux / cfg.num_layers
        else:
            y = _ffn(p, y, cfg)
        return x + y, aux

    return block


# ---------------------------------------------------------------------------
# the hybrid train step


def _local_loss(params, tokens, labels, cfg, mesh_sizes, num_microbatches):
    """INSIDE shard_map: tokens/labels [B_loc, S_loc] on (data, seq)."""
    n_stage = mesh_sizes["stage"]
    s_loc = tokens.shape[1]
    seq_idx = lax.axis_index("seq")
    stage_idx = lax.axis_index("stage")

    emb = params["embed"][tokens]                        # [B_loc, s_loc, H]
    # positions are global: slice the table at this seq shard's offset
    pos = lax.dynamic_slice_in_dim(params["pos_emb"], seq_idx * s_loc, s_loc, 0)
    x = emb + pos[None]

    x_mb = split_microbatches(x, num_microbatches)       # [M, mb, s_loc, H]
    # zero per-microbatch aux accumulator deriving x's device-varying type
    aux_mb = (0.0 * x_mb.astype(jnp.float32)).sum(axis=(1, 2, 3))
    block = make_block_fn(cfg, mesh_sizes["model"])
    outs, aux = pipeline_apply(
        block, params["blocks"], (x_mb, aux_mb), "stage", collect="last"
    )
    hs = outs.reshape(x.shape)                           # valid on last stage

    hs = _layer_norm(hs, params["lnf_s"], params["lnf_b"])
    logits = hs @ params["embed"].T                      # [B_loc, s_loc, V]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    # head/loss only counts on the last stage (collect='last' zeros others)
    ce_sum = jnp.where(stage_idx == n_stage - 1, nll.sum(), 0.0)
    total = lax.psum(ce_sum, ("data", "seq", "stage"))
    n_tokens = (
        tokens.shape[0] * s_loc * mesh_sizes["data"] * mesh_sizes["seq"]
    )
    loss = total / n_tokens
    if cfg.num_experts:
        # load-balance aux loss: mean over microbatches and (data, seq)
        # shards; only the last stage holds the accumulated value
        aux_sum = jnp.where(stage_idx == n_stage - 1, aux.sum(), 0.0)
        aux_total = lax.psum(aux_sum, ("data", "seq", "stage"))
        n_shards = (
            num_microbatches * mesh_sizes["data"] * mesh_sizes["seq"]
        )
        loss = loss + cfg.aux_loss_weight * aux_total / n_shards
    return loss


def build_train_step(cfg, mesh, num_microbatches=2, lr=1e-3, b1=0.9, b2=0.95,
                     eps=1e-8, weight_decay=0.0):
    """Returns (step, init_state). step(state, tokens, labels) -> (state, loss)
    — jitted, params/opt-state donated, every axis of `mesh` exercised."""
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    for ax in AXES:
        assert ax in sizes, f"mesh must name axis {ax!r} (size may be 1)"
    specs = param_specs(cfg)

    def local_fn(params, tokens, labels):
        loss, grads = jax.value_and_grad(_local_loss)(
            params, tokens, labels, cfg=cfg, mesh_sizes=sizes,
            num_microbatches=num_microbatches,
        )
        grads = jax.tree_util.tree_map(
            lambda g, s: lax.psum(g, grad_psum_axes(s)) if grad_psum_axes(s) else g,
            grads,
            specs,
        )
        return loss, grads

    data_spec = P("data", "seq")
    sharded = _shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(specs, data_spec, data_spec),
        out_specs=(P(), specs),
    )

    def step(state, tokens, labels):
        params, m, v, t = state
        loss, grads = sharded(params, tokens, labels)
        t = t + 1
        lr_t = lr * jnp.sqrt(1 - b2 ** t) / (1 - b1 ** t)

        def upd(p, g, m_, v_):
            m_ = b1 * m_ + (1 - b1) * g
            v_ = b2 * v_ + (1 - b2) * g * g
            p = p - lr_t * (m_ / (jnp.sqrt(v_) + eps) + weight_decay * p)
            return p, m_, v_

        flat_p, tree = jax.tree_util.tree_flatten(params)
        flat_g = jax.tree_util.tree_leaves(grads)
        flat_m = jax.tree_util.tree_leaves(m)
        flat_v = jax.tree_util.tree_leaves(v)
        new = [upd(p, g, m_, v_) for p, g, m_, v_ in zip(flat_p, flat_g, flat_m, flat_v)]
        params = jax.tree_util.tree_unflatten(tree, [n[0] for n in new])
        m = jax.tree_util.tree_unflatten(tree, [n[1] for n in new])
        v = jax.tree_util.tree_unflatten(tree, [n[2] for n in new])
        return (params, m, v, t), loss

    from paddle_tpu.core.lowering import jit_compile

    jit_step = jit_compile(step, donate_argnums=(0,))

    def init_state(rng):
        params = init_params(rng, cfg)
        from jax.sharding import NamedSharding

        put = lambda tree: jax.tree_util.tree_map(
            lambda a, s: jax.device_put(jnp.asarray(a), NamedSharding(mesh, s)),
            tree,
            specs,
        )
        params = put(params)
        zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
        return (params, zeros, jax.tree_util.tree_map(jnp.zeros_like, params),
                jnp.zeros((), jnp.int32))

    return jit_step, init_state


def synthetic_batch(rng, batch, seq_len, cfg):
    tokens = rng.randint(0, cfg.vocab_size, size=(batch, seq_len + 1))
    return tokens[:, :-1].astype(np.int32), tokens[:, 1:].astype(np.int32)
