from paddle_tpu.models import mnist, resnet, bert, ctr, transformer
from paddle_tpu.models import mobilenet, seq2seq, yolov3
