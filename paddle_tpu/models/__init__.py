from paddle_tpu.models import mnist, resnet, bert, ctr, transformer
