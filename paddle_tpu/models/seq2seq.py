"""Seq2seq (GRU encoder-decoder with attention) + beam-search inference —
the reference model zoo's machine-translation workload (PaddleNLP
seq2seq/rnn_search, built on fluid layers + beam_search ops).

Training uses teacher forcing over padded+lengths batches; inference runs
the fixed-beam beam_search op step-by-step from the host (the reference's
while_loop + LoDTensorArray plumbing is a design refusal here — see
layers/control_flow.py) and backtracks with beam_search_decode.
"""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.layer_helper import LayerHelper
from paddle_tpu.param_attr import ParamAttr


def _gru_layer(x, hidden_size, name, h0=None):
    """Unidirectional fusion_gru over [B, S, M] (optional initial state)."""
    helper = LayerHelper(name)
    M = x.shape[-1]
    init = fluid.initializer.XavierInitializer()
    wx = helper.create_parameter(
        ParamAttr(name=f"{name}_wx", initializer=init),
        shape=[M, 3 * hidden_size], dtype="float32",
    )
    wh = helper.create_parameter(
        ParamAttr(name=f"{name}_wh", initializer=init),
        shape=[hidden_size, 3 * hidden_size], dtype="float32",
    )
    out = helper.create_variable_for_type_inference("float32")
    ins = {"X": [x.name], "WeightX": [wx.name], "WeightH": [wh.name]}
    if h0 is not None:
        ins["H0"] = [h0.name]
    helper.append_op("fusion_gru", ins, {"Hidden": [out.name]}, {})
    return out


def build_seq2seq_train(src_vocab, tgt_vocab, hidden=64, emb=32,
                        src_len=12, tgt_len=10, lr=1e-3):
    """Teacher-forced training program. Returns (main, startup, feeds,
    loss)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        src = fluid.data("src", [-1, src_len], dtype="int64")
        tgt_in = fluid.data("tgt_in", [-1, tgt_len], dtype="int64")
        tgt_out = fluid.data("tgt_out", [-1, tgt_len], dtype="int64")
        src_emb = fluid.layers.embedding(
            src, size=[src_vocab, emb],
            param_attr=ParamAttr(name="src_emb"),
        )
        enc = _gru_layer(src_emb, hidden, "enc_gru")      # [B, S, H]
        tgt_emb = fluid.layers.embedding(
            tgt_in, size=[tgt_vocab, emb],
            param_attr=ParamAttr(name="tgt_emb"),
        )
        dec = _gru_layer(tgt_emb, hidden, "dec_gru")      # [B, T, H]
        # Luong-style attention: scores = dec @ enc^T, context = softmax@enc
        scores = fluid.layers.matmul(dec, enc, transpose_y=True)
        probs = fluid.layers.softmax(scores)
        ctx = fluid.layers.matmul(probs, enc)             # [B, T, H]
        feat = fluid.layers.concat([dec, ctx], axis=-1)
        logits = fluid.layers.fc(
            feat, size=tgt_vocab, num_flatten_dims=2,
            param_attr=ParamAttr(name="s2s_out_w"),
            bias_attr=ParamAttr(name="s2s_out_b"),
        )
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(
                logits, fluid.layers.reshape(tgt_out, [0, tgt_len, 1])
            )
        )
        fluid.optimizer.Adam(learning_rate=lr).minimize(loss)
    return main, startup, [src, tgt_in, tgt_out], loss


def build_decode_step(src_vocab, tgt_vocab, hidden=64, emb=32, src_len=12,
                      beam=4, end_id=1):
    """One inference step as a program: (enc_states, prev_token, prev_h,
    pre_ids, pre_scores) -> (next beam selections, new hidden).

    The host loop feeds selections back in (models/seq2seq.py
    beam_search_infer)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        enc = fluid.data("enc", [-1, src_len, hidden])     # [B*W, S, H]
        tok = fluid.data("tok", [-1, 1], dtype="int64")    # [B*W, 1]
        h_prev = fluid.data("h_prev", [-1, hidden])
        pre_ids = fluid.data("pre_ids", [-1, beam], dtype="int64")
        pre_scores = fluid.data("pre_scores", [-1, beam])
        temb = fluid.layers.embedding(
            tok, size=[tgt_vocab, emb],
            param_attr=ParamAttr(name="tgt_emb"),
        )
        temb = fluid.layers.reshape(temb, [0, 1, emb])
        dec1 = _gru_layer(temb, hidden, "dec_gru", h0=h_prev)
        dec = fluid.layers.reshape(dec1, [0, 1, hidden])
        scores_att = fluid.layers.matmul(dec, enc, transpose_y=True)
        probs_att = fluid.layers.softmax(scores_att)
        ctx = fluid.layers.matmul(probs_att, enc)
        feat = fluid.layers.concat([dec, ctx], axis=-1)
        logits = fluid.layers.fc(
            feat, size=tgt_vocab, num_flatten_dims=2,
            param_attr=ParamAttr(name="s2s_out_w"),
            bias_attr=ParamAttr(name="s2s_out_b"),
        )
        logp = fluid.layers.log_softmax(logits)            # [B*W, 1, V]
        # top-K expansions per live beam
        topk_scores, topk_ids = fluid.layers.topk(
            fluid.layers.reshape(logp, [0, tgt_vocab]), k=beam
        )
        # fixed-beam step over [B, W, K]
        ids3 = fluid.layers.reshape(topk_ids, [-1, beam, beam])
        sc3 = fluid.layers.reshape(topk_scores, [-1, beam, beam])
        sel_ids, sel_scores, parent = fluid.layers.beam_search(
            pre_ids, pre_scores, ids3, sc3, beam_size=beam, end_id=end_id,
            is_accumulated=False,
        )
        new_h = fluid.layers.reshape(dec, [0, hidden])
    return main, startup, {
        "sel_ids": sel_ids, "sel_scores": sel_scores, "parent": parent,
        "new_h": new_h,
    }


def beam_search_infer(exe, enc_main, enc_fetch, step_prog, step_outs,
                     src_batch, tgt_len, beam=4, hidden=64, start_id=0,
                     end_id=1):
    """Host-driven beam search: encode once, then step the decode program,
    gathering hidden states by parent pointers between steps; decode with
    beam_search_decode at the end. Returns [B, beam, T] sentences."""
    B, S = src_batch.shape
    enc_out = exe.run(enc_main, feed={"src": src_batch},
                      fetch_list=[enc_fetch])[0]
    enc_np = np.asarray(enc_out)                           # [B, S, H]
    enc_tiled = np.repeat(enc_np, beam, axis=0)            # [B*W, S, H]
    tok = np.full((B * beam, 1), start_id, "int64")
    h = np.zeros((B * beam, hidden), "float32")
    pre_ids = np.full((B, beam), start_id, "int64")
    pre_scores = np.zeros((B, beam), "float32")
    pre_scores[:, 1:] = -1e9  # only beam 0 live at step 0 (avoid dup paths)
    hist_ids, hist_parents = [], []
    for _ in range(tgt_len):
        outs = exe.run(step_prog, feed={
            "enc": enc_tiled, "tok": tok, "h_prev": h,
            "pre_ids": pre_ids, "pre_scores": pre_scores,
        }, fetch_list=[step_outs["sel_ids"], step_outs["sel_scores"],
                       step_outs["parent"], step_outs["new_h"]])
        sel_ids = np.asarray(outs[0]).astype("int64")      # [B, W]
        pre_scores = np.asarray(outs[1])
        parent = np.asarray(outs[2]).astype("int64")
        new_h = np.asarray(outs[3]).reshape(B, beam, hidden)
        # each selected lane continues from its parent's hidden state
        h = np.take_along_axis(new_h, parent[:, :, None], axis=1
                               ).reshape(B * beam, hidden)
        tok = sel_ids.reshape(B * beam, 1)
        pre_ids = sel_ids
        hist_ids.append(sel_ids)
        hist_parents.append(parent)
    # backtrack on the static side
    from paddle_tpu.core.ir import Program, program_guard

    dmain, dstart = Program(), Program()
    with program_guard(dmain, dstart):
        ids_v = fluid.data("ids_v", [len(hist_ids), B, beam], dtype="int64")
        par_v = fluid.data("par_v", [len(hist_ids), B, beam], dtype="int32")
        sc_v = fluid.data("sc_v", [B, beam])
        sent, sc = fluid.layers.beam_search_decode(ids_v, par_v, sc_v)
    exe.run(dstart)
    out = exe.run(dmain, feed={
        "ids_v": np.stack(hist_ids),
        "par_v": np.stack(hist_parents).astype("int32"),
        "sc_v": pre_scores,
    }, fetch_list=[sent, sc])
    return np.asarray(out[0]), np.asarray(out[1])
