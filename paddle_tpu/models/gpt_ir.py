"""GPT built on the Program/IR path with hybrid parallelism.

The product-surface counterpart of models/gpt.py's functional hybrid: the
decoder stack is a layers.PipelinedStack (ONE pipeline_stack op running the
GPipe schedule over the 'stage' mesh axis), tensor parallelism is Megatron
column/row-parallel weights declared with per-layer specs plus an explicit
c_allreduce bound to the 'model' axis (reference: the v1.7 codebase has no
TP — SURVEY §2.7 flags it as new first-class work), and data parallelism is
the batch dimension sharded on 'data' by CompiledProgram.with_parallel.
A user drives it exactly like any fluid program: build, minimize, compile,
exe.run.
"""

import math

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.param_attr import ParamAttr


class GPTIRConfig:
    def __init__(self, vocab_size=256, hidden_size=64, num_layers=4,
                 num_heads=4, ffn_mult=4, max_seq_len=64, tp=1,
                 use_flash_attention=True):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.ffn_mult = ffn_mult
        self.max_seq_len = max_seq_len
        # tensor-parallel degree is a BUILD-time quantity (Megatron-style):
        # reshape attrs inside the layer body use per-shard head counts
        self.tp = tp
        # fused scaled_dot_product_attention op (Pallas flash kernel on
        # TPU): no [1,1,S,S] bias materialization, no S^2 probs buffer.
        # False falls back to the unfused matmul/softmax path (kept for
        # parity testing).
        self.use_flash_attention = use_flash_attention


def _causal_bias(seq_len):
    """[1, 1, S, S] additive causal mask built IN-GRAPH from an O(S)
    position vector (an O(S^2) assign_value attr would bloat the program
    quadratically at long sequence lengths)."""
    from paddle_tpu.layer_helper import LayerHelper

    helper = LayerHelper("causal_pos")
    pos = helper.block.create_var(
        name=helper.name, shape=[seq_len], dtype="float32",
        stop_gradient=True,
    )
    helper.append_op(
        "assign_value",
        {},
        {"Out": [pos.name]},
        {"shape": [seq_len], "dtype": "float32",
         "values": [float(i) for i in range(seq_len)]},
    )
    rows = fluid.layers.reshape(pos, [seq_len, 1])
    cols = fluid.layers.reshape(pos, [1, seq_len])
    future = fluid.layers.cast(
        fluid.layers.greater_than(cols, rows), "float32"
    )  # 1 above the diagonal
    bias = fluid.layers.scale(future, scale=-1e9)
    out = fluid.layers.reshape(bias, [1, 1, seq_len, seq_len])
    out.stop_gradient = True
    return out


def build_gpt_ir(cfg, seq_len, num_microbatches=1, lr=1e-3):
    """Returns (main, startup, feeds, loss, stack). The batch size is a
    run-time property of the feed (dim 0 is dynamic)."""
    H = cfg.hidden_size
    n_local_heads = cfg.num_heads // cfg.tp
    d_head = H // cfg.num_heads
    h_local = n_local_heads * d_head            # attention width per shard
    init = fluid.initializer.TruncatedNormal(0.0, 0.02)

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        tokens = fluid.data("tokens", shape=[-1, seq_len], dtype="int64")
        labels = fluid.data("labels", shape=[-1, seq_len], dtype="int64")
        emb = fluid.layers.embedding(
            tokens, size=[cfg.vocab_size, H],
            param_attr=ParamAttr(name="wte", initializer=init),
        )
        pos = fluid.layers.embedding(
            _pos_ids(seq_len), size=[cfg.max_seq_len, H],
            param_attr=ParamAttr(name="wpe", initializer=init),
        )
        x = fluid.layers.elementwise_add(emb, pos)
        flash = getattr(cfg, "use_flash_attention", True)
        # unfused fallback needs the additive causal mask materialized;
        # the sdpa op handles causality inside the kernel (no S^2 buffer)
        bias = None if flash else _causal_bias(seq_len)

        stack = fluid.layers.PipelinedStack(
            num_layers=cfg.num_layers,
            num_microbatches=num_microbatches,
            ring_bindings={1: "model"},
        )
        with stack.layer():
            h = stack.input(x)
            ln1_s = stack.layer_param([H], attr=ParamAttr(
                initializer=fluid.initializer.Constant(1.0)))
            ln1_b = stack.layer_param([H], is_bias=True)
            # column-parallel q/k/v (separate weights: a fused [q|k|v]
            # concat cannot be contiguously sharded per head group); shapes
            # are GLOBAL — the ('model') spec splits them per shard
            w_q, w_k, w_v = (
                stack.layer_param([H, H], attr=ParamAttr(initializer=init),
                                  spec=(None, "model"))
                for _ in range(3)
            )
            b_q, b_k, b_v = (
                stack.layer_param([H], is_bias=True, spec=("model",))
                for _ in range(3)
            )
            # row-parallel attn out: global [H, H], dim 0 sharded
            w_ao = stack.layer_param(
                [H, H], attr=ParamAttr(initializer=init),
                spec=("model", None),
            )
            b_ao = stack.layer_param([H], is_bias=True)
            ln2_s = stack.layer_param([H], attr=ParamAttr(
                initializer=fluid.initializer.Constant(1.0)))
            ln2_b = stack.layer_param([H], is_bias=True)
            w_f1 = stack.layer_param(
                [H, cfg.ffn_mult * H], attr=ParamAttr(initializer=init),
                spec=(None, "model"),
            )
            b_f1 = stack.layer_param([cfg.ffn_mult * H], is_bias=True,
                                     spec=("model",))
            w_f2 = stack.layer_param(
                [cfg.ffn_mult * H, H], attr=ParamAttr(initializer=init),
                spec=("model", None),
            )
            b_f2 = stack.layer_param([H], is_bias=True)

            # -- attention ---------------------------------------------
            hn = _ln(h, ln1_s, ln1_b)
            q = fluid.layers.elementwise_add(fluid.layers.matmul(hn, w_q), b_q)
            k = fluid.layers.elementwise_add(fluid.layers.matmul(hn, w_k), b_k)
            v = fluid.layers.elementwise_add(fluid.layers.matmul(hn, w_v), b_v)

            def heads(t):
                t = fluid.layers.reshape(
                    t, [0, seq_len, n_local_heads, d_head]
                )
                return fluid.layers.transpose(t, [0, 2, 1, 3])

            qh, kh, vh = heads(q), heads(k), heads(v)
            if flash:
                ctx = fluid.layers.scaled_dot_product_attention(
                    qh, kh, vh, causal=True,
                    sm_scale=1.0 / math.sqrt(d_head),
                )
            else:
                scores = fluid.layers.matmul(
                    qh, kh, transpose_y=True, alpha=1.0 / math.sqrt(d_head)
                )
                scores = fluid.layers.elementwise_add(scores, bias)
                probs = fluid.layers.softmax(scores)
                ctx = fluid.layers.matmul(probs, vh)
            ctx = fluid.layers.transpose(ctx, [0, 2, 1, 3])
            ctx = fluid.layers.reshape(ctx, [0, seq_len, h_local])
            attn = fluid.layers.matmul(ctx, w_ao)  # partial over 'model'
            attn = fluid.layers.collective._allreduce(attn, ring_id=1)
            attn = fluid.layers.elementwise_add(attn, b_ao)
            h1 = fluid.layers.elementwise_add(h, attn)

            # -- mlp ----------------------------------------------------
            hm = _ln(h1, ln2_s, ln2_b)
            f = fluid.layers.gelu(
                fluid.layers.elementwise_add(
                    fluid.layers.matmul(hm, w_f1), b_f1
                )
            )
            f = fluid.layers.matmul(f, w_f2)  # partial over 'model'
            f = fluid.layers.collective._allreduce(f, ring_id=1)
            f = fluid.layers.elementwise_add(f, b_f2)
            h2 = fluid.layers.elementwise_add(h1, f)
            stack.output(h2)
        hs = stack()

        lnf_s = _vec_param("lnf_s", H, fluid.initializer.Constant(1.0))
        lnf_b = _vec_param("lnf_b", H, fluid.initializer.Constant(0.0))
        hs = _ln(hs, lnf_s, lnf_b)
        logits = fluid.layers.matmul(hs, _mat_param("head_w", [H, cfg.vocab_size], init))
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(
                logits, fluid.layers.reshape(labels, [0, seq_len, 1])
            )
        )
        fluid.optimizer.Adam(learning_rate=lr).minimize(loss)
    return main, startup, [tokens, labels], loss, stack


def _pos_ids(seq_len):
    from paddle_tpu.layer_helper import LayerHelper

    helper = LayerHelper("pos_ids")
    out = helper.block.create_var(
        name=helper.name, shape=[1, seq_len], dtype="int64",
        stop_gradient=True,
    )
    helper.append_op(
        "assign_value",
        {},
        {"Out": [out.name]},
        {"shape": [1, seq_len], "dtype": "int64",
         "values": list(range(seq_len))},
    )
    return out


def _vec_param(name, size, initializer):
    return _mat_param(name, [size], initializer)


def _mat_param(name, shape, initializer):
    from paddle_tpu.layer_helper import LayerHelper

    helper = LayerHelper("gpt_ir_param")
    return helper.create_parameter(
        ParamAttr(name=name, initializer=initializer), shape=shape,
        dtype="float32",
    )


def _ln(x, scale, bias):
    """layer_norm op applied with EXPLICIT scale/bias vars (the layer fn
    creates its own params; the pipeline body needs per-layer stacked
    ones)."""
    from paddle_tpu.layer_helper import LayerHelper

    helper = LayerHelper("ln_apply")
    out = helper.create_variable_for_type_inference(x.dtype)
    mean = helper.create_variable_for_type_inference(x.dtype)
    var = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "layer_norm",
        {"X": [x.name], "Scale": [scale.name], "Bias": [bias.name]},
        {"Y": [out.name], "Mean": [mean.name], "Variance": [var.name]},
        {"begin_norm_axis": 2, "epsilon": 1e-5},
    )
    return out


def synthetic_batch(rng, batch, seq_len, cfg):
    toks = rng.randint(0, cfg.vocab_size, (batch, seq_len + 1))
    return (
        toks[:, :-1].astype("int64"),
        toks[:, 1:].astype("int64"),
    )
