"""YOLOv3 detector — the reference model zoo's one-stage detection
workload (PaddleCV yolov3.py), scaled to a compact darknet-style backbone.

Training wires conv features into the yolov3_loss op per scale; inference
decodes the same heads with yolo_box + multiclass_nms (ops/detection.py).
"""

import paddle_tpu as fluid
from paddle_tpu.layer_helper import LayerHelper
from paddle_tpu.param_attr import ParamAttr

ANCHORS = [10, 13, 16, 30, 33, 23, 30, 61, 62, 45, 59, 119]


def _conv_bn(x, filters, ksize, stride=1, name=None):
    """Explicitly named params so train/infer programs share weights."""
    conv = fluid.layers.conv2d(
        x, num_filters=filters, filter_size=ksize, stride=stride,
        padding=(ksize - 1) // 2, bias_attr=False,
        param_attr=ParamAttr(name=f"{name}_w" if name else None),
    )
    return fluid.layers.batch_norm(
        conv, act="relu",
        param_attr=ParamAttr(name=f"{name}_bn_s" if name else None),
        bias_attr=ParamAttr(name=f"{name}_bn_b" if name else None),
        moving_mean_name=f"{name}_bn_mean" if name else None,
        moving_variance_name=f"{name}_bn_var" if name else None,
    )


def _backbone(img, base=16):
    """Compact darknet-ish stack: 3 downsamples -> stride 8 features."""
    h = _conv_bn(img, base, 3, name="bb0")
    h = _conv_bn(h, base * 2, 3, stride=2, name="bb1")
    h = _conv_bn(h, base * 2, 3, name="bb2")
    h = _conv_bn(h, base * 4, 3, stride=2, name="bb3")
    h = _conv_bn(h, base * 4, 3, name="bb4")
    h = _conv_bn(h, base * 8, 3, stride=2, name="bb5")
    return h


def build_yolov3_train(class_num=10, image_size=64, max_boxes=10, lr=1e-3,
                       anchor_mask=(0, 1, 2), base=16):
    """One-scale YOLOv3 training program (the multi-scale form repeats the
    head per pyramid level). Returns (main, startup, feeds, loss)."""
    S = len(anchor_mask)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.data("img", [-1, 3, image_size, image_size])
        gtbox = fluid.data("gt_box", [-1, max_boxes, 4])
        gtlabel = fluid.data("gt_label", [-1, max_boxes], dtype="int64")
        feat = _backbone(img, base)
        head = fluid.layers.conv2d(
            feat, num_filters=S * (5 + class_num), filter_size=1,
            param_attr=ParamAttr(name="yolo_head_w"),
            bias_attr=ParamAttr(name="yolo_head_b"),
        )
        helper = LayerHelper("yolo_loss")
        loss_v = helper.create_variable_for_type_inference("float32")
        om = helper.create_variable_for_type_inference("float32")
        gm = helper.create_variable_for_type_inference("int32")
        helper.append_op(
            "yolov3_loss",
            {"X": [head.name], "GTBox": [gtbox.name],
             "GTLabel": [gtlabel.name]},
            {"Loss": [loss_v.name], "ObjectnessMask": [om.name],
             "GTMatchMask": [gm.name]},
            {"anchors": list(ANCHORS), "anchor_mask": list(anchor_mask),
             "class_num": class_num, "ignore_thresh": 0.7,
             "downsample_ratio": 8},
        )
        loss = fluid.layers.mean(loss_v)
        fluid.optimizer.Adam(learning_rate=lr).minimize(loss)
    return main, startup, [img, gtbox, gtlabel], loss


def build_yolov3_infer(class_num=10, image_size=64, anchor_mask=(0, 1, 2),
                      base=16, conf_thresh=0.01, nms_topk=100,
                      keep_topk=50, nms_thresh=0.45):
    """Inference program: head -> yolo_box decode -> multiclass NMS slate.
    Shares weights with the training program by name."""
    S = len(anchor_mask)
    masked = []
    for m in anchor_mask:
        masked += ANCHORS[2 * m:2 * m + 2]
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.data("img", [-1, 3, image_size, image_size])
        im_size = fluid.data("im_size", [-1, 2], dtype="int32")
        feat = _backbone(img, base)
        head = fluid.layers.conv2d(
            feat, num_filters=S * (5 + class_num), filter_size=1,
            param_attr=ParamAttr(name="yolo_head_w"),
            bias_attr=ParamAttr(name="yolo_head_b"),
        )
        boxes, scores = fluid.layers.yolo_box(
            head, im_size, anchors=masked, class_num=class_num,
            conf_thresh=conf_thresh, downsample_ratio=8,
        )
        out, num_det = fluid.layers.multiclass_nms(
            bboxes=boxes,
            scores=fluid.layers.transpose(scores, [0, 2, 1]),
            score_threshold=conf_thresh, nms_top_k=nms_topk,
            keep_top_k=keep_topk, nms_threshold=nms_thresh,
            background_label=-1,
        )
        test_prog = main.clone(for_test=True)
    return test_prog, startup, [img, im_size], (out, num_det)
