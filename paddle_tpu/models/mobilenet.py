"""MobileNet V1/V2 for ImageNet — the depthwise-separable vision family
(reference model zoo: PaddleCV image_classification mobilenet.py /
mobilenet_v2.py, built on the same fluid layers the reference used).

Depthwise convolutions lower to grouped conv2d (groups == channels), which
ops/nn.py maps to XLA feature_group_count — the MXU-friendly form; no
special depthwise kernel is needed.
"""

import paddle_tpu as fluid
from paddle_tpu.param_attr import ParamAttr


def _conv_bn(x, filters, ksize, stride=1, groups=1, act="relu", name=None):
    conv = fluid.layers.conv2d(
        x, num_filters=filters, filter_size=ksize, stride=stride,
        padding=(ksize - 1) // 2, groups=groups, bias_attr=False,
        param_attr=ParamAttr(name=name + "_w" if name else None),
    )
    return fluid.layers.batch_norm(conv, act=act)


def _depthwise_separable(x, out_c, stride, scale=1.0, name=None):
    """MobileNetV1 block: depthwise 3x3 + pointwise 1x1."""
    in_c = x.shape[1]
    dw = _conv_bn(x, in_c, 3, stride=stride, groups=in_c,
                  name=f"{name}_dw" if name else None)
    return _conv_bn(dw, int(out_c * scale), 1,
                    name=f"{name}_pw" if name else None)


def _inverted_residual(x, out_c, stride, expansion, name=None):
    """MobileNetV2 block: 1x1 expand, depthwise 3x3, 1x1 project (linear),
    residual when shapes allow."""
    in_c = x.shape[1]
    mid = in_c * expansion
    h = _conv_bn(x, mid, 1, name=f"{name}_exp" if name else None)
    h = _conv_bn(h, mid, 3, stride=stride, groups=mid,
                 name=f"{name}_dw" if name else None)
    h = _conv_bn(h, out_c, 1, act=None,
                 name=f"{name}_proj" if name else None)
    if stride == 1 and in_c == out_c:
        h = fluid.layers.elementwise_add(x, h)
    return h


def mobilenet_v1(img, class_dim=1000, scale=1.0):
    cfg = [
        # (out_c, stride)
        (64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
        (512, 1), (512, 1), (512, 1), (512, 1), (512, 1), (1024, 2),
        (1024, 1),
    ]
    h = _conv_bn(img, int(32 * scale), 3, stride=2, name="conv1")
    for i, (c, s) in enumerate(cfg):
        h = _depthwise_separable(h, c, s, scale, name=f"dws{i}")
    h = fluid.layers.adaptive_pool2d(h, 1, pool_type="avg")
    h = fluid.layers.flatten(h)
    return fluid.layers.fc(h, size=class_dim, act="softmax")


def mobilenet_v2(img, class_dim=1000):
    cfg = [
        # (expansion, out_c, repeats, stride)
        (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
        (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1),
    ]
    h = _conv_bn(img, 32, 3, stride=2, name="conv1")
    i = 0
    for t, c, n, s in cfg:
        for r in range(n):
            h = _inverted_residual(h, c, s if r == 0 else 1, t,
                                   name=f"ir{i}")
            i += 1
    h = _conv_bn(h, 1280, 1, name="conv_last")
    h = fluid.layers.adaptive_pool2d(h, 1, pool_type="avg")
    h = fluid.layers.flatten(h)
    return fluid.layers.fc(h, size=class_dim, act="softmax")


def build_mobilenet_train(version=1, class_dim=1000, lr=0.1, use_amp=False,
                          image_shape=(3, 224, 224)):
    """Returns (main, startup, feeds, fetches) — same contract as
    resnet.build_resnet_train."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.data("img", [-1] + list(image_shape))
        label = fluid.data("label", [-1, 1], dtype="int64")
        net = mobilenet_v1 if version == 1 else mobilenet_v2
        prob = net(img, class_dim=class_dim)
        loss = fluid.layers.mean(
            fluid.layers.cross_entropy(prob, label)
        )
        acc = fluid.layers.accuracy(prob, label)
        opt = fluid.optimizer.MomentumOptimizer(lr, 0.9)
        if use_amp:
            from paddle_tpu.amp import decorate

            opt = decorate(opt)
        opt.minimize(loss)
    return main, startup, [img, label], [loss, acc]
